"""Pallas Q40 kernel variants — measured head-to-head on the real chip.

Each variant computes y = x @ dequant(W).T for W (d, n) in packed Q40.
Correctness is checked against the XLA dequant path before timing.
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")

from distributed_llama_tpu.quants.jax_codec import QuantizedTensor, dequantize_q40_jax
from distributed_llama_tpu.ops.pallas_q40 import q40_matmul, _split_activation

L, D, H = 32, 4096, 11008
R1, R2 = 2, 10


def slope(make_run, *args):
    ts = {}
    for reps in (R1, R2):
        fn = make_run(reps)
        out = fn(*args)
        np.asarray(jax.tree.leaves(out)[0])
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn(*args)
            np.asarray(jax.tree.leaves(out)[0])
            best = min(best, time.perf_counter() - t0)
        ts[reps] = best
    return (ts[R2] - ts[R1]) / (R2 - R1)


def _q40(shape_d, shape_n, layers=L, seed=0):
    rng = np.random.default_rng(seed)
    nb = shape_n // 32
    packed = rng.integers(0, 256, (layers, shape_d, 16, nb), dtype=np.uint8)
    scales = (rng.random((layers, shape_d, nb), dtype=np.float32) * 0.004).astype(np.float16)
    return QuantizedTensor(jnp.asarray(packed), jnp.asarray(scales))


# ---- variant A: bf16 muls + bf16 MXU dots, keep -8 on VPU -----------------

def _kernel_a(x_lo_ref, x_hi_ref, packed_ref, scales_ref, out_ref, *, nb):
    pk = packed_ref[:].astype(jnp.int32)
    lo = (pk & 0xF).astype(jnp.bfloat16) - jnp.bfloat16(8)
    hi = (pk >> 4).astype(jnp.bfloat16) - jnp.bfloat16(8)
    s = scales_ref[:]
    s16 = pltpu.repeat(s, 16, axis=1).astype(jnp.bfloat16)
    wlo = lo * s16
    whi = hi * s16
    dot = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc = dot(x_lo_ref[:], wlo) + dot(x_hi_ref[:], whi)
    out_ref[:] = acc


def q40_matmul_a(x, w, td=256):
    d, _, nb = w.packed.shape
    n, m = nb * 32, nb * 16
    t = x.shape[0]
    x_lo, x_hi = _split_activation(x.astype(jnp.float32), nb)
    x_lo = x_lo.astype(jnp.bfloat16)
    x_hi = x_hi.astype(jnp.bfloat16)
    packed2d = w.packed.reshape(d, m)
    grid = (d // td,)
    out = pl.pallas_call(
        functools.partial(_kernel_a, nb=nb),
        grid=grid,
        in_specs=[
            pl.BlockSpec((t, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((t, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((td, m), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((td, nb), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((t, td), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
    )(x_lo, x_hi, packed2d, w.scales.astype(jnp.float32))
    return out


# ---- variant B: bf16 + correction trick (no -8 in the hot loop) -----------

def _kernel_b(x_lo_ref, x_hi_ref, packed_ref, scales_ref, corr_ref, out_ref, *, nb):
    pk = packed_ref[:].astype(jnp.int32)
    lo = (pk & 0xF).astype(jnp.bfloat16)
    hi = (pk >> 4).astype(jnp.bfloat16)
    s16 = pltpu.repeat(scales_ref[:], 16, axis=1).astype(jnp.bfloat16)
    wlo = lo * s16
    whi = hi * s16
    dot = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc = dot(x_lo_ref[:], wlo) + dot(x_hi_ref[:], whi)
    out_ref[:] = acc + corr_ref[:]


def q40_matmul_b(x, w, td=256):
    d, _, nb = w.packed.shape
    n, m = nb * 32, nb * 16
    t = x.shape[0]
    xf = x.astype(jnp.float32)
    x_lo, x_hi = _split_activation(xf, nb)
    # correction: -8 * sum_b s[d,b] * (sum_j (x_lo+x_hi)[t, j*nb+b])
    xs = (x_lo + x_hi).reshape(t, 16, nb).sum(axis=1)          # (t, nb)
    corr = -8.0 * jnp.einsum("tb,db->td", xs, w.scales.astype(jnp.float32))
    x_lo = x_lo.astype(jnp.bfloat16)
    x_hi = x_hi.astype(jnp.bfloat16)
    packed2d = w.packed.reshape(d, m)
    grid = (d // td,)
    out = pl.pallas_call(
        functools.partial(_kernel_b, nb=nb),
        grid=grid,
        in_specs=[
            pl.BlockSpec((t, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((t, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((td, m), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((td, nb), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((t, td), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((t, td), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
    )(x_lo, x_hi, packed2d, w.scales.astype(jnp.float32), corr)
    return out


# ---- harness ---------------------------------------------------------------

def check(name, fn):
    w1 = _q40(256, 512, layers=1)
    w1 = QuantizedTensor(w1.packed[0], w1.scales[0])
    x = jnp.asarray(np.random.default_rng(1).standard_normal((1, 512), np.float32))
    want = x @ dequantize_q40_jax(w1, jnp.float32).T
    got = fn(x, w1)
    err = float(jnp.max(jnp.abs(want - got)))
    rel = err / float(jnp.max(jnp.abs(want)))
    print(f"{name}: max rel err {rel:.2e}")
    assert rel < 2e-2, f"{name} wrong"


def bench(name, fn, td=256):
    w = _q40(H, D)
    x = jnp.ones((1, D), jnp.bfloat16)

    def make(reps):
        def run(w, x):
            def rep(x, _):
                def layer(x, wl):
                    y = fn(x, wl, td)
                    return x + y[:, :D].astype(x.dtype) * jnp.bfloat16(1e-6), None
                x, _ = jax.lax.scan(layer, x, w)
                return x, None
            x, _ = jax.lax.scan(rep, x, None, length=reps)
            return x
        return jax.jit(run)

    dt = slope(make, w, x)
    gb = (w.packed.size + w.scales.size * 2) / 1e9
    print(f"{name} (td={td}): {dt*1e3:.3f} ms/pass for {gb:.2f} GB -> {gb/dt:.0f} GB/s")


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "a"):
        check("A", lambda x, w: q40_matmul_a(x, w))
        bench("A bf16", q40_matmul_a, 256)
        bench("A bf16", q40_matmul_a, 512)
    if which in ("all", "b"):
        check("B", lambda x, w: q40_matmul_b(x, w))
        bench("B bf16+corr", q40_matmul_b, 256)
        bench("B bf16+corr", q40_matmul_b, 512)
