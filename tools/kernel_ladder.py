"""Cost ladder for the Q40 kernel: add one stage at a time, measure each.

Stages: read (DMA only) -> unpack -> convert -> scale-mul -> dots.
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")
from distributed_llama_tpu.quants.jax_codec import QuantizedTensor

L, D, H = 32, 4096, 11008
R1, R2 = 2, 10
TD = 256


def slope(make_run, *args):
    ts = {}
    for reps in (R1, R2):
        fn = make_run(reps)
        out = fn(*args)
        np.asarray(jax.tree.leaves(out)[0])
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn(*args)
            np.asarray(jax.tree.leaves(out)[0])
            best = min(best, time.perf_counter() - t0)
        ts[reps] = best
    return (ts[R2] - ts[R1]) / (R2 - R1)


def make_kernel(stage):
    def kernel(x_lo_ref, x_hi_ref, packed_ref, scales_ref, out_ref, *, nb):
        t = x_lo_ref.shape[0]
        td = packed_ref.shape[0]
        if stage == "read":
            # touch one lane of the block so the DMA isn't elided
            out_ref[:] = jnp.broadcast_to(
                packed_ref[0:1, 0:1].astype(jnp.int32).astype(jnp.float32)
                + scales_ref[0:1, 0:1],
                out_ref.shape)
            return
        pk = packed_ref[:].astype(jnp.int32)
        if stage == "unpack":
            lo = pk & 0xF
            hi = pk >> 4
            out_ref[:] = jnp.broadcast_to(
                (lo[0:1, 0:1] + hi[0:1, 0:1]).astype(jnp.float32), out_ref.shape)
            return
        lo = (pk & 0xF).astype(jnp.float32)
        hi = (pk >> 4).astype(jnp.float32)
        if stage == "convert":
            out_ref[:] = jnp.broadcast_to(lo[0:1, 0:1] + hi[0:1, 0:1], out_ref.shape)
            return
        s16 = pltpu.repeat(scales_ref[:], 16, axis=1)
        wlo = lo * s16
        whi = hi * s16
        if stage == "mul":
            out_ref[:] = jnp.broadcast_to(wlo[0:1, 0:1] + whi[0:1, 0:1], out_ref.shape)
            return
        dot = functools.partial(
            jax.lax.dot_general,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        out_ref[:] = dot(x_lo_ref[:], wlo) + dot(x_hi_ref[:], whi)
    return kernel


def run_stage(stage):
    rng = np.random.default_rng(0)
    nb = D // 32
    m = 16 * nb
    packed = jnp.asarray(rng.integers(0, 256, (L, H, m), dtype=np.uint8))
    scales = jnp.asarray((rng.random((L, H, nb), dtype=np.float32) * 0.004))
    t = 1
    x_lo = jnp.ones((t, m), jnp.float32)
    x_hi = jnp.ones((t, m), jnp.float32)

    kern = make_kernel(stage)

    def one(p2, s2, x_lo, x_hi):
        return pl.pallas_call(
            functools.partial(kern, nb=nb),
            grid=(H // TD,),
            in_specs=[
                pl.BlockSpec((t, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((t, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((TD, m), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((TD, nb), lambda i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((t, TD), lambda i: (0, i), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((t, H), jnp.float32),
        )(x_lo, x_hi, p2, s2)

    def make(reps):
        def run(packed, scales, x):
            def rep(x, _):
                def layer(x, ws):
                    p2, s2 = ws
                    y = one(p2, s2, x, x)
                    return x + y[:, :m] * 1e-6, None
                x, _ = jax.lax.scan(layer, x, (packed, scales))
                return x, None
            x, _ = jax.lax.scan(rep, x, None, length=reps)
            return x
        return jax.jit(run)

    dt = slope(make, packed, scales, x_lo)
    gb = (packed.size + scales.size * 2) / 1e9
    print(f"{stage:8s}: {dt*1e3:.3f} ms/pass -> {gb/dt:.0f} GB/s")


if __name__ == "__main__":
    for stage in (sys.argv[1:] or ["read", "unpack", "convert", "mul", "dot"]):
        run_stage(stage)
