#!/usr/bin/env python
"""autotune — measured batch-knee calibration → a versioned AUTOTUNE.json.

The measurement half of the batch-knee loop (ROADMAP item 1): sweep the
SERVING step shapes — the slot scheduler's real executables, driven
through a real ``runtime/scheduler.Scheduler`` exactly like bench.py's
``_serve_row`` so calibration and live ``--trace-dir`` timelines are the
same units — across batch sizes, fit the composition→ms/step curve with
``tools/dlprof.py``'s knee estimator, and emit an artifact that:

  * ``dllama api --serve-batch auto --autotune AUTOTUNE.json`` consumes
    at startup (``runtime/profiler.resolve_auto_shape`` caps the
    HBM-ledger headroom by the calibrated knee),
  * ``tools/dlprof.py --autotune`` compares against live step timelines
    and flags drift (knee moved >= 25% since calibration),
  * ``BENCH_AUTOTUNE=1 bench.py`` runs inline for the committed A/B row.

Per batch size B the sweep serves B concurrent requests through a fresh
B-slot scheduler (one full-width prefill chunk each, then a pure decode
phase at occupancy B) and reads the flight recorder's per-composition
step histograms; the decode-only composition ``dec{B}_pre0_c0`` is the
curve point. Supplementary shapes measured on the LARGEST batch:

  * the adaptive chunk-width ladder (``scheduler.chunk_ladder``) —
    per-width prefill-forward cost, the data behind the SLO policy's
    shrink/widen tradeoff,
  * the prefix-cache pass — the same trace re-served with a shared
    prefix through a radix cache, so seed-path admissions and hit-path
    step times are in the artifact.

Methodology is backend-agnostic: the same sweep runs on the CPU-tiny
config in CI smoke form and on real silicon with a production model
(``--model 7b``); the artifact records backend + model so consumers can
refuse a mismatched calibration. ``--selftest`` exercises the fit +
artifact round-trip + both validators with no jax at all (the CI step).

Usage:
  python tools/autotune.py --model tiny --batches 2,4,8,16,32,64,128 \
      --out AUTOTUNE.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_TOOLS = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_TOOLS)
for _p in (_TOOLS, _REPO):
    if _p not in sys.path:
        sys.path.insert(0, _p)

import dlprof  # noqa: E402 — the knee estimator + artifact validator

AUTOTUNE_KIND = dlprof.AUTOTUNE_KIND
AUTOTUNE_VERSION = dlprof.AUTOTUNE_VERSION
DEFAULT_BATCHES = (2, 4, 8, 16, 32, 64, 128)
# small batches anchor the knee criterion: dlprof's marginal-throughput
# test references the SMALL-batch per-row throughput — a grid that starts
# at an already-amortized batch size understates the baseline and calls
# the knee one rung early


def build_artifact(*, model: str, backend: str, jax_version: str,
                   chunk: int, seq_len: int, steps_per_batch: int,
                   decode_curve: list[dict],
                   prefill_ms_by_width: dict | None = None,
                   prefix: dict | None = None,
                   hbm: dict | None = None,
                   created_unix: float | None = None) -> dict:
    """Assemble + knee-fit the versioned artifact from measured points.
    Pure (no jax): the selftest builds one from synthetic timings."""
    curve = [(int(p["rows"]), float(p["p50_ms"])) for p in decode_curve
             if p.get("p50_ms")]
    knee = dlprof.knee_estimate(sorted(curve))
    art = {
        "kind": AUTOTUNE_KIND,
        "version": AUTOTUNE_VERSION,
        "created_unix": (time.time() if created_unix is None
                         else created_unix),
        "model": model,
        "backend": backend,
        "jax": jax_version,
        "chunk": int(chunk),
        "seq_len": int(seq_len),
        "steps_per_batch": int(steps_per_batch),
        "decode_curve": decode_curve,
        "prefill_ms_by_width": prefill_ms_by_width or {},
        "prefix": prefix or {},
        "knee": knee,
        "recommendation": dlprof.serve_batch_recommendation(knee, hbm),
        "hbm": hbm,
    }
    problems = dlprof.validate_autotune(art)
    if problems:
        raise ValueError("calibration produced an invalid artifact: "
                         + "; ".join(problems))
    return art


def _sweep_batch(spec, params, b: int, *, chunk: int, steps: int,
                 cdt, seq: int, prefix_block_len: int = 16,
                 with_prefix: bool = False) -> dict:
    """Serve one batch size through a real scheduler and return its
    per-composition step timeline (+ prefix-cache stats when asked)."""
    import gc

    import numpy as np

    from distributed_llama_tpu.runtime.engine import Engine
    from distributed_llama_tpu.runtime.prefix_cache import PrefixCache
    from distributed_llama_tpu.runtime.scheduler import Scheduler
    from distributed_llama_tpu.runtime.trace import TRACER
    from distributed_llama_tpu.sampler import Sampler

    eng = Engine(spec, params, compute_dtype=cdt, cache_dtype=cdt,
                 max_seq_len=seq, batch=b)
    pc = None
    if with_prefix:
        pc = PrefixCache(eng, num_blocks=max(2 * b, 8)
                         * (chunk // prefix_block_len + 1),
                         block_len=prefix_block_len)
    sched = Scheduler(eng, chunk=chunk, prefix_cache=pc)
    sched.warmup()

    rng = np.random.default_rng(0)
    shared = rng.integers(1, spec.vocab_size, chunk).astype(
        np.int64).tolist()
    if with_prefix:
        # shared-prefix trace: request 0 publishes, the rest seed — the
        # hit-path admission + seeded steps land on the timeline
        prompts = [shared + rng.integers(1, spec.vocab_size, 4).astype(
            np.int64).tolist() for _ in range(b)]
        prime = sched.submit(prompts[0], 2,
                             Sampler(spec.vocab_size, temperature=0.0,
                                     topp=0.9, seed=7))
        while not prime.finished.is_set():
            sched.step()
    else:
        # one full-width chunk each: every request prefills in a single
        # (B, chunk) forward, then decodes `steps` tokens — the timeline
        # is dominated by the decode-only composition at occupancy B
        prompts = [rng.integers(1, spec.vocab_size, chunk).astype(
            np.int64).tolist() for _ in range(b)]

    TRACER.reset()
    TRACER.configure(capacity=8192, decode_every=1 << 30)
    try:
        live = [sched.submit(p, steps,
                             Sampler(spec.vocab_size, temperature=0.0,
                                     topp=0.9, seed=7))
                for p in prompts]
        guard = 0
        while not all(r.finished.is_set() for r in live):
            sched.step()
            guard += 1
            assert guard < 100 * (steps + chunk), "sweep did not drain"
        timeline = TRACER.steps.summary_json()
    finally:
        TRACER.reset()
        sched.close()
    out = {"timeline": timeline}
    if pc is not None:
        out["prefix_stats"] = pc.stats.summary()
    if with_prefix:
        # the largest engine survives the sweep: the caller reads its
        # HBM ledger and times the prefill width ladder on it
        out["engine"], out["pc"] = eng, pc
    else:
        del eng
        gc.collect()
    return out


def _prefill_ladder_ms(engine, chunk: int, repeats: int = 5) -> dict:
    """Direct per-width cost of the adaptive ladder's prefill forwards
    (all rows gated — state-neutral, same flops as a live chunk): the
    shrink/widen tradeoff the SLO policy trades on, in ms."""
    import numpy as np

    from distributed_llama_tpu.runtime.scheduler import chunk_ladder

    gate = np.full((engine.batch,), engine.seq_len, np.int32)
    zl = np.zeros((engine.batch,), np.int32)
    out = {}
    for w in chunk_ladder(chunk):
        tok = np.zeros((engine.batch, w), np.int32)
        engine.slot_prefill_chunk(tok, gate, zl)  # compile off the clock
        best = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            logits = engine.slot_prefill_chunk(tok, gate, zl)
            logits.block_until_ready()
            dt = (time.perf_counter() - t0) * 1e3
            best = dt if best is None else min(best, dt)
        out[str(w)] = round(best, 4)
    return out


def calibrate(*, model: str = "tiny", batches=DEFAULT_BATCHES,
              chunk: int = 32, steps: int = 32, seq: int | None = None,
              spec=None, params=None, log=print) -> dict:
    """Run the sweep on the current backend and return the artifact.
    `spec`/`params` override the bench model table (BENCH_AUTOTUNE=1
    reuses bench.py's already-synthesized weights)."""
    import jax

    import bench
    from distributed_llama_tpu.runtime.profiler import hbm_ledger

    if spec is None:
        spec = {"7b": bench.LLAMA2_7B, "8b": bench.LLAMA3_8B,
                "13b": bench.LLAMA2_13B, "moe": bench.MIXTRAL_MOE,
                "grok": bench.GROK1_TRUNC,
                "70bt": bench.LLAMA2_70B_TRUNC}.get(model, bench.TINY)
    if params is None:
        params = bench.synth_q40_params(spec)
    import jax.numpy as jnp

    cdt = jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16
    seq = int(seq or min(256, spec.seq_len))
    batches = sorted({int(b) for b in batches})
    decode_curve = []
    largest = None
    for b in batches:
        t0 = time.perf_counter()
        res = _sweep_batch(spec, params, b, chunk=chunk, steps=steps,
                           cdt=cdt, seq=seq, with_prefix=(b == batches[-1]))
        comp = res["timeline"].get(f"dec{b}_pre0_c0")
        if comp:
            decode_curve.append({"rows": b, "p50_ms": comp["p50_ms"],
                                 "mean_ms": comp["mean_ms"],
                                 "n": comp["n"]})
        log(f"autotune: batch {b}: "
            f"{comp['p50_ms'] if comp else None} ms/step p50 "
            f"({time.perf_counter() - t0:.1f}s)", file=sys.stderr)
        if b == batches[-1]:
            largest = res
    prefix = {"timeline": largest["timeline"],
              "stats": largest.get("prefix_stats")}
    eng = largest["engine"]
    hbm = hbm_ledger(eng, largest.get("pc"))
    ladder_ms = _prefill_ladder_ms(eng, chunk)
    art = build_artifact(
        model=model, backend=jax.default_backend(), jax_version=jax.__version__,
        chunk=chunk, seq_len=seq, steps_per_batch=steps,
        decode_curve=decode_curve, prefill_ms_by_width=ladder_ms,
        prefix=prefix, hbm=hbm)
    del largest, eng
    import gc

    gc.collect()
    return art


# -- selftest (the CI smoke: fit + artifact contract, no jax) ---------------


def _selftest() -> int:
    import tempfile

    # a synthetic curve with a knee at 4 rows -> artifact round-trip
    curve = [{"rows": r, "p50_ms": ms, "mean_ms": ms, "n": 32}
             for r, ms in ((1, 5.0), (2, 5.4), (4, 6.2), (8, 14.0))]
    art = build_artifact(model="selftest", backend="none",
                         jax_version="none", chunk=32, seq_len=256,
                         steps_per_batch=32, decode_curve=curve,
                         prefill_ms_by_width={"32": 4.0, "16": 2.2},
                         created_unix=0.0)
    assert art["knee"]["knee_rows"] == 4, art["knee"]
    assert art["recommendation"]["serve_batch"] == 4
    assert not dlprof.validate_autotune(art)

    # BOTH validators accept the artifact after a disk round-trip: the
    # standalone dlprof one and the canonical runtime/profiler one (the
    # consumer `--serve-batch auto` trusts) must agree
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "AUTOTUNE.json")
        with open(path, "w") as f:
            json.dump(art, f)
        loaded = dlprof.load_autotune(path)
        from distributed_llama_tpu.runtime.profiler import (
            AUTOTUNE_KIND as PK, AUTOTUNE_VERSION as PV, load_autotune)
        assert (PK, PV) == (AUTOTUNE_KIND, AUTOTUNE_VERSION)
        assert load_autotune(path)["knee"]["knee_rows"] == 4
    assert loaded["knee"]["knee_rows"] == 4

    # empty sweep -> a clear error, never a kneeless artifact
    try:
        build_artifact(model="x", backend="none", jax_version="none",
                       chunk=32, seq_len=256, steps_per_batch=1,
                       decode_curve=[])
    except ValueError as e:
        assert "knee" in str(e)
    else:
        raise AssertionError("kneeless artifact was not refused")
    print("autotune selftest: OK (knee=4, both validators agree, "
          "kneeless sweep refused)")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="autotune", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--model", default="tiny",
                    choices=["tiny", "7b", "8b", "13b", "moe", "grok",
                             "70bt"],
                    help="bench.py model table entry (synthetic Q40 "
                         "weights — step time does not depend on values)")
    ap.add_argument("--batches", default=",".join(map(str,
                                                      DEFAULT_BATCHES)),
                    help="comma list of batch sizes to sweep")
    ap.add_argument("--chunk", type=int, default=32,
                    help="prefill chunk width (the adaptive ladder's "
                         "widest rung)")
    ap.add_argument("--steps", type=int, default=32,
                    help="decode steps measured per batch size")
    ap.add_argument("--seq", type=int, default=None,
                    help="engine context for the sweep (default: "
                         "min(256, model seq_len))")
    ap.add_argument("--out", default="AUTOTUNE.json",
                    help="artifact path (default ./AUTOTUNE.json)")
    ap.add_argument("--selftest", action="store_true",
                    help="fit + artifact-contract smoke, no jax (CI)")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    batches = [int(x) for x in str(args.batches).split(",") if x.strip()]
    if not batches:
        ap.error("--batches must name at least one batch size")
    art = calibrate(model=args.model, batches=batches, chunk=args.chunk,
                    steps=args.steps, seq=args.seq)
    with open(args.out, "w") as f:
        json.dump(art, f, indent=1)
        f.write("\n")
    rec = art["recommendation"]
    print(f"autotune: wrote {args.out} — knee={art['knee']['knee_rows']} "
          f"rows ({art['knee']['method']}), recommended --serve-batch "
          f"{rec['serve_batch']}"
          + (f" (HBM caps at {rec['hbm_cap_rows']})"
             if rec.get("hbm_cap_rows") is not None else ""))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
