"""Experiment: let XLA choose the decode step's INPUT layouts (round 5).

The decode trace (tools/exp_decode_profile.py) shows per-step async copies
of Q40 scale arrays (e.g. u16[4096,344] -> tiled (8,128)(2,1)): the
executable accepts default-layout parameters and re-tiles them INSIDE the
program every call — recoverable HBM traffic if the conversion can happen
once at load instead. jax.experimental.layout.Format(Layout.AUTO) on the
jit inputs lets XLA pick its preferred parameter layouts; device_put-ing
the params into those layouts once should then make the per-step copies
vanish.

Measures whole-model 7B decode, interleaved best-of-N:
  a) default layouts (the shipped path)
  b) AUTO input layouts + params re-placed to the compiled preference

Result (v5e, 2026-07-31, 256 tokens, best of 3 interleaved): NEGATIVE.
AUTO does prefer tiled layouts for exactly 32 leaves — every layer's w2
scales, u16 (4096, 344) -> tiling ((8,128),(2,1)), matching the per-step
copy-start ops in the trace — but feeding pre-tiled parameters measures
0.997x (11.637 vs 11.602 ms/token in this no-donation harness; both modes
identical within jitter). The in-program re-tiling copies are fully
overlapped with the VPU-bound kernels and cost nothing on the critical
path; the trace's big async "copy" spans were window time, not work.
Decode stays at the kernel VPU ceiling. (Harness note: this experiment's
jit does not donate the cache, so its absolute ms/token runs ~2 ms above
the engine's donated path — the A/B is relative.)
"""

import sys
import time

sys.path.insert(0, ".")

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.layout import Format, Layout

from bench import LLAMA2_7B, synth_q40_params
from distributed_llama_tpu.models.transformer import forward
from distributed_llama_tpu.runtime import Engine


def main():
    spec = dataclasses.replace(LLAMA2_7B, seq_len=2048)
    params = synth_q40_params(spec)
    eng = Engine(spec, params, compute_dtype=jnp.bfloat16,
                 cache_dtype=jnp.bfloat16)
    eng.reset()

    def run(p, tok, pos, cache):
        return forward(p, spec, tok, pos, cache,
                       compute_dtype=jnp.bfloat16, use_pallas=True)

    tok = jnp.zeros((1, 1), jnp.int32)
    args = (eng.params, tok, jnp.int32(0), eng.cache)

    # AUTO layouts on every input leaf; lower+compile; inspect choices
    autos = jax.tree.map(lambda _: Format(Layout.AUTO), args)
    jitted = jax.jit(run, in_shardings=autos)
    comp = jitted.lower(*args).compile()
    in_fmts, _kw = comp.input_formats  # (args formats, kwargs formats)
    n_diff = 0
    for a, f in zip(jax.tree.leaves(args), jax.tree.leaves(in_fmts)):
        if str(getattr(a, "format", None)) != str(f):
            n_diff += 1
            if n_diff <= 3:
                print("AUTO prefers", f.layout, "for", a.shape, a.dtype)
    print(f"leaves with non-default preferred layout: {n_diff}")

    if n_diff:
        args_auto = jax.tree.map(jax.device_put, args, in_fmts)
    else:
        args_auto = args
    # the AUTO-signature jit cannot be CALLED with concrete arrays; re-jit
    # pinned to the chosen formats and feed the re-placed params
    jitted = jax.jit(run, in_shardings=in_fmts)

    base = jax.jit(run)

    def decode(fn, a, n=256):
        p, t, _, cache = a
        logits, cache = fn(p, t, jnp.int32(0), cache)
        np.asarray(logits)  # warm + sync
        t0 = time.perf_counter()
        for i in range(1, n + 1):
            logits, cache = fn(p, t, jnp.int32(i), cache)
        np.asarray(logits)
        return (time.perf_counter() - t0) / n * 1e3

    best = {}
    for r in range(3):
        for name, (fn, a) in (("default", (base, args)),
                              ("auto", (jitted, args_auto))):
            ms = decode(fn, a)
            best[name] = ms if name not in best else min(best[name], ms)
    for k, v in best.items():
        print(f"{k:8s} {v:.3f} ms/token")
    print(f"ratio default/auto: {best['default'] / best['auto']:.3f}")


if __name__ == "__main__":
    main()
