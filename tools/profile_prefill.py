"""Prefill time breakdown on the real chip: where does a 256-token chunk go?

The fused Q40 matmul kernel now overlaps unpack with the MXU (+9.5%
whole-model prefill, ops/pallas_q40._n_sub); this tool measures what is
left — per-layer component times for a 7B prefill chunk so the next lever
is picked by data, not guess:

  * q40 matmuls per layer: qkv+o (d=4096 shapes, td=1024 whole-tile) and
    w1/w3 (td=256, n_sub=8) + w2 (td=256, n_sub=2)
  * flash chunked-prefill attention at a representative fill
  * everything else (norms, rope, residuals, embed/logits amortized) =
    whole-step time minus the above

Discipline (tools/hw_runbook.sh): chain 8 calls per jit to amortize the
~140 ms tunnel dispatch; interleave variants best-of-N in one process;
sync via np.asarray, never block_until_ready.

Usage: python tools/profile_prefill.py   (no PYTHONPATH override!)

MEASURED (round 4, v5e, healthy tunnel window — whole model 5926 tok/s):
    dispatch floor   2.42 ms/run-slot (n=64 chains, ~155 ms/run)
    ffn w1+w3+w2     0.856 ms/layer  -> 27.4 ms/chunk = 63% of the chunk
    qkvo + attn      below the jitter floor individually (<~0.5 ms/layer)
    unaccounted      15.7 ms/chunk (36%) — embed/logits tail, norms/rope,
                     plus the qkvo/attn signal lost under jitter
FFN at 63% of chunk = ~81 TFLOP/s = 41% MFU on the sub-tiled kernel: the
quantized FFN matmul is still the prefill ceiling; attention and the
projections are not the next lever at 2k context.
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

import bench
from distributed_llama_tpu.ops import pallas_q40 as q40
from distributed_llama_tpu.ops.pallas_attention import flash_attention
from distributed_llama_tpu.runtime.engine import Engine

T = 256          # the engine's prefill chunk
FILL = 1024      # representative mid-prompt cache fill


def chain(fn, x0, n=64):
    @jax.jit
    def run(x):
        y = x
        for _ in range(n):
            y = fn(y)
        return y
    np.asarray(run(x0))  # compile
    return run, x0, n


def timed(run, x0, n, reps=4):
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(run(x0))
        best = min(best, (time.perf_counter() - t0) / n)
    return best * 1e3  # ms per call


def main() -> None:
    spec = bench.LLAMA2_7B
    params = bench.synth_q40_params(spec)
    layer0 = params["layers"][0]
    wq, wk, wv, wo = (layer0[k] for k in ("wq", "wk", "wv", "wo"))
    w1, w2, w3 = (layer0[k] for k in ("w1", "w2", "w3"))

    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (T, spec.dim), dtype=np.float32)).astype(jnp.bfloat16)

    jobs = {}
    # identity-ish chain measures the per-run dispatch/transfer floor —
    # subtracted from every component row (the tunnel's floor drifts by
    # hundreds of ms between phases, swamping ms-scale per-layer times)
    jobs["dispatch floor"] = chain(lambda v: v * 1.0000001, x)
    # attention projections: all four are (4096, 4096) for 7B MHA -> td=1024
    jobs["qkvo (4x d4096 td1024)"] = chain(
        lambda v: sum(q40.q40_matmul(v, w, out_dtype=jnp.bfloat16)
                      for w in (wq, wk, wv, wo)), x)
    jobs["ffn (w1+w3+w2 td256)"] = chain(
        lambda v: q40.q40_matmul(
            q40.q40_matmul(v, w1, out_dtype=jnp.bfloat16)
            * q40.q40_matmul(v, w3, out_dtype=jnp.bfloat16),
            w2, out_dtype=jnp.bfloat16), x)

    hs = spec.dim // spec.n_heads
    qh = jnp.asarray(np.random.default_rng(1).standard_normal(
        (1, T, spec.n_heads, hs), dtype=np.float32)).astype(jnp.bfloat16)
    kc = jnp.zeros((1, spec.n_kv_heads, spec.seq_len, hs), jnp.bfloat16)
    pos = (FILL + jnp.arange(T, dtype=jnp.int32))[None, :]  # (B=1, T)

    def attn(v):
        o = flash_attention(v, kc, kc, pos)
        return (v + o.reshape(v.shape) * 1e-3).astype(jnp.bfloat16)

    jobs[f"flash attn (T={T}, fill={FILL})"] = chain(attn, qh)

    # whole-model single chunk via the engine for the total
    engine = Engine(spec, params, compute_dtype=jnp.bfloat16,
                    cache_dtype=jnp.bfloat16, max_seq_len=spec.seq_len)
    engine.reset()
    tokens = list(np.ones(2048, np.int32))
    for rep in range(3):
        engine.reset()
        t0 = time.perf_counter()
        logits = engine.prefill(tokens)
        np.asarray(logits)
        dt = time.perf_counter() - t0
        if rep == 0:
            continue
        total = min(dt if rep == 1 else total, dt)
    per_chunk_ms = total / (2048 / T) * 1e3

    results = {}
    for _ in range(4):
        for name, (run, x0, n) in jobs.items():
            ms = timed(run, x0, n, reps=1)
            results[name] = min(results.get(name, 1e9), ms)

    print(f"whole-model: {total * 1e3:8.1f} ms / 2048 tok "
          f"({2048 / total:6.0f} tok/s) -> {per_chunk_ms:6.2f} ms/chunk")
    floor = results.pop("dispatch floor")
    print(f"dispatch floor: {floor:.3f} ms/call-slot")
    acc = 0.0
    for name, ms in results.items():
        ms = max(ms - floor, 0.0)
        per_layer = ms
        per_chunk = per_layer * spec.n_layers
        acc += per_chunk
        print(f"{name:32s}: {per_layer:7.3f} ms/layer -> "
              f"{per_chunk:7.1f} ms/chunk-all-layers "
              f"({per_chunk / per_chunk_ms * 100:5.1f}% of chunk)")
    print(f"{'unaccounted (norms/rope/embed/…)':32s}: "
          f"{per_chunk_ms - acc:7.1f} ms/chunk "
          f"({(per_chunk_ms - acc) / per_chunk_ms * 100:5.1f}%)")


if __name__ == "__main__":
    main()
