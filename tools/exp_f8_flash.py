"""Experiment: make the fp8 KV cache PAY in the flash kernel (VERDICT r4 #3).

BENCH_r04 showed the f8 cache as a 2.3x decode REGRESSION (42.1 vs
18.4 ms/token at 8k fill) even though the flash kernel upcasts per block
in-kernel — Mosaic's e4m3->bf16 `astype` on v5e (no native fp8) lowers to
slow element conversion. Candidates measured here, interleaved best-of-N
(tunnel jitter is +/-30%):

  a) bf16 cache — the baseline the f8 row must approach
  b) f8 cache, in-kernel astype (the shipped path)
  c) f8 cache read as uint8 bits, manual bf16 reassembly in integer lanes
     (sign<<8 | (mag<<4)+0x3C00, subnormal lane fixed via an f32 ladder)
  d) like (c) but subnormals flushed to zero (requires the WRITE side to
     flush |v| < 2^-6 — one extra where per cache write)

Result (v5e, 2026-07-31, B=1 KVH=32 S=8192 hs=128, fill 7680, t=1,
best of 6 interleaved, dispatch-amortized x32):
  bf16 3.715   astype-f8 4.447   bits-f8 3.686   bitsflush-f8 3.673 ms/call
  -> the manual bit reassembly is BIT-EXACT with astype and recovers the
  bf16 rate; astype costs +0.73 ms/call here, which matched the
  end-to-end regression per layer ((42.1-18.4)/32 = 0.74 ms). Flush-vs-
  exact-subnormal is noise — keep exact subnormals (no write-side
  contract change). A second end-to-end stall remained after promoting
  the in-kernel decode: an XLA-side whole-cache bitcast materialized a
  copy per step (f8 ratio 1.52x); moving the u8 reinterpret INSIDE the
  kernel (per block, in-register) fixed it. Final whole-model A/B at 7680
  fill: bf16 18.80 vs f8 18.88 ms/token — ratio 1.004, the r4 2.3x f8
  regression is gone (BENCH_r04 42.1 -> 18.9). Promoted into
  ops/pallas_attention.py (_f8_bits_to).
"""

import sys
import time

sys.path.insert(0, ".")

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _f8_bits_to_bf16(u8, flush_sub: bool):
    """e4m3fn bits (uint8) -> bf16 via f32-bit reassembly in 32-bit lanes
    (Mosaic v5e has no 16-bit vector shifts): normal numbers become
    sign<<31 | (exp+120)<<23 | mant<<20 bitcast to f32; subnormals take an
    int->float ladder (mag * 2^-9, exact in 3 mantissa bits); the final
    f32 -> bf16 convert is native."""
    i = u8.astype(jnp.int32)
    sign = (i & 0x80) << 24
    mag = i & 0x7F
    normal = (mag << 20) + (120 << 23)
    if flush_sub:
        bits = jnp.where(mag < 8, 0, normal) | sign
    else:
        sub = mag.astype(jnp.float32) * jnp.float32(2.0 ** -9)
        sub_bits = jax.lax.bitcast_convert_type(sub, jnp.int32)
        bits = jnp.where(mag < 8, sub_bits, normal) | sign
    return jax.lax.bitcast_convert_type(bits, jnp.float32).astype(
        jnp.bfloat16)


def _kernel(pos_ref, q_ref, k_ref, v_ref, out_ref, acc_ref, m_ref, l_ref,
            *, sb, n_sb, kvh, scale, mode):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    b = pl.program_id(0) // kvh
    pos = pos_ref[b]

    @pl.when(j * sb <= pos)
    def _accumulate():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        if mode == "astype":
            k = k.astype(q.dtype)
            v = v.astype(q.dtype)
        elif mode in ("bits", "bitsflush"):
            k = _f8_bits_to_bf16(k, mode == "bitsflush")
            v = _f8_bits_to_bf16(v, mode == "bitsflush")
        dot = functools.partial(jax.lax.dot_general,
                                preferred_element_type=jnp.float32)
        scores = dot(q, k, dimension_numbers=(((1,), (1,)), ((), ()))) * scale
        s_pos = j * sb + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(s_pos <= pos, scores, NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = dot(p.astype(v.dtype), v,
                 dimension_numbers=(((1,), (0,)), ((), ())))
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = m_new

    @pl.when(j == n_sb - 1)
    def _done():
        out_ref[0] = (acc_ref[:] / l_ref[:]).astype(jnp.bfloat16)


def build(mode, b, kvh, s, hs, sb=512):
    n_sb = s // sb

    @jax.jit
    def run(pos, q, k, v):
        return pl.pallas_call(
            functools.partial(_kernel, sb=sb, n_sb=n_sb, kvh=kvh,
                              scale=1.0 / (hs ** 0.5), mode=mode),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(b * kvh, n_sb),
                in_specs=[
                    pl.BlockSpec((1, 1, hs), lambda i, j, p: (i, 0, 0)),
                    pl.BlockSpec((1, sb, hs),
                                 lambda i, j, p: (i, jnp.minimum(
                                     j, p[i // kvh] // sb), 0)),
                    pl.BlockSpec((1, sb, hs),
                                 lambda i, j, p: (i, jnp.minimum(
                                     j, p[i // kvh] // sb), 0)),
                ],
                out_specs=pl.BlockSpec((1, 1, hs), lambda i, j, p: (i, 0, 0)),
                scratch_shapes=[
                    pltpu.VMEM((1, hs), jnp.float32),
                    pltpu.VMEM((1, 1), jnp.float32),
                    pltpu.VMEM((1, 1), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct((b * kvh, 1, hs), jnp.bfloat16),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary", "arbitrary")),
        )(pos, q, k, v)

    return run


def main():
    b, kvh, s, hs = 1, 32, 8192, 128
    fill = 7680
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b * kvh, 1, hs)), jnp.bfloat16)
    k_b = jnp.asarray(rng.standard_normal((b * kvh, s, hs)), jnp.bfloat16)
    v_b = jnp.asarray(rng.standard_normal((b * kvh, s, hs)), jnp.bfloat16)
    k_8 = k_b.astype(jnp.float8_e4m3fn)
    v_8 = v_b.astype(jnp.float8_e4m3fn)
    k_u = jax.lax.bitcast_convert_type(k_8, jnp.uint8)
    v_u = jax.lax.bitcast_convert_type(v_8, jnp.uint8)
    pos = jnp.asarray([fill], jnp.int32)

    variants = {
        "bf16": (build("plain", b, kvh, s, hs), (pos, q, k_b, v_b)),
        "astype-f8": (build("astype", b, kvh, s, hs), (pos, q, k_8, v_8)),
        "bits-f8": (build("bits", b, kvh, s, hs), (pos, q, k_u, v_u)),
        "bitsflush-f8": (build("bitsflush", b, kvh, s, hs), (pos, q, k_u, v_u)),
    }

    # numeric parity first: bits must equal astype exactly (same stored
    # values, exact upcast)
    outs = {n: np.asarray(fn(*a), np.float32) for n, (fn, a) in variants.items()}
    np.testing.assert_array_equal(outs["bits-f8"], outs["astype-f8"])
    print("bits == astype exact: ok")

    iters = 32
    best = {n: None for n in variants}
    for r in range(6):
        for n, (fn, a) in variants.items():
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(*a)
            np.asarray(out)  # D2H = the only true sync on tunneled TPU
            dt = (time.perf_counter() - t0) / iters * 1e3
            best[n] = dt if best[n] is None else min(best[n], dt)
    for n, v in best.items():
        print(f"{n:14s} {v:.3f} ms/call")


if __name__ == "__main__":
    main()
