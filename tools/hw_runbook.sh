#!/bin/sh
# Hardware runbook: everything to run the moment the TPU tunnel is back.
# The round-3/4 tunnel outages repeatedly ate the measurement window, so
# the sequence is ordered by information-per-chip-minute:
#   1. bounded liveness probe (never hang the shell)
#   2. tools/exp_unpack_overlap.py — the known ~40%-MFU prefill headroom
#      experiment (minutes; interleaved best-of-N inside one process)
#   3. full default bench — 7B decode + prefill + 8k bf16/f8 + lookup +
#      MoE rows (~95 min; each row flushes to stderr as it is measured,
#      so a mid-run outage keeps completed rows)
# Artifacts land in tools/artifacts/ for the README/BENCH refresh.
set -e
cd "$(dirname "$0")/.."
mkdir -p tools/artifacts

echo "== probe (120s bound) =="
if ! timeout 120 python -c "import jax; print(jax.devices())"; then
    echo "TPU backend unavailable — rerun when the tunnel is back" >&2
    exit 1
fi

echo "== unpack/MXU overlap experiment =="
# NOTE: do NOT override PYTHONPATH here — the TPU plugin registers via the
# environment's existing PYTHONPATH (/root/.axon_site), and the script
# sys.path-inserts the repo root itself. Three legs: FFN w1/w3 shape
# (td=256 8-way), attention-projection shape (td=1024, stays whole-tile),
# w2 shape (m=5504, the n_sub=2 VMEM-bound regime).
timeout 1800 python tools/exp_unpack_overlap.py \
    2>&1 | tee tools/artifacts/overlap_$(date +%H%M).txt
EXP_D=4096 timeout 1800 python tools/exp_unpack_overlap.py \
    2>&1 | tee tools/artifacts/overlap_attn_$(date +%H%M).txt
EXP_D=4096 EXP_N=11008 timeout 1800 python tools/exp_unpack_overlap.py \
    2>&1 | tee tools/artifacts/overlap_w2_$(date +%H%M).txt

echo "== full default bench =="
timeout 10800 python bench.py \
    2> tools/artifacts/bench_rows_$(date +%H%M).jsonl \
    | tee tools/artifacts/bench_$(date +%H%M).json
