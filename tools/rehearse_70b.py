"""70B dress rehearsal without 16 chips (VERDICT r4 #4).

The reference actually ran Llama-2-70B across socket clusters
(ref: README.md:78,90; src/transformer.cpp:607-683 streams each worker its
shard). The repo's 70B claim was a README projection; this tool turns it
into artifacts:

1. writes a REAL 70B-width `.m` (dim 8192, hidden 28672, 64 heads, 8 kv
   heads, vocab 32000 — Llama-2-70B's exact widths), layer-truncated to
   N_LAYERS=4 for disk (~3.1 GB; full depth is the same bytes x 20),
   with valid random Q40 blocks streamed straight to disk;
2. stream-loads it at tp=16 AND tp=8 x pp=2 on a 16-virtual-device CPU
   mesh (load_params_streamed: per-device placement, kv-head replication
   at tp=16 > 8 kv heads, bounded host memory — the peak is asserted
   far below the file size);
3. AOT-lowers the decode step per mesh, counts the collective ops in the
   optimized HLO, executes real greedy steps, and cross-checks the two
   meshes emit IDENTICAL tokens (same file, same math, different
   partitioning);
4. records per-device parameter bytes and extrapolates to full 80-layer
   depth against the README's 2.42 GB/chip budget.

Writes tools/artifacts/MULTICHIP_70B.json. Each mesh config runs in a
subprocess (the virtual device count can only be set once per process).

Usage: python tools/rehearse_70b.py [--keep-file]
"""

import json
import os
import re
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N_LAYERS = 4
FULL_LAYERS = 80
MODEL_PATH = "/tmp/llama70b_width_4l.m"
OUT_PATH = os.path.join(os.path.dirname(__file__), "artifacts",
                        "MULTICHIP_70B.json")


def spec70():
    from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
    from distributed_llama_tpu.quants.types import FloatType

    return ModelSpec(arch=ArchType.LLAMA, dim=8192, hidden_dim=28672,
                     n_layers=N_LAYERS, n_heads=64, n_kv_heads=8,
                     vocab_size=32000, seq_len=2048,
                     hidden_act=HiddenAct.SILU, rope_theta=10000.0,
                     weights_float_type=FloatType.Q40)


def write_file(path: str) -> int:
    """Stream random-but-valid tensors in exact plan order: Q40 blocks get
    f16 scales in [0.005, 0.02] + uniform nibble bytes; f32 tensors small
    gaussians (norm weights near 1). Returns total bytes."""
    import numpy as np

    from distributed_llama_tpu.io.model_file import (model_tensor_plan,
                                                     write_header)
    from distributed_llama_tpu.quants.types import (FloatType,
                                                    Q40_BLOCK_BYTES,
                                                    BLOCK_SIZE, batch_bytes)

    spec = spec70()
    rng = np.random.default_rng(70)
    t0 = time.time()
    with open(path, "wb") as f:
        write_header(f, spec)
        for name, shape, ftype in model_tensor_plan(spec):
            n = shape[-1]
            d = int(np.prod(shape[:-1])) if len(shape) > 1 else 1
            if ftype == FloatType.F32:
                if name.startswith(("rms", "layers")) and "rms" in name:
                    x = 1.0 + rng.standard_normal(d * n, dtype=np.float32) * 0.02
                else:
                    x = rng.standard_normal(d * n, dtype=np.float32) * 0.02
                f.write(x.astype(np.float32).tobytes())
            elif ftype == FloatType.Q40:
                nb = (n // BLOCK_SIZE) * d
                raw = np.empty((nb, Q40_BLOCK_BYTES), np.uint8)
                scales = rng.uniform(0.005, 0.02, nb).astype(np.float16)
                raw[:, :2] = scales.reshape(nb, 1).view(np.uint8)
                raw[:, 2:] = rng.integers(0, 256, (nb, Q40_BLOCK_BYTES - 2),
                                          dtype=np.uint8)
                f.write(raw.tobytes())
            else:
                raise AssertionError(ftype)
    size = os.path.getsize(path)
    print(f"wrote {path}: {size / 1e9:.2f} GB in {time.time() - t0:.0f}s")
    return size


def run_config(cfg: str) -> None:
    """Subprocess body: load + lower + step + account for one mesh."""
    # BEFORE importing jax: 16 virtual CPU devices via the shared
    # XLA_FLAGS bootstrap (utils/virtual_mesh.py) — the
    # jax_num_cpu_devices config option does not exist on the 0.4.x
    # jaxlib this image pins, and XLA parses the flag once per process
    from distributed_llama_tpu.utils.virtual_mesh import \
        ensure_virtual_cpu_devices

    ensure_virtual_cpu_devices(16)
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 16)
    except AttributeError:  # jax 0.4.x: the XLA_FLAGS path above rules
        pass
    assert jax.device_count() == 16, jax.devices()
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_llama_tpu.models.loader import load_params_streamed
    from distributed_llama_tpu.parallel.mesh import make_mesh
    from distributed_llama_tpu.runtime import Engine
    from distributed_llama_tpu.sampler import Sampler

    axes = dict(kv.split("=") for kv in cfg.split(","))
    mesh = make_mesh(**{k: int(v) for k, v in axes.items()})
    spec = spec70()

    t0 = time.time()
    params, stats = load_params_streamed(
        spec, MODEL_PATH, mesh, mode="q40", dtype=jnp.bfloat16)
    load_s = time.time() - t0
    total = os.path.getsize(MODEL_PATH)
    # the streamed-load contract: host residency is bounded by the largest
    # single tensor/fusion group (here tok_emb f32, 1.05 GB), never the
    # file — at the full 80-layer depth (~48 GB) the same bound holds
    biggest = spec.vocab_size * spec.dim * 4 + (1 << 20)
    assert stats.peak_host_bytes <= biggest * 2, (
        stats.peak_host_bytes, biggest)

    # per-device parameter bytes (packed Q40 + scales + dense leaves),
    # split into layer weights (scale with depth) and the rest (tok_emb is
    # REPLICATED per device — the honest full-depth number must carry it)
    def per_device(tree) -> int:
        acc: dict[int, int] = {}
        for leaf in jax.tree.leaves(tree):
            for sh in leaf.addressable_shards:
                acc[sh.device.id] = (acc.get(sh.device.id, 0)
                                     + sh.data.size * sh.data.dtype.itemsize)
        return max(acc.values())

    dev_layer_bytes = per_device(params["layers"])
    # vocab sharding (ops/sharded_vocab.py, ISSUE-15): tok_emb/wcls are
    # row-split at LOAD over the mesh's vocab axes — the 533 MB/chip
    # replicated table (VERDICT weak #3) becomes vocab/S per chip. The
    # split is reported separately so the artifact shows the freed bytes.
    dev_vocab_bytes = per_device(
        {k: v for k, v in params.items() if k in ("tok_emb", "wcls")})
    dev_other_bytes = per_device(
        {k: v for k, v in params.items()
         if k not in ("layers", "tok_emb", "wcls")})
    dev_bytes = dev_layer_bytes + dev_other_bytes + dev_vocab_bytes

    eng = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, max_seq_len=256)

    # AOT-lower the decode step, count collectives in the optimized HLO,
    # then EXECUTE through the same compiled object (the 70B-width CPU
    # compile is minutes; one compile serves both purposes)
    eng.reset()
    step_fn = eng._compiled_step(1)  # key 1 = the 1-token decode step
    # the compile ledger (runtime/profiler.py) wraps fresh mints in a
    # first-call watch with no .lower — AOT-lower the raw jitted callable
    step_fn = getattr(step_fn, "_fn", step_fn)
    print(f"[{cfg}] loaded in {load_s:.0f}s; lowering decode...",
          flush=True)
    t0 = time.time()
    tok = np.zeros((1, 1), np.int32)
    compiled = step_fn.lower(eng.params, jnp.asarray(tok), jnp.int32(3),
                             eng.cache).compile()
    hlo = compiled.as_text()
    compile_s = time.time() - t0
    colls = {}
    for kind in ("all-reduce", "reduce-scatter", "all-gather", "all-to-all",
                 "collective-permute"):
        colls[kind] = len(re.findall(rf"= \S+ {kind}(?:-start)?\(", hlo))

    # real greedy steps off the compiled executable — the two configs must
    # agree token-for-token (same file, different partitioning)
    print(f"[{cfg}] compiled in {compile_s:.0f}s; stepping...", flush=True)
    t0 = time.time()
    logits = eng.prefill([1, 2, 3])
    toks = [int(np.argmax(eng.fetch_logits(logits)[0]))]
    for _ in range(3):
        logits, new_cache = compiled(
            eng.params, jnp.asarray([[toks[-1]]], jnp.int32),
            jnp.int32(eng.pos), eng.cache)
        eng.cache = new_cache
        eng.pos += 1
        toks.append(int(np.argmax(eng.fetch_logits(logits)[0])))
    step_s = time.time() - t0

    # full-depth extrapolation: layer bytes scale 80/4; the vocab shards
    # and norms stay as-is (tok_emb used to be replicated at 524 MB/chip
    # — now vocab/S, included honestly either way)
    dev_full = (dev_other_bytes + dev_vocab_bytes
                + dev_layer_bytes * (FULL_LAYERS // N_LAYERS))

    out = {
        "config": cfg,
        "mesh_devices": int(mesh.size),
        "decode_compile_seconds": round(compile_s, 1),
        "file_gb": round(total / 1e9, 3),
        "load_seconds": round(load_s, 1),
        "peak_host_mb_during_load": round(stats.peak_host_bytes / 1e6, 1),
        "per_device_param_mb": round(dev_bytes / 1e6, 1),
        "per_device_layer_mb": round(dev_layer_bytes / 1e6, 1),
        "per_device_vocab_mb": round(dev_vocab_bytes / 1e6, 1),
        "per_device_replicated_mb": round(dev_other_bytes / 1e6, 1),
        "shard_vocab": bool(eng.shard_vocab),
        "vocab_axes": list(getattr(eng, "_vocab_axes", ()) or ()),
        "per_device_param_gb_extrapolated_80_layers":
            round(dev_full / 1e9, 3),
        "readme_budget_gb_per_chip": 2.42,
        "budget_met_80_layers": bool(dev_full <= 2.42e9),
        "collectives_decode_step": colls,
        "greedy_tokens": toks,
        "four_token_wall_seconds": round(step_s, 1),
    }
    print("RESULT " + json.dumps(out))
    with open(f"/tmp/r70b_{cfg.replace(',', '_').replace('=', '')}.json",
              "w") as f:
        json.dump(out, f)


def main():
    if "--config" in sys.argv:
        run_config(sys.argv[sys.argv.index("--config") + 1])
        return

    if not os.path.exists(MODEL_PATH):
        write_file(MODEL_PATH)
    results = []
    for cfg in ("tp=16", "tp=8,pp=2"):
        part = f"/tmp/r70b_{cfg.replace(',', '_').replace('=', '')}.json"
        if os.path.exists(part):  # a prior (interrupted) run finished this
            with open(part) as f:
                results.append(json.load(f))
            print(f"--- {cfg}: reusing {part}")
            continue
        print(f"--- {cfg}")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # run_config pins cpu in-process
        # a preset device-count flag (an 8-device test env) would beat
        # run_config's 16-device bootstrap — strip it
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        env["XLA_FLAGS"] = " ".join(flags)
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--config", cfg],
            text=True, env=env, timeout=3600,
            cwd=os.path.join(os.path.dirname(__file__), ".."))
        if r.returncode != 0:
            sys.exit(f"config {cfg} failed rc={r.returncode}")
        with open(part) as f:
            results.append(json.load(f))

    # cross-mesh parity: same file, same math, different partitioning
    assert results[0]["greedy_tokens"] == results[1]["greedy_tokens"], results
    artifact = {
        "model_widths": "llama2-70b (dim 8192, hidden 28672, 64h/8kv)",
        "n_layers_on_disk": N_LAYERS,
        "full_depth": FULL_LAYERS,
        "cross_mesh_greedy_match": True,
        "configs": results,
    }
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {OUT_PATH}")
    if "--keep-file" not in sys.argv:
        os.remove(MODEL_PATH)


if __name__ == "__main__":
    main()
