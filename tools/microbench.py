"""Component microbenchmarks: achievable GEMV bandwidth, attention cost,
cache-update cost — isolates where decode time goes.

The axon tunnel adds ~90 ms of dispatch latency per jit call, so each
benchmark runs its body R times inside one jit (outer lax.scan with a
feedback dependency) at two values of R; the slope (t2-t1)/(R2-R1) is the
true per-iteration time, free of the constant.

Usage: python tools/microbench.py [all|gemv|gemv_q40|gemv_pallas|attn|cache]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

from distributed_llama_tpu.quants.jax_codec import QuantizedTensor, dequantize_q40_jax
from distributed_llama_tpu.ops.attention import decode_attention

L, D, H = 32, 4096, 11008
SEQ, KVH, HS = 2048, 32, 128
R1, R2 = 4, 32  # wide spread: tunnel jitter ~1-2 ms swamps small slopes


def slope_time(make_run, *args):
    """make_run(reps) -> jitted fn; returns per-rep seconds via slope."""
    times = {}
    for reps in (R1, R2):
        fn = make_run(reps)
        out = fn(*args)
        np.asarray(jax.tree.leaves(out)[0])  # warm/compile
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn(*args)
            np.asarray(jax.tree.leaves(out)[0])
            best = min(best, time.perf_counter() - t0)
        times[reps] = best
    return (times[R2] - times[R1]) / (R2 - R1)


def _outer(body_scan, reps):
    """Repeat body_scan(x, w) -> x' reps times with feedback."""
    def run(w, x):
        def rep(x, _):
            return body_scan(x, w), None
        x, _ = jax.lax.scan(rep, x, None, length=reps)
        return x
    return jax.jit(run)


def bench_gemv_dense():
    w = jnp.zeros((L, H, D), jnp.bfloat16)
    x = jnp.ones((1, D), jnp.bfloat16)

    def body(x, w):
        def layer(x, wl):
            y = jnp.einsum("bn,dn->bd", x, wl, preferred_element_type=jnp.bfloat16)
            return x + y[:, :D] * jnp.bfloat16(1e-6), None
        x, _ = jax.lax.scan(layer, x, w)
        return x

    dt = slope_time(lambda r: _outer(body, r), w, x)
    gb = L * H * D * 2 / 1e9
    print(f"gemv dense bf16: {dt*1e3:.3f} ms/pass for {gb:.2f} GB -> {gb/dt:.0f} GB/s")


def _q40(shape_d, shape_n, layers=L, seed=0):
    rng = np.random.default_rng(seed)
    nb = shape_n // 32
    packed = rng.integers(0, 256, (layers, shape_d, 16 * nb), dtype=np.uint8)
    scales = (rng.random((layers, shape_d, nb), dtype=np.float32) * 0.004)
    return QuantizedTensor(jnp.asarray(packed), jnp.asarray(scales))


def bench_gemv_q40():
    w = _q40(H, D)
    x = jnp.ones((1, D), jnp.bfloat16)

    def body(x, w):
        def layer(x, wl):
            wd = dequantize_q40_jax(wl, jnp.bfloat16)
            y = jnp.einsum("bn,dn->bd", x, wd, preferred_element_type=jnp.bfloat16)
            return x + y[:, :D] * jnp.bfloat16(1e-6), None
        x, _ = jax.lax.scan(layer, x, w)
        return x

    dt = slope_time(lambda r: _outer(body, r), w, x)
    gb = (w.packed.size + w.scales.size * 2) / 1e9
    print(f"gemv q40 xla: {dt*1e3:.3f} ms/pass for {gb:.2f} GB packed -> {gb/dt:.0f} GB/s")


def bench_gemv_pallas():
    from distributed_llama_tpu.ops.pallas_q40 import q40_matmul

    w = _q40(H, D)
    x = jnp.ones((1, D), jnp.bfloat16)

    def body(x, w):
        def layer(x, wl):
            y = q40_matmul(x, wl, out_dtype=jnp.bfloat16)
            return x + y[:, :D] * jnp.bfloat16(1e-6), None
        x, _ = jax.lax.scan(layer, x, w)
        return x

    dt = slope_time(lambda r: _outer(body, r), w, x)
    gb = (w.packed.size + w.scales.size * 2) / 1e9
    print(f"gemv q40 pallas: {dt*1e3:.3f} ms/pass for {gb:.2f} GB packed -> {gb/dt:.0f} GB/s")


def bench_attn():
    # head-major cache layout (B, KVH, S, hs) — models/transformer.KVCache
    k = jnp.zeros((L, 1, KVH, SEQ, HS), jnp.bfloat16)
    v = jnp.zeros((L, 1, KVH, SEQ, HS), jnp.bfloat16)
    q0 = jnp.ones((1, 1, KVH, HS), jnp.bfloat16)
    pos = jnp.full((1, 1), SEQ - 1, jnp.int32)

    def body(q, kv):
        def layer(q, kvl):
            kl, vl = kvl
            att = decode_attention(q, kl, vl, pos)
            return q + att * jnp.bfloat16(1e-6), None
        q, _ = jax.lax.scan(layer, q, kv)
        return q

    dt = slope_time(lambda r: _outer(body, r), (k, v), q0)
    gb = (k.size + v.size) * 2 / 1e9
    print(f"attention (seq={SEQ}): {dt*1e3:.3f} ms/pass for {gb:.2f} GB cache -> {gb/dt:.0f} GB/s")


def bench_cache():
    k = jnp.zeros((L, 1, KVH, SEQ, HS), jnp.bfloat16)
    new0 = jnp.ones((1, 1, KVH, HS), jnp.bfloat16)

    def body(new, k):
        def layer(new, kl):
            kl = jax.lax.dynamic_update_slice(
                kl, new.transpose(0, 2, 1, 3), (0, 0, SEQ - 1, 0))
            return new + kl[:, :, -1] * jnp.bfloat16(1e-6), kl
        new, k2 = jax.lax.scan(layer, new, k)
        return new

    dt = slope_time(lambda r: _outer(body, r), k, new0)
    gb = k.size * 2 / 1e9
    print(f"cache update scan: {dt*1e3:.3f} ms/pass ({gb:.2f} GB buffer)")


ALL = {
    "gemv": bench_gemv_dense,
    "gemv_q40": bench_gemv_q40,
    "gemv_pallas": bench_gemv_pallas,
    "attn": bench_attn,
    "cache": bench_cache,
}

if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    for name, fn in ALL.items():
        if which in ("all", name):
            fn()
