"""Experiment: f16-bit scales (decoded in-kernel via integer ops) vs the
current f32 scales — tests whether the kernel is HBM-bound enough that the
~10% scale-traffic cut wins over the extra ~0.5 VPU ops/byte.

Usage: python tools/exp_scale_f16.py
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")

from distributed_llama_tpu.ops.pallas_q40 import (
    _f16_bits_to_f32,   # the SHIPPED decode — this tool measures that kernel
    q40_matmul,
    _split_activation,
    _tile_d,
)
from distributed_llama_tpu.quants.jax_codec import QuantizedTensor

L, T = 32, 1
D_OUT, D_IN = 11008 * 2, 4096  # w13-sized


def _kernel_u16(x_lo_ref, x_hi_ref, xsum_ref, packed_ref, scales_ref, out_ref,
                *, nb, out_dtype):
    pk = packed_ref[:].astype(jnp.int32)
    lo = (pk & 0xF).astype(jnp.float32)
    hi = (pk >> 4).astype(jnp.float32)
    s = _f16_bits_to_f32(scales_ref[:].astype(jnp.int32))
    s16 = pltpu.repeat(s, 16, axis=1)
    dot = functools.partial(
        jax.lax.dot_general, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)
    acc = dot(x_lo_ref[:], lo * s16)
    acc += dot(x_hi_ref[:], hi * s16)
    acc += dot(xsum_ref[:], s) * -8.0
    out_ref[:] = acc.astype(out_dtype)


@functools.partial(jax.jit, static_argnames=())
def q40_matmul_u16(x, packed, scales_u16):
    d, m = packed.shape
    nb = m // 16
    n = nb * 32
    t = x.shape[0]
    x_lo, x_hi = _split_activation(x.reshape(t, n).astype(jnp.float32), nb)
    xsum = (x_lo + x_hi).reshape(t, 16, nb).sum(axis=1)
    td = _tile_d(d, m)
    return pl.pallas_call(
        functools.partial(_kernel_u16, nb=nb, out_dtype=jnp.float32),
        grid=(d // td,),
        in_specs=[
            pl.BlockSpec((t, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((t, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((t, nb), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((td, m), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((td, nb), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((t, td), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
    )(x_lo, x_hi, xsum, packed, scales_u16)


def bench(fn, args, reps=64) -> float:
    @jax.jit
    def run(x, *rest):
        def body(c, _):
            o = fn(c, *rest)
            return c + o[:, :D_IN] * 1e-9, o  # feedback dep
        c, o = jax.lax.scan(body, x, None, length=reps)
        return c
    out = run(*args)
    np.asarray(out)
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(run(*args))
        best = min(best, time.perf_counter() - t0)
    return best / reps


def main():
    rng = np.random.default_rng(0)
    layers = []
    for _ in range(L):
        packed = rng.integers(0, 256, (D_OUT, 16 * (D_IN // 32)), dtype=np.uint8)
        sc = (rng.random((D_OUT, D_IN // 32), dtype=np.float32) * 0.004 + 0.001)
        layers.append((jnp.asarray(packed),
                       jnp.asarray(sc),
                       jnp.asarray(sc.astype(np.float16).view(np.uint16))))
    x = jnp.asarray(rng.standard_normal((T, D_IN), dtype=np.float32))

    packed_b = sum(l[0].nbytes for l in layers)
    f32_b = packed_b + sum(l[1].nbytes for l in layers)
    u16_b = packed_b + sum(l[2].nbytes for l in layers)

    def run_f32(x):
        o = None
        for p, s, _ in layers:
            o = q40_matmul(x, QuantizedTensor(p, s))
        return o

    def run_u16(x):
        o = None
        for p, _, su in layers:
            o = q40_matmul_u16(x, p, su)
        return o

    # correctness first
    a = np.asarray(q40_matmul(x, QuantizedTensor(layers[0][0], layers[0][1])))
    b = np.asarray(q40_matmul_u16(x, layers[0][0],
                                  jnp.asarray(np.asarray(layers[0][1]).astype(np.float16).view(np.uint16))))
    err = np.abs(a - b).max() / max(np.abs(a).max(), 1e-9)
    print(f"rel err u16 vs f32 scales: {err:.2e}")

    t_f32 = bench(lambda x: run_f32(x), (x,), reps=16)
    t_u16 = bench(lambda x: run_u16(x), (x,), reps=16)
    print(f"f32 scales: {t_f32*1e3:7.3f} ms  ({f32_b/t_f32/1e9:6.1f} GB/s total)")
    print(f"u16 scales: {t_u16*1e3:7.3f} ms  ({u16_b/t_u16/1e9:6.1f} GB/s total)")
    print(f"speedup: {t_f32/t_u16:.3f}x")


if __name__ == "__main__":
    main()
