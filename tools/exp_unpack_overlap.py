"""Experiment: sub-tile unpack/MXU interleave for PREFILL chunks.

Hypothesis: whole-model prefill sits at ~40% MFU because the fused Q40
kernel's nibble unpack (VPU) and its MXU contraction serialize within each
grid step (ops/pallas_q40.py docstring). Splitting the output tile into
n_sub sub-tiles and issuing each sub-tile's dot right after its unpack
could let the MXU queue chew on sub-tile i while the VPU unpacks i+1 —
IF Mosaic's scheduler lets the data-independent VPU work run ahead of an
issued matmul.

STATUS: NOT YET MEASURED — the tunneled TPU backend went unavailable when
this was queued (end of round 3). Run when a chip is free:

    PYTHONPATH=/root/repo python tools/exp_unpack_overlap.py

Expected decision rule: if any (td, n_sub) beats the current kernel by
>10% at t=256, thread an n_sub parameter through pallas_q40._kernel for
the mxu_bf16 (prefill) mode only; decode (t=1) stays VPU-bound and cannot
benefit.
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, ".")

from distributed_llama_tpu.ops import pallas_q40 as q  # noqa: E402
from distributed_llama_tpu.quants.jax_codec import QuantizedTensor  # noqa: E402

D, N, T = 11008, 4096, 256
NB = N // 32
M = 16 * NB


def matmul_sub(x, w, n_sub, td):
    """Like q40_matmul's bf16-MXU mode, but unpack+dot per sub-tile."""
    from jax.experimental.pallas import tpu as pltpu

    def kern(x_lo_ref, x_hi_ref, xsum_ref, packed_ref, scales_ref, out_ref):
        dot = functools.partial(
            jax.lax.dot_general,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        x_lo = x_lo_ref[:].astype(jnp.bfloat16)
        x_hi = x_hi_ref[:].astype(jnp.bfloat16)
        xs = xsum_ref[:]
        h = td // n_sub
        for i in range(n_sub):
            pk = packed_ref[i * h:(i + 1) * h, :].astype(jnp.int32)
            lo = (pk & 0xF).astype(jnp.float32)
            hi = (pk >> 4).astype(jnp.float32)
            s = q._f16_bits_to_f32(
                scales_ref[i * h:(i + 1) * h, :].astype(jnp.int32))
            s16 = pltpu.repeat(s, 16, axis=1)
            wl = (lo * s16).astype(jnp.bfloat16)
            wh = (hi * s16).astype(jnp.bfloat16)
            acc = dot(x_lo, wl)
            acc += dot(x_hi, wh)
            acc += dot(xs, s) * -8.0
            out_ref[:, i * h:(i + 1) * h] = acc.astype(jnp.bfloat16)

    t = x.shape[0]
    x_lo, x_hi = q._split_activation(x.astype(jnp.float32), NB)
    xsum = (x_lo + x_hi).reshape(t, 16, NB).sum(axis=1)
    return pl.pallas_call(
        kern, grid=(D // td,),
        in_specs=[
            pl.BlockSpec((t, M), lambda i: (0, 0)),
            pl.BlockSpec((t, M), lambda i: (0, 0)),
            pl.BlockSpec((t, NB), lambda i: (0, 0)),
            pl.BlockSpec((td, M), lambda i: (i, 0)),
            pl.BlockSpec((td, NB), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((t, td), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((t, D), jnp.bfloat16),
        cost_estimate=pl.CostEstimate(flops=2 * t * D * N,
                                      bytes_accessed=D * M,
                                      transcendentals=0),
    )(x_lo, x_hi, xsum, w.packed, w.scales)


def main():
    rng = np.random.default_rng(0)
    packed = jnp.asarray(rng.integers(0, 256, (D, M), dtype=np.uint8))
    scales = jnp.asarray((rng.random((D, NB), dtype=np.float32) * 0.004
                          ).astype(np.float16).view(np.uint16))
    w = QuantizedTensor(packed, scales)
    x = jnp.asarray(rng.standard_normal((T, N), dtype=np.float32))

    def chain(fn):
        @jax.jit
        def run(x):
            y = x
            for _ in range(8):
                o = fn(y)
                y = (o[:, :N] * 1e-3).astype(jnp.float32)
            return y
        return run

    fl = 2 * T * D * N
    variants = [("current", lambda v: q.q40_matmul(v, w, out_dtype=jnp.bfloat16))]
    # tile sizes must divide D = 11008 = 2^8 * 43 exactly — a flooring
    # grid would silently skip rows and bias the comparison (td=512 would
    # cover only 97.7% of the output) — and both the tile and its
    # sub-slices must stay 32-row aligned (the uint8 sublane tile)
    combos = ((128, 2), (128, 4), (256, 2), (256, 4), (256, 8), (2752, 2))
    assert all(D % td == 0 and td % 32 == 0 and (td // ns) % 32 == 0
               for td, ns in combos), combos
    variants += [(f"td={td} n_sub={ns}",
                  lambda v, td=td, ns=ns: matmul_sub(v, w, ns, td))
                 for td, ns in combos]
    # the tunneled platform's run-to-run jitter is ±30%: variants are only
    # comparable INTERLEAVED in one process, best-of-N each (the repo's
    # A/B measurement discipline)
    runs = [(name, chain(fn)) for name, fn in variants]
    best: dict = {}
    for name, run in runs:
        np.asarray(run(x))  # compile
    for _ in range(4):
        for name, run in runs:
            t0 = time.perf_counter()
            np.asarray(run(x))
            dt = (time.perf_counter() - t0) / 8
            best[name] = min(best.get(name, dt), dt)
    base = best["current"]
    for name, _ in runs:
        dt = best[name]
        rel = base / dt
        print(f"{name}: {dt*1e3:.3f} ms/call, {fl/dt/1e12:.1f} TFLOP/s, "
              f"{rel:.2f}x vs current")
    winner = min(best, key=best.get)
    if winner != "current" and base / best[winner] > 1.10:
        print(f"DECISION: {winner} beats current by >10% — thread n_sub "
              "through pallas_q40._kernel's mxu_bf16 mode")
    else:
        print("DECISION: no variant beats current by >10% — record the "
              "negative result in ops/pallas_q40.py and keep the kernel")


if __name__ == "__main__":
    main()
