"""Experiment: sub-tile unpack/MXU interleave for PREFILL chunks.

Hypothesis: whole-model prefill sits at ~40% MFU because the fused Q40
kernel's nibble unpack (VPU) and its MXU contraction serialize within each
grid step (ops/pallas_q40.py docstring). Splitting the output tile into
n_sub sub-tiles and issuing each sub-tile's dot right after its unpack
could let the MXU queue chew on sub-tile i while the VPU unpacks i+1 —
IF Mosaic's scheduler lets the data-independent VPU work run ahead of an
issued matmul.

STATUS: MEASURED (round 4, v5e). FFN shape D=11008 (td=256):
    current          36.10 ms/call   1.00x
    td=128 n_sub=2   27.48           1.31x
    td=128 n_sub=4   37.95           0.95x
    td=256 n_sub=2   26.36           1.37x
    td=256 n_sub=4   26.14           1.38x
    td=256 n_sub=8   25.60           1.41x   <- WINNER, threaded through
Attention-projection shape EXP_D=4096 (td=1024): every sub-tile variant
flat or worse (0.89-0.98x), so _n_sub in ops/pallas_q40.py sub-tiles ONLY
the td=256 tile. (ms/call includes the tunnel's ~17 ms amortized dispatch;
the kernel-only delta is larger than 1.41x.) Run with:

    cd /root/repo && python tools/exp_unpack_overlap.py          # D=11008
    EXP_D=4096 python tools/exp_unpack_overlap.py                # td=1024
(do NOT override PYTHONPATH — the TPU plugin registers through it)
"""

from __future__ import annotations

import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, ".")

from distributed_llama_tpu.ops import pallas_q40 as q  # noqa: E402
from distributed_llama_tpu.quants.jax_codec import QuantizedTensor  # noqa: E402

# EXP_D=4096 covers the attention-projection shape whose _tile_d pick is
# td=1024 (the FFN shape D=11008 can only tile at 128/256); EXP_D=4096 +
# EXP_N=11008 covers the w2 shape (m=5504, the n_sub=2 VMEM-bound regime)
D = int(os.environ.get("EXP_D", "11008"))
N = int(os.environ.get("EXP_N", "4096"))
T = 256
NB = N // 32
M = 16 * NB


def matmul_sub(x, w, n_sub, td):
    """Like q40_matmul's bf16-MXU mode, but unpack+dot per sub-tile."""
    from jax.experimental.pallas import tpu as pltpu

    def kern(x_lo_ref, x_hi_ref, xsum_ref, packed_ref, scales_ref, out_ref):
        dot = functools.partial(
            jax.lax.dot_general,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        x_lo = x_lo_ref[:].astype(jnp.bfloat16)
        x_hi = x_hi_ref[:].astype(jnp.bfloat16)
        xs = xsum_ref[:]
        h = td // n_sub
        for i in range(n_sub):
            pk = packed_ref[i * h:(i + 1) * h, :].astype(jnp.int32)
            lo = (pk & 0xF).astype(jnp.float32)
            hi = (pk >> 4).astype(jnp.float32)
            s = q._f16_bits_to_f32(
                scales_ref[i * h:(i + 1) * h, :].astype(jnp.int32))
            s16 = pltpu.repeat(s, 16, axis=1)
            wl = (lo * s16).astype(jnp.bfloat16)
            wh = (hi * s16).astype(jnp.bfloat16)
            acc = dot(x_lo, wl)
            acc += dot(x_hi, wh)
            acc += dot(xs, s) * -8.0
            out_ref[:, i * h:(i + 1) * h] = acc.astype(jnp.bfloat16)

    t = x.shape[0]
    x_lo, x_hi = q._split_activation(x.astype(jnp.float32), NB)
    xsum = (x_lo + x_hi).reshape(t, 16, NB).sum(axis=1)
    return pl.pallas_call(
        kern, grid=(D // td,),
        in_specs=[
            pl.BlockSpec((t, M), lambda i: (0, 0)),
            pl.BlockSpec((t, M), lambda i: (0, 0)),
            pl.BlockSpec((t, NB), lambda i: (0, 0)),
            pl.BlockSpec((td, M), lambda i: (i, 0)),
            pl.BlockSpec((td, NB), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((t, td), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((t, D), jnp.bfloat16),
        cost_estimate=pl.CostEstimate(flops=2 * t * D * N,
                                      bytes_accessed=D * M,
                                      transcendentals=0),
    )(x_lo, x_hi, xsum, w.packed, w.scales)


def main():
    rng = np.random.default_rng(0)
    packed = jnp.asarray(rng.integers(0, 256, (D, M), dtype=np.uint8))
    scales = jnp.asarray((rng.random((D, NB), dtype=np.float32) * 0.004
                          ).astype(np.float16).view(np.uint16))
    w = QuantizedTensor(packed, scales)
    x = jnp.asarray(rng.standard_normal((T, N), dtype=np.float32))

    def chain(fn):
        @jax.jit
        def run(x):
            y = x
            for _ in range(8):
                o = fn(y)
                y = (o[:, :N] * 1e-3).astype(jnp.float32)
            return y
        return run

    fl = 2 * T * D * N

    def whole_tile(v):
        # the engine kernel sub-tiles since round 4 — pin the baseline to
        # n_sub=1 so this experiment keeps measuring landed-vs-whole-tile
        orig = q._n_sub
        q._n_sub = lambda td, m, mxu: 1
        try:
            q.q40_matmul.clear_cache()
            return q.q40_matmul(v, w, out_dtype=jnp.bfloat16)
        finally:
            q._n_sub = orig

    def landed(v):
        # clear q40_matmul's inner jit cache at trace time so this variant
        # cannot reuse the whole-tile trace cached by the baseline above
        q.q40_matmul.clear_cache()
        return q.q40_matmul(v, w, out_dtype=jnp.bfloat16)

    variants = [("whole-tile", whole_tile), ("landed", landed)]
    # tile sizes must divide D = 11008 = 2^8 * 43 exactly — a flooring
    # grid would silently skip rows and bias the comparison (td=512 would
    # cover only 97.7% of the output) — and both the tile and its
    # sub-slices must stay 32-row aligned (the uint8 sublane tile)
    if D == 11008:
        combos = ((128, 2), (128, 4), (256, 2), (256, 4), (256, 8))
    elif N > 4096:  # w2 shape: m > 4096 bytes/row — n_sub=8 OOMs scoped VMEM
        combos = ((256, 2), (256, 4))
    else:  # D=4096: the engine's _tile_d picks 1024 here
        combos = ((256, 8), (512, 8), (1024, 2), (1024, 4), (1024, 8))
    # ... and the OUTPUT block's last dim (td) must itself be 128-aligned:
    # D = 11008 = 2^8 * 43, so the only legal tile sizes are 128 and 256
    # (td=2752 = 64*43 fails Mosaic's last-dim-divisible-by-128 check)
    assert all(D % td == 0 and td % 128 == 0 and (td // ns) % 32 == 0
               for td, ns in combos), combos
    variants += [(f"td={td} n_sub={ns}",
                  lambda v, td=td, ns=ns: matmul_sub(v, w, ns, td))
                 for td, ns in combos]
    # the tunneled platform's run-to-run jitter is ±30%: variants are only
    # comparable INTERLEAVED in one process, best-of-N each (the repo's
    # A/B measurement discipline)
    runs = [(name, chain(fn)) for name, fn in variants]
    best: dict = {}
    for name, run in runs:
        np.asarray(run(x))  # compile
    for _ in range(4):
        for name, run in runs:
            t0 = time.perf_counter()
            np.asarray(run(x))
            dt = (time.perf_counter() - t0) / 8
            best[name] = min(best.get(name, dt), dt)
    base = best["whole-tile"]
    for name, _ in runs:
        dt = best[name]
        rel = base / dt
        print(f"{name}: {dt*1e3:.3f} ms/call, {fl/dt/1e12:.1f} TFLOP/s, "
              f"{rel:.2f}x vs whole-tile")
    winner = min(best, key=best.get)
    if winner == "landed" or best["landed"] <= best[winner] * 1.02:
        print("DECISION: the landed _n_sub policy is (still) within 2% of "
              "the best variant — keep it")
    elif winner == "whole-tile":
        print("DECISION: whole-tile now beats the landed sub-tiling — "
              "re-measure and revisit _n_sub in ops/pallas_q40.py")
    else:
        print(f"DECISION: {winner} beats the landed policy by "
              f"{best['landed'] / best[winner]:.2f}x — update _n_sub in "
              "ops/pallas_q40.py to match")


if __name__ == "__main__":
    main()
