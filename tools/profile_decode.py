"""Decode-path profiler: A/B the weight-read strategies on the real chip.

Establishes the roofline picture VERDICT asked for:
  * packed Q40 bytes/token and dense-bf16 bytes/token for the chosen model
  * measured ms/token per mode -> effective HBM bandwidth
Modes: q40_xla (dequant-in-XLA), q40_pallas (fused kernel), dense_bf16.

Usage: PROF_MODE=q40_xla PROF_LAYERS=32 PROF_TOKENS=32 python tools/profile_decode.py
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from bench import LLAMA2_7B, synth_q40_params
from distributed_llama_tpu.quants.jax_codec import QuantizedTensor, dequantize_q40_jax
from distributed_llama_tpu.runtime.engine import Engine


def model_bytes(params: dict, dense_bytes_per_el: int | None = None) -> int:
    total = 0
    for w in jax.tree.leaves(params):
        total += w.size * w.dtype.itemsize
    return total


def main() -> None:
    mode = os.environ.get("PROF_MODE", "q40_xla")
    n_layers = int(os.environ.get("PROF_LAYERS", "32"))
    n_tokens = int(os.environ.get("PROF_TOKENS", "32"))
    seq_len = int(os.environ.get("PROF_SEQ", "2048"))

    spec = dataclasses.replace(LLAMA2_7B, n_layers=n_layers)
    params = synth_q40_params(spec)

    if mode == "dense_bf16":
        params = jax.tree.map(
            lambda v: dequantize_q40_jax(v, jnp.bfloat16) if isinstance(v, QuantizedTensor) else v,
            params, is_leaf=lambda v: isinstance(v, QuantizedTensor))

    engine = Engine(
        spec, params,
        compute_dtype=jnp.bfloat16, cache_dtype=jnp.bfloat16,
        max_seq_len=seq_len,
        use_pallas=(mode == "q40_pallas"),
    )

    _, dt = engine.decode_greedy_device(first_token=1, n_tokens=n_tokens)
    ms = dt / n_tokens * 1e3

    wbytes = model_bytes(engine.params)
    cache_bytes = sum(k.size * k.dtype.itemsize for k in engine.cache.k) * 2
    eff_bw = (wbytes + cache_bytes) / (ms / 1e3) / 1e9

    print(json.dumps({
        "mode": mode, "layers": n_layers, "tokens": n_tokens,
        "ms_per_token": round(ms, 3),
        "weight_gb": round(wbytes / 1e9, 3),
        "cache_gb": round(cache_bytes / 1e9, 3),
        "eff_bw_gbps": round(eff_bw, 1),
    }))


if __name__ == "__main__":
    main()
