"""Experiment: int8-MXU grouped-scale gemv vs the f32-VPU Q40 kernel.

Hypothesis: decode is VPU-bound (~7 ops/packed byte) in the fused Q40
kernel; an int4 weight unpacked to int8 with pure int ops (~3 ops/byte)
feeding int8 MXU dots batched over scale groups of 128 could approach the
HBM roofline instead. Group 128 (vs Q40's 32) matches the MXU contraction.

Run: PYTHONPATH=/root/repo python tools/exp_int8_dot.py
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_llama_tpu.ops.pallas_q40 import q40_matmul
from distributed_llama_tpu.quants.jax_codec import QuantizedTensor
from distributed_llama_tpu.quants.numpy_codec import quantize_q40

D, K = 11008, 4096
G = K // 128           # scale groups of 128
REPS = 64


def _kernel(xq_ref, pk_ref, sc_ref, o_ref, *, td):
    # pk: (TD, K/2) uint8; byte j holds col j (lo nibble) and col K/2+j
    # (hi nibble) — a pack-time column split, so no interleave is needed
    # and the unpack stays int ops in int8 lanes
    pk = pk_ref[:].astype(jnp.int32)
    lo = ((pk & 0xF) - 8).astype(jnp.int8)
    hi = ((pk >> 4) - 8).astype(jnp.int8)
    xq = xq_ref[:]                                   # (1, K) int8
    dot = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    half = pk.shape[1]
    p = dot(xq[:, :half], lo) + dot(xq[:, half:], hi)   # (1, TD)
    # NOTE: per-row scale only — group-scale precision handled outside; this
    # measures throughput.
    o_ref[:] = p.astype(jnp.float32) * sc_ref[:].reshape(1, td)


def int8_gemv(xq, pk, sc, td=256):
    grid = (D // td,)
    return pl.pallas_call(
        functools.partial(_kernel, td=td),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, K), lambda i: (0, 0)),
            pl.BlockSpec((td, K // 2), lambda i: (i, 0)),
            pl.BlockSpec((td, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, td), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, D), jnp.float32),
    )(xq, pk, sc)


L = 24          # distinct weight instances per pass: stream real HBM bytes
R1, R2 = 2, 8   # slope over passes removes the constant dispatch cost


def slope(make_run, *args):
    times = {}
    for reps in (R1, R2):
        fn = make_run(reps)
        np.asarray(jax.tree.leaves(fn(*args))[0])
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn(*args)
            np.asarray(jax.tree.leaves(out)[0])
            best = min(best, time.perf_counter() - t0)
        times[reps] = best
    return (times[R2] - times[R1]) / (R2 - R1)


def main():
    rng = np.random.default_rng(0)
    # int8 path: L stacked weight instances, scan with carry feedback
    pk = jnp.asarray(rng.integers(0, 256, (L, D, K // 2), dtype=np.uint8))
    sc = jnp.asarray(rng.random((L, D, 1), dtype=np.float32))
    xq0 = jnp.asarray(rng.integers(-8, 8, (1, K), dtype=np.int8))

    def make8(reps):
        def run(pk, sc, xq):
            def rep(xq, _):
                def layer(xq, wl):
                    p, s = wl
                    out = int8_gemv(xq, p, s)
                    # data dependency without changing values
                    xq = jnp.where(out[0, 0] > 1e30, xq ^ 1, xq)
                    return xq, None
                xq, _ = jax.lax.scan(layer, xq, (pk, sc))
                return xq, None
            xq, _ = jax.lax.scan(rep, xq, None, length=reps)
            return xq
        return jax.jit(run)

    dt8 = slope(make8, pk, sc, xq0)
    gb = (pk.size + sc.size * 4) / 1e9
    print(f"int8-MXU int4 gemv: {dt8*1e3:.3f} ms/pass {gb:.2f} GB -> {gb/dt8:.0f} GB/s packed")

    # current kernel: same structure
    scales, packed = quantize_q40(rng.standard_normal((D, K), np.float32))
    hpk, hsc = QuantizedTensor.host_layout(scales, packed)
    wq = QuantizedTensor(
        jnp.broadcast_to(jnp.asarray(hpk), (L,) + hpk.shape).copy(),
        jnp.broadcast_to(jnp.asarray(hsc), (L,) + hsc.shape).copy())
    x0 = jnp.ones((1, K), jnp.bfloat16)

    def makeq(reps):
        def run(wq, x):
            def rep(x, _):
                def layer(x, wl):
                    out = q40_matmul(x, QuantizedTensor(wl[0], wl[1]),
                                     out_dtype=jnp.bfloat16)
                    x = jnp.where(out[0, 0] > 1e30, x + 1, x)
                    return x, None
                x, _ = jax.lax.scan(layer, x, (wq.packed, wq.scales))
                return x, None
            x, _ = jax.lax.scan(rep, x, None, length=reps)
            return x
        return jax.jit(run)

    dtq = slope(makeq, wq, x0)
    gbq = (wq.packed.size + wq.scales.size * 2) / 1e9
    print(f"f32-VPU q40 gemv:   {dtq*1e3:.3f} ms/pass {gbq:.2f} GB -> {gbq/dtq:.0f} GB/s packed")


if __name__ == "__main__":
    main()
