#!/usr/bin/env python
"""dlprof — offline capacity/latency analyzer over the flight-recorder's
artifacts (the consumer the PR-8 data never had).

Inputs (any combination; at least one):

  * ``--trace-dir DIR``  — the rotating JSONL the server writes under
    ``--trace-dir`` (worker subdirs included): request spans + per-step
    timeline events (docs/observability.md schema).
  * ``--bench FILE``     — a bench.py artifact (the single JSON object a
    run prints, or a committed ``BENCH_rXX.json``): every row's
    ``step_timeline`` block feeds the curve, its ``hbm`` block caps the
    recommendation.

Outputs a JSON + markdown report with four sections:

  * **Per-request critical path** — each completed span decomposed into
    queue → route → seed → prefill → first-token → decode, with
    percentiles per phase: WHERE time goes, not just how much.
  * **Batch-composition → ms/step curve + knee** — decode-only step
    compositions plotted rows vs p50 ms; the knee is the largest batch
    whose marginal throughput per added row still clears half the
    small-batch per-row throughput (past it, KV-cache traffic is eating
    the weight-read amortization — Orca's iteration-level tradeoff,
    ROADMAP item 1), emitted with a ``--serve-batch`` recommendation.
  * **Goodput at SLO** — the fraction of requests (and tokens/s) that
    met ``--slo-ttft-ms`` / ``--slo-itl-ms``: the serving number that
    actually matters under load, vs raw throughput.
  * **Tail attribution** — the slowest requests, each annotated with the
    phase that ate its budget (queue vs prefill vs decode), so a p99
    regression names its layer.

Pure host-side file crunching: no jax import, runs anywhere (the CI
``dlprof smoke`` step runs ``--selftest``, which synthesizes a tiny
trace + timeline and asserts the report parses with a non-null knee).

Usage:
  python tools/dlprof.py --trace-dir /var/log/dllama-trace \\
      --bench BENCH_r06.json --out report --slo-ttft-ms 500
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

# -- small stats helpers (no package import: dlprof must run with no
# jax/repo on the path — operators copy it next to an artifact) -------------


def percentile(xs: list, p: float):
    """Nearest-rank percentile, the same convention as
    runtime/stats.percentile (no interpolation; None when empty)."""
    if not xs:
        return None
    xs = sorted(xs)
    k = min(len(xs) - 1, max(0, round(p / 100.0 * (len(xs) - 1))))
    return xs[k]


def _rnd(v, nd: int = 3):
    return None if v is None else round(v, nd)


# -- input loading ----------------------------------------------------------


def load_trace_dir(path: str) -> list[dict]:
    """Every event from every ``trace-*.jsonl`` under `path` (recursive —
    replica workers write ``worker-rK/`` subdirs), sorted by wall time
    so cross-process events interleave correctly."""
    events: list[dict] = []
    for f in glob.glob(os.path.join(path, "**", "trace-*.jsonl"),
                       recursive=True):
        with open(f) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # a torn final line in a live sink
                if "kind" in rec:
                    events.append(rec)
    events.sort(key=lambda e: e.get("ts_wall", e.get("ts", 0.0)))
    return events


_TL_KEY = re.compile(r"^(?:r\d+_)?dec(\d+)_pre(\d+)_c(\d+)$")


def load_bench(path: str) -> list[dict]:
    """bench.py artifact -> flat row list (the main row + its variants).
    Accepts the one-object-per-run shape bench prints and committed
    BENCH_rXX.json artifacts of the same shape."""
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, list):
        rows = list(obj)
    else:
        rows = [obj] + list(obj.get("variants") or [])
    return [r for r in rows if isinstance(r, dict)]


def merge_timelines(events: list[dict], bench_rows: list[dict]) -> dict:
    """{(dec, pre, chunk): {"n", "p50_ms", "p99_ms", "mean_ms"}} merged
    from raw step events (exact — re-percentiled here) and bench rows'
    pre-summarized ``step_timeline`` blocks (worker ``rK_`` prefixes
    stripped; when several sources cover one composition the larger-n
    summary wins)."""
    raw: dict[tuple, list] = {}
    for e in events:
        if e.get("kind") != "step":
            continue
        key = (int(e.get("dec", 0)), int(e.get("pre", 0)),
               int(e.get("chunk", 0)))
        raw.setdefault(key, []).append(float(e.get("ms", 0.0)))
    out: dict[tuple, dict] = {}
    for key, xs in raw.items():
        out[key] = {"n": len(xs), "p50_ms": _rnd(percentile(xs, 50), 4),
                    "p99_ms": _rnd(percentile(xs, 99), 4),
                    "mean_ms": _rnd(sum(xs) / len(xs), 4)}
    for row in bench_rows:
        for k, v in (row.get("step_timeline") or {}).items():
            m = _TL_KEY.match(str(k))
            if not m or not isinstance(v, dict):
                continue
            key = tuple(int(g) for g in m.groups())
            if key not in out or (v.get("n", 0) > out[key].get("n", 0)):
                out[key] = {"n": v.get("n", 0),
                            "p50_ms": v.get("p50_ms"),
                            "p99_ms": v.get("p99_ms"),
                            "mean_ms": v.get("mean_ms")}
    return out


# -- per-request critical path ----------------------------------------------

_TERMINAL = ("finish", "error")


def spans_from_events(events: list[dict]) -> dict[int, list[dict]]:
    spans: dict[int, list[dict]] = {}
    for e in events:
        tid = e.get("tid") or 0
        if tid:
            spans.setdefault(int(tid), []).append(e)
    return spans


def critical_path(span: list[dict]) -> dict | None:
    """One span -> its phase decomposition (ms). None when the span has
    no terminal event (still in flight when the sink rotated, or a
    SIGKILL casualty whose retry carried the id — the RETRY's terminal
    closes the span, so those still analyze)."""

    def first(kind):
        return next((e for e in span if e.get("kind") == kind), None)

    def ts(e):
        return e.get("ts_wall", e.get("ts")) if e is not None else None

    term = next((e for e in reversed(span)
                 if e.get("kind") in _TERMINAL), None)
    enq = first("enqueue")
    if term is None or enq is None:
        return None
    admit = first("admit")
    route = first("route")
    seed = first("seed")
    ft = first("first_token")
    t0, t_end = ts(enq), ts(term)
    t_admit, t_ft = ts(admit), ts(ft)
    queue_ms = (admit.get("queue_ms") if admit is not None else None)
    if queue_ms is None and t_admit is not None:
        queue_ms = (t_admit - t0) * 1e3
    prefill_ms = ((t_ft - t_admit) * 1e3
                  if t_ft is not None and t_admit is not None else None)
    decode_ms = (t_end - t_ft) * 1e3 if t_ft is not None else None
    n_out = int(term.get("n_out") or 0)
    retries = sum(1 for e in span if e.get("kind") == "failover")
    out = {
        "tid": span[0].get("tid"),
        "status": (term.get("reason") if term.get("kind") == "finish"
                   else f"error:{term.get('code', 'error')}"),
        "n_prompt": enq.get("n_prompt"),
        "n_out": n_out,
        "seed_hit": seed.get("hit") if seed is not None else None,
        "retries": retries,
        "queue_ms": _rnd(queue_ms),
        "route_ms": _rnd((ts(route) - t0) * 1e3
                         if route is not None else None),
        "prefill_ms": _rnd(prefill_ms),
        "ttft_ms": _rnd(ft.get("ttft_ms") if ft is not None
                        else ((t_ft - t0) * 1e3 if t_ft is not None
                              else None)),
        "decode_ms": _rnd(decode_ms),
        "itl_ms": _rnd(decode_ms / (n_out - 1)
                       if decode_ms is not None and n_out > 1 else None),
        "total_ms": _rnd((t_end - t0) * 1e3),
    }
    phases = {k: out[k] for k in ("queue_ms", "prefill_ms", "decode_ms")
              if out.get(k) is not None}
    out["dominant_phase"] = (max(phases, key=phases.get).removesuffix("_ms")
                            if phases else None)
    return out


def request_summary(paths: list[dict]) -> dict:
    def pcts(field):
        xs = [p[field] for p in paths if p.get(field) is not None]
        return {"n": len(xs), "p50": _rnd(percentile(xs, 50)),
                "p99": _rnd(percentile(xs, 99))}

    return {
        "requests": len(paths),
        "completed": sum(1 for p in paths
                         if not str(p["status"]).startswith("error")),
        "errors": sum(1 for p in paths
                      if str(p["status"]).startswith("error")),
        "retried": sum(1 for p in paths if p.get("retries")),
        "queue_ms": pcts("queue_ms"),
        "prefill_ms": pcts("prefill_ms"),
        "ttft_ms": pcts("ttft_ms"),
        "itl_ms": pcts("itl_ms"),
        "decode_ms": pcts("decode_ms"),
        "total_ms": pcts("total_ms"),
    }


# -- the batch knee ---------------------------------------------------------


def decode_curve(timeline: dict) -> list[tuple[int, float]]:
    """Decode-only compositions -> sorted (rows, p50 ms) points (the
    batch-composition → ms/step curve; prefill-mixed compositions are
    admission noise for this question)."""
    pts = [(k[0], v["p50_ms"]) for k, v in timeline.items()
           if k[0] > 0 and k[1] == 0 and v.get("p50_ms")]
    return sorted(pts)


def knee_estimate(curve: list[tuple[int, float]]) -> dict | None:
    """Where batching stops paying. Decode is weight-read-bound, so
    ms/step should be nearly flat in rows until KV-cache traffic starts
    competing; the knee is the largest measured batch whose MARGINAL
    aggregate throughput per added row still clears half the small-batch
    per-row throughput. Emits the whole throughput table so the caller
    (and ROADMAP item 1's auto-sizing) can re-derive with its own
    threshold. None only when no decode composition was measured."""
    if not curve:
        return None
    table = [{"rows": b, "p50_ms": ms,
              "rows_per_s": _rnd(b / ms * 1e3, 2)} for b, ms in curve]
    if len(curve) == 1:
        b, ms = curve[0]
        return {"knee_rows": b, "method": "single_point",
                "curve": table,
                "note": "one composition measured — bench more batch "
                        "sizes (BENCH_SERVE with a larger --serve-batch) "
                        "to place the knee"}
    b0, ms0 = curve[0]
    per_row0 = (b0 / ms0) / b0          # rows/ms each small-batch row buys
    knee = b0
    saturated = False
    for (b1, m1), (b2, m2) in zip(curve, curve[1:]):
        t1, t2 = b1 / m1, b2 / m2
        marginal = (t2 - t1) / (b2 - b1)
        if marginal < 0.5 * per_row0:
            saturated = True
            break
        knee = b2
    return {"knee_rows": knee,
            "method": "marginal_throughput" if saturated
            else "no_saturation_observed",
            "curve": table,
            "note": None if saturated else
            f"throughput still scaling at rows={knee} — measure larger "
            "batches to find the true knee"}


# -- the calibration artifact (tools/autotune.py) ---------------------------

# duplicated from runtime/profiler.py on purpose: dlprof must run with NO
# repo on the path (operators copy it next to an artifact — the same
# reason percentile() above is local). tests/test_autotune.py pins the
# two validators against each other so the contract cannot drift.
AUTOTUNE_KIND = "dllama-autotune"
AUTOTUNE_VERSION = 1
DRIFT_FRAC = 0.25  # calibrated vs measured knee movement worth flagging


def validate_autotune(art) -> list[str]:
    """Schema problems of one AUTOTUNE.json artifact (empty = valid)."""
    problems = []
    if not isinstance(art, dict):
        return ["not a JSON object"]
    if art.get("kind") != AUTOTUNE_KIND:
        problems.append(f"kind must be {AUTOTUNE_KIND!r}, "
                        f"got {art.get('kind')!r}")
    if art.get("version") != AUTOTUNE_VERSION:
        problems.append(f"version must be {AUTOTUNE_VERSION}, "
                        f"got {art.get('version')!r}")
    knee = art.get("knee")
    if not isinstance(knee, dict) or not knee.get("knee_rows"):
        problems.append("missing knee.knee_rows (re-run the calibration "
                        "with >= 1 measured batch size)")
    if not isinstance(art.get("decode_curve"), list):
        problems.append("missing decode_curve list")
    return problems


def load_autotune(path: str) -> dict:
    with open(path) as f:
        art = json.load(f)
    problems = validate_autotune(art)
    if problems:
        raise ValueError("invalid autotune artifact: "
                         + "; ".join(problems))
    return art


def autotune_comparison(knee: dict | None, art: dict) -> dict:
    """Calibrated knee (AUTOTUNE.json) vs the knee measured from the
    LIVE inputs of this report — the drift check an operator runs before
    trusting yesterday's calibration: a knee moved >= DRIFT_FRAC means
    the workload, model, or backend shifted enough that the auto-sized
    batch is stale and tools/autotune.py should re-run."""
    calibrated = int((art.get("knee") or {}).get("knee_rows") or 0)
    measured = int(knee["knee_rows"]) if knee else None
    drift_frac = None
    drift = False
    if measured is not None and calibrated:
        drift_frac = abs(measured - calibrated) / calibrated
        drift = drift_frac >= DRIFT_FRAC
    return {
        "calibrated_knee_rows": calibrated or None,
        "calibrated_model": art.get("model"),
        "calibrated_backend": art.get("backend"),
        "calibrated_unix": art.get("created_unix"),
        "measured_knee_rows": measured,
        "drift_frac": _rnd(drift_frac, 4),
        "drift": drift,
        "note": ("no live decode compositions to compare against — "
                 "feed --trace-dir or a bench artifact"
                 if measured is None else
                 (f"knee moved {drift_frac:.0%} from calibration "
                  "(>= 25%): re-run tools/autotune.py and re-resolve "
                  "--serve-batch auto" if drift else None)),
    }


def serve_batch_recommendation(knee: dict | None,
                               hbm: dict | None) -> dict | None:
    """The knee, capped by what HBM can actually hold: current batch
    rows + ``slots_addable`` from the hbm block (when a backend
    reported a limit — CPU artifacts carry null headroom and the knee
    stands alone)."""
    if knee is None:
        return None
    rec = int(knee["knee_rows"])
    cap = None
    if hbm and hbm.get("slots_addable") is not None:
        cur = max((r["rows"] for r in knee["curve"]), default=rec)
        cap = cur + int(hbm["slots_addable"])
        rec = min(rec, cap)
    return {"serve_batch": rec, "hbm_cap_rows": cap,
            "basis": knee["method"]}


# -- the wire report (dlwire: measured cluster-plane comms) -----------------

# mirrored from runtime/netstats.WIRE_DRIFT_FRAC on purpose (same reason
# as the AUTOTUNE constants above: dlprof runs with no repo on the path);
# tests pin the two against each other
WIRE_DRIFT_FRAC = 0.25


def wire_report(events: list[dict], bench_rows: list[dict]) -> dict | None:
    """The comms section: per-peer measured bytes/frames and RTT tails
    (from bench rows' ``wire`` blocks — the cluster chaos row, MULTICHIP
    rows when silicon returns), the sampled device sync-vs-compute share
    (from ``sync`` trace events — runtime/profiler.py's per-step
    collective attribution), and every measured-vs-modeled
    reconciliation found, drift flagged at >= 25% like the autotune knee
    check. None when no input carries wire data."""
    peers: dict[str, dict] = {}
    reconciles: list[dict] = []

    def eat_summary(side: str, w: dict) -> None:
        for peer, rec in (w.get("peers") or {}).items():
            key = f"{side}:peer{peer}" if side else f"peer{peer}"
            out = peers.setdefault(key, {"tx_bytes": 0, "rx_bytes": 0,
                                         "tx_frames": 0, "rx_frames": 0,
                                         "by_kind": {}})
            for dirn in ("tx", "rx"):
                for kind, kb in (rec.get(dirn) or {}).items():
                    out[f"{dirn}_bytes"] += kb.get("bytes", 0)
                    out[f"{dirn}_frames"] += kb.get("frames", 0)
                    out["by_kind"][f"{dirn}:{kind}"] = {
                        "frames": kb.get("frames"),
                        "bytes": kb.get("bytes")}
            rtt = rec.get("rtt_ms")
            if rtt:
                out["rtt_ms"] = {k: rtt.get(k)
                                 for k in ("n", "p50_ms", "p99_ms",
                                           "mean_ms")}
            if rec.get("clock_offset_ms") is not None:
                out["clock_offset_ms"] = rec["clock_offset_ms"]

    kvx_blocks: list[dict] = []
    for row in bench_rows:
        w = row.get("wire")
        if isinstance(w, dict) and w:
            if "peers" in w:  # a raw WireStats summary
                eat_summary("", w)
            else:             # {"root": summary, "worker": summary, ...}
                for side, sub in w.items():
                    if isinstance(sub, dict) and "peers" in sub:
                        eat_summary(side, sub)
            if isinstance(w.get("reconcile"), dict):
                # COPY: the drift flag is re-derived below, and the
                # report must never mutate the caller's loaded rows
                reconciles.append(dict(w["reconcile"]))
        # KV block transfer blocks (runtime/kv_transfer.py): a bench
        # row's (or /stats dump's) kv_transfer summary, incl. its own
        # measured-vs-modeled reconcile entry
        kvx = row.get("kv_transfer")
        if isinstance(kvx, dict) and kvx:
            kvx_blocks.append(kvx)
            if isinstance(kvx.get("reconcile"), dict):
                reconciles.append(dict(kvx["reconcile"]))
            sub = kvx.get("wire")
            if isinstance(sub, dict) and "peers" in sub:
                eat_summary("kvx", sub)

    syncs = [e for e in events if e.get("kind") == "sync"]
    sync = None
    if syncs:
        sync_ms = [float(e.get("sync_ms") or 0.0) for e in syncs]
        dev_ms = [float(e.get("device_ms") or 0.0) for e in syncs]
        total_dev = sum(dev_ms)
        sync = {
            "sampled_steps": len(syncs),
            "sync_p50_ms": _rnd(percentile(sync_ms, 50), 4),
            "sync_p99_ms": _rnd(percentile(sync_ms, 99), 4),
            "device_p50_ms": _rnd(percentile(dev_ms, 50), 4),
            # window sums, not mean-of-ratios (an idle step's ratio must
            # not swamp the loaded steps) — same rule as SyncStats
            "sync_share": (_rnd(sum(sync_ms) / total_dev, 4)
                           if total_dev else None),
        }

    kvx = None
    if kvx_blocks:
        # sum the counters across blocks (a disaggregated bench row may
        # carry one block per party); transfer tails only report when
        # exactly one block has them (percentiles do not merge)
        keys = ("fills_requested", "fills_ok", "fill_fallbacks",
                "fill_misses", "tokens_filled", "blocks_filled",
                "bytes_rx", "bytes_tx", "blocks_exported",
                "queries_served", "query_misses", "prefill_passes",
                "prefill_pass_fallbacks", "shadow_truncates")
        kvx = {k: sum(int(b.get(k) or 0) for b in kvx_blocks)
               for k in keys}
        with_ms = [b for b in kvx_blocks
                   if b.get("transfer_p50_ms") is not None]
        kvx["transfer_p50_ms"] = (with_ms[0]["transfer_p50_ms"]
                                  if len(with_ms) == 1 else None)
        kvx["transfer_p99_ms"] = (with_ms[0].get("transfer_p99_ms")
                                  if len(with_ms) == 1 else None)
        req = kvx["fills_requested"]
        kvx["fill_hit_rate"] = (_rnd(kvx["fills_ok"] / req, 4)
                                if req else None)

    if not peers and sync is None and not reconciles and kvx is None:
        return None
    # re-derive the drift flag locally: committed artifacts may predate
    # the producer's threshold, and the report must flag consistently
    for rec in reconciles:
        if rec.get("drift_frac") is not None:
            rec["drift"] = rec["drift_frac"] >= WIRE_DRIFT_FRAC
    return {"peers": peers, "sync": sync, "kv_transfer": kvx,
            "reconcile": reconciles,
            "drift": any(r.get("drift") for r in reconciles)}


# -- goodput + tail ---------------------------------------------------------


def goodput(paths: list[dict], events: list[dict], *, slo_ttft_ms: float,
            slo_itl_ms: float) -> dict:
    done = [p for p in paths
            if not str(p["status"]).startswith("error")]
    ok = [p for p in done
          if (p.get("ttft_ms") is not None
              and p["ttft_ms"] <= slo_ttft_ms
              and (p.get("itl_ms") is None or p["itl_ms"] <= slo_itl_ms))]
    ts = [e.get("ts_wall", e.get("ts")) for e in events
          if e.get("ts_wall") is not None or e.get("ts") is not None]
    window_s = (max(ts) - min(ts)) if len(ts) > 1 else None
    tok_ok = sum(p.get("n_out") or 0 for p in ok)
    tok_all = sum(p.get("n_out") or 0 for p in done)
    return {
        "slo_ttft_ms": slo_ttft_ms,
        "slo_itl_ms": slo_itl_ms,
        "completed": len(done),
        "within_slo": len(ok),
        "slo_fraction": _rnd(len(ok) / len(done), 4) if done else None,
        "window_s": _rnd(window_s),
        "goodput_tok_s": _rnd(tok_ok / window_s, 2) if window_s else None,
        "throughput_tok_s": _rnd(tok_all / window_s, 2)
        if window_s else None,
    }


def tail_attribution(paths: list[dict], k: int = 5) -> list[dict]:
    """The k slowest requests, each naming the phase that ate its
    budget — a p99 regression debugging session starts here, not at an
    aggregate percentile."""
    ranked = sorted((p for p in paths if p.get("total_ms") is not None),
                    key=lambda p: -p["total_ms"])
    out = []
    for p in ranked[:k]:
        total = p["total_ms"] or 1.0
        shares = {ph: _rnd((p.get(f"{ph}_ms") or 0.0) / total, 3)
                  for ph in ("queue", "prefill", "decode")}
        out.append({**p, "phase_shares": shares})
    return out


# -- the report -------------------------------------------------------------


def analyze(events: list[dict], bench_rows: list[dict] | None = None, *,
            slo_ttft_ms: float = 500.0, slo_itl_ms: float = 100.0,
            autotune: dict | None = None, wire: bool = False) -> dict:
    bench_rows = bench_rows or []
    timeline = merge_timelines(events, bench_rows)
    paths = [p for p in (critical_path(s)
                         for s in spans_from_events(events).values())
             if p is not None]
    curve = decode_curve(timeline)
    knee = knee_estimate(curve)
    hbm = next((r["hbm"] for r in bench_rows
                if isinstance(r.get("hbm"), dict) and r["hbm"]), None)
    report = {
        "inputs": {"events": len(events), "spans": len(paths),
                   "bench_rows": len(bench_rows),
                   "compositions": len(timeline)},
        "requests": request_summary(paths),
        "critical_paths": paths,
        "step_curve": {
            "compositions": {f"dec{k[0]}_pre{k[1]}_c{k[2]}": v
                             for k, v in sorted(timeline.items())},
            "decode_points": [{"rows": b, "p50_ms": ms}
                              for b, ms in curve],
            "knee": knee,
            "recommendation": serve_batch_recommendation(knee, hbm),
        },
        "goodput": goodput(paths, events, slo_ttft_ms=slo_ttft_ms,
                           slo_itl_ms=slo_itl_ms),
        "tail": tail_attribution(paths),
        "hbm": hbm,
    }
    if autotune is not None:
        report["autotune"] = autotune_comparison(knee, autotune)
    if wire:
        report["wire"] = wire_report(events, bench_rows)
    return report


def render_markdown(report: dict) -> str:
    lines = ["# dlprof report", ""]
    inp = report["inputs"]
    lines += [f"Inputs: {inp['events']} events, {inp['spans']} spans, "
              f"{inp['bench_rows']} bench rows, "
              f"{inp['compositions']} step compositions.", ""]

    r = report["requests"]
    lines += ["## Requests", "",
              f"{r['requests']} analyzed — {r['completed']} completed, "
              f"{r['errors']} errors, {r['retried']} retried.", "",
              "| phase | p50 ms | p99 ms | n |", "|---|---|---|---|"]
    for ph in ("queue_ms", "prefill_ms", "ttft_ms", "itl_ms",
               "decode_ms", "total_ms"):
        row = r[ph]
        lines.append(f"| {ph.removesuffix('_ms')} | {row['p50']} | "
                     f"{row['p99']} | {row['n']} |")
    lines.append("")

    sc = report["step_curve"]
    lines += ["## Batch-composition → ms/step", "",
              "| rows | p50 ms | rows/s |", "|---|---|---|"]
    knee = sc["knee"]
    for p in (knee or {}).get("curve", []) or [
            {"rows": q["rows"], "p50_ms": q["p50_ms"], "rows_per_s": None}
            for q in sc["decode_points"]]:
        lines.append(f"| {p['rows']} | {p['p50_ms']} | "
                     f"{p.get('rows_per_s')} |")
    if knee:
        lines += ["", f"**Knee: {knee['knee_rows']} rows** "
                      f"({knee['method']})."]
        if knee.get("note"):
            lines.append(f"_{knee['note']}_")
    rec = sc["recommendation"]
    if rec:
        cap = (f" (HBM caps at {rec['hbm_cap_rows']})"
               if rec.get("hbm_cap_rows") is not None else "")
        lines += ["", f"**Recommended `--serve-batch "
                      f"{rec['serve_batch']}`**{cap}."]
    lines.append("")

    at = report.get("autotune")
    if at:
        lines += ["## Calibration drift (AUTOTUNE.json)", "",
                  f"Calibrated knee {at['calibrated_knee_rows']} rows "
                  f"({at['calibrated_model']}/{at['calibrated_backend']})"
                  f" vs measured {at['measured_knee_rows']} — drift "
                  f"{at['drift_frac']}"
                  + (" ⚠️ **DRIFTED**" if at["drift"] else " (ok)")
                  + ".", ""]
        if at.get("note"):
            lines += [f"_{at['note']}_", ""]

    g = report["goodput"]
    lines += ["## Goodput", "",
              f"{g['within_slo']}/{g['completed']} requests within "
              f"TTFT ≤ {g['slo_ttft_ms']} ms ∧ ITL ≤ {g['slo_itl_ms']} ms"
              + (f" — {g['goodput_tok_s']} tok/s goodput of "
                 f"{g['throughput_tok_s']} tok/s total"
                 if g.get("goodput_tok_s") is not None else "") + ".", ""]

    if report["tail"]:
        lines += ["## Tail attribution", "",
                  "| tid | total ms | status | dominant phase | "
                  "queue/prefill/decode share |", "|---|---|---|---|---|"]
        for t in report["tail"]:
            sh = t["phase_shares"]
            lines.append(
                f"| {t['tid']} | {t['total_ms']} | {t['status']} | "
                f"{t['dominant_phase']} | {sh['queue']}/{sh['prefill']}/"
                f"{sh['decode']} |")
        lines.append("")

    w = report.get("wire")
    if w:
        lines += ["## Wire (measured cluster plane)", ""]
        if w["peers"]:
            lines += ["| peer | tx bytes | rx bytes | frames (tx/rx) | "
                      "rtt p50/p99 ms | clock offset ms |",
                      "|---|---|---|---|---|---|"]
            for name, rec in sorted(w["peers"].items()):
                rtt = rec.get("rtt_ms") or {}
                lines.append(
                    f"| {name} | {rec['tx_bytes']} | {rec['rx_bytes']} | "
                    f"{rec['tx_frames']}/{rec['rx_frames']} | "
                    f"{rtt.get('p50_ms')}/{rtt.get('p99_ms')} | "
                    f"{rec.get('clock_offset_ms')} |")
            lines.append("")
        sync = w.get("sync")
        if sync:
            lines += [f"Sync vs compute (sampled device steps, "
                      f"n={sync['sampled_steps']}): collective p50 "
                      f"{sync['sync_p50_ms']} ms of device p50 "
                      f"{sync['device_p50_ms']} ms — **share "
                      f"{sync['sync_share']}**.", ""]
        kvx = w.get("kv_transfer")
        if kvx:
            lines += ["### KV transfer", "",
                      f"Fills: {kvx['fills_ok']}/"
                      f"{kvx['fills_requested']} ok "
                      f"(hit rate {kvx.get('fill_hit_rate')}), "
                      f"{kvx['fill_fallbacks']} degraded to re-prefill, "
                      f"{kvx['fill_misses']} donor misses.",
                      f"Moved: {kvx['tokens_filled']} tokens / "
                      f"{kvx['blocks_filled']} blocks "
                      f"({kvx['bytes_rx']} B rx, {kvx['bytes_tx']} B "
                      f"tx); transfer p50/p99 "
                      f"{kvx.get('transfer_p50_ms')}/"
                      f"{kvx.get('transfer_p99_ms')} ms.",
                      f"Disaggregation: {kvx['prefill_passes']} prefill "
                      f"passes, {kvx['prefill_pass_fallbacks']} mixed-"
                      f"path fallbacks; {kvx['shadow_truncates']} stale "
                      f"shadow paths cleared.", ""]
        for rec in w.get("reconcile") or ():
            flag = " ⚠️ **DRIFTED**" if rec.get("drift") else " (ok)"
            lines.append(
                f"Measured vs modeled ({rec.get('unit', 'bytes')}): "
                f"{rec.get('measured')} vs {rec.get('modeled')} — drift "
                f"{rec.get('drift_frac')}{flag}.")
            if rec.get("note"):
                lines.append(f"_{rec['note']}_")
        if w.get("reconcile"):
            lines.append("")

    hbm = report.get("hbm")
    if hbm:
        lines += ["## HBM ledger (from bench row)", "",
                  "| category | bytes |", "|---|---|"]
        for k in ("weights_bytes", "vocab_bytes", "kv_slot_bytes",
                  "prefix_arena_bytes", "logits_workspace_bytes",
                  "headroom_bytes"):
            lines.append(f"| {k.removesuffix('_bytes')} | {hbm.get(k)} |")
        if hbm.get("slots_addable") is not None:
            lines.append(f"| slots_addable | {hbm['slots_addable']} |")
        lines.append("")
    return "\n".join(lines)


# -- selftest (the CI smoke) ------------------------------------------------


def _selftest() -> int:
    """Synthesize a tiny trace + step_timeline and assert the report
    parses with a non-null knee — the CI `dlprof smoke` (fast, no jax)."""
    import tempfile

    events = []
    t = 1000.0
    for tid in (1, 2, 3):
        t += 0.010
        events.append({"ts_wall": t, "kind": "enqueue", "tid": tid,
                       "n_prompt": 9, "max_tokens": 6})
        t += 0.004
        events.append({"ts_wall": t, "kind": "admit", "tid": tid,
                       "slot": 0, "queue_ms": 4.0})
        events.append({"ts_wall": t, "kind": "seed", "tid": tid,
                       "hit": 0 if tid == 1 else 8, "n_prompt": 9})
        t += 0.020
        events.append({"ts_wall": t, "kind": "first_token", "tid": tid,
                       "ttft_ms": 24.0})
        t += 0.050
        events.append({"ts_wall": t, "kind": "finish", "tid": tid,
                       "reason": "length", "n_out": 6})
    # a decode curve with a visible knee at 4 rows
    for rows, ms in ((1, 5.0), (2, 5.4), (4, 6.2), (8, 14.0)):
        for _ in range(8):
            events.append({"ts_wall": t, "kind": "step", "tid": 0,
                           "dec": rows, "pre": 0, "chunk": 0,
                           "queue": 0, "ms": ms})
    bench_row = {"metric": "selftest", "step_timeline": {
        "dec8_pre0_c0": {"n": 64, "p50_ms": 14.0, "p99_ms": 15.0,
                         "mean_ms": 14.1}},
        "hbm": {"weights_bytes": 1 << 20, "kv_slot_bytes": 1 << 18,
                "prefix_arena_bytes": 1 << 18,
                "logits_workspace_bytes": 1 << 16,
                "slots_addable": None}}
    # round-trip through a real trace dir: the loader is part of the smoke
    with tempfile.TemporaryDirectory() as d:
        with open(os.path.join(d, "trace-00000001.jsonl"), "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        report = analyze(load_trace_dir(d), [bench_row])
    assert report["requests"]["requests"] == 3, report["requests"]
    assert report["requests"]["completed"] == 3
    knee = report["step_curve"]["knee"]
    assert knee is not None and knee["knee_rows"] == 4, knee
    assert report["step_curve"]["recommendation"]["serve_batch"] == 4
    assert report["goodput"]["completed"] == 3
    assert report["tail"], "tail attribution empty"
    json.dumps(report)                      # JSON-clean
    md = render_markdown(report)
    assert "Knee: 4 rows" in md, md

    # the AUTOTUNE.json input path: a matching calibration reads clean, a
    # knee that moved 2x flags drift in the report AND the markdown
    art = {"kind": AUTOTUNE_KIND, "version": AUTOTUNE_VERSION,
           "model": "selftest", "backend": "none", "created_unix": 0.0,
           "decode_curve": [],
           "knee": {"knee_rows": 4, "method": "marginal_throughput"}}
    assert not validate_autotune(art), validate_autotune(art)
    assert validate_autotune({"kind": "bogus"})  # bad artifact named
    with tempfile.TemporaryDirectory() as d:
        ap = os.path.join(d, "AUTOTUNE.json")
        with open(ap, "w") as f:
            json.dump(art, f)
        with open(os.path.join(d, "trace-00000001.jsonl"), "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        r2 = analyze(load_trace_dir(d), [bench_row],
                     autotune=load_autotune(ap))
    at = r2["autotune"]
    assert at["measured_knee_rows"] == 4 and not at["drift"], at
    drifted = autotune_comparison({"knee_rows": 8},
                                  dict(art, knee={"knee_rows": 4}))
    assert drifted["drift"] and drifted["drift_frac"] == 1.0, drifted
    assert "Calibration drift" in render_markdown(r2)

    # the wire section (dlwire): a bench row's measured cluster ledger +
    # sampled sync events -> per-peer table, sync share, and the
    # reconciliation — exact-match reads clean, a 30%-off model flags
    wire_row = {"metric": "wire-selftest", "wire": {
        "root": {"peers": {"1": {
            "tx": {"PING": {"frames": 5, "bytes": 120},
                   "RUN": {"frames": 2, "bytes": 223}},
            "rx": {"PONG": {"frames": 5, "bytes": 160}},
            "rtt_ms": {"n": 5, "p50_ms": 0.9, "p99_ms": 1.7,
                       "mean_ms": 1.1},
            "clock_offset_ms": 0.07}}},
        "reconcile": {"measured": 223.0, "modeled": 223.0,
                      "unit": "bytes", "drift_frac": 0.0,
                      "drift": False}}}
    sync_events = [{"ts_wall": t, "kind": "sync", "tid": 0,
                    "sync_ms": 2.0, "device_ms": 8.0, "share": 0.25}
                   for _ in range(4)]
    rw = analyze(events + sync_events, [bench_row, wire_row], wire=True)
    w = rw["wire"]
    assert w is not None and not w["drift"], w
    assert w["peers"]["root:peer1"]["tx_bytes"] == 343, w["peers"]
    assert w["sync"]["sync_share"] == 0.25, w["sync"]
    md_w = render_markdown(rw)
    assert "Wire (measured cluster plane)" in md_w and "0.25" in md_w
    drifted_row = {"metric": "w2", "wire": {
        "reconcile": {"measured": 130.0, "modeled": 100.0,
                      "unit": "bytes", "drift_frac": 0.3, "drift": True}}}
    wd = analyze(events, [drifted_row], wire=True)["wire"]
    assert wd["drift"] and wd["reconcile"][0]["drift"], wd
    assert "DRIFTED" in render_markdown({**rw, "wire": wd})
    # the analyzer without --wire is unchanged (no section, no key)
    assert "wire" not in analyze(events, [wire_row]), "wire leaked"

    # the KV transfer section (runtime/kv_transfer.py): a bench row's
    # kv_transfer block -> fills/bytes/disagg lines + its reconcile
    # entry folded into the wire report (exact reads clean; drift flags)
    kvx_row = {"metric": "kvx-selftest", "kv_transfer": {
        "enabled": True, "tier": "aggregate",
        "fills_requested": 4, "fills_ok": 3, "fill_fallbacks": 1,
        "fill_misses": 1, "tokens_filled": 96, "blocks_filled": 6,
        "bytes_rx": 6144, "bytes_tx": 6144, "blocks_exported": 6,
        "queries_served": 4, "query_misses": 1, "prefill_passes": 2,
        "prefill_pass_fallbacks": 1, "shadow_truncates": 1,
        "transfer_p50_ms": 2.5, "transfer_p99_ms": 4.0,
        "reconcile": {"measured": 6144.0, "modeled": 6144.0,
                      "unit": "bytes", "drift_frac": 0.0,
                      "drift": False}}}
    rk = analyze(events, [kvx_row], wire=True)["wire"]
    assert rk is not None and rk["kv_transfer"] is not None, rk
    assert rk["kv_transfer"]["fills_ok"] == 3, rk["kv_transfer"]
    assert rk["kv_transfer"]["fill_hit_rate"] == 0.75
    assert rk["kv_transfer"]["transfer_p50_ms"] == 2.5
    assert not rk["drift"], rk
    md_k = render_markdown({**rw, "wire": rk})
    assert "KV transfer" in md_k and "3/4 ok" in md_k, md_k
    kvx_drift = {"metric": "kvx2", "kv_transfer": {
        "fills_requested": 1, "fills_ok": 1, "fill_fallbacks": 0,
        "fill_misses": 0, "tokens_filled": 16, "blocks_filled": 1,
        "bytes_rx": 1300, "bytes_tx": 1300, "blocks_exported": 1,
        "queries_served": 1, "query_misses": 0, "prefill_passes": 0,
        "prefill_pass_fallbacks": 0, "shadow_truncates": 0,
        "reconcile": {"measured": 1300.0, "modeled": 1000.0,
                      "unit": "bytes", "drift_frac": 0.3,
                      "drift": True}}}
    rkd = analyze(events, [kvx_drift], wire=True)["wire"]
    assert rkd["drift"], rkd

    print("dlprof selftest: OK (knee=4, 3 spans, autotune drift check, "
          "wire section + sync share + drift flag, KV transfer section, "
          "report renders)")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dlprof", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--trace-dir", default=None,
                    help="server --trace-dir (rotating JSONL; worker "
                         "subdirs included)")
    ap.add_argument("--bench", action="append", default=[],
                    help="bench.py artifact JSON (repeatable)")
    ap.add_argument("--autotune", default=None, metavar="FILE",
                    help="AUTOTUNE.json calibration artifact "
                         "(tools/autotune.py): the report compares its "
                         "calibrated knee against the live measured one "
                         "and flags >= 25%% drift")
    ap.add_argument("--wire", action="store_true",
                    help="add the measured cluster-plane comms section: "
                         "per-peer bytes + RTT tails from bench rows' "
                         "`wire` blocks, device sync-vs-compute share "
                         "from sampled `sync` trace events, and every "
                         "measured-vs-modeled reconciliation (drift "
                         "flagged at >= 25%%)")
    ap.add_argument("--slo-ttft-ms", type=float, default=500.0)
    ap.add_argument("--slo-itl-ms", type=float, default=100.0)
    ap.add_argument("--out", default=None, metavar="PREFIX",
                    help="write PREFIX.json + PREFIX.md (default: JSON "
                         "to stdout)")
    ap.add_argument("--selftest", action="store_true",
                    help="synthesize inputs, assert the report parses "
                         "with a non-null knee (the CI smoke)")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.trace_dir and not args.bench:
        ap.error("need --trace-dir and/or --bench (or --selftest)")
    events = load_trace_dir(args.trace_dir) if args.trace_dir else []
    rows: list[dict] = []
    for b in args.bench:
        rows += load_bench(b)
    art = None
    if args.autotune:
        try:
            art = load_autotune(args.autotune)
        except (OSError, ValueError) as e:
            ap.error(f"--autotune {args.autotune}: {e}")
    report = analyze(events, rows, slo_ttft_ms=args.slo_ttft_ms,
                     slo_itl_ms=args.slo_itl_ms, autotune=art,
                     wire=args.wire)
    at = report.get("autotune")
    if at and at["drift"]:
        print(f"dlprof: ⚠️ knee drift {at['drift_frac']:.0%} — calibrated "
              f"{at['calibrated_knee_rows']} vs measured "
              f"{at['measured_knee_rows']} rows (re-run tools/autotune.py)",
              file=sys.stderr)
    w = report.get("wire")
    if w and w.get("drift"):
        print("dlprof: ⚠️ measured wire traffic drifted >= 25% from the "
              "model — see the report's wire.reconcile entries",
              file=sys.stderr)
    if args.out:
        with open(args.out + ".json", "w") as f:
            json.dump(report, f, indent=1)
        with open(args.out + ".md", "w") as f:
            f.write(render_markdown(report))
        print(f"dlprof: wrote {args.out}.json + {args.out}.md "
              f"({report['inputs']['spans']} spans, knee="
              f"{(report['step_curve']['knee'] or {}).get('knee_rows')})")
    else:
        json.dump(report, sys.stdout, indent=1)
        print()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
