"""Where does the 9.5 ms decode token actually go? (VERDICT r4 #2 scouting)

Traces 32 real 7B Q40 decode steps with jax.profiler and aggregates XLA op
time by (grouped) op name — separating the Q40 matmul kernels (VPU-bound
unpack floor) from attention, norms/elementwise fusions, and logits. The
unpack ceiling argument says the kernel floor is ~8.0 ms (3.79 GB packed
at ~475 GB/s); this measures how much of the remainder is addressable.

Result (v5e, 2026-07-31, fill 256, 32 steps): see artifacts/ or the
printed table. Usage: python tools/exp_decode_profile.py
"""

import sys

sys.path.insert(0, ".")

import collections
import dataclasses
import glob
import re
import tempfile

import jax
import jax.numpy as jnp

from bench import LLAMA2_7B, synth_q40_params, _measure_decode
from distributed_llama_tpu.runtime import Engine


def group(name: str) -> str:
    """Collapse op names into readable buckets."""
    n = name.lower()
    if "custom-call" in n or "mosaic" in n or "tpu_custom_call" in n:
        return "pallas-kernel"
    for key in ("fusion", "dynamic-update-slice", "copy", "convert",
                "reduce", "dot", "transpose", "broadcast", "iota"):
        if key in n:
            return key
    return name.split(".")[0][:32]


def main():
    n_steps = 32
    spec = dataclasses.replace(LLAMA2_7B, seq_len=2048)
    params = synth_q40_params(spec)
    eng = Engine(spec, params, compute_dtype=jnp.bfloat16,
                 cache_dtype=jnp.bfloat16)
    ms = _measure_decode(eng, n_steps, 0, 1)  # warm/compile
    print(f"warm decode: {ms:.3f} ms/token", flush=True)

    trace_dir = tempfile.mkdtemp(prefix="decprof-")
    with jax.profiler.trace(trace_dir):
        ms = _measure_decode(eng, n_steps, 256, 1)
    print(f"traced decode: {ms:.3f} ms/token", flush=True)

    from jax.profiler import ProfileData

    files = sorted(glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True))
    pd = ProfileData.from_file(files[-1])
    per_group = collections.Counter()
    per_op = collections.Counter()
    total = 0.0
    for plane in pd.planes:
        if not plane.name.startswith("/device:"):
            continue
        lines = {ln.name: ln for ln in plane.lines}
        for ln_name in ("XLA Ops", "Async XLA Ops"):
            ops = lines.get(ln_name)
            if ops is None:
                continue
            for e in ops.events:
                ms_e = e.duration_ns / 1e6
                per_group[group(e.name)] += ms_e
                per_op[e.name[:80]] += ms_e
                total += ms_e
    print(f"\ntotal device op time: {total:.1f} ms over {n_steps} steps "
          f"= {total / n_steps:.3f} ms/token busy")
    print("\nby group (ms/token):")
    for g, v in per_group.most_common(12):
        print(f"  {g:28s} {v / n_steps:7.3f}")
    print("\ntop ops (ms/token):")
    for g, v in per_op.most_common(15):
        print(f"  {g:78s} {v / n_steps:6.3f}")


if __name__ == "__main__":
    main()
