"""Experiment: 6-op decode unpack via the pk-substitution (VERDICT r4 #2).

The decode kernel's ~7 VPU ops/packed byte (widen, and, shr, cvt x2,
scale-mul x2) are its measured ceiling (~475 GB/s packed). Substituting
lo = pk - 16*hi into the contraction:

    y = x_lo·lo·s + x_hi·hi·s
      = x_lo·(pk·s) + (x_hi - 16·x_lo)·(hi·s)

drops the `& 0xF`: per byte the kernel now does widen, shr, cvt(pk),
cvt(hi), mul x2 = 6 ops, with the activation combination (x_hi - 16·x_lo)
hoisted OUTSIDE the kernel (t x M elementwise, free at t=1). The -8 offset
fold is unchanged.

Result (v5e, 2026-07-31): REJECTED, two independent ways.

1. Speed: FLAT. This standalone harness (DMA-bound, so only a relative
   signal): base 1.779 vs pk 1.778 ms (w1 shape), 1.745 vs 1.747 (attn
   shape) — 1.000x. The `& 0xF` co-issues with the loads/converts; it is
   not on the VPU critical path, so removing it buys nothing.
2. Precision: 6.4% relative error on the whole q40_matmul (whole-model
   A/B via DLLAMA_PK_DECODE=1 tripped its parity probe at 6.39e-2). The
   hoped-for "36x f32 rounding ~ 1e-5" was wrong because DEFAULT-precision
   dots pass f32 operands through the MXU as bf16: pk in [0,255] consumes
   the entire bf16 mantissa by itself, and the 16x cancellation amplifies
   that truncation to percent level. (HIGHEST-precision f32 dots would fix
   the error but are ~5x slower — pallas_q40.py module docstring.)

Conclusion: the 7-ops/byte decode unpack remains the measured design
ceiling; with the round-4 negatives (int8 MXU gemv 4x loss, bf16 VPU
arithmetic slower than f32, prefill-chunk ladder) every VERDICT r4 #2
candidate is now a recorded negative. (A pk_mode production knob was
briefly threaded through the kernel for the whole-model A/B and then
REMOVED — a wrong-output trapdoor has no place in the hot kernel; this
file is the record.)
"""

import sys
import time

sys.path.insert(0, ".")

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_llama_tpu.ops.pallas_q40 import _f16_bits_to_f32
from distributed_llama_tpu.quants.numpy_codec import quantize_q40


def _kernel(x1_ref, x2_ref, xs_ref, pk_ref, s_ref, o_ref, *, mode):
    pk = pk_ref[:].astype(jnp.int32)
    if mode == "base":
        lo = (pk & 0xF).astype(jnp.float32)
        hi = (pk >> 4).astype(jnp.float32)
    else:  # pk-substitution: x1 = x_lo, x2 = x_hi - 16*x_lo
        lo = pk.astype(jnp.float32)          # actually pk; paired with x1
        hi = (pk >> 4).astype(jnp.float32)
    s = _f16_bits_to_f32(s_ref[:].astype(jnp.int32))
    s16 = pltpu.repeat(s, 16, axis=1)
    dot = functools.partial(jax.lax.dot_general,
                            dimension_numbers=(((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    acc = dot(x1_ref[:], lo * s16)
    acc += dot(x2_ref[:], hi * s16)
    acc += dot(xs_ref[:], s) * -8.0
    o_ref[:] = acc


def build(mode, d, m, td):
    nb = m // 16

    @jax.jit
    def run(x1, x2, xs, pk, s):
        return pl.pallas_call(
            functools.partial(_kernel, mode=mode),
            grid=(d // td,),
            in_specs=[
                pl.BlockSpec((1, m), lambda i: (0, 0)),
                pl.BlockSpec((1, m), lambda i: (0, 0)),
                pl.BlockSpec((1, nb), lambda i: (0, 0)),
                pl.BlockSpec((td, m), lambda i: (i, 0)),
                pl.BlockSpec((td, nb), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((1, td), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((1, d), jnp.float32),
        )(x1, x2, xs, pk, s)

    return run


def main():
    rng = np.random.default_rng(0)
    for name, d, n, td in (("w1", 22016, 4096, 256),
                           ("attn", 4096, 4096, 1024)):
        m, nb = n // 2, n // 32
        w = rng.standard_normal((d, n)).astype(np.float32) * 0.05
        scales, packed = quantize_q40(w)
        # lane order m = j*nb + b (jax_codec layout)
        pk = np.asarray(packed).reshape(d, nb, 16).transpose(0, 2, 1).reshape(d, m)
        su = np.asarray(scales).view(np.uint16).reshape(d, nb)
        x = rng.standard_normal((1, n)).astype(np.float32)
        xr = x.reshape(nb, 32)
        x_lo = xr[:, :16].T.reshape(1, m)   # x_lo[j*nb+b] = x[b*32+j]
        x_hi = xr[:, 16:].T.reshape(1, m)
        xs = xr.sum(axis=1).reshape(1, nb)

        a_pk = jnp.asarray(pk)
        a_s = jnp.asarray(su)
        args_base = (jnp.asarray(x_lo), jnp.asarray(x_hi), jnp.asarray(xs),
                     a_pk, a_s)
        args_pk = (jnp.asarray(x_lo), jnp.asarray(x_hi - 16.0 * x_lo),
                   jnp.asarray(xs), a_pk, a_s)
        fns = {"base": (build("base", d, m, td), args_base),
               "pk": (build("pk", d, m, td), args_pk)}

        outs = {k: np.asarray(f(*a)) for k, (f, a) in fns.items()}
        ref = x @ w.T  # true f32 matmul on the QUANTIZED values
        err = np.abs(outs["pk"] - outs["base"]).max() / (
            np.abs(outs["base"]).max() + 1e-9)
        best = {}
        iters = 64
        for r in range(6):
            for k, (f, a) in fns.items():
                t0 = time.perf_counter()
                for _ in range(iters):
                    o = f(*a)
                np.asarray(o)
                dt = (time.perf_counter() - t0) / iters * 1e3
                best[k] = dt if k not in best else min(best[k], dt)
        print(f"{name}: base {best['base']:.3f} ms  pk {best['pk']:.3f} ms  "
              f"-> {best['base'] / best['pk']:.3f}x  max-rel-err {err:.2e}")


if __name__ == "__main__":
    main()
