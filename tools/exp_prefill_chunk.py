"""Prefill chunk-size ladder: fused-kernel 256-chunks vs larger XLA-dequant
segments on the real chip.

The engine prefers prefill_chunk=256 (the Pallas MAX_T); segments above that
take the XLA dequant path, which re-materializes bf16 weights per matmul but
amortizes over more tokens. This measures tokens/sec for a 2048-token prompt
at several chunk sizes to find the crossover (if any).

Usage: python tools/exp_prefill_chunk.py [7b|tiny]

Measured (v5e, 7B Q40, 2048-token prompt): 256-token fused chunks win by
>2x — 128: 2196 tok/s, 256: 5771, 512: 2600, 1024: 1762, 2048: 2461 —
the XLA dequant path never catches up even with the whole prompt in one
segment, and 256 is also the kernel's VMEM ceiling for its (t, m) f32
activation blocks. The engine default stands confirmed.

Re-measured (round 4) after the unpack/MXU sub-tile interleave landed in
the kernel (ops/pallas_q40._n_sub): 128: 4650 tok/s, 256: 6317, 512: 3337,
1024: 4056, 2048: 4461 — chunk 256 still the winner, now +9.5% whole-model
over the round-3 kernel (6317 vs 5771).
"""

from __future__ import annotations

import sys
import time

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

import bench
from distributed_llama_tpu.runtime.engine import Engine

PROMPT_LEN = 2048


def run(model: str) -> None:
    spec = bench.LLAMA2_7B if model == "7b" else bench.TINY
    params = bench.synth_q40_params(spec)
    tokens = np.ones((1, PROMPT_LEN), np.int32)

    for chunk in (128, 256, 512, 1024, 2048):
        engine = Engine(spec, params, compute_dtype=jnp.bfloat16,
                        cache_dtype=jnp.bfloat16, max_seq_len=PROMPT_LEN,
                        prefill_chunk=chunk)
        best = 1e9
        for rep in range(3):
            engine.reset()
            t0 = time.perf_counter()
            logits = engine.prefill(list(tokens[0]))
            np.asarray(logits)  # D2H sync (block_until_ready lies on axon)
            dt = time.perf_counter() - t0
            if rep:  # rep 0 compiles
                best = min(best, dt)
        print(f"chunk={chunk:5d}: {PROMPT_LEN / best:8.1f} tok/s "
              f"({best * 1e3:7.1f} ms)", flush=True)


if __name__ == "__main__":
    run(sys.argv[1] if len(sys.argv) > 1 else "7b")
