#!/bin/sh
# Multi-device demo on N virtual CPU devices — the analogue of the
# reference's examples/n-workers.sh (which screen-launches N worker
# processes); here the "cluster" is one SPMD program over an N-device mesh.
#
# Usage: N=8 ./examples/n-devices.sh
set -e
cd "$(dirname "$0")/.."
N="${N:-8}"
JAX_PLATFORMS=cpu python - <<EOF
import __graft_entry__ as g
g.dryrun_multichip($N)
print("✅ dp x tp batched generation, sp ring prefill + sp-cache decode,")
print("   q80-collective TP, shard_map Pallas kernels, ep expert placement")
print("   and pp pipeline stages all ran on a $N-device mesh")
EOF
