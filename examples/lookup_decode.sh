#!/bin/sh
# Prompt-lookup speculative decoding demo: the same greedy request run
# plain and with --lookup-decode must print the same text, while the
# speculative run reports its tokens/forward acceptance (net-new — the
# reference generates strictly one token per forward).
# Uses the test fixture model; swap --model/--tokenizer for a real one.
set -e
cd "$(dirname "$0")/.."
TMP=$(mktemp -d)
trap 'rm -rf "$TMP"' EXIT
python - "$TMP" <<'EOF'
import pathlib, sys
from distributed_llama_tpu.testing import write_fixture
write_fixture(pathlib.Path(sys.argv[1]), seed=5, seq_len=192)
EOF
echo "=== plain greedy ==="
python -m distributed_llama_tpu.apps.dllama inference \
    --model "$TMP/model.m" --tokenizer "$TMP/tok.t" \
    --prompt "abab" --steps 12 --temperature 0 --seed 7
echo "=== speculative (--lookup-decode 5) ==="
python -m distributed_llama_tpu.apps.dllama inference \
    --model "$TMP/model.m" --tokenizer "$TMP/tok.t" \
    --prompt "abab" --steps 12 --temperature 0 --seed 7 --lookup-decode 5
