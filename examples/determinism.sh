#!/bin/sh
# Fixed-seed end-to-end determinism check — the reference's examples/
# macbeth.sh (fixed seed/temp/topp, transcript comparison), using the
# pinned-token-sequence test fixture instead of a 4 GB model download.
# Exits nonzero if the generated sequence diverges from the stored golden.
set -e
cd "$(dirname "$0")/.."
python -m pytest tests/test_determinism.py -q
