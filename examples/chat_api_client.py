#!/usr/bin/env python3
"""Minimal client for the dllama api server — the counterpart of the
reference's examples/chat-api-client.js (same two-question demo against
/v1/chat/completions), stdlib-only, plus an SSE streaming variant.

Usage:
  1. Start the server:  python -m distributed_llama_tpu.apps.dllama api \
         --model model.m --tokenizer tok.t --port 9990
  2. Run this script:   python examples/chat_api_client.py
     (HOST/PORT env vars override the default 127.0.0.1:9990)
"""

from __future__ import annotations

import http.client
import json
import os

HOST = os.environ.get("HOST", "127.0.0.1")
PORT = int(os.environ.get("PORT", "9990"))


def chat(messages, max_tokens: int, stream: bool = False):
    conn = http.client.HTTPConnection(HOST, PORT, timeout=600)
    conn.request("POST", "/v1/chat/completions", json.dumps({
        "messages": messages,
        "temperature": 0.7,
        "stop": ["<|eot_id|>"],
        "max_tokens": max_tokens,
        "stream": stream,
    }), {"Content-Type": "application/json"})
    resp = conn.getresponse()
    if not stream:
        out = json.loads(resp.read())
        conn.close()
        return out
    # SSE: one "data: {...}" chunk per piece, terminated by "data: [DONE]"
    for raw in resp:
        line = raw.decode().strip()
        if not line.startswith("data: "):
            continue
        payload = line[len("data: "):]
        if payload == "[DONE]":
            break
        delta = json.loads(payload)["choices"][0]["delta"]
        if "content" in delta:
            print(delta["content"], end="", flush=True)
    print()
    conn.close()


def ask(system: str, user: str, max_tokens: int) -> None:
    print(f"> system: {system}")
    print(f"> user: {user}")
    resp = chat([
        {"role": "system", "content": system},
        {"role": "user", "content": user},
    ], max_tokens)
    print(resp["choices"][0]["message"]["content"])
    usage = resp["usage"]
    print(f"({usage['prompt_tokens']} prompt + "
          f"{usage['completion_tokens']} completion tokens)\n")


if __name__ == "__main__":
    ask("You are an excellent math teacher.", "What is 1 + 2?", 128)
    ask("You are a weather forecaster.",
        "What is the weather like in Tokyo?", 128)
    print("> streaming:")
    chat([{"role": "user", "content": "Count to five."}], 64, stream=True)
