#!/bin/sh
# Local multi-process cluster demo — the direct analogue of the reference's
# examples/n-workers.sh (which screen-launches N worker processes on ports
# 9999-w for the root to dial). Here rank 0 is the root and ranks 1..N-1 run
# `dllama worker`, all joined through a jax.distributed coordinator into ONE
# global mesh (1 virtual CPU device per process). On real hosts, run the
# same commands on each machine with a reachable --coordinator address.
#
# Usage: N=2 ./examples/cluster.sh
set -e
cd "$(dirname "$0")/.."
N="${N:-2}"
PORT="${PORT:-12765}"

# tiny fixture model + tokenizer (the test suite's shared fixture writer);
# on exit, kill any still-running workers before removing their model file
TMP="$(mktemp -d)"
trap 'for p in $(jobs -p); do kill "$p" 2>/dev/null || true; done; rm -rf "$TMP"' EXIT
python - "$TMP" <<'EOF'
import sys

import jax

jax.config.update("jax_platforms", "cpu")  # don't touch a TPU for file IO

from distributed_llama_tpu.testing import write_fixture

write_fixture(sys.argv[1], seed=7)
EOF

RUN="import jax; jax.config.update('jax_platforms','cpu'); \
import sys; from distributed_llama_tpu.apps.dllama import main; \
main(sys.argv[1:])"
COMMON="--model $TMP/model.m --tokenizer $TMP/tok.t \
  --nnodes $N --coordinator 127.0.0.1:$PORT --temperature 0 --seed 7"
export XLA_FLAGS=--xla_force_host_platform_device_count=1

r=1
while [ "$r" -lt "$N" ]; do
  python -c "$RUN" worker $COMMON --node-rank "$r" &
  r=$((r + 1))
done
python -c "$RUN" inference $COMMON --node-rank 0 --prompt "Hello" --steps 8
wait
echo "✅ $N-process cluster: root + $((N - 1)) worker(s) generated in lock-step"
