// Native host-side components: BPE tokenizer + sampler.
//
// C++ twins of the Python implementations in distributed_llama_tpu/
// tokenizer.py and sampler.py, behavior-equivalent to the reference's
// tokenizer/sampler (ref: src/tokenizer.cpp:109-229 encode, 89-100 decode,
// 231-364 sampler; RNG ref: src/utils.cpp:53-64). Exposed as a C ABI
// consumed via ctypes (distributed_llama_tpu/native.py); the Python
// versions remain the correctness oracle and fallback.
//
// Build: make -C native   (produces libdllama_native.so)

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- tokenizer

struct Tokenizer {
    std::vector<std::string> vocab;
    std::vector<float> scores;
    std::unordered_map<std::string, int32_t> index;  // first occurrence wins
    int32_t bos_id;
    int32_t eos_id;
};

void* dllama_tok_create(int32_t n, const uint8_t* pieces,
                        const int32_t* piece_lens, const float* scores,
                        int32_t bos_id, int32_t eos_id) {
    Tokenizer* t = new Tokenizer();
    t->bos_id = bos_id;
    t->eos_id = eos_id;
    t->vocab.reserve(n);
    t->scores.assign(scores, scores + n);
    size_t off = 0;
    for (int32_t i = 0; i < n; i++) {
        t->vocab.emplace_back(reinterpret_cast<const char*>(pieces) + off,
                              (size_t)piece_lens[i]);
        off += piece_lens[i];
        t->index.emplace(t->vocab.back(), i);  // emplace keeps the first id
    }
    return t;
}

void dllama_tok_free(void* h) { delete static_cast<Tokenizer*>(h); }

static int32_t lookup(const Tokenizer* t, const std::string& s) {
    auto it = t->index.find(s);
    return it == t->index.end() ? -1 : it->second;
}

// Encode `text` (UTF-8, text_len bytes) into out[max_out]; returns the token
// count, or -1 if out is too small. Mirrors tokenizer.py:encode.
int32_t dllama_tok_encode(void* h, const uint8_t* text, int32_t text_len,
                          int32_t add_bos, int32_t add_eos,
                          int32_t* out, int32_t max_out) {
    const Tokenizer* t = static_cast<Tokenizer*>(h);
    std::vector<int32_t> toks;
    if (add_bos) toks.push_back(t->bos_id);
    if (text_len > 0) {
        // dummy space prefix (ref: src/tokenizer.cpp:140-144)
        int32_t space = lookup(t, " ");
        if (space >= 0) toks.push_back(space);
    }
    // codepoint scan with byte fallback at +3 (ref: src/tokenizer.cpp:155-192)
    int32_t i = 0;
    const int32_t nv = (int32_t)t->vocab.size();
    while (i < text_len) {
        int32_t j = i + 1;
        while (j < text_len && (text[j] & 0xC0) == 0x80 && (j - i) < 4) j++;
        std::string piece(reinterpret_cast<const char*>(text) + i, (size_t)(j - i));
        int32_t tid = lookup(t, piece);
        if (tid >= 0) {
            toks.push_back(tid);
        } else {
            for (int32_t b = i; b < j; b++)
                toks.push_back(text[b] + 3 < nv ? text[b] + 3 : 0);
        }
        i = j;
    }
    // greedy highest-score adjacent-pair merge (ref: src/tokenizer.cpp:195-223)
    while (true) {
        float best_score = -1e10f;
        int32_t best_id = -1, best_idx = -1;
        for (size_t k = 0; k + 1 < toks.size(); k++) {
            std::string merged = t->vocab[toks[k]] + t->vocab[toks[k + 1]];
            int32_t mid = lookup(t, merged);
            if (mid >= 0 && t->scores[mid] > best_score) {
                best_score = t->scores[mid];
                best_id = mid;
                best_idx = (int32_t)k;
            }
        }
        if (best_idx < 0) break;
        toks[best_idx] = best_id;
        toks.erase(toks.begin() + best_idx + 1);
    }
    if (add_eos) toks.push_back(t->eos_id);
    if ((int32_t)toks.size() > max_out) return -1;
    std::memcpy(out, toks.data(), toks.size() * sizeof(int32_t));
    return (int32_t)toks.size();
}

// Decode one piece given the previous token; returns byte length written.
// Mirrors tokenizer.py:decode_piece (ref: src/tokenizer.cpp:89-100).
int32_t dllama_tok_decode_piece(void* h, int32_t prev, int32_t tok,
                                uint8_t* out, int32_t max_out) {
    const Tokenizer* t = static_cast<Tokenizer*>(h);
    if (tok < 0 || tok >= (int32_t)t->vocab.size()) return 0;
    const std::string& p = t->vocab[tok];
    const char* s = p.data();
    size_t len = p.size();
    if (prev == t->bos_id && len > 0 && s[0] == ' ') { s++; len--; }
    // raw-byte pieces: "<0xAB>"
    if (len == 6 && s[0] == '<' && s[1] == '0' && s[2] == 'x' && s[5] == '>') {
        auto hex = [](char c) -> int {
            if (c >= '0' && c <= '9') return c - '0';
            if (c >= 'A' && c <= 'F') return c - 'A' + 10;
            if (c >= 'a' && c <= 'f') return c - 'a' + 10;
            return -1;
        };
        int hi = hex(s[3]), lo = hex(s[4]);
        if (hi >= 0 && lo >= 0) {
            if (max_out < 1) return -1;
            out[0] = (uint8_t)(hi * 16 + lo);
            return 1;
        }
    }
    if ((int32_t)len > max_out) return -1;
    std::memcpy(out, s, len);
    return (int32_t)len;
}

// ------------------------------------------------------------------ sampler

struct Sampler {
    int32_t vocab_size;
    float temperature;
    float topp;
    uint64_t state;
};

// xorshift* (ref: src/utils.cpp:53-64) — bit-exact with utils/rng.py
static uint32_t rand_u32(uint64_t* s) {
    *s ^= *s >> 12;
    *s ^= *s << 25;
    *s ^= *s >> 27;
    return (uint32_t)((*s * 0x2545F4914F6CDD1DULL) >> 32);
}
static float rand_f32(uint64_t* s) {
    return (float)(rand_u32(s) >> 8) / 16777216.0f;
}

void* dllama_sampler_create(int32_t vocab_size, float temperature, float topp,
                            uint64_t seed) {
    Sampler* sp = new Sampler{vocab_size, temperature, topp, seed};
    return sp;
}
void dllama_sampler_free(void* h) { delete static_cast<Sampler*>(h); }
void dllama_sampler_set_temp(void* h, float t) {
    static_cast<Sampler*>(h)->temperature = t;
}
void dllama_sampler_set_seed(void* h, uint64_t seed) {
    static_cast<Sampler*>(h)->state = seed;
}
uint64_t dllama_sampler_get_state(void* h) {
    return static_cast<Sampler*>(h)->state;
}
void dllama_sampler_set_state(void* h, uint64_t state) {
    static_cast<Sampler*>(h)->state = state;
}

// Greedy / temperature multinomial / top-p nucleus over `logits[0..n)`
// (ref: src/tokenizer.cpp:231-364). logits is scratch (not preserved);
// n is the buffer's actual length (may be < vocab_size — never read past).
int32_t dllama_sampler_sample(void* h, float* logits, int32_t n) {
    Sampler* sp = static_cast<Sampler*>(h);
    if (n > sp->vocab_size) n = sp->vocab_size;
    if (n <= 0) return 0;
    if (sp->temperature == 0.0f) {
        int32_t best = 0;
        for (int32_t i = 1; i < n; i++)
            if (logits[i] > logits[best]) best = i;
        return best;
    }
    // softmax with max-subtraction (ref: src/funcs.cpp:63-92) — same
    // operation order as sampler.py (divide, max, exp, normalize) so the
    // two implementations agree to float rounding
    for (int32_t i = 0; i < n; i++) logits[i] /= sp->temperature;
    float maxv = logits[0];
    for (int32_t i = 1; i < n; i++) maxv = std::max(maxv, logits[i]);
    double sum = 0.0;
    for (int32_t i = 0; i < n; i++) {
        logits[i] = std::exp(logits[i] - maxv);
        sum += logits[i];
    }
    for (int32_t i = 0; i < n; i++) logits[i] = (float)(logits[i] / sum);

    float coin = rand_f32(&sp->state);
    if (sp->topp <= 0.0f || sp->topp >= 1.0f) {
        double cdf = 0.0;
        for (int32_t i = 0; i < n; i++) {
            cdf += logits[i];
            if ((double)coin < cdf) return i;
        }
        return n - 1;
    }
    // top-p: cutoff pre-filter, stable sort descending, truncate, sample
    const float cutoff = (1.0f - sp->topp) / (float)(n - 1);
    std::vector<int32_t> cand;
    cand.reserve(256);
    for (int32_t i = 0; i < n; i++)
        if (logits[i] >= cutoff) cand.push_back(i);
    if (cand.empty()) {
        // near-uniform probs with topp < 1/n can leave no candidate; keep
        // the (first) argmax so the nucleus is never empty — same fallback
        // as the Python sampler and the device twin
        int32_t am = 0;
        for (int32_t i = 1; i < n; i++)
            if (logits[i] > logits[am]) am = i;
        cand.push_back(am);
    }
    std::stable_sort(cand.begin(), cand.end(), [&](int32_t a, int32_t b) {
        return logits[a] > logits[b];
    });
    double cum = 0.0;
    size_t last = cand.size() - 1;
    for (size_t k = 0; k < cand.size(); k++) {
        cum += logits[cand[k]];
        if (cum > (double)sp->topp) { last = k; break; }
    }
    double total = 0.0;
    for (size_t k = 0; k <= last; k++) total += logits[cand[k]];
    double r = (double)coin * total;
    double acc = 0.0;
    for (size_t k = 0; k <= last; k++) {
        acc += logits[cand[k]];
        if (r < acc) return cand[k];
    }
    return cand[last];
}

// Bulk sequential xorshift* f32 stream (raw <0,1) values, no scaling —
// callers apply the reference tests' `/ 120.0` as a float64 divide to
// match C's double-then-narrow arithmetic). The reference's golden block
// tests seed hundreds of MB of weights from this stream
// (ref: src/llama2-tasks-test.cpp:555-569); a Python-loop xorshift at that
// scale is minutes, this is ~1 s. Returns the advanced state.
uint64_t dllama_rng_fill_f32(uint64_t state, float* out, int64_t n) {
    for (int64_t i = 0; i < n; i++) out[i] = rand_f32(&state);
    return state;
}

}  // extern "C"
