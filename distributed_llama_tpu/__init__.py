"""distributed_llama_tpu — a TPU-native tensor-parallel LLM inference framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of the reference
CPU cluster engine `distributed-llama` (see /root/reference): Llama-2/3,
Mixtral 8x7B and Grok-1 inference with Q40 (4-bit) weights and Q80 (int8)
quantized activation exchange, tensor-parallel over a `jax.sharding.Mesh`
instead of root/worker TCP nodes.

Layer map (mirrors SURVEY.md §1, re-architected for TPU):

  quants/    Q40/Q80 block codecs (host numpy + device jnp)       [ref L1]
  ops/       rmsnorm, rope, attention, activations, matmul paths  [ref L2]
  parallel/  mesh, partition specs, quantized collectives         [ref L3/L4]
  models/    llama / mixtral / grok-1 forward definitions         [ref L5/L6]
  io/        .m model-file and .t tokenizer-file formats          [ref L5/L9]
  runtime/   KV cache, inference engine, stats                    [ref L4/L7]
  utils/     xorshift RNG parity, misc                            [ref L0]
  server/    OpenAI-compatible HTTP API                           [ref L8]
  tokenizer  BPE encode/decode, sampler                           [ref L7]
"""

__version__ = "0.1.0"
