"""ctypes binding for the native C++ tokenizer/sampler (native/).

The shared library is optional: `available()` is False when it has not been
built (`make -C native`), and the pure-Python implementations in
tokenizer.py / sampler.py — the correctness oracles the native code is
tested against — are used instead. The reference ships these components as
C++ (ref: src/tokenizer.cpp), so the native build restores that layering
for host-side hot paths (prompt encoding, per-token sampling).
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB_PATHS = (
    os.path.join(os.path.dirname(__file__), "..", "native",
                 "libdllama_native.so"),
    "libdllama_native.so",
)

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    for p in _LIB_PATHS:
        try:
            lib = ctypes.CDLL(p)
        except OSError:
            continue
        lib.dllama_tok_create.restype = ctypes.c_void_p
        lib.dllama_tok_create.argtypes = [
            ctypes.c_int32, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int32, ctypes.c_int32]
        lib.dllama_tok_free.argtypes = [ctypes.c_void_p]
        lib.dllama_tok_encode.restype = ctypes.c_int32
        lib.dllama_tok_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
        lib.dllama_tok_decode_piece.restype = ctypes.c_int32
        lib.dllama_tok_decode_piece.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int32]
        lib.dllama_sampler_create.restype = ctypes.c_void_p
        lib.dllama_sampler_create.argtypes = [
            ctypes.c_int32, ctypes.c_float, ctypes.c_float, ctypes.c_uint64]
        lib.dllama_sampler_free.argtypes = [ctypes.c_void_p]
        lib.dllama_sampler_set_temp.argtypes = [ctypes.c_void_p, ctypes.c_float]
        lib.dllama_sampler_set_seed.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.dllama_sampler_get_state.restype = ctypes.c_uint64
        lib.dllama_sampler_get_state.argtypes = [ctypes.c_void_p]
        lib.dllama_sampler_set_state.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.dllama_sampler_sample.restype = ctypes.c_int32
        lib.dllama_sampler_sample.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int32]
        if hasattr(lib, "dllama_rng_fill_f32"):  # older .so builds lack it
            lib.dllama_rng_fill_f32.restype = ctypes.c_uint64
            lib.dllama_rng_fill_f32.argtypes = [
                ctypes.c_uint64, ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64]
        _lib = lib
        return lib
    return None


def available() -> bool:
    return _load() is not None


def rng_fill_f32(state: int, n: int) -> tuple[int, np.ndarray]:
    """n sequential xorshift* f32 draws (raw <0,1) stream, no scaling) as a
    float32 array, plus the advanced state — the bulk form of
    utils.rng.xorshift_f32 for golden-fixture weight generation
    (tests/test_reference_golden.py seeds ~200M weights this way)."""
    lib = _load()
    if lib is None or not hasattr(lib, "dllama_rng_fill_f32"):
        raise RuntimeError("native library not built (make -C native)")
    out = np.empty(n, np.float32)
    new_state = lib.dllama_rng_fill_f32(
        state & ((1 << 64) - 1),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n)
    return int(new_state), out


class NativeTokenizer:
    """C++ tokenizer backend; drop-in for Tokenizer's encode/decode_piece."""

    def __init__(self, vocab: list[bytes], scores: list[float],
                 bos_id: int, eos_id: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library not built (make -C native)")
        self._lib = lib
        blob = b"".join(vocab)
        lens = (ctypes.c_int32 * len(vocab))(*[len(v) for v in vocab])
        sc = (ctypes.c_float * len(scores))(*scores)
        self._h = lib.dllama_tok_create(len(vocab), blob, lens, sc,
                                        bos_id, eos_id)
        # one reusable piece buffer sized to the longest piece — decode is
        # called per generated token
        self._piece_cap = max((len(v) for v in vocab), default=16) + 1
        self._piece_buf = ctypes.create_string_buffer(self._piece_cap)

    def __del__(self):
        if getattr(self, "_h", None) and self._lib is not None:
            self._lib.dllama_tok_free(self._h)
            self._h = None

    def encode(self, text: str, add_bos: bool = True,
               add_eos: bool = False) -> list[int]:
        raw = text.encode("utf-8")
        cap = len(raw) + 3
        out = (ctypes.c_int32 * cap)()
        n = self._lib.dllama_tok_encode(self._h, raw, len(raw),
                                        int(add_bos), int(add_eos), out, cap)
        assert n >= 0
        return list(out[:n])

    def decode_piece(self, prev_token: int, token: int) -> bytes:
        buf = self._piece_buf
        n = self._lib.dllama_tok_decode_piece(
            self._h, prev_token, token,
            ctypes.cast(buf, ctypes.c_char_p), self._piece_cap)
        assert n >= 0
        return buf.raw[:n]


class NativeSampler:
    """C++ sampler backend with the shared xorshift state exposed so the
    Python Sampler API (rng_state save/restore) keeps working."""

    def __init__(self, vocab_size: int, temperature: float, topp: float,
                 seed: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library not built (make -C native)")
        self._lib = lib
        self.vocab_size = vocab_size
        self.temperature = float(temperature)
        self.topp = float(topp)
        self._h = lib.dllama_sampler_create(
            vocab_size, temperature, topp, seed & ((1 << 64) - 1))

    def __del__(self):
        if getattr(self, "_h", None) and self._lib is not None:
            self._lib.dllama_sampler_free(self._h)
            self._h = None

    @property
    def rng_state(self) -> int:
        return int(self._lib.dllama_sampler_get_state(self._h))

    @rng_state.setter
    def rng_state(self, v: int) -> None:
        self._lib.dllama_sampler_set_state(self._h, v & ((1 << 64) - 1))

    def set_temp(self, temperature: float) -> None:
        self.temperature = float(temperature)
        self._lib.dllama_sampler_set_temp(self._h, temperature)

    def set_seed(self, seed: int) -> None:
        self._lib.dllama_sampler_set_seed(self._h, seed & ((1 << 64) - 1))

    def sample(self, logits: np.ndarray) -> int:
        # always copy: the C sampler scribbles softmax into the buffer, and
        # the caller may hand us a read-only zero-copy view of a jax array
        x = np.array(np.asarray(logits).reshape(-1)[: self.vocab_size],
                     dtype=np.float32)
        return int(self._lib.dllama_sampler_sample(
            self._h, x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            x.size))
