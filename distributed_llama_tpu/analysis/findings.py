"""Finding model, suppression comments, baseline, and output formats.

This module is JAX-free on purpose: Level 1 (the AST lint) must run in a
bare Python environment — a CI annotation job or a pre-commit hook should
not pay a jaxlib import (or require one at all). Everything that needs to
trace lives in jaxpr_audit.py and is imported lazily by the CLI.

A `Finding` is one diagnostic: a rule id (DLG1xx = AST lint, DLG2xx =
jaxpr audit), a severity, a file:line anchor, and a message. The baseline
file (analysis/baseline.json) allowlists ACCEPTED findings — deliberate
host-device boundary syncs (sampler output, stats lines) and the current
entry-point signature fingerprints — so the CI gate fails only on
regressions, never on the accepted steady state.

Baseline keys deliberately omit the line number: an unrelated edit that
shifts a deliberate sync down three lines must not break CI. The key is
(rule, file, message); messages are written to be stable per-site (they
name the offending call/variable, not positions). Identical keys are
COUNTED, not deduplicated: two accepted `int(n)` syncs in engine.py are
two baseline entries, and a third occurrence of the same message is a new
finding — without counts, one allowlisted sync would mask any number of
reintroduced copies.
"""

from __future__ import annotations

import dataclasses
import json
import re

SEVERITIES = ("error", "warning", "info")

# inline suppression: `# dlgrind: ignore[DLG101]`, `ignore[DLG101,DLG203]`,
# or a bare `# dlgrind: ignore` (suppresses every rule on that line).
# The dlrace (DLG3xx) family reuses the same syntax under its own marker:
# `# dlrace: ignore[DLG305]` — one mechanism, two spellings, so a lock-
# discipline suppression reads as what it is.
_IGNORE_RE = re.compile(
    r"#\s*dl(?:grind|race):\s*ignore(?:\[(?P<rules>[A-Z0-9,\s]+)\])?")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str                # "DLG101"
    severity: str            # error | warning | info
    file: str                # repo-relative posix path, or "<entry:NAME>"
    line: int                # 1-based; 0 for whole-entry-point findings
    message: str

    def key(self) -> str:
        """Stable baseline key (no line number — see module docstring)."""
        return f"{self.rule}|{self.file}|{self.message}"

    def anchor(self) -> str:
        return f"{self.file}:{self.line}" if self.line else self.file


def parse_suppressions(source: str) -> dict[int, set[str] | None]:
    """Map 1-based line number -> suppressed rule ids (None = all rules).

    Inline suppression is an AST-lint (DLG1xx) mechanism: those findings
    anchor to a source line the comment can sit on. Jaxpr-audit findings
    (DLG2xx) describe a whole traced entry point (`<entry:NAME>`, line 0)
    — accepted ones go in the baseline instead.
    """
    out: dict[int, set[str] | None] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _IGNORE_RE.search(text)
        if not m:
            continue
        rules = m.group("rules")
        if rules is None:
            out[i] = None
        else:
            ids = {r.strip() for r in rules.split(",") if r.strip()}
            out[i] = ids or None
    return out


def is_suppressed(f: Finding, supp: dict[int, set[str] | None]) -> bool:
    rules = supp.get(f.line, "missing")
    if rules == "missing":
        return False
    return rules is None or f.rule in rules


# -- baseline ---------------------------------------------------------------


def load_baseline(path: str) -> dict:
    """{"findings": [key, ...], "fingerprints": {entry: hex},
    "justifications": {key: one-line reason}} (all optional in the file;
    absent file = empty baseline, i.e. everything is new). Duplicate keys
    in "findings" are meaningful — one entry per accepted site (see
    module docstring). Every distinct findings key must carry a
    justification: an allowlist entry is a reviewed decision, and the
    baseline is where the decision's one-line rationale lives."""
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except FileNotFoundError:
        return {"findings": [], "fingerprints": {}, "justifications": {}}
    return {"findings": list(raw.get("findings", [])),
            "fingerprints": dict(raw.get("fingerprints", {})),
            "justifications": dict(raw.get("justifications", {}))}


def write_baseline(path: str, findings: list[Finding],
                   fingerprints: dict[str, str],
                   justifications: dict[str, str] | None = None) -> None:
    keys = sorted(f.key() for f in findings)  # one entry PER SITE
    just = justifications or {}
    data = {
        "findings": keys,
        "fingerprints": dict(sorted(fingerprints.items())),
        # carry forward only justifications for keys that still exist;
        # keys without one get an explicit TODO so the gap is visible in
        # review instead of silently absent
        "justifications": {k: just.get(k, "TODO: justify this entry")
                           for k in sorted(set(keys))},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def unjustified_keys(baseline: dict) -> list[str]:
    """Distinct baseline findings keys with no (or placeholder) one-line
    justification — the gate treats these as findings (DLG109)."""
    just = baseline.get("justifications", {})
    out = []
    for key in sorted(set(baseline.get("findings", []))):
        reason = str(just.get(key, "")).strip()
        if not reason or reason.startswith("TODO"):
            out.append(key)
    return out


def split_by_baseline(
    findings: list[Finding], baseline: dict,
) -> tuple[list[Finding], list[Finding]]:
    """(new, accepted). Multiset semantics: a key appearing N times in the
    baseline accepts at most N findings with that key — occurrence N+1 is
    new (a reintroduced copy of an allowlisted sync must not ride along)."""
    from collections import Counter

    budget = Counter(baseline.get("findings", []))
    new, accepted = [], []
    for f in findings:
        if budget[f.key()] > 0:
            budget[f.key()] -= 1
            accepted.append(f)
        else:
            new.append(f)
    return new, accepted


# -- output formats ---------------------------------------------------------

_SEV_ORDER = {s: i for i, s in enumerate(SEVERITIES)}


def sort_findings(findings: list[Finding]) -> list[Finding]:
    return sorted(findings, key=lambda f: (_SEV_ORDER.get(f.severity, 9),
                                           f.file, f.line, f.rule))


def format_text(findings: list[Finding], *, accepted: int = 0) -> str:
    lines = [f"{f.anchor()}: {f.severity} {f.rule}: {f.message}"
             for f in sort_findings(findings)]
    lines.append(f"{len(findings)} finding(s)"
                 + (f", {accepted} baselined" if accepted else ""))
    return "\n".join(lines)


def format_github(findings: list[Finding]) -> str:
    """GitHub Actions annotation syntax — findings render inline on PRs."""
    out = []
    for f in sort_findings(findings):
        level = "error" if f.severity == "error" else "warning"
        # '<entry:...>' pseudo-files carry no annotatable path; anchor the
        # annotation to the baseline file so it still surfaces on the PR
        file = f.file if not f.file.startswith("<") else (
            "distributed_llama_tpu/analysis/baseline.json")
        line = max(f.line, 1)
        msg = f"{f.rule}: {f.message}".replace("%", "%25").replace(
            "\n", "%0A")
        out.append(f"::{level} file={file},line={line}::{msg}")
    return "\n".join(out)


def format_json(findings: list[Finding]) -> str:
    return json.dumps([dataclasses.asdict(f) for f in sort_findings(findings)],
                      indent=2)
