"""Level 2: trace the public jitted entry points and audit their jaxprs.

The AST lint (Level 1) sees what the source SAYS; this level sees what the
tracer actually BUILT. Each entry point from entrypoints.py is traced with
tiny abstract-friendly inputs (no XLA compile) and its jaxpr — including
every sub-jaxpr under pjit/scan/while/cond/shard_map/custom-call — is
walked for:

  DLG201  device-to-host transfer primitives (pure_callback, io_callback,
          debug_callback, ...) — a host round-trip compiled INTO the step
          function stalls the TPU pipeline every token
  DLG202  float64 anywhere in the program. Traced under
          jax.experimental.enable_x64 so promotion leaks are visible: with
          the production x64=off default JAX silently truncates them to
          f32, and the first time the flag flips (a debug session, a new
          deployment) the step function doubles its HBM traffic
  DLG203  full-precision activation re-replication: an all_gather whose
          float output is at least the full activation size. The Q80 TP
          path exists precisely to move int8 blocks instead of replicating
          f32 partial sums (ref: src/tasks.cpp:124-163) — an f32/bf16
          all_gather of a whole activation inside a manual region is the
          regression this guards against. int8/uint8 gathers (the q80
          payload) and sub-activation gathers (flash stats, scales) pass
  DLG204  entry-point signature fingerprint drift vs the committed
          baseline — the jit compilation key changed (an input dtype
          widened, a scalar became weak-typed, an argument appeared):
          every distinct call now recompiles or the cache key churns
  DLG205  full-vocab logits materialization in a vocab-sharded serving
          program (entries declaring meta["vocab"]): a program output or
          an all_gather with a vocab-sized dim — the sharded sampling
          path (ops/sharded_vocab.py) exists so only candidate
          summaries ever cross to the host

Severity: DLG201/202/203/205 are errors, DLG204 a warning (legitimate
signature changes are accepted by re-running with --update-baseline).
DLG200 (error) reports an entry point the backend could not audit at all
(too few devices) — the gate must fail loudly rather than pass vacuously.
"""

from __future__ import annotations

import numpy as np

from .entrypoints import (EntryPoint, entry_points, make_jaxpr_for,
                          signature_fingerprint)
from .findings import Finding

# primitives that move data to the host (or schedule host execution)
D2H_PRIMITIVES = {
    "pure_callback", "io_callback", "debug_callback", "host_callback_call",
    "outside_call", "device_get", "callback",
}

# collective primitives that replicate data (vs reduce it)
GATHER_PRIMITIVES = {"all_gather", "all_gather_invariant"}

FLOAT_WIDE = {np.dtype("float32"), np.dtype("float64"),
              np.dtype("bfloat16"), np.dtype("float16")}


def _iter_eqns(jaxpr):
    """Depth-first over every eqn in jaxpr and all sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _sub_jaxprs(param):
    # duck-typed: Jaxpr has .eqns, ClosedJaxpr wraps one as .jaxpr — no
    # isinstance against jax internals (their module moved across versions)
    vals = param if isinstance(param, (list, tuple)) else [param]
    for v in vals:
        if hasattr(v, "eqns"):
            yield v
        elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
            yield v.jaxpr


def _callback_name(cb) -> str:
    """Stable name for a callback param — never its repr, which embeds a
    memory address and would make the baseline key differ every process."""
    if cb is None:
        return ""
    inner = getattr(cb, "func", cb)  # unwrap functools.partial
    return (getattr(inner, "__qualname__", "")
            or getattr(inner, "__name__", "")
            or type(cb).__name__)


def _aval_dtype(var):
    aval = getattr(var, "aval", None)
    return getattr(aval, "dtype", None)


def _aval_size(var) -> int:
    aval = getattr(var, "aval", None)
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except TypeError:  # symbolic dim
            return 0
    return n


def audit_entry(ep: EntryPoint) -> tuple[list[Finding], str]:
    """(findings, fingerprint) for one entry point."""
    findings: list[Finding] = []
    file = f"<entry:{ep.name}>"

    closed = make_jaxpr_for(ep)

    # DLG201: host transfers compiled into the step
    for eqn in _iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name in D2H_PRIMITIVES:
            cb_name = _callback_name(eqn.params.get("callback"))
            findings.append(Finding(
                "DLG201", "error", file, 0,
                f"host callback `{name}`"
                + (f" ({cb_name})" if cb_name else "")
                + " compiled into the step — device-to-host round-trip "
                "every invocation"))

    # DLG203: full-precision activation re-replication
    act = max(int(ep.meta.get("activation_elems", 0)), 1)
    for eqn in _iter_eqns(closed.jaxpr):
        if eqn.primitive.name not in GATHER_PRIMITIVES:
            continue
        out = eqn.outvars[0]
        dt = _aval_dtype(out)
        if dt is None or np.dtype(dt) not in FLOAT_WIDE:
            continue  # int8 q80 payload (or bool masks) — the cheap wire
        if _aval_size(out) >= act:
            axis = eqn.params.get("axis_name",
                                  eqn.params.get("axes", "?"))
            findings.append(Finding(
                "DLG203", "error", file, 0,
                f"float all_gather over {axis} re-replicates a "
                f"full activation ({_aval_size(out)} elems, dtype "
                f"{np.dtype(dt).name}) — the sharded-on-entry tensor "
                "comes back replicated; use a psum/reduce_scatter or the "
                "q80 exchange (parallel/collectives.py)"))

    # DLG205: full-vocab logits materialization on a vocab-sharded
    # serving program (entries declaring meta["vocab"]). Two shapes of
    # the leak: the program RETURNS an array with a vocab-sized dim
    # (the host fetch would gather the whole head output), or an
    # all_gather inside it re-replicates one (the sharded matmul's
    # output coming back whole). The sharded sampling path exists
    # precisely so only (B, S·k) candidate summaries cross; a
    # vocab-sized anything here is the regression this rule guards.
    vocab = int(ep.meta.get("vocab", 0))
    if vocab:
        for var in closed.jaxpr.outvars:
            aval = getattr(var, "aval", None)
            dims = tuple(getattr(aval, "shape", ()) or ())
            if any(d == vocab for d in dims if isinstance(d, int)):
                findings.append(Finding(
                    "DLG205", "error", file, 0,
                    f"program output of shape {dims} carries a full "
                    f"vocab ({vocab}) dim — the serving path must fetch "
                    "candidate summaries, never the logits "
                    "(ops/sharded_vocab.py)"))
        for eqn in _iter_eqns(closed.jaxpr):
            if eqn.primitive.name not in GATHER_PRIMITIVES:
                continue
            out = eqn.outvars[0]
            aval = getattr(out, "aval", None)
            dims = tuple(getattr(aval, "shape", ()) or ())
            if any(d == vocab for d in dims if isinstance(d, int)):
                findings.append(Finding(
                    "DLG205", "error", file, 0,
                    f"all_gather re-replicates a full-vocab array "
                    f"{dims} inside a vocab-sharded serving program"))

    # DLG202: f64 promotion, visible only under x64 tracing
    closed64 = make_jaxpr_for(ep, x64=True)
    seen_f64 = set()
    for eqn in _iter_eqns(closed64.jaxpr):
        for var in list(eqn.outvars):
            dt = _aval_dtype(var)
            if dt is not None and np.dtype(dt) == np.dtype("float64"):
                key = eqn.primitive.name
                if key not in seen_f64:
                    seen_f64.add(key)
                    findings.append(Finding(
                        "DLG202", "error", file, 0,
                        f"float64 produced by `{key}` under x64 tracing — "
                        "an unpinned literal/np-constant promotes; pin the "
                        "dtype (jnp.float32(...)) so the program is "
                        "x64-proof"))

    return findings, signature_fingerprint(ep)


def audit_all(baseline_fingerprints: dict[str, str] | None = None,
              ) -> tuple[list[Finding], dict[str, str]]:
    """Audit every entry point available on this backend. Returns findings
    (including DLG204 fingerprint drift vs the given baseline) plus the
    current fingerprint map."""
    import jax

    findings: list[Finding] = []
    fingerprints: dict[str, str] = {}
    n_dev = jax.device_count()
    entries, unavailable = entry_points()
    # an un-audited entry point is a FINDING, not a silent skip — otherwise
    # a short virtual mesh (stray XLA_FLAGS) makes the gate pass vacuously
    # on exactly the tp/ep paths DLG203 exists to watch
    for name, needs in unavailable:
        findings.append(Finding(
            "DLG200", "error", f"<entry:{name}>", 0,
            f"entry point not audited: needs {needs} devices, "
            f"backend has {n_dev} — run with XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 (the CI/test "
            "configuration)"))
    for ep in entries:
        f, fp = audit_entry(ep)
        findings.extend(f)
        fingerprints[ep.name] = fp
        if baseline_fingerprints and ep.name in baseline_fingerprints:
            want = baseline_fingerprints[ep.name]
            if fp != want:
                findings.append(Finding(
                    "DLG204", "warning", f"<entry:{ep.name}>", 0,
                    f"static-signature fingerprint drift ({want} -> {fp}) "
                    "— the jit compilation key changed (input dtype/"
                    "weak-type/arity); intended changes re-baseline with "
                    "--update-baseline"))
    if baseline_fingerprints is not None:
        # completeness both ways: a NEW entry point must be pinned
        # deliberately (not silently accepted), and a DELETED one must not
        # leave a stale fingerprint in the baseline forever. Entries the
        # mesh could not build already failed via DLG200 — not stale.
        for name in sorted(set(fingerprints) - set(baseline_fingerprints)):
            findings.append(Finding(
                "DLG204", "warning", f"<entry:{name}>", 0,
                "new entry point with no pinned signature fingerprint — "
                "accept with --update-baseline"))
        skipped = {n for n, _ in unavailable}
        for name in sorted(set(baseline_fingerprints) - set(fingerprints)
                           - skipped):
            findings.append(Finding(
                "DLG108", "warning", f"<entry:{name}>", 0,
                "stale baseline: pinned fingerprint for an entry point "
                "that no longer exists — prune with --update-baseline"))
    return findings, fingerprints
