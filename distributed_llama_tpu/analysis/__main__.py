"""CLI: python -m distributed_llama_tpu.analysis [--check] [--format=...]

Exit codes: without --check, always 0 unless the analyzer itself fails
(report mode — safe in `set -e` scripts); with --check, 1 when findings
beyond the baseline exist (the CI gate); 2 = analyzer failure.

--no-jaxpr skips Level 2 so the lint runs without importing JAX at all
(pre-commit hooks, bare environments). The CI job runs the full analyzer
on JAX_PLATFORMS=cpu with 8 virtual devices (entrypoints.py needs a mesh
for the tp/ep entries).
"""

from __future__ import annotations

import argparse
import os
import sys

from .ast_lint import lint_package
from .findings import (format_github, format_json, format_text,
                       load_baseline, sort_findings, split_by_baseline,
                       write_baseline)

PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def run(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_llama_tpu.analysis",
        description="dlgrind: JAX-aware static analysis (AST lint + "
                    "jaxpr audit)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on findings not in the baseline (CI gate)")
    ap.add_argument("--format", choices=("text", "github", "json"),
                    default="text")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept the current findings/fingerprints as the "
                         "new baseline")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="AST lint only (no JAX import)")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print findings the baseline accepts")
    args = ap.parse_args(argv)

    try:
        findings = lint_package(PKG_DIR, prefix="distributed_llama_tpu/")
    except SyntaxError as e:
        print(f"analyzer failed to parse source: {e}", file=sys.stderr)
        return 2

    baseline = load_baseline(args.baseline)
    fingerprints: dict[str, str] = dict(baseline.get("fingerprints", {}))

    if not args.no_jaxpr:
        # the virtual mesh must be configured before jax initializes —
        # same convention as tests/conftest.py so the tp/ep entries exist
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from ..utils.virtual_mesh import ensure_virtual_cpu_devices

        ensure_virtual_cpu_devices()
        try:
            from .jaxpr_audit import audit_all

            jaxpr_findings, fingerprints = audit_all(
                baseline.get("fingerprints", {}))
        except Exception as e:  # analyzer crash, NOT a gate failure —
            # keep exit code 2 distinguishable from "new findings" (1)
            print(f"jaxpr audit failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2
        findings.extend(jaxpr_findings)

    new, accepted = split_by_baseline(findings, baseline)

    if args.update_baseline:
        # a short mesh cannot produce a trustworthy baseline: the tp/ep
        # entries were never audited, and pinning their DLG200 findings
        # (or dropping their fingerprints) would defeat the vacuous-pass
        # guard permanently
        if any(f.rule == "DLG200" for f in findings):
            print("refusing --update-baseline: some entry points were not "
                  "audited (DLG200) — rerun with the full virtual mesh "
                  "(XLA_FLAGS=--xla_force_host_platform_device_count=8)",
                  file=sys.stderr)
            return 2
        # DLG204 drift findings embed the old->new hashes in their message
        # — as allowlist keys they could never match again. Fingerprints
        # are re-pinned via their own map; keep them out of the findings.
        pinned = [f for f in findings if f.rule != "DLG204"]
        write_baseline(args.baseline, pinned, fingerprints)
        print(f"baseline updated: {len(pinned)} finding(s), "
              f"{len(fingerprints)} fingerprint(s) -> {args.baseline}")
        return 0

    to_show = sort_findings(new)
    if args.format == "github":
        out = format_github(to_show)
    elif args.format == "json":
        out = format_json(to_show)
    else:
        out = format_text(to_show, accepted=len(accepted))
        if args.show_baselined and accepted:
            out += "\n-- baselined --\n" + format_text(
                sort_findings(accepted))
    if out:
        print(out)

    if new and args.check:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(run())
