"""CLI: python -m distributed_llama_tpu.analysis [--check] [--format=...]

Exit codes: without --check, always 0 unless the analyzer itself fails
(report mode — safe in `set -e` scripts); with --check, 1 when findings
beyond the baseline exist (the CI gate); 2 = analyzer failure.

--no-jaxpr skips Level 2 so the lint runs without importing JAX at all
(pre-commit hooks, bare environments). The CI job runs the full analyzer
on JAX_PLATFORMS=cpu with 8 virtual devices (entrypoints.py needs a mesh
for the tp/ep entries).

Passes, in order: Level 1 AST lint (DLG1xx), the dlrace lock-discipline
lint (DLG3xx, runtime/apps/multihost scope), the serving-path D2H audit
(DLG206), then — unless --no-jaxpr — the Level 2 jaxpr audit (DLG2xx).
After the baseline split, hygiene findings are appended: DLG108 for
baseline entries (allowlist keys or pinned fingerprints) that no longer
match anything in the tree, DLG109 for baseline entries carrying no
one-line justification. Hygiene findings are never themselves written
to the baseline — --update-baseline prunes/annotates instead.
"""

from __future__ import annotations

import argparse
import os
import sys
from collections import Counter

from .ast_lint import lint_package
from .findings import (Finding, format_github, format_json, format_text,
                       load_baseline, sort_findings, split_by_baseline,
                       unjustified_keys, write_baseline)
from .race_lint import race_lint_package
from .serving_d2h import audit_serving_path

PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def gather_findings(baseline: dict, *, no_jaxpr: bool = False,
                    pkg_dir: str = PKG_DIR,
                    ) -> tuple[list[Finding], dict[str, str]]:
    """Everything the gate judges, pre-baseline-split: AST lint, dlrace,
    serving-path D2H, and (unless no_jaxpr) the jaxpr audit. Shared by
    the CLI and the pytest gates so they cannot drift. Raises on analyzer
    failure (SyntaxError from the lints, anything from the audit)."""
    prefix = "distributed_llama_tpu/"
    findings = lint_package(pkg_dir, prefix=prefix)
    findings.extend(race_lint_package(pkg_dir, prefix=prefix))
    findings.extend(audit_serving_path(pkg_dir, prefix=prefix))
    fingerprints: dict[str, str] = dict(baseline.get("fingerprints", {}))
    if not no_jaxpr:
        # the virtual mesh must be configured before jax initializes —
        # same convention as tests/conftest.py so the tp/ep entries exist
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from ..utils.virtual_mesh import ensure_virtual_cpu_devices

        ensure_virtual_cpu_devices()
        from .jaxpr_audit import audit_all

        jaxpr_findings, fingerprints = audit_all(
            baseline.get("fingerprints", {}))
        findings.extend(jaxpr_findings)
    return findings, fingerprints


# rules that describe the BASELINE's own hygiene (or embed old->new state
# in their message) — they can never be allowlist keys
HYGIENE_RULES = ("DLG108", "DLG109", "DLG204")


def hygiene_findings(findings: list[Finding], baseline: dict) -> list[Finding]:
    """DLG108 stale allowlist keys + DLG109 unjustified entries. Stale
    fingerprints are DLG108 too, emitted by audit_all (it knows which
    entries were mesh-skipped rather than deleted)."""
    out: list[Finding] = []
    leftover = (Counter(baseline.get("findings", []))
                - Counter(f.key() for f in findings))
    for key, n in sorted(leftover.items()):
        extra = f" (x{n})" if n > 1 else ""
        out.append(Finding(
            "DLG108", "warning", "<baseline>", 0,
            f"stale baseline: allowlist entry matches no current site"
            f"{extra}: `{key}` — prune with --update-baseline"))
    for key in unjustified_keys(baseline):
        out.append(Finding(
            "DLG109", "warning", "<baseline>", 0,
            f"baseline entry lacks a one-line justification: `{key}` — "
            "every allowlisted finding is a reviewed decision; write "
            "down why"))
    return out


def run(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_llama_tpu.analysis",
        description="dlgrind: JAX-aware static analysis (AST lint + "
                    "dlrace lock-discipline lint + jaxpr audit)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on findings not in the baseline (CI gate)")
    ap.add_argument("--format", choices=("text", "github", "json"),
                    default="text")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept the current findings/fingerprints as the "
                         "new baseline")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="AST lint only (no JAX import)")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print findings the baseline accepts")
    args = ap.parse_args(argv)

    baseline = load_baseline(args.baseline)
    try:
        findings, fingerprints = gather_findings(baseline,
                                                 no_jaxpr=args.no_jaxpr)
    except SyntaxError as e:
        print(f"analyzer failed to parse source: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # analyzer crash, NOT a gate failure —
        # keep exit code 2 distinguishable from "new findings" (1)
        print(f"analysis failed: {type(e).__name__}: {e}", file=sys.stderr)
        return 2

    new, accepted = split_by_baseline(findings, baseline)

    if args.update_baseline:
        # a short mesh cannot produce a trustworthy baseline: the tp/ep
        # entries were never audited, and pinning their DLG200 findings
        # (or dropping their fingerprints) would defeat the vacuous-pass
        # guard permanently
        if any(f.rule == "DLG200" for f in findings):
            print("refusing --update-baseline: some entry points were not "
                  "audited (DLG200) — rerun with the full virtual mesh "
                  "(XLA_FLAGS=--xla_force_host_platform_device_count=8)",
                  file=sys.stderr)
            return 2
        pinned = [f for f in findings if f.rule not in HYGIENE_RULES]
        write_baseline(args.baseline, pinned, fingerprints,
                       baseline.get("justifications", {}))
        print(f"baseline updated: {len(pinned)} finding(s), "
              f"{len(fingerprints)} fingerprint(s) -> {args.baseline}")
        return 0

    # hygiene findings join AFTER the split/update paths: they describe
    # the baseline itself, so they can never be accepted by it
    new.extend(hygiene_findings(findings, baseline))

    to_show = sort_findings(new)
    if args.format == "github":
        out = format_github(to_show)
    elif args.format == "json":
        out = format_json(to_show)
    else:
        out = format_text(to_show, accepted=len(accepted))
        if args.show_baselined and accepted:
            out += "\n-- baselined --\n" + format_text(
                sort_findings(accepted))
    if out:
        print(out)

    if new and args.check:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(run())
