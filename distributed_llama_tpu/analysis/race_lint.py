"""Level 1c: lock-discipline lint (dlrace, DLG3xx) over the host runtime.

The serving stack is a dozen cooperating threads — scheduler step loop,
supervisor watchdog + rebuild, router monitor, worker pump, tracer sink,
profiler capture — and the dominant residual bug class in this repo's
history is the host-side race found only by manual review: the half-open
probe leak (a bare `acquire()` stranded by an exception), the
deque-mutated-during-iteration scan crash, the close/submit TOCTOU, and
the unjoined `_rebuild` thread segfaulting interpreter teardown. These
rules encode that reviewer's eye:

  DLG301  write (assignment, aug-assign, item-store, or mutating method
          call) to a `# dlrace: guarded-by(<lock>)` field without the
          guard held
  DLG302  blocking call (socket send/recv, subprocess, jit/compile,
          time.sleep, thread .join) while holding a declared guard lock
          — the watchdog-vs-capture stall shape
  DLG303  bare `.acquire()` not paired with try/finally release and not
          a context manager — an exception strands the lock forever
  DLG304  thread stored on `self` and started, but never `.join()`ed on
          any close/shutdown path — teardown runs callbacks into a
          half-destroyed interpreter
  DLG305  iteration (for / comprehension / list()/sorted()/.items()...)
          over a guarded container field outside its guard — mutation
          during iteration raises at runtime
  DLG306  `time.time()` used for interval arithmetic — wall clock jumps
          under NTP slew; deadlines and durations take perf_counter()

Discipline model, deliberately lightweight and intraprocedural:

* Shared state is DECLARED, not inferred: an attribute assignment whose
  line carries `# dlrace: guarded-by(self._lock)` marks that field as
  owned by that lock for the whole class. Only declared fields get
  DLG301/DLG305 checks — the annotation is the reviewer's statement of
  intent, the lint enforces it.
* Per-method lock-held sets come from `with self._lock:` blocks,
  linear `acquire()`/`release()` pairs within a statement list, and the
  `_locked`-suffix naming convention (a `*_locked` method asserts its
  caller holds the class guards).
* Accesses are `self.<field>` only: cross-object lock-free peeks (a
  router reading `sched._queue`) are design decisions documented at the
  reading site, not races this pass can judge.
* `__init__`/`__post_init__` are exempt from DLG301/DLG305 — the object
  is not shared
  during construction.
* DLG302 fires only while a DECLARED guard lock is held: dedicated I/O
  mutexes (a per-socket send lock exists precisely to serialize a
  blocking send) are deliberately not annotated and never trip it.
* Locals-only threads (`t = Thread(...); t.start()`) are fire-and-forget
  by construction and out of DLG304 scope; the rule tracks instance
  attributes, the shape the historical segfault took.

False negatives are acceptable, false positives are not (every rule has
a clean fixture). Deliberate exceptions — GIL-atomic deque appends on
the submit hot path, lock-free heartbeat floats — are baselined with a
one-line justification, never bare-suppressed.
"""

from __future__ import annotations

import ast
import os
import re

from .findings import Finding, is_suppressed, parse_suppressions

# modules the lock-discipline pass runs over (package-relative, posix)
RACE_SCOPE = ("runtime/", "apps/", "parallel/multihost.py")

# `self._queue = deque()  # dlrace: guarded-by(self._mutex)`
GUARD_RE = re.compile(
    r"#\s*dlrace:\s*guarded-by\(\s*(?:self\.)?(?P<lock>[A-Za-z_]\w*)\s*\)")
# `def _step_body(self):  # dlrace: holds(self._mutex)` — the def-line
# form of the `_locked`-suffix convention: the caller owns the lock.
# For helpers whose name can't carry the suffix (public API contracts,
# roots other passes reference by name).
HOLDS_RE = re.compile(
    r"#\s*dlrace:\s*holds\(\s*(?:self\.)?(?P<lock>[A-Za-z_]\w*)\s*\)")

# receivers that look like locks even without an annotation (DLG303)
_LOCKISH_RE = re.compile(r"(?:^|_)(?:lock|mutex|sem|rlock)\b|_lock$|_mutex$",
                         re.IGNORECASE)

# container constructors: a guarded field built from one of these gets
# DLG305 iteration checks (scalar guarded fields don't — reading a float
# outside the lock is a staleness question, not a crash)
_CONTAINER_CTORS = {"deque", "dict", "list", "set", "OrderedDict",
                    "defaultdict", "Counter"}
# mutating methods on containers — a call through `self.<field>.<m>(...)`
# is a write for DLG301
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "pop", "popleft", "popitem", "remove", "discard", "clear",
             "add", "update", "setdefault", "move_to_end", "rotate"}
# consuming calls: `list(self._q)` / `sorted(self._m)` iterate the operand
_ITER_CONSUMERS = {"list", "tuple", "set", "frozenset", "sorted", "sum",
                   "max", "min", "any", "all", "dict"}
# `self._m.items()` etc. iterate (or hand out an iterator over) the field
_ITER_METHODS = {"items", "values", "keys", "copy"}

# DLG302 blocking sinks while a guard is held
_BLOCKING_DOTTED = {"time.sleep", "jax.jit", "subprocess.run",
                    "subprocess.call", "subprocess.check_call",
                    "subprocess.check_output", "subprocess.Popen",
                    "socket.create_connection", "socket.create_server"}
_BLOCKING_LEAVES = {"recv", "recv_into", "sendall", "accept", "connect",
                    "block_until_ready", "wait_ready", "spawn"}
# the repo's framed socket codec helpers — module-level functions
_BLOCKING_NAMES = {"_send_frame", "_recv_frame", "send_frame", "recv_frame"}

_CLOSE_METHOD_RE = re.compile(
    r"^(close|shutdown|stop|terminate|join|aclose|__exit__|__del__)")


def _dotted(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _norm_lock(name: str) -> str:
    """Normalize a lock reference for held-set membership: `self._lock`
    and `_lock` are the same guard."""
    return name[5:] if name.startswith("self.") else name


def _self_field(node: ast.AST) -> str | None:
    """'X' when node is exactly `self.X`, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _ClassModel:
    """Per-class discipline facts collected in pass A."""

    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.guards: dict[str, str] = {}       # field -> normalized lock
        self.containers: set[str] = set()      # guarded container fields
        self.threads: dict[str, int] = {}      # thread attr -> decl line
        self.joined: set[str] = set()          # thread attrs joined on a
        #                                        close/shutdown path

    def guard_locks(self) -> set[str]:
        return set(self.guards.values())


class RaceLinter:
    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.source = source
        self.tree = ast.parse(source, filename=relpath)
        self.findings: list[Finding] = []
        # line -> lock name, from guarded-by / holds comments
        self.guard_lines: dict[int, str] = {}
        self.holds_lines: dict[int, str] = {}
        for i, text in enumerate(source.splitlines(), start=1):
            m = GUARD_RE.search(text)
            if m:
                self.guard_lines[i] = _norm_lock(m.group("lock"))
            m = HOLDS_RE.search(text)
            if m:
                self.holds_lines[i] = _norm_lock(m.group("lock"))

    def add(self, rule: str, severity: str, node: ast.AST, msg: str) -> None:
        self.findings.append(Finding(rule, severity, self.relpath,
                                     getattr(node, "lineno", 0), msg))

    def run(self) -> list[Finding]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                model = self._collect(node)
                self._check_class(node, model)
        # DLG306 also applies to module-level functions (no class state)
        for stmt in self.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._lint_wall_clock(stmt)
        supp = parse_suppressions(self.source)
        out, seen = [], set()
        for f in self.findings:
            if is_suppressed(f, supp):
                continue
            if (f.rule, f.line) in seen:
                continue
            seen.add((f.rule, f.line))
            out.append(f)
        return out

    # -- pass A: collect the class discipline model ------------------------

    def _methods(self, cls: ast.ClassDef):
        return [n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def _collect(self, cls: ast.ClassDef) -> _ClassModel:
        model = _ClassModel(cls)
        for meth in self._methods(cls):
            for node in ast.walk(meth):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    field = _self_field(tgt)
                    if field is None:
                        continue
                    lock = self._guard_for(node)
                    if lock is not None:
                        model.guards[field] = lock
                        if self._is_container(node.value):
                            model.containers.add(field)
                    if self._is_thread_ctor(node.value):
                        model.threads.setdefault(field, node.lineno)
        # joins that count: inside a close/shutdown-shaped method, either
        # directly (`self._t.join()`) or through a local snapshot taken
        # under the lock (`t = self._t` ... `t.join()` — the idiomatic
        # shape when the attr itself is guarded)
        for meth in self._methods(cls):
            if not _CLOSE_METHOD_RE.match(meth.name):
                continue
            aliases: dict[str, str] = {}
            for node in ast.walk(meth):
                if isinstance(node, ast.Assign):
                    field = _self_field(node.value)
                    if field is not None:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                aliases[tgt.id] = field
            for node in ast.walk(meth):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "join"):
                    field = _self_field(node.func.value)
                    if field is None and isinstance(node.func.value,
                                                    ast.Name):
                        field = aliases.get(node.func.value.id)
                    if field:
                        model.joined.add(field)
        return model

    def _guard_for(self, stmt: ast.AST) -> str | None:
        end = getattr(stmt, "end_lineno", stmt.lineno) or stmt.lineno
        for line in range(stmt.lineno, end + 1):
            if line in self.guard_lines:
                return self.guard_lines[line]
        return None

    def _is_container(self, value: ast.AST | None) -> bool:
        if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)):
            return True
        if isinstance(value, ast.Call):
            return _dotted(value.func).rsplit(".", 1)[-1] in _CONTAINER_CTORS
        return False

    def _is_thread_ctor(self, value: ast.AST | None) -> bool:
        if not isinstance(value, ast.Call):
            return False
        return _dotted(value.func).rsplit(".", 1)[-1] == "Thread"

    # -- pass B: per-method checks -----------------------------------------

    def _check_class(self, cls: ast.ClassDef, model: _ClassModel) -> None:
        # DLG304: every instance-attribute thread needs a join on a
        # close/shutdown path (fire-and-forget locals are out of scope)
        for field, line in sorted(model.threads.items()):
            if field not in model.joined:
                self.findings.append(Finding(
                    "DLG304", "warning", self.relpath, line,
                    f"thread `self.{field}` in `{cls.name}` is never "
                    "joined on a close/shutdown path — interpreter "
                    "teardown can run its callback into freed state"))
        for meth in self._methods(cls):
            self._meth_name = f"{cls.name}.{meth.name}"
            held: set[str] = set()
            if meth.name.endswith("_locked"):
                # convention: the caller holds the class guards
                held = model.guard_locks()
            held |= self._declared_holds(meth)
            self._scan(meth.body, held, set(), model, meth)
            self._lint_wall_clock(meth)
        self._meth_name = "?"

    def _scan(self, stmts: list[ast.stmt], held: set[str],
              finally_releases: set[str], model: _ClassModel, meth) -> None:
        cur = set(held)
        for idx, stmt in enumerate(stmts):
            acq = self._acquire_target(stmt)
            if acq is not None:
                nxt = stmts[idx + 1] if idx + 1 < len(stmts) else None
                protected = (acq in finally_releases
                             or (isinstance(nxt, ast.Try)
                                 and self._releases(nxt.finalbody, acq)))
                if not protected:
                    self.add("DLG303", "error", stmt,
                             f"bare `{acq}.acquire()` without try/finally "
                             "release — an exception before the release "
                             "strands the lock (use `with` or wrap in "
                             "try/finally)")
                cur.add(acq)
                continue
            rel = self._release_target(stmt)
            if rel is not None:
                cur.discard(rel)
                continue
            self._check_stmt(stmt, cur, model, meth)
            # recursion with the updated held set
            if isinstance(stmt, ast.With):
                locks = set()
                for item in stmt.items:
                    name = _dotted(item.context_expr)
                    if not name and isinstance(item.context_expr, ast.Call):
                        name = _dotted(item.context_expr.func)
                    norm = _norm_lock(name)
                    if norm and (norm in model.guard_locks()
                                 or _LOCKISH_RE.search(norm)):
                        locks.add(norm)
                self._scan(stmt.body, cur | locks, finally_releases,
                           model, meth)
            elif isinstance(stmt, ast.Try):
                fin = self._lockish_released(stmt.finalbody)
                self._scan(stmt.body, cur, finally_releases | fin,
                           model, meth)
                for h in stmt.handlers:
                    self._scan(h.body, cur, finally_releases | fin,
                               model, meth)
                self._scan(stmt.orelse, cur, finally_releases | fin,
                           model, meth)
                self._scan(stmt.finalbody, cur, finally_releases,
                           model, meth)
            elif isinstance(stmt, (ast.If, ast.While)):
                self._scan(stmt.body, cur, finally_releases, model, meth)
                self._scan(stmt.orelse, cur, finally_releases, model, meth)
            elif isinstance(stmt, ast.For):
                self._scan(stmt.body, cur, finally_releases, model, meth)
                self._scan(stmt.orelse, cur, finally_releases, model, meth)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def runs LATER (usually on another thread) —
                # the enclosing held set does not apply
                inner = (model.guard_locks()
                         if stmt.name.endswith("_locked") else set())
                inner |= self._declared_holds(stmt)
                self._scan(stmt.body, inner, set(), model, meth)

    def _acquire_target(self, stmt: ast.stmt) -> str | None:
        value = None
        if isinstance(stmt, ast.Expr):
            value = stmt.value
        elif isinstance(stmt, ast.Assign):
            value = stmt.value
        if (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr == "acquire"):
            name = _norm_lock(_dotted(value.func.value))
            if name and (_LOCKISH_RE.search(name) or name in
                         self._all_guard_locks()):
                return name
        return None

    def _release_target(self, stmt: ast.stmt) -> str | None:
        if (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr == "release"):
            return _norm_lock(_dotted(stmt.value.func.value)) or None
        return None

    def _releases(self, stmts: list[ast.stmt], lock: str) -> bool:
        for node in (n for s in stmts for n in ast.walk(s)):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "release"
                    and _norm_lock(_dotted(node.func.value)) == lock):
                return True
        return False

    def _lockish_released(self, stmts: list[ast.stmt]) -> set[str]:
        out = set()
        for node in (n for s in stmts for n in ast.walk(s)):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "release"):
                name = _norm_lock(_dotted(node.func.value))
                if name:
                    out.add(name)
        return out

    def _all_guard_locks(self) -> set[str]:
        return set(self.guard_lines.values())

    def _declared_holds(self, fn) -> set[str]:
        """Locks a `# dlrace: holds(...)` comment on the def line (or the
        signature's continuation lines) declares the caller owns."""
        first_body = fn.body[0].lineno if fn.body else fn.lineno + 1
        out = set()
        for line in range(fn.lineno, max(first_body, fn.lineno + 1)):
            if line in self.holds_lines:
                out.add(self.holds_lines[line])
        return out

    # -- per-statement sinks ----------------------------------------------

    def _stmt_exprs(self, stmt):
        for field, value in ast.iter_fields(stmt):
            if field in ("body", "orelse", "finalbody", "handlers"):
                continue
            for v in (value if isinstance(value, list) else [value]):
                if isinstance(v, ast.AST):
                    yield from ast.walk(v)

    def _check_stmt(self, stmt, held: set[str], model: _ClassModel,
                    meth) -> None:
        in_init = meth.name in ("__init__", "__post_init__")
        # DLG301: assignment-shaped writes
        if not in_init:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, ast.AugAssign):
                targets = [stmt.target]
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets = [stmt.target]
            for tgt in targets:
                self._check_write_target(tgt, stmt, held, model)
        # expression-level sinks
        for node in self._stmt_exprs(stmt):
            if isinstance(node, ast.Call):
                if not in_init:
                    self._check_mutator_call(node, held, model)
                    self._check_iter_call(node, held, model)
                self._check_blocking_call(node, held, model)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp, ast.DictComp)):
                if not in_init:
                    for gen in node.generators:
                        self._check_iter_expr(gen.iter, node, held, model)
        # DLG305: for-loop over a guarded container
        if isinstance(stmt, ast.For) and not in_init:
            self._check_iter_expr(stmt.iter, stmt, held, model)

    def _guarded_field_expr(self, node: ast.AST,
                            model: _ClassModel) -> str | None:
        """'X' when node reads guarded container `self.X` (directly or via
        .items()/.values()/.keys()/.copy())."""
        field = _self_field(node)
        if field in model.containers:
            return field
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _ITER_METHODS):
            field = _self_field(node.func.value)
            if field in model.containers:
                return field
        return None

    def _check_write_target(self, tgt, stmt, held, model) -> None:
        field = _self_field(tgt)
        if field is None and isinstance(tgt, ast.Subscript):
            field = _self_field(tgt.value)
        if field is None and isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._check_write_target(el, stmt, held, model)
            return
        if field in model.guards and model.guards[field] not in held:
            self.add("DLG301", "error", stmt,
                     f"unguarded write to `self.{field}` (guarded-by "
                     f"`{model.guards[field]}`) in `{self._meth_name}`")

    def _check_mutator_call(self, node: ast.Call, held, model) -> None:
        if not isinstance(node.func, ast.Attribute):
            return
        if node.func.attr not in _MUTATORS:
            return
        field = _self_field(node.func.value)
        if field in model.guards and model.guards[field] not in held:
            self.add("DLG301", "error", node,
                     f"unguarded `self.{field}.{node.func.attr}()` "
                     f"(guarded-by `{model.guards[field]}`) in "
                     f"`{self._meth_name}`")

    def _check_iter_call(self, node: ast.Call, held, model) -> None:
        # list(self._q) / sorted(self._m.items()) / self._m.items()
        field = None
        leaf = _dotted(node.func).rsplit(".", 1)[-1]
        if leaf in _ITER_CONSUMERS and node.args:
            field = self._guarded_field_expr(node.args[0], model)
        if field is None:
            field = self._guarded_field_expr(node, model) \
                if isinstance(node.func, ast.Attribute) else None
        if field and model.guards[field] not in held:
            self.add("DLG305", "error", node,
                     f"iteration over guarded container `self.{field}` "
                     f"outside `{model.guards[field]}` in "
                     f"`{self._meth_name}` — concurrent mutation raises "
                     "mid-iteration")

    def _check_iter_expr(self, it: ast.AST, anchor, held, model) -> None:
        field = self._guarded_field_expr(it, model)
        if field and model.guards[field] not in held:
            self.add("DLG305", "error", anchor,
                     f"iteration over guarded container `self.{field}` "
                     f"outside `{model.guards[field]}` in "
                     f"`{self._meth_name}` — concurrent mutation raises "
                     "mid-iteration")

    def _check_blocking_call(self, node: ast.Call, held,
                             model: _ClassModel) -> None:
        # only while a DECLARED guard is held — dedicated I/O mutexes are
        # deliberately unannotated and never trip this rule
        guard_held = held & model.guard_locks()
        if not guard_held:
            return
        fn = _dotted(node.func)
        leaf = fn.rsplit(".", 1)[-1]
        blocking = (fn in _BLOCKING_DOTTED
                    or fn in _BLOCKING_NAMES
                    or (isinstance(node.func, ast.Attribute)
                        and leaf in _BLOCKING_LEAVES))
        if not blocking and isinstance(node.func, ast.Attribute) \
                and leaf == "join":
            # .join() is blocking only on thread values; str.join is not
            field = _self_field(node.func.value)
            blocking = field in model.threads
        if blocking:
            lock = sorted(guard_held)[0]
            self.add("DLG302", "warning", node,
                     f"blocking call `{fn}` while holding `{lock}` — "
                     "every reader of that guard stalls behind it (move "
                     "the slow work outside the critical section)")

    # -- DLG306: wall clock in interval arithmetic -------------------------

    def _lint_wall_clock(self, fn) -> None:
        wall_names: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                if _dotted(node.value.func) == "time.time":
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            wall_names.add(tgt.id)

        def wallish(n: ast.AST, direct_only: bool = False) -> bool:
            if isinstance(n, ast.Call) and _dotted(n.func) == "time.time":
                return True
            if not direct_only and isinstance(n, ast.Name):
                return n.id in wall_names
            return False

        for node in ast.walk(fn):
            if not isinstance(node, ast.BinOp):
                continue
            if isinstance(node.op, ast.Sub):
                hit = wallish(node.left) or wallish(node.right)
            elif isinstance(node.op, ast.Add):
                # deadline construction: `time.time() + timeout`
                hit = wallish(node.left, True) or wallish(node.right, True)
            else:
                continue
            if hit:
                self.add("DLG306", "warning", node,
                         "`time.time()` in interval arithmetic "
                         f"(`{ast.unparse(node)}`) — wall clock slews "
                         "under NTP; use time.perf_counter() or "
                         "time.monotonic() for durations/deadlines")

    # the method currently being scanned, for finding messages (stable
    # per-site keys name the method, never the line)
    _meth_name = "?"


def race_lint_source(relpath: str, source: str) -> list[Finding]:
    return RaceLinter(relpath, source).run()


def in_race_scope(relpath: str) -> bool:
    scope = relpath.split("distributed_llama_tpu/", 1)[-1]
    return any(scope == m or (m.endswith("/") and scope.startswith(m))
               for m in RACE_SCOPE)


def race_lint_package(pkg_root: str, prefix: str = "") -> list[Finding]:
    from .ast_lint import iter_package_files

    findings: list[Finding] = []
    for rel in iter_package_files(pkg_root):
        if not in_race_scope(rel):
            continue
        with open(os.path.join(pkg_root, rel), encoding="utf-8") as f:
            src = f.read()
        findings.extend(race_lint_source(prefix + rel, src))
    return findings
