"""Level 1: JAX-aware AST lint over the package's own source (no JAX import).

The reference C++ engine makes every host sync, dtype, and shard boundary
explicit in its task list; the JAX port hides them behind tracing, where a
stray `np.asarray` on a traced value or a Python `if` on a traced boolean
compiles silently (as a baked-in constant or a ConcretizationError at the
worst possible moment) and surfaces only as a perf cliff on real TPUs.
These rules encode the port's tracing discipline:

  DLG101  host sync inside a traced context (.item()/float()/np.asarray/
          jax.device_get/.tolist()/.block_until_ready on a traced value)
  DLG102  numpy call on a value that flows from a traced function param
  DLG103  Python if/while/assert on a traced boolean
  DLG104  implicit-dtype bare float literal in an ops kernel (0.5 vs
          jnp.float32(0.5)) — promotion bait once x64 or a wider dtype
          appears on the other side
  DLG105  jax.jit of a cache-carrying step in runtime/engine.py without
          donate_argnums (decode would realloc the KV cache every token)
  DLG106  leftover jax.debug.* / print() in ops/ or parallel/
  DLG107  host-device boundary sync in runtime/quants host code
          (np.asarray / int() / .block_until_ready on a device value) —
          every one is either a deliberate, baselined boundary (sampler
          input, stats) or a hidden stall

Taint model: within a traced function, parameters are traced values unless
their annotation, default, or name marks them static (ModelSpec, meshes,
flags). Assignments propagate taint; static attributes (.shape/.ndim/
.dtype/...), len(), isinstance() and `is None` tests break it. The model
is intraprocedural and one-pass — false negatives are acceptable, false
positives are not (every rule has a clean-fixture test).

A function is a traced context when it is decorated with/passed to
jax.jit, shard_map, lax.scan/while_loop/cond/vmap — or when it lives in a
kernel module (ops/, parallel/ compute files, models/transformer.py,
quants/jax_codec.py), where all array-taking code is traced by design.
"""

from __future__ import annotations

import ast
import os

from .findings import Finding, is_suppressed, parse_suppressions

# modules where every top-level function is a traced context
KERNEL_MODULES = (
    "ops/",
    "models/transformer.py",
    "quants/jax_codec.py",
    "parallel/collectives.py",
    "parallel/ep_moe.py",
    "parallel/pp.py",
    "parallel/ring_attention.py",
    "parallel/tp_q80.py",
)
# DLG104 scope: hand-written kernels where literal dtype discipline matters
OPS_MODULES = ("ops/",)
# DLG106 scope
DEBUG_BAN_MODULES = ("ops/", "parallel/")
# DLG105 scope
DONATE_MODULES = ("runtime/engine.py",)
# DLG107 scope: host-side runtime code that touches device values
HOST_SYNC_MODULES = ("runtime/", "quants/", "sampler.py")

# attribute reads that yield static (trace-time) values — access breaks taint
STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "itemsize", "weak_type", "sharding",
    "is_fully_replicated", "nbytes", "files",
}
# calls whose result is static regardless of argument taint
STATIC_CALLS = {"len", "isinstance", "hasattr", "type", "range", "enumerate"}
# codebase shape/precondition predicates: they read only .shape/.dtype of
# their operands, so their result is trace-time static (kept in sync with
# the code — a new predicate that reads VALUES must not be added here)
SHAPE_PREDICATES = {"supports_pallas", "gpipe_microbatches", "_flash_ok",
                    "_n_sub"}
# annotations marking a parameter as static configuration, not data
STATIC_ANNOTATIONS = {
    "ModelSpec", "HiddenAct", "ArchType", "Mesh", "PartitionSpec", "P",
    "str", "bool", "int", "float", "Sampler", "Callable", "FloatType",
}
# annotations marking a parameter as a HOST array (numpy) — never traced
HOST_ANNOTATIONS = {"np.ndarray", "numpy.ndarray"}
# annotations marking a parameter as a DEVICE array for DLG107 host tracking
DEVICE_ANNOTATIONS = {"jax.Array", "jnp.ndarray", "jax.numpy.ndarray",
                      "KVCache"}
# parameter names that are static config by convention in kernel modules
STATIC_NAMES = {
    "mesh", "spec", "cfg", "act", "arch", "axis", "axis_name", "block",
    "tp", "sp", "ep", "pp", "dp", "n", "theta", "act_fn", "dtype",
    "reduce", "head_size", "draft_len", "max_ngram", "min_ngram", "n_mb",
}
# calls that hand a function to the tracer: any local function referenced
# as an argument becomes a traced context
TRACING_CALLS = {
    "jit", "scan", "while_loop", "cond", "shard_map", "vmap", "pmap",
    "checkpoint", "remat", "make_jaxpr", "eval_shape", "switch",
    "pallas_call", "fori_loop",
}
# host-sync sinks shared by DLG101 (traced ctx) and DLG107 (host ctx)
NUMPY_SYNC_FUNCS = {"asarray", "array", "float32", "float64", "int32",
                    "int64", "copy", "ascontiguousarray"}
BUILTIN_SYNC_FUNCS = {"float", "int", "bool"}
SYNC_METHODS = {"item", "tolist", "block_until_ready", "__array__"}
# calls whose RESULT is host data (they break device taint in DLG107 —
# the sync itself is the finding; downstream host math is fine)
HOST_RESULT_CALLS = {"fetch_logits"}


def _dotted(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _ann_name(ann: ast.AST | None) -> str:
    if ann is None:
        return ""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.split("|")[0].strip()
    if isinstance(ann, ast.BinOp):  # "X | None" unions
        return _ann_name(ann.left)
    if isinstance(ann, ast.Subscript):
        return _ann_name(ann.value)
    return _dotted(ann)


def _is_static_const(node: ast.AST | None) -> bool:
    return isinstance(node, ast.Constant) or (
        isinstance(node, ast.UnaryOp) and isinstance(node.operand,
                                                     ast.Constant))


class _Taint:
    """Name-set taint with static-aware expression queries."""

    def __init__(self, names: set[str]):
        self.names = set(names)

    def expr(self, node: ast.AST | None) -> bool:
        """Does evaluating `node` produce a (possibly) traced/device value?"""
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr(node.value)
        if isinstance(node, ast.Call):
            fn = _dotted(node.func)
            leaf = fn.rsplit(".", 1)[-1]
            if leaf in STATIC_CALLS or leaf in SHAPE_PREDICATES:
                return False
            if leaf == "getattr" and len(node.args) >= 2 and (
                    isinstance(node.args[1], ast.Constant)
                    and node.args[1].value in STATIC_ATTRS):
                return False
            if leaf in HOST_RESULT_CALLS or fn.startswith(("np.", "numpy.")):
                return False  # result is host data (the call site itself is
                # judged separately as a sync sink)
            if fn in BUILTIN_SYNC_FUNCS or (
                    isinstance(node.func, ast.Attribute)
                    and leaf in SYNC_METHODS):
                return False  # int(x)/x.item()/x.tolist() SYNC — flagged as
                # a sink once; their result is a plain host value
            # method call on a tainted object, or any tainted argument,
            # or a call THROUGH a tainted callable (a jitted step handle)
            return (self.expr(node.func)
                    or any(self.expr(a) for a in node.args)
                    or any(self.expr(k.value) for k in node.keywords))
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` yields a static bool under trace
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            # membership against string constants is pytree-structure /
            # config logic, not array math: `'wqkv' in lw`, `role in
            # ('row', 'col')`
            if all(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
                def _const_ish(n):
                    if isinstance(n, ast.Constant):
                        return True
                    return isinstance(n, (ast.Tuple, ast.List, ast.Set)) and \
                        all(isinstance(e, ast.Constant) for e in n.elts)
                if _const_ish(node.left) or all(_const_ish(c)
                                                for c in node.comparators):
                    return False
            return self.expr(node.left) or any(self.expr(c)
                                               for c in node.comparators)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.expr(v) for v in node.values if v is not None)
        if isinstance(node, ast.Subscript):
            return self.expr(node.value)
        if isinstance(node, ast.BinOp):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.expr(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return self.expr(node.body) or self.expr(node.orelse)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self.expr(node.elt)
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        if isinstance(node, ast.JoinedStr):
            return False
        return False

    def assign(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            (self.names.add if tainted else self.names.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self.assign(el, tainted)
        elif isinstance(target, ast.Starred):
            self.assign(target.value, tainted)
        # attribute/subscript targets (self.x = ...) are not tracked


def _stmt_exprs(stmt):
    """Expressions belonging to this statement alone — nested statement
    blocks (body/orelse/finalbody/handlers) are excluded; their statements
    are visited in their own turn with up-to-date taint."""
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers"):
            continue
        for v in (value if isinstance(value, list) else [value]):
            if isinstance(v, ast.AST):
                yield from ast.walk(v)


def _collect_traced_fn_names(tree: ast.Module) -> set[str]:
    """Names of locally-defined functions handed to a tracing call anywhere
    in the module (jax.jit(run), lax.while_loop(cond, body), ...)."""
    defined: set[str] = {n.name for n in ast.walk(tree)
                         if isinstance(n, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))}
    traced: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        leaf = _dotted(node.func).rsplit(".", 1)[-1]
        if leaf not in TRACING_CALLS:
            continue
        for arg in list(node.args) + [k.value for k in node.keywords]:
            if isinstance(arg, ast.Name) and arg.id in defined:
                traced.add(arg.id)
    return traced


def _has_jit_decorator(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    for dec in fn.decorator_list:
        d = dec.func if isinstance(dec, ast.Call) else dec
        name = _dotted(d).rsplit(".", 1)[-1]
        if name in TRACING_CALLS:
            return True
        if name == "partial" and isinstance(dec, ast.Call) and dec.args:
            inner = _dotted(dec.args[0]).rsplit(".", 1)[-1]
            if inner in TRACING_CALLS:
                return True
    return False


def _param_entries(fn) -> list[tuple[str, ast.AST | None, ast.AST | None]]:
    """(name, annotation, default) for every parameter."""
    a = fn.args
    out = []
    pos = a.posonlyargs + a.args
    defaults = [None] * (len(pos) - len(a.defaults)) + list(a.defaults)
    for arg, d in zip(pos, defaults):
        out.append((arg.arg, arg.annotation, d))
    for arg, d in zip(a.kwonlyargs, a.kw_defaults):
        out.append((arg.arg, arg.annotation, d))
    if a.vararg:
        out.append((a.vararg.arg, a.vararg.annotation, None))
    if a.kwarg:
        out.append((a.kwarg.arg, a.kwarg.annotation, None))
    return out


def _traced_params(fn) -> set[str]:
    """Parameters treated as traced values in a traced context.

    Keyword-only parameters are static by codebase convention: jax
    transforms (donate/static argnums, vmap axes) address operands
    positionally, so traced arrays ride positional slots and `*`-section
    params carry configuration (forward()'s flag block, the pallas
    kernels' scales_u16/mxu_bf16)."""
    out = set()
    a = fn.args
    kwonly = {arg.arg for arg in a.kwonlyargs}
    for name, ann, default in _param_entries(fn):
        if name == "self" or name in kwonly:
            continue
        ann_name = _ann_name(ann)
        ann_leaf = ann_name.rsplit(".", 1)[-1]
        if ann_name in HOST_ANNOTATIONS:
            continue
        if ann_leaf in STATIC_ANNOTATIONS:
            continue
        if name in STATIC_NAMES or name.endswith(("_mesh", "_dtype",
                                                  "_fn", "_name")):
            continue
        if _is_static_const(default):  # flag/config params default to
            continue                   # literals (False, "exact", 7, None)
        out.add(name)
    return out


def _device_params(fn) -> set[str]:
    """Parameters explicitly annotated as device arrays (DLG107 sources).
    Exact annotation match only: `np.ndarray` must NOT leaf-match
    `jnp.ndarray` — host numpy params are never device values."""
    return {name for name, ann, _ in _param_entries(fn)
            if _ann_name(ann) in DEVICE_ANNOTATIONS}


class ModuleLinter:
    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.source = source
        self.tree = ast.parse(source, filename=relpath)
        self.findings: list[Finding] = []
        self.traced_names = _collect_traced_fn_names(self.tree)
        # scope rules match package-relative paths whether the caller passed
        # "ops/norms.py" or "distributed_llama_tpu/ops/norms.py"
        scope = relpath.split("distributed_llama_tpu/", 1)[-1]
        self.is_kernel = scope.startswith(KERNEL_MODULES)
        self.in_ops = scope.startswith(OPS_MODULES)
        self.ban_debug = scope.startswith(DEBUG_BAN_MODULES)
        self.check_donate = any(scope.endswith(m) for m in DONATE_MODULES)
        self.host_sync = scope.startswith(HOST_SYNC_MODULES)

    def add(self, rule: str, severity: str, node: ast.AST, msg: str) -> None:
        self.findings.append(Finding(rule, severity, self.relpath,
                                     getattr(node, "lineno", 0), msg))

    def run(self) -> list[Finding]:
        if self.ban_debug:
            self._lint_debug_leftovers()
        for fn in self._functions(self.tree):
            if self._is_traced(fn):
                self._lint_traced_fn(fn)
            elif self.host_sync:
                self._lint_host_fn(fn)
            if self.check_donate:
                self._lint_donate(fn)
        supp = parse_suppressions(self.source)
        out, seen = [], set()
        for f in self.findings:
            if is_suppressed(f, supp):
                continue
            # one finding per (rule, line): a sync nested in a sync (e.g.
            # `int(min(..., int(n)))`) is one boundary crossing to fix
            if (f.rule, f.line) in seen:
                continue
            seen.add((f.rule, f.line))
            out.append(f)
        return out

    # -- helpers ----------------------------------------------------------

    def _functions(self, root) -> list[ast.FunctionDef]:
        return [n for n in ast.walk(root)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def _is_traced(self, fn) -> bool:
        if _has_jit_decorator(fn) or fn.name in self.traced_names:
            return True
        return self.is_kernel and not fn.name.startswith("host_")

    # -- DLG106: leftover debug output ------------------------------------

    def _lint_debug_leftovers(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _dotted(node.func)
            if fn.startswith("jax.debug.") or fn.startswith("debug."):
                self.add("DLG106", "error", node,
                         f"leftover `{fn}` in kernel code — remove before "
                         "merge (host callback per step on TPU)")
            elif fn == "print":
                self.add("DLG106", "error", node,
                         "leftover `print()` in kernel code — it runs at "
                         "trace time (or as a host callback) on TPU")

    # -- DLG105: donate_argnums on cache-carrying jits ---------------------

    def _lint_donate(self, fn) -> None:
        """Flag jax.jit(step_fn) where step_fn takes a `cache` param but the
        jit call passes no donate_argnums — decode would copy the KV cache
        every token instead of updating in place."""
        local_defs = {f.name: f for f in self._functions(fn)}

        def wrapped_params(callee) -> list[str]:
            if isinstance(callee, ast.Name) and callee.id in local_defs:
                return [p for p, _, _ in _param_entries(local_defs[callee.id])]
            if isinstance(callee, ast.Lambda):
                return [a.arg for a in callee.args.args]
            return []

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            leaf = _dotted(node.func).rsplit(".", 1)[-1]
            if leaf != "jit" or not node.args:
                continue
            params = wrapped_params(node.args[0])
            if "cache" not in params:
                continue
            kwargs = {k.arg for k in node.keywords}
            if "donate_argnums" not in kwargs and "donate_argnames" not in kwargs:
                self.add("DLG105", "warning", node,
                         "jax.jit of a cache-carrying step without "
                         "donate_argnums — the KV cache update copies "
                         "instead of aliasing (per-token realloc)")

        # decorator form: @partial(jax.jit, ...) / @jax.jit on a def whose
        # params include `cache`
        for f in self._functions(fn) + [fn]:
            params = [p for p, _, _ in _param_entries(f)]
            if "cache" not in params:
                continue
            for dec in f.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                name = _dotted(d).rsplit(".", 1)[-1]
                is_jit = name == "jit" or (
                    name == "partial" and isinstance(dec, ast.Call)
                    and dec.args
                    and _dotted(dec.args[0]).rsplit(".", 1)[-1] == "jit")
                if not is_jit:
                    continue
                kw = ({k.arg for k in dec.keywords}
                      if isinstance(dec, ast.Call) else set())
                if "donate_argnums" not in kw and "donate_argnames" not in kw:
                    self.add("DLG105", "warning", f,
                             f"jitted `{f.name}` takes a cache but donates "
                             "nothing — KV cache copies every step")

    # -- traced-context rules (DLG101/102/103/104) -------------------------

    def _lint_traced_fn(self, fn) -> None:
        taint = _Taint(_traced_params(fn))
        nested = {n for f in self._functions(fn) if f is not fn
                  for n in ast.walk(f)}
        self._walk_stmts(fn.body, taint, fn, skip=nested)

    def _walk_stmts(self, stmts, taint: _Taint, fn, skip) -> None:
        for stmt in stmts:
            self._lint_stmt(stmt, taint, fn, skip)

    def _lint_stmt(self, stmt, taint: _Taint, fn, skip) -> None:
        if stmt in skip:
            return
        # sink checks cover THIS statement's own expressions only; nested
        # blocks are linted by the recursion below AFTER earlier statements
        # in them have propagated (a pre-walk of the whole subtree would
        # judge inner lines with stale pre-branch taint — false positives)
        for node in _stmt_exprs(stmt):
            if node in skip:
                continue
            if isinstance(node, ast.Call):
                self._check_sync_call(node, taint, "DLG101",
                                      "inside a traced context")
                self._check_numpy_call(node, taint)
            elif self.in_ops and isinstance(node, ast.BinOp):
                self._check_literal_dtype(node, taint)
        # control flow on traced booleans
        if isinstance(stmt, (ast.If, ast.While)):
            if self._branch_taint(stmt.test, taint):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                self.add("DLG103", "error", stmt,
                         f"Python `{kind}` on a traced value "
                         f"(`{ast.unparse(stmt.test)}`) — use lax.cond/"
                         "while_loop or jnp.where; this concretizes under "
                         "jit")
            self._walk_stmts(stmt.body, taint, fn, skip)
            self._walk_stmts(stmt.orelse, taint, fn, skip)
            return
        if isinstance(stmt, ast.Assert) and self._branch_taint(stmt.test,
                                                               taint):
            self.add("DLG103", "error", stmt,
                     "assert on a traced value — concretizes under jit; "
                     "use checkify or move the check to host code")
        # taint propagation
        if isinstance(stmt, ast.Assign):
            t = taint.expr(stmt.value)
            for tgt in stmt.targets:
                taint.assign(tgt, t)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            taint.assign(stmt.target, taint.expr(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            if taint.expr(stmt.value):
                taint.assign(stmt.target, True)
        elif isinstance(stmt, ast.For):
            taint.assign(stmt.target, taint.expr(stmt.iter))
            self._walk_stmts(stmt.body, taint, fn, skip)
            self._walk_stmts(stmt.orelse, taint, fn, skip)
        elif isinstance(stmt, (ast.With,)):
            self._walk_stmts(stmt.body, taint, fn, skip)
        elif isinstance(stmt, ast.Try):
            for block in (stmt.body, stmt.orelse, stmt.finalbody):
                self._walk_stmts(block, taint, fn, skip)
            for h in stmt.handlers:
                self._walk_stmts(h.body, taint, fn, skip)

    def _branch_taint(self, test: ast.AST, taint: _Taint) -> bool:
        """Branch-condition taint: bare-name truthiness is NOT flagged —
        `if layers:` / `if params and ...:` on pytree containers is
        len()-style static logic, and the real traced-branch bug is a
        comparison or computation on a traced value (`if pos > 0:`). A
        tainted Compare/BinOp/Call/Subscript inside the test still fires.
        """
        if isinstance(test, ast.Name):
            return False
        if isinstance(test, ast.BoolOp):
            return any(self._branch_taint(v, taint) for v in test.values)
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._branch_taint(test.operand, taint)
        return taint.expr(test)

    def _check_sync_call(self, node: ast.Call, taint: _Taint, rule: str,
                         where: str) -> None:
        fn = _dotted(node.func)
        leaf = fn.rsplit(".", 1)[-1]
        # x.item() / x.tolist() / x.block_until_ready()
        if (isinstance(node.func, ast.Attribute) and leaf in SYNC_METHODS
                and taint.expr(node.func.value)):
            self.add(rule, "error" if rule == "DLG101" else "info", node,
                     f"`.{leaf}()` on `{ast.unparse(node.func.value)}` "
                     f"{where} — device-to-host sync")
            return
        if fn in ("jax.device_get", "device_get"):
            self.add(rule, "error" if rule == "DLG101" else "info", node,
                     f"`jax.device_get` {where} — device-to-host sync")
            return
        args_tainted = any(taint.expr(a) for a in node.args)
        if not args_tainted:
            return
        if fn in BUILTIN_SYNC_FUNCS:
            arg = ast.unparse(node.args[0]) if node.args else ""
            self.add(rule, "error" if rule == "DLG101" else "info", node,
                     f"`{fn}({arg})` {where} — concretizes/syncs the value")
        elif fn.startswith(("np.", "numpy.")) and leaf in NUMPY_SYNC_FUNCS:
            arg = ast.unparse(node.args[0]) if node.args else ""
            self.add(rule, "error" if rule == "DLG101" else "info", node,
                     f"`{fn}({arg})` {where} — device-to-host transfer")

    def _check_numpy_call(self, node: ast.Call, taint: _Taint) -> None:
        fn = _dotted(node.func)
        if not fn.startswith(("np.", "numpy.")):
            return
        leaf = fn.rsplit(".", 1)[-1]
        if leaf in NUMPY_SYNC_FUNCS:
            return  # DLG101's finding; don't double-report
        if any(taint.expr(a) for a in node.args) or any(
                taint.expr(k.value) for k in node.keywords):
            self.add("DLG102", "error", node,
                     f"`{fn}` called on a traced value — numpy cannot "
                     "trace; this concretizes (host round-trip) or raises "
                     "TracerError")

    def _check_literal_dtype(self, node: ast.BinOp, taint: _Taint) -> None:
        for lit, other in ((node.left, node.right), (node.right, node.left)):
            if isinstance(lit, ast.UnaryOp):
                lit = lit.operand
            if (isinstance(lit, ast.Constant) and isinstance(lit.value, float)
                    and taint.expr(other)):
                self.add("DLG104", "info", node,
                         f"bare float literal `{lit.value}` in kernel "
                         "arithmetic — wrap as jnp.float32(...) so the op "
                         "dtype is explicit (promotion bait under x64/"
                         "mixed-precision edits)")
                return

    # -- DLG107: host-side boundary syncs ----------------------------------

    def _lint_host_fn(self, fn) -> None:
        """Track device values through HOST code in runtime modules and flag
        every host-sync conversion. Sources: params annotated jax.Array,
        results of jnp/compiled-step calls. Deliberate boundaries (sampler
        input, stats) are baselined or inline-ignored."""
        taint = _Taint(_device_params(fn))
        devfns: set[str] = set()  # names holding jitted-step callables

        nested = {n for f in self._functions(fn) if f is not fn
                  for n in ast.walk(f)}

        class T(_Taint):
            def expr(self, node):  # calls through jitted handles yield
                if isinstance(node, ast.Call):  # device values
                    f = _dotted(node.func)
                    if f in devfns or f.rsplit(".", 1)[-1] in devfns:
                        return True
                    if f.startswith(("jnp.", "jax.numpy.")):
                        return True
                    if f in ("self._compiled_step", "jax.device_put"):
                        return True
                return _Taint.expr(self, node)

        t = T(taint.names)

        def is_devfn_expr(node) -> bool:
            if isinstance(node, ast.Subscript):
                return _dotted(node.value) in ("self._steps",)
            if isinstance(node, ast.Call):
                return _dotted(node.func) in ("self._compiled_step",
                                              "jax.jit", "jit")
            return False

        # statement order matters: `n = int(n)` must flag the sync AND
        # un-taint `n` for the lines below — so sinks are checked per
        # statement BEFORE that statement's assignment propagates
        def walk_body(stmts):
            for stmt in stmts:
                if stmt in nested:
                    continue
                for node in _stmt_exprs(stmt):
                    if node in nested or not isinstance(node, ast.Call):
                        continue
                    self._check_sync_call(node, t, "DLG107",
                                          "at the host-device boundary")
                if isinstance(stmt, ast.Assign):
                    if is_devfn_expr(stmt.value):
                        for tgt in stmt.targets:
                            if isinstance(tgt, ast.Name):
                                devfns.add(tgt.id)
                    else:
                        tv = t.expr(stmt.value)
                        for tgt in stmt.targets:
                            t.assign(tgt, tv)
                elif isinstance(stmt, ast.AugAssign) and t.expr(stmt.value):
                    t.assign(stmt.target, True)
                for block in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, block, None)
                    if isinstance(sub, list):
                        walk_body(sub)
                for h in getattr(stmt, "handlers", []):
                    walk_body(h.body)

        walk_body(fn.body)


def lint_source(relpath: str, source: str) -> list[Finding]:
    return ModuleLinter(relpath, source).run()


def lint_file(root: str, relpath: str) -> list[Finding]:
    # explicit utf-8: the locale default is cp1252 on the Windows CI leg,
    # which cannot decode this repo's source bytes
    with open(os.path.join(root, relpath), encoding="utf-8") as f:
        return lint_source(relpath, f.read())


def iter_package_files(pkg_root: str) -> list[str]:
    """All .py files under the package dir, POSIX-style relative paths,
    sorted. Posix separators are load-bearing twice over: the scope rules
    (KERNEL_MODULES etc.) match with '/', and Finding.file is a baseline
    key that must be identical across platforms."""
    out = []
    for dirpath, _, files in os.walk(pkg_root):
        for name in files:
            if name.endswith(".py"):
                full = os.path.join(dirpath, name)
                rel = os.path.relpath(full, pkg_root).replace(os.sep, "/")
                out.append(rel)
    return sorted(out)


def lint_package(pkg_root: str, prefix: str = "") -> list[Finding]:
    """Lint every module under pkg_root; `prefix` is prepended to relative
    paths in findings (e.g. 'distributed_llama_tpu/')."""
    findings: list[Finding] = []
    for rel in iter_package_files(pkg_root):
        with open(os.path.join(pkg_root, rel), encoding="utf-8") as f:
            src = f.read()
        findings.extend(lint_source(prefix + rel, src))
    return findings
