"""DLG206: device-to-host transfers on the per-token serving path.

DLG107 makes every host-device boundary sync in runtime code a visible
decision; this pass adds the dimension that matters for ROADMAP item 4
(the dispatch-bound host loop): WHICH of those syncs sit on the per-token
serving path. A `.item()` in a save/load helper costs nothing; the same
call reachable from the scheduler's step body executes once per decode
iteration across the whole batch and is exactly the host work a
multi-token dispatch redesign must move or batch.

Mechanism: a leaf-name call graph over the runtime tier (plus
sampler.py), BFS-reachable from the per-token roots below, then the
DLG107 taint machinery re-run per file — any DLG107-shaped sync whose
line falls inside a reachable function is re-emitted as DLG206. The
call graph matches by attribute/function leaf name, so `self.engine.
slot_decode_step(...)` reaches `Engine.slot_decode_step` without type
inference; over-approximation is fine (a false edge can only ADD a
finding that DLG107 already judged a real sync).

The currently-accepted host-sampling sites are baselined with
justifications — the rule lands green but the per-token sync budget is
now enumerated in one place (`baseline.json`, keys starting DLG206).
"""

from __future__ import annotations

import ast
import os

from .ast_lint import _dotted, iter_package_files, lint_source
from .findings import Finding

# files the call graph covers (package-relative, posix)
SERVING_FILES = ("runtime/", "sampler.py")

# per-token serving roots: (file suffix, function leaf name). The
# scheduler step body is the continuous-batching inner loop; the legacy
# streaming generators are the apps/ serving path for single requests.
SERVING_ROOTS = (
    ("runtime/scheduler.py", "_step_body"),
    ("runtime/engine.py", "generate"),
    ("runtime/engine.py", "generate_lookup_stream"),
    ("runtime/engine.py", "generate_draft_sampled_stream"),
)


def _functions_with_spans(tree: ast.Module):
    """(leaf name, lineno, end_lineno, called leaf names) per function."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        calls = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                leaf = _dotted(sub.func).rsplit(".", 1)[-1]
                if leaf:
                    calls.add(leaf)
        out.append((node.name, node.lineno,
                    getattr(node, "end_lineno", node.lineno), calls))
    return out


def audit_serving_path(pkg_root: str, prefix: str = "") -> list[Finding]:
    # parse the serving tier once
    table: dict[str, list] = {}       # file -> [(name, lo, hi, calls)]
    by_name: dict[str, list] = {}     # leaf name -> [(file, lo, hi, calls)]
    sources: dict[str, str] = {}
    for rel in iter_package_files(pkg_root):
        scope = rel.split("distributed_llama_tpu/", 1)[-1]
        if not (scope.startswith(SERVING_FILES[0])
                or scope == SERVING_FILES[1]):
            continue
        with open(os.path.join(pkg_root, rel), encoding="utf-8") as f:
            src = f.read()
        sources[rel] = src
        fns = _functions_with_spans(ast.parse(src, filename=rel))
        table[rel] = fns
        for name, lo, hi, calls in fns:
            by_name.setdefault(name, []).append((rel, lo, hi, calls))

    # BFS by leaf name from the roots
    reachable: set[tuple[str, str]] = set()    # (file, fn name)
    frontier: list[tuple[str, str, set]] = []
    for root_file, root_fn in SERVING_ROOTS:
        for rel, fns in table.items():
            if not rel.endswith(root_file):
                continue
            for name, lo, hi, calls in fns:
                if name == root_fn:
                    frontier.append((rel, name, calls))
    while frontier:
        rel, name, calls = frontier.pop()
        if (rel, name) in reachable:
            continue
        reachable.add((rel, name))
        for callee in calls:
            for crel, lo, hi, ccalls in by_name.get(callee, []):
                if (crel, callee) not in reachable:
                    frontier.append((crel, callee, ccalls))

    # re-run the DLG107 machinery and keep syncs inside reachable spans.
    # nested defs share the enclosing function's span — containment over
    # the SMALLEST enclosing reachable function keeps it precise enough.
    findings: list[Finding] = []
    for rel, src in sources.items():
        spans = [(lo, hi) for (name, lo, hi, _) in table[rel]
                 if (rel, name) in reachable]
        if not spans:
            continue
        for f in lint_source(prefix + rel, src):
            if f.rule != "DLG107":
                continue
            if any(lo <= f.line <= hi for lo, hi in spans):
                findings.append(Finding(
                    "DLG206", "info", f.file, f.line,
                    f"{f.message} — on the per-token serving path (runs "
                    "every decode iteration)"))
    return findings
