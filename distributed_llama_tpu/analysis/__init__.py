"""dlgrind: JAX-aware static analysis for the TPU port.

Two levels (see docs/analysis.md for the rule catalogue):

  * Level 1 — AST lint over the package source (ast_lint.py, no JAX
    import): host syncs / numpy calls / Python control flow on traced
    values, implicit-dtype literals in kernels, missing donate_argnums,
    leftover debug output.
  * Level 2 — jaxpr audit of the public jitted entry points
    (jaxpr_audit.py + entrypoints.py): host-callback primitives, f64
    promotion under x64 tracing, full-precision activation re-replication,
    signature-fingerprint drift.

Run `python -m distributed_llama_tpu.analysis --check` (the CI gate), or
let pytest collect the same gate via tests/test_analysis.py. Accepted
findings live in analysis/baseline.json; suppress single lines with
`# dlgrind: ignore[RULE]`.
"""

from .findings import Finding  # noqa: F401
