"""Audited entry points: the public jitted programs, built with tiny
abstract-friendly inputs.

One registry shared by the jaxpr audit (`python -m
distributed_llama_tpu.analysis`) and the test suite (tests/conftest.py
exposes `build_forward_inputs` so tests/test_hlo_wire.py lowers the SAME
programs the audit walks — the wire model, the HLO counter, and the static
analyzer all look at one set of entry points).

Inputs are tiny concrete zero-weight models (dim 64, 2 layers): tracing
never reads values, only shapes/dtypes, and building zeros is cheaper and
simpler than threading ShapeDtypeStructs through the params pytree. No XLA
compilation happens here — `jax.make_jaxpr` stops at the jaxpr.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class EntryPoint:
    name: str
    fn: Callable          # positional-args callable to trace
    args: tuple           # example inputs (tiny, concrete)
    meta: dict            # activation_elems: full (B*T*dim) activation size
    needs_mesh: int = 1   # device count required (skip if unavailable)


def _tiny_spec(arch="LLAMA", **overrides):
    from ..models import ArchType, HiddenAct, ModelSpec

    base = dict(
        arch=getattr(ArchType, arch), dim=64, hidden_dim=128, n_layers=2,
        n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=32,
        hidden_act=HiddenAct.SILU, rope_theta=10000.0,
    )
    if arch in ("MIXTRAL", "GROK1"):
        base.update(n_experts=4, n_active_experts=2)
    base.update(overrides)
    return ModelSpec(**base)


def _zero_params(spec, dtype=jnp.float32):
    from ..models.params import load_params, random_tensors

    # random_tensors builds the full HostTensor plan; zeros would do, but
    # the plan builder is the one source of truth for tensor shapes
    host = random_tensors(spec, seed=0, scale=0.0)
    return load_params(spec, host, mode="dense", dtype=dtype)


def build_forward_inputs(spec=None, *, batch: int = 1, t: int = 1,
                         seq_len: int | None = None, dtype=jnp.float32,
                         arch: str = "LLAMA"):
    """(spec, params, tokens, pos0, cache) for a forward() call — the shared
    builder tests/test_hlo_wire.py and the jaxpr audit both trace through.
    """
    from ..models.transformer import KVCache

    if spec is None:
        spec = _tiny_spec(arch)
    params = _zero_params(spec, dtype)
    cache = KVCache.create(spec, batch=batch,
                           seq_len=seq_len or spec.seq_len, dtype=dtype)
    tokens = jnp.zeros((batch, t), jnp.int32)
    return spec, params, tokens, jnp.int32(0), cache


def entry_points(max_devices: int | None = None,
                 ) -> tuple[list[EntryPoint], list[tuple[str, int]]]:
    """The audited programs: (buildable entries, unavailable ones).

    Mesh-dependent entries can only be BUILT when enough devices exist
    (the CI/lint environment pins 8 virtual CPU devices via XLA_FLAGS,
    same as tests/conftest.py); the ones that cannot are still DECLARED in
    the second list as (name, devices_needed) so the audit can fail loudly
    instead of passing vacuously on a short mesh."""
    from ..models.transformer import forward

    n_dev = jax.device_count() if max_devices is None else max_devices
    out: list[EntryPoint] = []
    unavailable: list[tuple[str, int]] = []
    if n_dev < 2:
        unavailable += [("tp_q80_col", 2), ("tp_exact_col", 2),
                        ("tp_row", 2)]
    if n_dev < 4:
        unavailable += [("ep_moe_decode", 4)]

    # -- decode step (single token, donated cache in the engine) ----------
    spec, params, tok, pos0, cache = build_forward_inputs(t=1)

    def decode_step(params, tok, pos0, cache):
        return forward(params, spec, tok, pos0, cache,
                       compute_dtype=jnp.float32)

    out.append(EntryPoint(
        "decode_step", decode_step, (params, tok, pos0, cache),
        {"activation_elems": 1 * 1 * spec.dim, "dim": spec.dim}))

    # -- prefill segment (T tokens, logit_index like the engine's bpre) ---
    spec_p, params_p, tok_p, pos0_p, cache_p = build_forward_inputs(t=8)

    def prefill(params, tok, logit_index, cache):
        return forward(params, spec_p, tok, jnp.int32(0), cache,
                       logit_index=logit_index, compute_dtype=jnp.float32)

    out.append(EntryPoint(
        "prefill", prefill, (params_p, tok_p, jnp.asarray([7]), cache_p),
        {"activation_elems": 1 * 8 * spec_p.dim, "dim": spec_p.dim}))

    # -- continuous-batching scheduler hot path (runtime/scheduler.py) ----
    # slot_decode_step: (B, 1) tokens at per-row positions (the scatter
    # cache-write path); gated rows pass pos == seq_len. Any host callback
    # or f64 traced into this program stalls EVERY serving step — the
    # audit is the CI gate the scheduler rides on.
    spec_s, params_s, tok_s, _, cache_s = build_forward_inputs(batch=4, t=1)
    pos_s = jnp.zeros((4,), jnp.int32)

    def slot_decode_step(params, tok, pos, cache):
        return forward(params, spec_s, tok, pos, cache,
                       compute_dtype=jnp.float32)

    out.append(EntryPoint(
        "slot_decode_step", slot_decode_step,
        (params_s, tok_s, pos_s, cache_s),
        {"activation_elems": 4 * 1 * spec_s.dim, "dim": spec_s.dim}))

    # slot_prefill_chunk: (B, C) chunk at per-row offsets with per-row
    # logit_index (C is the engine's only prefill compilation key — tail
    # chunks pad to C, so this ONE signature covers the whole prefill path)
    spec_c, params_c, tok_c, _, cache_c = build_forward_inputs(batch=4, t=8)
    pos_c = jnp.zeros((4,), jnp.int32)
    lidx_c = jnp.full((4,), 7, jnp.int32)

    def slot_prefill_chunk(params, tok, pos, logit_index, cache):
        return forward(params, spec_c, tok, pos, cache,
                       logit_index=logit_index, compute_dtype=jnp.float32)

    out.append(EntryPoint(
        "slot_prefill_chunk", slot_prefill_chunk,
        (params_c, tok_c, pos_c, lidx_c, cache_c),
        {"activation_elems": 4 * 8 * spec_c.dim, "dim": spec_c.dim}))

    # slot_seed_prefix: the radix prefix cache's admission-time seeding
    # (runtime/prefix_cache.py) — an on-device arena-block gather written
    # as a slot row's leading cache positions. Traced through the SAME
    # module-level body the engine jits (engine.seed_rows_from_blocks),
    # so the pinned fingerprint covers the real serving seed path: a
    # drifting block_ids dtype or arity here would retrace per admission.
    from ..runtime.engine import seed_rows_from_blocks

    spec_x, _, _, _, cache_x = build_forward_inputs(batch=4, t=1)
    bl_x = 8
    mb_x = spec_x.seq_len // bl_x
    arena_shape = (4, spec_x.n_layers, spec_x.n_kv_heads, bl_x,
                   spec_x.head_size)
    arena_k = jnp.zeros(arena_shape, jnp.float32)
    arena_v = jnp.zeros(arena_shape, jnp.float32)
    ids_x = jnp.zeros((mb_x,), jnp.int32)

    def slot_seed_prefix(cache, arena_k, arena_v, row, block_ids):
        return seed_rows_from_blocks(cache, arena_k, arena_v, row,
                                     block_ids)

    out.append(EntryPoint(
        "slot_seed_prefix", slot_seed_prefix,
        (cache_x, arena_k, arena_v, jnp.int32(0), ids_x),
        {"activation_elems": mb_x * bl_x * spec_x.n_kv_heads
         * spec_x.head_size, "dim": spec_x.dim}))

    # block_export / block_import: the cross-replica KV transfer plane's
    # two arena executables (runtime/kv_transfer.py) — traced through
    # the SAME module-level bodies the engine jits
    # (engine.export_arena_block / import_arena_block), so the pinned
    # fingerprints cover the real donor/importer paths: a drifting
    # block-index dtype here would retrace per transferred block.
    from ..runtime.engine import export_arena_block, import_arena_block

    def block_export(arena_k, arena_v, src):
        return export_arena_block(arena_k, arena_v, src)

    out.append(EntryPoint(
        "block_export", block_export,
        (arena_k, arena_v, jnp.int32(0)),
        {"activation_elems": bl_x * spec_x.n_kv_heads * spec_x.head_size,
         "dim": spec_x.dim}))

    blk_k = jnp.zeros(arena_shape[1:], jnp.float32)
    blk_v = jnp.zeros(arena_shape[1:], jnp.float32)

    def block_import(arena_k, arena_v, k_blk, v_blk, dst):
        return import_arena_block(arena_k, arena_v, k_blk, v_blk, dst)

    out.append(EntryPoint(
        "block_import", block_import,
        (arena_k, arena_v, blk_k, blk_v, jnp.int32(0)),
        {"activation_elems": bl_x * spec_x.n_kv_heads * spec_x.head_size,
         "dim": spec_x.dim}))

    # -- speculative-decoding serving executables (runtime/draft.py) ------
    # draft_forward: the k-step greedy draft scan (truncated-depth spec —
    # n_layers 1 of the tiny 2 mirrors the self-draft slice). Traced
    # through the SAME module-level body the engine jits
    # (draft.draft_scan_tokens), so the pinned fingerprint covers the
    # real per-slot draft path; a drifting pos dtype here would retrace
    # per proposal and stall every speculative iteration.
    from ..runtime.draft import batched_verify, draft_scan_tokens

    import dataclasses as _dc

    spec_d = _dc.replace(_tiny_spec(), n_layers=1)
    params_d = _zero_params(spec_d)
    from ..models.transformer import KVCache as _KVC

    cache_d = _KVC.create(spec_d, batch=4, seq_len=spec_d.seq_len,
                          dtype=jnp.float32)
    tok_d = jnp.zeros((4, 1), jnp.int32)
    pos_d = jnp.zeros((4,), jnp.int32)

    def draft_forward(params, tok0, pos, cache):
        return draft_scan_tokens(params, spec_d, tok0, pos, cache, k=2,
                                 n_vocab=spec_d.vocab_size,
                                 fwd_kwargs=dict(
                                     compute_dtype=jnp.float32))

    out.append(EntryPoint(
        "draft_forward", draft_forward, (params_d, tok_d, pos_d, cache_d),
        {"activation_elems": 4 * 1 * spec_d.dim, "dim": spec_d.dim}))

    # slot_verify: the fixed-width (B, 1+K) verify forward with on-device
    # argmax — the scheduler's one speculative target executable
    # (Engine.slot_verify_step jits the same draft.batched_verify body)
    spec_v, params_v, tok_v, _, cache_v = build_forward_inputs(batch=4,
                                                               t=3)
    pos_v = jnp.zeros((4,), jnp.int32)

    def slot_verify(params, tok, pos, cache):
        return batched_verify(params, spec_v, tok, pos, cache,
                              n_vocab=spec_v.vocab_size,
                              fwd_kwargs=dict(compute_dtype=jnp.float32))

    out.append(EntryPoint(
        "slot_verify", slot_verify, (params_v, tok_v, pos_v, cache_v),
        {"activation_elems": 4 * 3 * spec_v.dim, "dim": spec_v.dim}))

    if n_dev < 2:
        unavailable += [("embed_tokens_sharded", 2),
                        ("sharded_sample_prep", 2)]

    if n_dev >= 2:
        from ..parallel import make_mesh
        from ..parallel.tp_q80 import tp_col_matmul, tp_row_matmul

        mesh = make_mesh(tp=2, dp=1)
        dim, hidden = 64, 128
        x = jnp.zeros((1, 1, hidden), jnp.float32)

        # -- vocab sharding (ops/sharded_vocab.py) ------------------------
        # embed_tokens_sharded: the masked local gather + all-reduce that
        # replaces the replicated emb[tokens] lookup. Traced through the
        # SAME module-level body the engine's forward() calls, so the
        # pinned fingerprint covers the real serving embedding path.
        from ..ops.sharded_vocab import (embed_tokens_sharded,
                                         sharded_sample_prep)

        spec_e = _tiny_spec()
        emb_e = jnp.zeros((spec_e.vocab_size, spec_e.dim), jnp.float32)
        tok_e = jnp.zeros((2, 4), jnp.int32)

        def embed_tokens(emb, tok):
            return embed_tokens_sharded(emb, tok, mesh, ("tp",),
                                        jnp.float32)

        out.append(EntryPoint(
            "embed_tokens_sharded", embed_tokens, (emb_e, tok_e),
            {"activation_elems": 2 * 4 * spec_e.dim, "dim": spec_e.dim},
            needs_mesh=2))

        # sharded_sample_prep: the serving-path sampling summary — device
        # argmax + per-shard top-k candidates off vocab-sharded logits.
        # meta["vocab"] arms DLG205: no output (and no all_gather) of
        # this program may carry a vocab-sized dim — the whole point is
        # that full logits never materialize on the serving path.
        lg_s2 = jnp.zeros((4, spec_e.vocab_size), jnp.float32)
        temps_s = jnp.ones((4,), jnp.float32)

        def sample_prep(logits, temps):
            return sharded_sample_prep(logits, temps, mesh, ("tp",),
                                       spec_e.vocab_size, 8)

        out.append(EntryPoint(
            "sharded_sample_prep", sample_prep, (lg_s2, temps_s),
            {"activation_elems": 4 * spec_e.dim, "dim": spec_e.dim,
             "vocab": spec_e.vocab_size},
            needs_mesh=2))

        # -- q80-compressed col-split reduce (the wire-compression path) --
        from ..parallel.tp_q80 import repack_col_tp

        w_col = repack_col_tp(jnp.zeros((dim, hidden), jnp.float32), 2)

        def tp_q80_col(x, w):
            return tp_col_matmul(x, w, mesh, reduce="q80",
                                 compute_dtype=jnp.float32)

        out.append(EntryPoint(
            "tp_q80_col", tp_q80_col, (x, w_col),
            {"activation_elems": 1 * 1 * dim, "dim": dim}, needs_mesh=2))

        # -- exact col-split reduce (GSPMD-equivalent shard_map path) -----
        def tp_exact_col(x, w):
            return tp_col_matmul(x, w, mesh, reduce="exact",
                                 compute_dtype=jnp.float32)

        out.append(EntryPoint(
            "tp_exact_col", tp_exact_col, (x, w_col),
            {"activation_elems": 1 * 1 * dim, "dim": dim}, needs_mesh=2))

        # -- row-split matmul (communication-free by design) --------------
        from ..parallel.tp_q80 import TpRowWeight

        xr = jnp.zeros((1, dim), jnp.float32)
        w_row = TpRowWeight(jnp.zeros((hidden, dim), jnp.float32))

        def tp_row(x, w):
            return tp_row_matmul(x, w, mesh, compute_dtype=jnp.float32,
                                 use_pallas=False)

        out.append(EntryPoint(
            "tp_row", tp_row, (xr, w_row),
            {"activation_elems": 1 * 1 * dim, "dim": dim}, needs_mesh=2))

    if n_dev >= 4:
        from ..parallel import make_mesh
        from ..parallel.ep_moe import repack_moe_ep

        spec_m = _tiny_spec("MIXTRAL")
        mesh_ep = make_mesh(ep=2, tp=2, dp=1)
        params_m = _zero_params(spec_m)
        params_m = dict(params_m)
        params_m["layers"] = [repack_moe_ep(lw, 2)
                              for lw in params_m["layers"]]
        from ..models.transformer import KVCache as _KV

        cache_m = _KV.create(spec_m, batch=1, seq_len=spec_m.seq_len,
                             dtype=jnp.float32)
        tok_m = jnp.zeros((1, 1), jnp.int32)

        def ep_moe_decode(params, tok, pos0, cache):
            return forward(params, spec_m, tok, pos0, cache,
                           compute_dtype=jnp.float32, tp_mesh=mesh_ep)

        out.append(EntryPoint(
            "ep_moe_decode", ep_moe_decode,
            (params_m, tok_m, jnp.int32(0), cache_m),
            {"activation_elems": 1 * 1 * spec_m.dim, "dim": spec_m.dim},
            needs_mesh=4))

    return out, unavailable


def signature_fingerprint(ep: EntryPoint) -> str:
    """Hash of the entry point's COMPILATION KEY — the input avals
    (shape/dtype/weak_type) in pytree order. A drifting fingerprint means
    the jit cache key changed: a host scalar became a weak-typed Python
    int (silent retrace per distinct value), an input dtype widened, or an
    argument was added. DLG204 compares this against the baseline."""
    import hashlib

    leaves = jax.tree_util.tree_leaves(ep.args)
    parts = []
    for leaf in leaves:
        aval = jax.api_util.shaped_abstractify(leaf)
        parts.append(f"{aval.shape}:{aval.dtype}:{getattr(aval, 'weak_type', False)}")
    blob = ep.name + ";" + "|".join(parts)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def make_jaxpr_for(ep: EntryPoint, x64: bool = False):
    """Trace the entry point to a ClosedJaxpr (no compilation). With
    x64=True the trace runs under jax.experimental.enable_x64 so an
    accidental f64 promotion becomes VISIBLE as an f64 aval instead of
    being silently truncated to f32 by the global x64=off default."""
    if x64:
        with jax.experimental.enable_x64():
            # re-cast inputs under the x64 regime: well-typed code keeps
            # every explicit dtype; only promotion leaks drift to f64
            return jax.make_jaxpr(ep.fn)(*ep.args)
    return jax.make_jaxpr(ep.fn)(*ep.args)
