from .types import FloatType, BLOCK_SIZE, batch_bytes, numbers_per_batch
from .numpy_codec import (
    quantize_q40,
    dequantize_q40,
    quantize_q80,
    dequantize_q80,
    q40_bytes_to_arrays,
    q40_arrays_to_bytes,
    q80_bytes_to_arrays,
    q80_arrays_to_bytes,
)
from .jax_codec import (
    dequantize_q40_jax,
    quantize_q80_jax,
    dequantize_q80_jax,
    QuantizedTensor,
)

__all__ = [
    "FloatType",
    "BLOCK_SIZE",
    "batch_bytes",
    "numbers_per_batch",
    "quantize_q40",
    "dequantize_q40",
    "quantize_q80",
    "dequantize_q80",
    "q40_bytes_to_arrays",
    "q40_arrays_to_bytes",
    "q80_bytes_to_arrays",
    "q80_arrays_to_bytes",
    "dequantize_q40_jax",
    "quantize_q80_jax",
    "dequantize_q80_jax",
    "QuantizedTensor",
]
