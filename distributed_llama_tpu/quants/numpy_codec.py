"""Host-side (numpy) Q40/Q80 block codecs.

Encoders follow the reference converter (ref: converter/writer.py:26-75) —
including the asymmetric `+8.5` offset with clamp-to-15 on Q40 — and decoders
follow the reference engine (ref: src/quants.cpp:133-180, 266-284), so bytes
produced here are loadable by the reference and vice versa.

All codecs are fully vectorized; these run at model-load time (the device-side
hot path lives in jax_codec.py / ops.matmul).
"""

from __future__ import annotations

import numpy as np

from .types import BLOCK_SIZE, Q40_BLOCK_BYTES, Q80_BLOCK_BYTES

_HALF = BLOCK_SIZE // 2


def quantize_q40(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """f32 (..., n) -> (scales f16 (..., n/32), packed uint8 (..., n/32, 16)).

    Matches converter/writer.py:26-54: scale = max-magnitude/-8 (sign kept),
    q = trunc(clip(x/scale + 8.5, None, 15)).
    """
    x = np.asarray(x, dtype=np.float32)
    assert x.shape[-1] % BLOCK_SIZE == 0, x.shape
    groups = x.reshape(*x.shape[:-1], -1, BLOCK_SIZE)
    gmax = groups.max(axis=-1)
    gmin = groups.min(axis=-1)
    deltas = np.where(-gmin > gmax, gmin, gmax) / -8.0
    inv = np.where(deltas != 0, np.divide(1.0, deltas, where=deltas != 0), 0.0)
    q = groups * inv[..., None] + 8.5
    q = np.minimum(q, 15.0).astype(np.int32)  # trunc toward zero like int()
    lo = q[..., :_HALF] & 0xF
    hi = q[..., _HALF:] & 0xF
    packed = (lo | (hi << 4)).astype(np.uint8)
    return deltas.astype(np.float16), packed


def dequantize_q40(scales: np.ndarray, packed: np.ndarray) -> np.ndarray:
    """Inverse of quantize_q40 per the engine decoder (ref: src/quants.cpp:166-179):
    value j in [0,16) = (lo nibble - 8) * d, value j+16 = (hi nibble - 8) * d.

    Arbitrary file bytes can carry NaN/inf f16 scale patterns (fuzz /
    malformed models); they propagate into the values exactly like the
    reference's f16 LUT lookup would, without a numpy warning."""
    lo = (packed & 0xF).astype(np.int8) - 8
    hi = (packed >> 4).astype(np.int8) - 8
    vals = np.concatenate([lo, hi], axis=-1).astype(np.float32)
    with np.errstate(invalid="ignore"):
        out = vals * scales[..., None].astype(np.float32)
    return out.reshape(*out.shape[:-2], -1)


def quantize_q80(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """f32 (..., n) -> (scales f16 (..., n/32), int8 (..., n/32, 32)).

    Matches converter/writer.py:56-75 (scale = absmax/127, round-half-even).
    """
    x = np.asarray(x, dtype=np.float32)
    assert x.shape[-1] % BLOCK_SIZE == 0, x.shape
    groups = x.reshape(*x.shape[:-1], -1, BLOCK_SIZE)
    absmax = np.abs(groups).max(axis=-1)
    deltas = absmax / 127.0
    inv = np.where(deltas != 0, np.divide(1.0, deltas, where=deltas != 0), 0.0)
    q = np.round(groups * inv[..., None]).astype(np.int8)
    return deltas.astype(np.float16), q


def dequantize_q80(scales: np.ndarray, q: np.ndarray) -> np.ndarray:
    """(ref: src/quants.cpp:266-284). NaN/inf scale bit patterns from
    arbitrary file bytes propagate warning-free, same contract as
    dequantize_q40."""
    with np.errstate(invalid="ignore"):
        out = q.astype(np.float32) * scales[..., None].astype(np.float32)
    return out.reshape(*out.shape[:-2], -1)


# ---------------------------------------------------------------------------
# Raw block-stream (de)serialization — the on-file layout: per block, the f16
# scale followed by the quantized payload (ref: src/quants.hpp:16-24).
# ---------------------------------------------------------------------------

def q40_bytes_to_arrays(buf: bytes | np.ndarray, n_values: int) -> tuple[np.ndarray, np.ndarray]:
    assert n_values % BLOCK_SIZE == 0
    nb = n_values // BLOCK_SIZE
    raw = np.frombuffer(buf, dtype=np.uint8, count=nb * Q40_BLOCK_BYTES).reshape(nb, Q40_BLOCK_BYTES)
    scales = raw[:, :2].copy().view(np.float16).reshape(nb)
    packed = raw[:, 2:].copy()
    return scales, packed


def q40_arrays_to_bytes(scales: np.ndarray, packed: np.ndarray) -> bytes:
    nb = int(np.prod(scales.shape))
    raw = np.empty((nb, Q40_BLOCK_BYTES), dtype=np.uint8)
    raw[:, :2] = scales.reshape(nb, 1).view(np.uint8)
    raw[:, 2:] = packed.reshape(nb, _HALF)
    return raw.tobytes()


def q80_bytes_to_arrays(buf: bytes | np.ndarray, n_values: int) -> tuple[np.ndarray, np.ndarray]:
    assert n_values % BLOCK_SIZE == 0
    nb = n_values // BLOCK_SIZE
    raw = np.frombuffer(buf, dtype=np.uint8, count=nb * Q80_BLOCK_BYTES).reshape(nb, Q80_BLOCK_BYTES)
    scales = raw[:, :2].copy().view(np.float16).reshape(nb)
    q = raw[:, 2:].copy().view(np.int8)
    return scales, q


def q80_arrays_to_bytes(scales: np.ndarray, q: np.ndarray) -> bytes:
    nb = int(np.prod(scales.shape))
    raw = np.empty((nb, Q80_BLOCK_BYTES), dtype=np.uint8)
    raw[:, :2] = scales.reshape(nb, 1).view(np.uint8)
    raw[:, 2:] = q.reshape(nb, BLOCK_SIZE).view(np.uint8)
    return raw.tobytes()
