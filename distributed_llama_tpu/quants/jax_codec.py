"""Device-side (jnp) quantization primitives.

`QuantizedTensor` is the canonical HBM-resident form of a Q40 weight matrix:
a struct-of-arrays (packed nibbles + per-block f16 scales) instead of the
reference's interleaved 18-byte blocks (ref: src/quants.hpp:16-19) — the
layout XLA/Pallas can tile: nibble-unpack and scale-multiply fuse into the
consuming matmul, and both arrays shard cleanly over a mesh axis.

Device layout is nibble-position-major and pre-flattened 2D: packed
(..., m) with m = 16*nb and lane order m = j*nb + b (packed[..., j*nb + b]
holds byte j of block b) — the transpose of the host/file block-major order
(..., nb, 16). This is chosen for the Pallas kernel (ops/pallas_q40.py):
the per-block scale expansion becomes a lane-tile (pltpu.repeat) instead of
an element-wise repeat Mosaic cannot lower, and storing the flattened form
directly means the kernel consumes the HBM buffer in place — a (..., 16, nb)
3D form would re-tile (copy) on every reshape because TPU tiling of the
last two dims differs. `from_numpy` performs the swap + flatten.

Numerics match the reference decoder (ref: src/quants.cpp:166-179): value =
(nibble - 8) * f16_scale, lower nibbles are elements [0,16) of the block and
upper nibbles are elements [16,32).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .types import BLOCK_SIZE


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedTensor:
    """Q40 tensor of logical shape (..., n): packed (..., n//2) u8 + scales
    (..., n//32) stored as raw f16 BITS (uint16).

    Scales are f16 in the file format and stay 2 bytes wide on device —
    they are 1/8 of the packed bytes, so widening them to f32 costs ~10% of
    the decode HBM traffic (measured 1.19x kernel slowdown). Mosaic has no
    f16 arithmetic, so the kernel (and the XLA fallback) decode the bit
    pattern exactly with integer ops / bitcast (`scales_to_float`).
    f32 scales are still accepted anywhere a QuantizedTensor is built by
    hand (tests, synthetic benches); consumers dispatch on dtype."""

    packed: jax.Array  # uint8
    scales: jax.Array  # uint16 f16-bits on device (f16 in the .m file)

    def tree_flatten(self):
        return (self.packed, self.scales), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self) -> tuple[int, ...]:
        s = self.scales.shape
        return (*s[:-1], s[-1] * BLOCK_SIZE)

    def __getitem__(self, idx) -> "QuantizedTensor":
        """Index leading (stacking) axes, e.g. per-layer or per-expert slices."""
        return QuantizedTensor(self.packed[idx], self.scales[idx])

    def take(self, indices, axis: int = 0) -> "QuantizedTensor":
        """Gather along a leading axis (used for MoE active-expert selection)."""
        import jax.numpy as jnp

        return QuantizedTensor(
            jnp.take(self.packed, indices, axis=axis),
            jnp.take(self.scales, indices, axis=axis),
        )

    @staticmethod
    def host_layout(scales: np.ndarray, packed: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Host block-major packed (..., nb, 16) -> the device layout as
        numpy: (flattened (..., 16*nb) u8, uint16 f16-bit scales). Split out
        from from_numpy so a sharded loader can jax.device_put the arrays
        with an explicit NamedSharding instead of the default device."""
        nb = packed.shape[-2]
        swapped = np.ascontiguousarray(packed.swapaxes(-1, -2))
        return (swapped.reshape(*swapped.shape[:-2], 16 * nb),
                scales.astype(np.float16).view(np.uint16))

    @classmethod
    def from_numpy(cls, scales: np.ndarray, packed: np.ndarray) -> "QuantizedTensor":
        """Host block-major packed (..., nb, 16) -> device flattened (..., 16*nb);
        f16 file scales stored as uint16 bits (see class docstring)."""
        pk, sc = cls.host_layout(scales, packed)
        return cls(jnp.asarray(pk), jnp.asarray(sc))


def scales_to_float(scales: jax.Array, dtype=jnp.float32) -> jax.Array:
    """Block scales -> float `dtype`; uint16 leaves are f16 bit patterns
    (exact bitcast), float leaves pass through (hand-built tensors)."""
    if scales.dtype == jnp.uint16:
        return jax.lax.bitcast_convert_type(scales, jnp.float16).astype(dtype)
    return scales.astype(dtype)


def dequantize_q40_jax(t: QuantizedTensor, dtype=jnp.bfloat16) -> jax.Array:
    """Unpack Q40 to a dense array of `dtype` with logical shape t.shape."""
    nb = t.scales.shape[-1]
    pk = t.packed.reshape(*t.packed.shape[:-1], 16, nb)  # [j, b]
    lo = (pk & 0xF).astype(jnp.int8) - 8
    hi = (pk >> 4).astype(jnp.int8) - 8
    vals = jnp.concatenate([lo, hi], axis=-2)    # (..., 32, nb): k = h*16 + j
    out = vals.astype(dtype) * scales_to_float(t.scales, dtype)[..., None, :]
    # dense[..., b*32 + k] = vals[..., k, b]
    out = jnp.swapaxes(out, -1, -2)
    return out.reshape(*out.shape[:-2], -1)


@partial(jax.jit, static_argnames=("block",))
def quantize_q80_jax(x: jax.Array, block: int = BLOCK_SIZE) -> tuple[jax.Array, jax.Array]:
    """f32/bf16 (..., n) -> (int8 (..., n//B, B), f16 scales (..., n//B)).

    Device-side equivalent of quantizeQ80Row (ref: src/quants.cpp:182-263);
    used for Q80-compressed activation exchange between shards.
    """
    g = x.astype(jnp.float32).reshape(*x.shape[:-1], -1, block)
    absmax = jnp.max(jnp.abs(g), axis=-1)
    scale = absmax / 127.0
    inv = jnp.where(scale > 0, 1.0 / jnp.where(scale > 0, scale, 1.0), 0.0)
    q = jnp.round(g * inv[..., None]).astype(jnp.int8)
    return q, scale.astype(jnp.float16)


def dequantize_q80_jax(q: jax.Array, scales: jax.Array, dtype=jnp.float32) -> jax.Array:
    out = q.astype(dtype) * scales[..., None].astype(dtype)
    return out.reshape(*out.shape[:-2], -1)
