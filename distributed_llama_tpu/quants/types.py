"""Quantization format descriptors.

Block formats are wire/file-compatible with the reference engine
(ref: src/quants.hpp:6-24):

  Q40: 32 values -> f16 scale + 16 packed nibble bytes  = 18 bytes
  Q80: 32 values -> f16 scale + 32 int8 bytes           = 34 bytes
"""

from __future__ import annotations

import enum

BLOCK_SIZE = 32
Q40_BLOCK_BYTES = 2 + BLOCK_SIZE // 2  # 18
Q80_BLOCK_BYTES = 2 + BLOCK_SIZE      # 34


class FloatType(enum.IntEnum):
    """On-file float types (ref: src/quants.hpp:6-11)."""

    F32 = 0
    F16 = 1
    Q40 = 2
    Q80 = 3


def numbers_per_batch(ftype: FloatType) -> int:
    """Granularity of a format (ref: src/quants.cpp:11-24)."""
    if ftype in (FloatType.F32, FloatType.F16):
        return 1
    return BLOCK_SIZE


def batch_bytes(ftype: FloatType, n: int, d: int) -> int:
    """Bytes of an (n x d) tensor in the given format (ref: src/quants.cpp:26-47)."""
    if ftype == FloatType.F32:
        return n * d * 4
    if ftype == FloatType.F16:
        return n * d * 2
    if ftype == FloatType.Q40:
        assert n % BLOCK_SIZE == 0, n
        return (n // BLOCK_SIZE) * d * Q40_BLOCK_BYTES
    if ftype == FloatType.Q80:
        assert n % BLOCK_SIZE == 0, n
        return (n // BLOCK_SIZE) * d * Q80_BLOCK_BYTES
    raise ValueError(f"unsupported float type {ftype}")


def parse_float_type(name: str) -> FloatType:
    try:
        return FloatType[name.upper()]
    except KeyError:
        raise ValueError(f"unknown float type {name!r} (expected f32/f16/q40/q80)")
