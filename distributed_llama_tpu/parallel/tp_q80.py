"""Explicit tensor-parallel execution paths (shard_map layer).

Two things GSPMD cannot express live here:

1. **Q80-compressed partial-sum exchange.** The reference quantizes every
   inter-node activation transfer to Q80 int8 blocks (ref:
   src/tasks.cpp:124-163), invoked around each layer's wo/w2 partial-sum
   exchange (ref: src/llama2-tasks.cpp:251-274) — its signature wire
   optimization (README measures 2048 kB -> 544 kB per token). Under pure
   GSPMD the col-split contraction's all-reduce is compiler-inserted and
   always exact/full-precision; `tp_col_matmul(reduce="q80")` is the
   execution path where that reduction moves int8 blocks instead, selected
   by `--buffer-float-type q80`.

2. **Pallas kernels on multi-device meshes.** GSPMD cannot auto-partition
   a `pallas_call` over sharded operands, so the fused Q40 kernel
   (ops/pallas_q40.py) and flash decode attention (ops/pallas_attention.py)
   would otherwise force the slower XLA-dequant path whenever the mesh has
   more than one device. `tp_row_matmul` / `tp_col_matmul(use_pallas=True)`
   / `tp_flash_attention` run the kernels per-shard inside `shard_map`:
   row-split weights need no communication at all (each shard produces its
   output rows), col-split partial sums reduce with an exact psum (default)
   or the quantized exchange, and attention shards over (dp, kv-heads).

Layout: a col-split weight (wo, w2, moe_down — ref ColMatmulSlice,
src/transformer.cpp:48-76) is repacked host/device-side into a stacked
(tp, ..., d, n/tp) form where slice k quantization-block-aligns with logical
input columns [k*n/tp, (k+1)*n/tp). The stack is sharded P('tp', ...) so
each device holds exactly its slice; inside `shard_map` the local partial
matmul runs on block-aligned Q40 data (no GSPMD re-tiling of packed bytes),
and the partial sums reduce via the two-shot quantized all-reduce
(parallel/collectives.py:q80_psum_2shot).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.matmul import local_matmul
from ..quants.jax_codec import QuantizedTensor
from .collectives import q80_psum_2shot
from .mesh import DP_AXIS, SP_AXIS, TP_AXIS
from .wrappers import WeightWrapper, weight_marker


@weight_marker
class TpColWeight(WeightWrapper):
    """A col-split weight repacked as a (tp, ..., d, n/tp) stack.

    `w` is a dense array or a QuantizedTensor whose packed/scales carry the
    same leading tp axis. Slice k holds the weight columns contracting with
    input columns [k*n/tp, (k+1)*n/tp) — the reference's ColMatmulSlice shard
    for node k (ref: src/transformer.cpp:60-76)."""

    w: QuantizedTensor | jax.Array


@weight_marker
class TpRowWeight(WeightWrapper):
    """A row-split (output-dim) matmul weight, marked for shard_map kernel
    execution. No repacking: the d axis shards contiguously, so each local
    block is itself a valid weight for its output rows (the reference's
    RowMatmulSlice, ref: src/transformer.cpp:14-46). With tp == 1 (dp-only
    meshes) the weight is replicated and the marker only routes the matmul
    through shard_map so the Pallas kernel sees local (unsharded) operands."""

    w: QuantizedTensor | jax.Array


def tp_row_pspec(w: TpRowWeight) -> TpRowWeight:
    """PartitionSpec pytree: the output-row axis (-2) on tp, rest replicated.
    Packed (lead..., d, m), scales (lead..., d, nb) and dense (lead..., d, n)
    all shard the same axis."""
    def spec(ndim):
        axes: list = [None] * ndim
        axes[ndim - 2] = TP_AXIS
        return P(*axes)

    if isinstance(w.w, QuantizedTensor):
        return TpRowWeight(QuantizedTensor(spec(w.w.packed.ndim),
                                           spec(w.w.scales.ndim)))
    return TpRowWeight(spec(w.w.ndim))


def _batch_axes(mesh, x):
    """(dp_ax, sp_ax) usable for this x's leading dims on this mesh."""
    dp = mesh.shape.get(DP_AXIS, 1)
    sp = mesh.shape.get(SP_AXIS, 1)
    b = x.shape[0]
    t = x.shape[1] if x.ndim == 3 else 1
    dp_ax = DP_AXIS if dp > 1 and b % dp == 0 else None
    sp_ax = (SP_AXIS if x.ndim == 3 and sp > 1 and t > 1 and t % sp == 0
             else None)
    return dp_ax, sp_ax


def tp_row_matmul(
    x: jnp.ndarray,
    w: TpRowWeight,
    mesh,
    *,
    compute_dtype=jnp.float32,
    use_pallas: bool = True,
    interpret: bool = False,
) -> jnp.ndarray:
    """y[..., d] = x @ W^T with the OUTPUT dim tp-split — communication-free
    (each shard computes its own output rows; the result stays tp-sharded on
    the last axis, which is exactly how downstream consumers want it: heads
    for attention, hidden columns for w2's col-split contraction).

    x is (B, n) or (B, T, n), replicated over tp (the reference likewise
    gives every node the full normed activation, ref: llama2-tasks.cpp:249).
    """
    from .compat import shard_map

    tp = mesh.shape.get(TP_AXIS, 1)
    tp_ax = TP_AXIS if tp > 1 else None
    dp_ax, sp_ax = _batch_axes(mesh, x)
    if x.ndim == 2:
        x_spec, out_spec = P(dp_ax, None), P(dp_ax, tp_ax)
    else:
        x_spec, out_spec = P(dp_ax, sp_ax, None), P(dp_ax, sp_ax, tp_ax)

    def body(x_l, w_l):
        return local_matmul(x_l, w_l.w, compute_dtype=compute_dtype,
                            use_pallas=use_pallas, interpret=interpret)

    fn = shard_map(body, mesh=mesh, in_specs=(x_spec, tp_row_pspec(w)),
                   out_specs=out_spec, check_vma=False)
    return fn(x, w)


def tp_flash_attention(
    q: jnp.ndarray,        # (B, T, H, hs)
    k_cache: jnp.ndarray,  # (B, KVH, S, hs)
    v_cache: jnp.ndarray,  # (B, KVH, S, hs)
    q_pos: jnp.ndarray,    # (B, T)
    mesh,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """flash_attention (decode and chunked prefill) over a (dp, tp) mesh:
    batch shards on dp, heads/kv-heads on tp (the reference's KvCacheSlice
    head split, ref: src/transformer.cpp:161-171). Pure shard-local —
    attention never mixes heads, so no collective is needed."""
    from .compat import shard_map

    from ..ops.pallas_attention import flash_attention

    b = q.shape[0]
    dp = mesh.shape.get(DP_AXIS, 1)
    tp = mesh.shape.get(TP_AXIS, 1)
    dp_ax = DP_AXIS if dp > 1 and b % dp == 0 else None
    tp_ax = TP_AXIS if tp > 1 else None

    def body(q_l, k_l, v_l, pos_l):
        return flash_attention(q_l, k_l, v_l, pos_l, interpret=interpret)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(dp_ax, None, tp_ax, None), P(dp_ax, tp_ax, None, None),
                  P(dp_ax, tp_ax, None, None), P(dp_ax, None)),
        out_specs=P(dp_ax, None, tp_ax, None), check_vma=False)
    return fn(q, k_cache, v_cache, q_pos)


def repack_col_tp(w, tp: int) -> TpColWeight:
    """Split a col-split weight into a block-aligned per-shard stack.

    Dense (..., d, n) -> (tp, ..., d, n/tp). Q40 packed (..., d, 16*nb) with
    lane order m = j*nb + b (quants/jax_codec.py) -> per-shard lane order
    m_local = j*(nb/tp) + b_local, i.e. each shard is itself a valid flattened
    QuantizedTensor for its logical column range — a pure relayout of the
    existing bytes (blocks never straddle shards because n/tp % 32 == 0,
    checked in sharding.check_tp_constraints)."""
    if isinstance(w, QuantizedTensor):
        nb = w.scales.shape[-1]
        assert nb % tp == 0, (nb, tp)
        lead = w.packed.shape[:-1]
        pk = w.packed.reshape(*lead, 16, tp, nb // tp)
        pk = jnp.moveaxis(pk, -2, 0).reshape(tp, *lead, 16 * (nb // tp))
        sc = jnp.moveaxis(w.scales.reshape(*lead, tp, nb // tp), -2, 0)
        return TpColWeight(QuantizedTensor(pk, sc))
    n = w.shape[-1]
    assert n % tp == 0, (n, tp)
    return TpColWeight(jnp.moveaxis(w.reshape(*w.shape[:-1], tp, n // tp), -2, 0))


def tp_col_pspec(w: TpColWeight):
    """PartitionSpec pytree for a TpColWeight: leading stack axis on tp."""
    def spec(ndim):
        return P(TP_AXIS, *([None] * (ndim - 1)))

    if isinstance(w.w, QuantizedTensor):
        return TpColWeight(QuantizedTensor(spec(w.w.packed.ndim), spec(w.w.scales.ndim)))
    return TpColWeight(spec(w.w.ndim))


def take_expert_col(w: TpColWeight, e) -> TpColWeight:
    """Select expert e from a stacked MoE col weight: (tp, E, d, n/tp) on the
    GSPMD path, or the shard-local (E, d, n/tp) form inside a fully-manual
    region (parallel/pp.py strips the tp stack axis) — discriminated by
    rank, since expert col stacks are the only 3D/4D TpColWeight leaves."""
    from jax import lax

    if isinstance(w.w, QuantizedTensor):
        ax = 1 if w.w.packed.ndim == 4 else 0
        return TpColWeight(QuantizedTensor(
            lax.dynamic_index_in_dim(w.w.packed, e, axis=ax, keepdims=False),
            lax.dynamic_index_in_dim(w.w.scales, e, axis=ax, keepdims=False),
        ))
    ax = 1 if w.w.ndim == 4 else 0
    return TpColWeight(lax.dynamic_index_in_dim(w.w, e, axis=ax, keepdims=False))


def manual_psum(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """lax.psum for code already inside a manual region (parallel/pp.py).
    On the CPU backend only, the payload transits in f32: XLA's CPU compiler
    miscompiles a bf16 all-reduce inside a manual region ("Invalid binary
    instruction opcode copy"); TPU keeps the native width."""
    if jax.default_backend() == "cpu" and x.dtype == jnp.bfloat16:
        return jax.lax.psum(x.astype(jnp.float32), axis).astype(x.dtype)
    return jax.lax.psum(x, axis)


def tp_col_matmul(
    x: jnp.ndarray,
    w: TpColWeight,
    mesh,
    *,
    compute_dtype=jnp.float32,
    reduce: str = "q80",
    use_pallas: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """y[b, t, d] = sum_n x[b, t, n] * W[d, n] with the contraction tp-split
    and the partial sums reduced exactly (`reduce="exact"`, jax.lax.psum) or
    Q80-compressed (`reduce="q80"`, the reference's wire optimization).

    x is a global (B, T, n) array (GSPMD-resident); the shard_map forces the
    last dim onto tp (matching how row-split producers already shard it), the
    local (B_l, T_l, n/tp) x slice contracts with this shard's weight slice
    (Pallas fused Q40 kernel when use_pallas), and partials all-reduce.
    Output is (B, T, d), replicated over tp like GSPMD's own all-reduce."""
    from .compat import shard_map

    tp = mesh.shape[TP_AXIS]
    dp_ax, sp_ax = _batch_axes(mesh, x)
    x_spec = P(dp_ax, sp_ax, TP_AXIS)
    out_spec = P(dp_ax, sp_ax, None)

    def body(x_l, w_l):
        wk = w_l.w
        if isinstance(wk, QuantizedTensor):
            wk = QuantizedTensor(wk.packed[0], wk.scales[0])
        else:
            wk = wk[0]
        partial = local_matmul(x_l, wk, compute_dtype=compute_dtype,
                                use_pallas=use_pallas, interpret=interpret)
        if reduce == "exact":
            return jax.lax.psum(partial, TP_AXIS) if tp > 1 else partial
        return q80_psum_2shot(partial, TP_AXIS, tp)

    fn = shard_map(body, mesh=mesh, in_specs=(x_spec, tp_col_pspec(w)),
                   out_specs=out_spec, check_vma=False)
    return fn(x, w)
