"""Q80-compressed tensor-parallel col-split matmul (shard_map path).

The reference quantizes every inter-node activation transfer to Q80 int8
blocks (ref: src/tasks.cpp:124-163), invoked around each layer's wo/w2
partial-sum exchange (ref: src/llama2-tasks.cpp:251-274) — its signature
wire optimization (README measures 2048 kB -> 544 kB per token). Under pure
GSPMD the col-split contraction's all-reduce is compiler-inserted and always
exact/full-precision; this module is the explicit execution path where that
reduction moves int8 blocks instead, selected by `--buffer-float-type q80`.

Layout: a col-split weight (wo, w2, moe_down — ref ColMatmulSlice,
src/transformer.cpp:48-76) is repacked host/device-side into a stacked
(tp, ..., d, n/tp) form where slice k quantization-block-aligns with logical
input columns [k*n/tp, (k+1)*n/tp). The stack is sharded P('tp', ...) so
each device holds exactly its slice; inside `shard_map` the local partial
matmul runs on block-aligned Q40 data (no GSPMD re-tiling of packed bytes),
and the partial sums reduce via the two-shot quantized all-reduce
(parallel/collectives.py:q80_psum_2shot).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..quants.jax_codec import QuantizedTensor, dequantize_q40_jax
from .collectives import q80_psum_2shot
from .mesh import DP_AXIS, SP_AXIS, TP_AXIS


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class TpColWeight:
    """A col-split weight repacked as a (tp, ..., d, n/tp) stack.

    `w` is a dense array or a QuantizedTensor whose packed/scales carry the
    same leading tp axis. Slice k holds the weight columns contracting with
    input columns [k*n/tp, (k+1)*n/tp) — the reference's ColMatmulSlice shard
    for node k (ref: src/transformer.cpp:60-76)."""

    w: QuantizedTensor | jax.Array

    def tree_flatten(self):
        return (self.w,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def repack_col_tp(w, tp: int) -> TpColWeight:
    """Split a col-split weight into a block-aligned per-shard stack.

    Dense (..., d, n) -> (tp, ..., d, n/tp). Q40 packed (..., d, 16*nb) with
    lane order m = j*nb + b (quants/jax_codec.py) -> per-shard lane order
    m_local = j*(nb/tp) + b_local, i.e. each shard is itself a valid flattened
    QuantizedTensor for its logical column range — a pure relayout of the
    existing bytes (blocks never straddle shards because n/tp % 32 == 0,
    checked in sharding.check_tp_constraints)."""
    if isinstance(w, QuantizedTensor):
        nb = w.scales.shape[-1]
        assert nb % tp == 0, (nb, tp)
        lead = w.packed.shape[:-1]
        pk = w.packed.reshape(*lead, 16, tp, nb // tp)
        pk = jnp.moveaxis(pk, -2, 0).reshape(tp, *lead, 16 * (nb // tp))
        sc = jnp.moveaxis(w.scales.reshape(*lead, tp, nb // tp), -2, 0)
        return TpColWeight(QuantizedTensor(pk, sc))
    n = w.shape[-1]
    assert n % tp == 0, (n, tp)
    return TpColWeight(jnp.moveaxis(w.reshape(*w.shape[:-1], tp, n // tp), -2, 0))


def tp_col_pspec(w: TpColWeight):
    """PartitionSpec pytree for a TpColWeight: leading stack axis on tp."""
    def spec(ndim):
        return P(TP_AXIS, *([None] * (ndim - 1)))

    if isinstance(w.w, QuantizedTensor):
        return TpColWeight(QuantizedTensor(spec(w.w.packed.ndim), spec(w.w.scales.ndim)))
    return TpColWeight(spec(w.w.ndim))


def take_expert_col(w: TpColWeight, e) -> TpColWeight:
    """Select expert e from a stacked (tp, E, d, n/tp) MoE col weight."""
    from jax import lax

    if isinstance(w.w, QuantizedTensor):
        return TpColWeight(QuantizedTensor(
            lax.dynamic_index_in_dim(w.w.packed, e, axis=1, keepdims=False),
            lax.dynamic_index_in_dim(w.w.scales, e, axis=1, keepdims=False),
        ))
    return TpColWeight(lax.dynamic_index_in_dim(w.w, e, axis=1, keepdims=False))


def tp_col_matmul(
    x: jnp.ndarray,
    w: TpColWeight,
    mesh,
    *,
    compute_dtype=jnp.float32,
) -> jnp.ndarray:
    """y[b, t, d] = sum_n x[b, t, n] * W[d, n] with the contraction tp-split
    and the partial-sum reduction Q80-compressed.

    x is a global (B, T, n) array (GSPMD-resident); the shard_map forces the
    last dim onto tp (matching how row-split producers already shard it), the
    local (B_l, T_l, n/tp) x slice contracts with this shard's weight slice,
    and partials all-reduce via the quantized two-shot exchange. Output is
    (B, T, d), replicated over tp like the GSPMD-exact path's all-reduce."""
    from jax import shard_map

    tp = mesh.shape[TP_AXIS]
    b, t, _ = x.shape
    dp = mesh.shape.get(DP_AXIS, 1)
    sp = mesh.shape.get(SP_AXIS, 1)
    dp_ax = DP_AXIS if dp > 1 and b % dp == 0 else None
    sp_ax = SP_AXIS if sp > 1 and t > 1 and t % sp == 0 else None
    x_spec = P(dp_ax, sp_ax, TP_AXIS)
    out_spec = P(dp_ax, sp_ax, None)

    def body(x_l, w_l):
        wk = w_l.w
        if isinstance(wk, QuantizedTensor):
            wk = QuantizedTensor(wk.packed[0], wk.scales[0])
            wd = dequantize_q40_jax(wk, dtype=compute_dtype)
        else:
            wd = wk[0].astype(compute_dtype)
        partial = jnp.einsum("btn,dn->btd", x_l.astype(compute_dtype), wd,
                             preferred_element_type=compute_dtype)
        return q80_psum_2shot(partial, TP_AXIS, tp)

    fn = shard_map(body, mesh=mesh, in_specs=(x_spec, tp_col_pspec(w)),
                   out_specs=out_spec, check_vma=False)
    return fn(x, w)
