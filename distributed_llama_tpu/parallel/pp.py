"""Pipeline parallelism: transformer layers placed in stages (shard_map).

Net-new vs the reference, where every node runs every layer in lock-step
(ref: src/llama2-tasks.cpp:214-220; SURVEY.md §2.5 marks PP absent). The
mesh's `pp` axis shards the LAYER axis: device p stores only layers
[p*L/pp, (p+1)*L/pp) — weights AND their KV cache — which is the
model-size axis orthogonal to tp (pp*tp devices fit a model pp*tp times
larger than one device; tp itself can also exceed n_kv_heads via kv-head
replication — models/params.kv_replication — which the engine applies
before stage stacking).

Execution model (single in-flight segment — decode and chunked prefill):
the layer pytree is restacked so slot j's leaves carry a leading (pp,)
stage axis sharded over pp. Inside a PARTIAL-MANUAL shard_map (manual over
pp and dp; tp stays auto so GSPMD keeps partitioning the per-layer matmuls
and inserting the tp all-reduces), every stage s runs in sequence:

    for s in range(pp):                      # static
        y = my_local_layers(x)               # all devices compute
        x = psum(where(stage_index == s, y, 0), pp)   # live stage broadcasts

All devices compute every stage iteration on whatever x they hold, but
only stage s's result survives iteration s — SPMD-uniform control flow,
wall-clock identical to the sequential layer loop (plus pp small dim-sized
broadcasts per segment). KV-cache writes are gated so a device's cache
slots are only written on its own stage's iteration (`write_gate` in
models/transformer._attention_block); off-turn iterations re-write the
existing values.

GPipe-style microbatch overlap across dp is a possible follow-up; this
path's purpose is the memory/placement axis, matching the reference's
inference-latency orientation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..quants.jax_codec import QuantizedTensor
from .mesh import PP_AXIS
from .wrappers import WeightWrapper, weight_marker


@weight_marker
class PpWeight(WeightWrapper):
    """A layer weight restacked with a leading (pp,) stage axis: element s
    of the stack is stage s's layer for this slot. Sharded P('pp', <the
    weight's usual tp split>) — see sharding._leaf_spec."""

    w: QuantizedTensor | jax.Array


def stack_stages(params: dict, pp: int) -> dict:
    """layers[L] -> layers[L/pp] slot dicts whose leaves stack the pp
    stages' weights: new_layers[j] leaf = stack(layers[s*L/pp + j] for s).
    Leaves become PpWeight so sharding/spec code routes them."""
    layers = params["layers"]
    if layers and any(isinstance(v, PpWeight) for v in layers[0].values()):
        return params  # already stage-stacked (the streamed loader's pp mode)
    n_l = len(layers)
    assert n_l % pp == 0, (n_l, pp)
    n_slot = n_l // pp

    def stack(leaves):
        if isinstance(leaves[0], PpWeight):  # already stacked
            return leaves[0]
        if isinstance(leaves[0], QuantizedTensor):
            return PpWeight(QuantizedTensor(
                jnp.stack([w.packed for w in leaves]),
                jnp.stack([w.scales for w in leaves])))
        return PpWeight(jnp.stack(leaves))

    out = dict(params)
    out["layers"] = [
        {k: stack([layers[s * n_slot + j][k] for s in range(pp)])
         for k in layers[j]}
        for j in range(n_slot)
    ]
    return out


def _unwrap0(w):
    """Strip the local (1,)-length stage axis off a PpWeight leaf inside the
    shard_map body, yielding this device's plain layer weight."""
    if isinstance(w.w, QuantizedTensor):
        return QuantizedTensor(w.w.packed[0], w.w.scales[0])
    return w.w[0]


def pp_layers(x, layers, spec, cache, q_pos, cfg, mesh, per_row_pos=False):
    """Run all L layers across the pp stages; returns (x, k_all, v_all).

    x: (B, T, dim) replicated over pp (dp/tp sharding rides the auto axes).
    layers: L/pp slot dicts of PpWeight leaves. cache: KVCache whose leaves
    are (pp, B, KVH, S, hs), sharded over pp on the stage axis.
    """
    from jax import shard_map

    from ..models.transformer import _layer
    from .mesh import DP_AXIS

    pp = mesh.shape[PP_AXIS]
    n_slot = len(layers)
    # inside the manual region the layer math runs the plain GSPMD path:
    # tp is the only auto axis there (dp is manual — XLA's partitioner
    # miscompiles the per-row cache scatter when the batch dim is an auto
    # subgroup axis), and the explicit shard_map kernel paths (tp_q80.py)
    # cannot nest inside it
    inner_cfg = {**cfg, "tp_mesh": None, "use_pallas": False}
    dp = mesh.shape.get(DP_AXIS, 1)
    b = x.shape[0]
    dp_ax = DP_AXIS if dp > 1 and b % dp == 0 else None

    def body(x_l, q_pos_l, layers_l, k_l, v_l):
        p = lax.axis_index(PP_AXIS)
        k_l = list(k_l)
        v_l = list(v_l)
        for s in range(pp):
            y = x_l
            gate = (p == s)
            for j in range(n_slot):
                lw = {k: _unwrap0(w) for k, w in layers_l[j].items()}
                y, k_new, v_new = _layer(
                    y, lw, spec, k_l[j][0], v_l[j][0], q_pos_l, inner_cfg,
                    per_row_pos=per_row_pos, write_gate=gate)
                k_l[j] = k_new[None]
                v_l[j] = v_new[None]
            # live-stage broadcast. On the CPU backend only, the psum payload
            # is upcast to f32: XLA's CPU compiler miscompiles a bf16
            # all-reduce inside the manual region ("Invalid binary
            # instruction opcode copy"); TPU keeps the native-width payload
            live = jnp.where(gate, y, jnp.zeros_like(y))
            if jax.default_backend() == "cpu" and live.dtype == jnp.bfloat16:
                x_l = lax.psum(live.astype(jnp.float32), PP_AXIS).astype(y.dtype)
            else:
                x_l = lax.psum(live, PP_AXIS)
        return x_l, tuple(k_l), tuple(v_l)

    def wspec(w):
        if isinstance(w.w, QuantizedTensor):
            return PpWeight(QuantizedTensor(P(PP_AXIS), P(PP_AXIS)))
        return PpWeight(P(PP_AXIS))

    layer_specs = [{k: wspec(w) for k, w in lw.items()} for lw in layers]
    cache_spec = (P(PP_AXIS, dp_ax),) * n_slot
    x_spec = P(dp_ax)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(x_spec, x_spec, layer_specs, cache_spec, cache_spec),
        out_specs=(x_spec, cache_spec, cache_spec),
        axis_names={PP_AXIS, DP_AXIS}, check_vma=False)
    return fn(x, q_pos, layers, cache.k, cache.v)
