"""Pipeline parallelism: transformer layers placed in stages (shard_map).

Net-new vs the reference, where every node runs every layer in lock-step
(ref: src/llama2-tasks.cpp:214-220; SURVEY.md §2.5 marks PP absent). The
mesh's `pp` axis shards the LAYER axis: device p stores only layers
[p*L/pp, (p+1)*L/pp) — weights AND their KV cache — which is the
model-size axis orthogonal to tp (pp*tp devices fit a model pp*tp times
larger than one device; tp itself can also exceed n_kv_heads via kv-head
replication — models/params.kv_replication — which the engine applies
before stage stacking).

Execution model (single in-flight segment — decode and chunked prefill):
the layer pytree is restacked so slot j's leaves carry a leading (pp,)
stage axis sharded over pp. Inside a FULLY-MANUAL shard_map (manual over
pp, dp AND tp), every stage s runs in sequence:

    for s in range(pp):                      # static
        y = my_local_layers(x)               # all devices compute
        x = psum(where(stage_index == s, y, 0), pp)   # live stage broadcasts

All devices compute every stage iteration on whatever x they hold, but
only stage s's result survives iteration s — SPMD-uniform control flow,
wall-clock identical to the sequential layer loop (plus pp small dim-sized
broadcasts per segment). KV-cache writes are gated so a device's cache
slots are only written on its own stage's iteration (`write_gate` in
models/transformer._attention_block); off-turn iterations re-write the
existing values.

tp inside the region is manual too (an earlier revision kept it GSPMD-auto,
which made the Pallas kernels unusable here — shard_map cannot nest, and
GSPMD cannot partition a pallas_call): row-split weights are shard-local so
the fused Q40 kernel runs on them directly, attention is kv-head-local, and
col-split partial sums reduce with an explicit psum over tp — the same
per-shard structure as parallel/tp_q80.py, minus the shard_map entry
(matmul(manual_tp=...) dispatches it). --pp therefore runs the SAME fused
hot path as --tp, closing the 2.1x per-weight-byte penalty the auto-tp
region paid (VERDICT r2 weak #1).

On the "every device computes every stage" structure (VERDICT r2 weak #2):
for DECODE this is the right call, not a compromise. Decode is weight-
read-bound — a stage-iteration's cost is its layers' HBM bytes, nearly
independent of how many batch rows ride along — so the pp devices all
stream their own layers' weights concurrently and the wall-clock equals
the sequential layer loop, which is the floor for a single in-flight
token. A GPipe microbatch rotation (b/pp rows per stage-step, 2pp-1
steps) would re-read the same weights (2pp-1)/pp times per token — ~2x
SLOWER for decode. The off-stage compute it "burns" costs energy, not
time: those devices would otherwise idle.

PREFILL is the opposite regime (flop-bound: T tokens amortize every
weight read), and there the all-stages scheme throws away the pp axis —
wall equals ONE device running all layers. `pp_layers_gpipe` recovers it
(VERDICT r3 weak #4): the T-token segment splits into M sequence-
microbatches that rotate through the stages GPipe-style — step t runs
microbatch t-s on stage s, activations hop stage s -> s+1 via ppermute,
and each device computes ONLY its own layers. Wall drops from T·L·c to
(M+pp-1)/M · T·L·c/pp (M=8, pp=2: 1.78x; -> pp x as M grows). Sequence-
microbatching keeps causality free: microbatch m reaches stage s after
m-1 already wrote that stage's KV slots, so attention reads are ready by
construction. Cache writes gate on schedule validity (bubble steps
re-write existing values); only the last stage's outputs survive into
the (single, final) psum. forward() picks the schedule per segment:
gpipe_microbatches() returns M > 1 only for long segments (>= 32 tokens
per stage), so decode and speculative verify stay all-stages.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..quants.jax_codec import QuantizedTensor
from .mesh import PP_AXIS, TP_AXIS
from .sharding import _SPLIT
from .tp_q80 import TpColWeight, TpRowWeight, manual_psum
from .wrappers import WeightWrapper, weight_marker


@weight_marker
class PpWeight(WeightWrapper):
    """A layer weight restacked with a leading (pp,) stage axis: element s
    of the stack is stage s's layer for this slot. The inner value may be a
    plain array/QuantizedTensor (sharded P('pp', <usual tp split>)) or a
    TpRowWeight/TpColWeight wrapper (kernel mode: P('pp', <its tp spec>)) —
    see sharding._leaf_spec."""

    w: QuantizedTensor | jax.Array | WeightWrapper


def _stack_leaves(leaves):
    if isinstance(leaves[0], QuantizedTensor):
        return QuantizedTensor(
            jnp.stack([w.packed for w in leaves]),
            jnp.stack([w.scales for w in leaves]))
    return jnp.stack(leaves)


def stack_stages(params: dict, pp: int) -> dict:
    """layers[L] -> layers[L/pp] slot dicts whose leaves stack the pp
    stages' weights: new_layers[j] leaf = stack(layers[s*L/pp + j] for s).
    Leaves become PpWeight so sharding/spec code routes them; Tp-wrapped
    leaves (the kernel/q80 modes) keep their inner wrapper:
    PpWeight(TpColWeight((pp, tp, ...)))."""
    layers = params["layers"]
    if layers and any(isinstance(v, PpWeight) for v in layers[0].values()):
        return params  # already stage-stacked (the streamed loader's pp mode)
    n_l = len(layers)
    assert n_l % pp == 0, (n_l, pp)
    n_slot = n_l // pp

    from .ep_moe import EpColWeight, EpRowWeight

    def stack(leaves):
        if isinstance(leaves[0], PpWeight):  # already stacked
            return leaves[0]
        if isinstance(leaves[0], (TpRowWeight, TpColWeight,
                                  EpRowWeight, EpColWeight)):
            inner = _stack_leaves([w.w for w in leaves])
            return PpWeight(type(leaves[0])(inner))
        return PpWeight(_stack_leaves(leaves))

    out = dict(params)
    out["layers"] = [
        {k: stack([layers[s * n_slot + j][k] for s in range(pp)])
         for k in layers[j]}
        for j in range(n_slot)
    ]
    return out


def _unwrap0(key: str, w, tp: int):
    """Strip the local (1,)-length stage axis (and, for Tp-wrapped leaves,
    the (1,)-length local tp stack axis) off a PpWeight leaf inside the
    manual region, yielding this device's local layer weight. Plain split
    leaves are re-marked TpRowWeight/TpColWeight by their _SPLIT role so
    matmul(manual_tp=...) knows whether a psum is owed."""
    from .ep_moe import EpColWeight, EpRowWeight

    inner = w.w

    def strip(v, n_axes):
        if isinstance(v, QuantizedTensor):
            pk, sc = v.packed, v.scales
            for _ in range(n_axes):
                pk, sc = pk[0], sc[0]
            return QuantizedTensor(pk, sc)
        for _ in range(n_axes):
            v = v[0]
        return v

    if isinstance(inner, (EpRowWeight, EpColWeight)):
        # ep x pp: strip the stage axis only — the inner layout (local
        # experts; for cols also the local tp stack) is exactly what the
        # manual ep body consumes (ep_moe._ep_body)
        return type(inner)(strip(inner.w, 1))
    if isinstance(inner, TpColWeight):
        return TpColWeight(strip(inner.w, 2))   # stage + tp stack axes
    if isinstance(inner, TpRowWeight):
        return TpRowWeight(strip(inner.w, 1))
    v = strip(inner, 1)
    split = _SPLIT.get(key)
    if tp > 1 and split == "col":
        return TpColWeight(v)
    if tp > 1 and split == "row":
        return TpRowWeight(v)
    return v


def _leaf_in_spec(key: str, w, tp_ax):
    """shard_map in_spec for one PpWeight leaf — must mirror
    sharding._leaf_spec's placement so entering the region moves no bytes."""
    def spec(ndim, role):
        axes: list = [None] * (ndim - 1)
        if tp_ax is not None and role in ("row", "col"):
            # row: shard the output-dim axis (ndim-1-2 of the inner array);
            # col (plain leaves only): shard the last axis
            axes[(ndim - 1) - 2 if role == "row" else (ndim - 1) - 1] = tp_ax
        return P(PP_AXIS, *axes)

    from .ep_moe import EpColWeight, EpRowWeight, ep_col_pspec, ep_row_pspec

    inner = w.w
    if isinstance(inner, (EpRowWeight, EpColWeight)):
        # ep x pp: the stage axis prepends the Ep layout's own spec
        ep_ps = ep_row_pspec if isinstance(inner, EpRowWeight) else ep_col_pspec

        def espec(ndim):
            return P(PP_AXIS, *ep_ps(ndim - 1))
        if isinstance(inner.w, QuantizedTensor):
            return PpWeight(type(inner)(QuantizedTensor(
                espec(inner.w.packed.ndim), espec(inner.w.scales.ndim))))
        return PpWeight(type(inner)(espec(inner.w.ndim)))
    if isinstance(inner, TpColWeight):
        def cspec(ndim):
            return P(PP_AXIS, tp_ax, *([None] * (ndim - 2)))
        if isinstance(inner.w, QuantizedTensor):
            return PpWeight(TpColWeight(QuantizedTensor(
                cspec(inner.w.packed.ndim), cspec(inner.w.scales.ndim))))
        return PpWeight(TpColWeight(cspec(inner.w.ndim)))
    role = _SPLIT.get(key)
    if isinstance(inner, TpRowWeight):
        if isinstance(inner.w, QuantizedTensor):
            return PpWeight(TpRowWeight(QuantizedTensor(
                spec(inner.w.packed.ndim, "row"),
                spec(inner.w.scales.ndim, "row"))))
        return PpWeight(TpRowWeight(spec(inner.w.ndim, "row")))
    if isinstance(inner, QuantizedTensor):
        return PpWeight(QuantizedTensor(
            spec(inner.packed.ndim, role), spec(inner.scales.ndim, role)))
    return PpWeight(spec(inner.ndim, role))


def _pp_scaffold(mesh, layers, cfg, b):
    """Shared scaffolding for the manual-pp execution schemes (all-stages
    and GPipe): axis derivation, per-leaf in/out specs, and the shard_map
    wiring — one place so the two schedules cannot drift.

    Inside the fully-manual region the layer math runs per-shard: the
    explicit shard_map wrappers must not re-enter (tp_mesh=None) and
    matmul/attention dispatch on manual_tp instead."""
    from .compat import shard_map

    from .mesh import DP_AXIS, EP_AXIS, SP_AXIS

    pp = mesh.shape[PP_AXIS]
    tp = mesh.shape.get(TP_AXIS, 1)
    dp = mesh.shape.get(DP_AXIS, 1)
    sp = mesh.shape.get(SP_AXIS, 1)
    n_slot = len(layers)
    inner_cfg = {**cfg, "tp_mesh": None, "manual_tp": tp,
                 "manual_ep": mesh.shape.get(EP_AXIS, 1),
                 "manual_sp": sp}
    dp_ax = DP_AXIS if dp > 1 and b % dp == 0 else None
    tp_ax = TP_AXIS if tp > 1 else None
    layer_specs = [{k: _leaf_in_spec(k, w, tp_ax) for k, w in lw.items()}
                   for lw in layers]
    # cache leaves are (pp, B, KVH, S, hs): stage on pp, kv-heads on tp,
    # and — when sp > 1 — the sequence dim on sp (per-device cache memory
    # seq_len/sp, the long-context axis composing with stage placement)
    cache_spec = (P(PP_AXIS, dp_ax, tp_ax,
                    SP_AXIS if sp > 1 else None),) * n_slot
    x_spec = P(dp_ax)

    def wrap(body):
        return shard_map(
            body, mesh=mesh,
            in_specs=(x_spec, x_spec, layer_specs, cache_spec, cache_spec),
            out_specs=(x_spec, cache_spec, cache_spec),
            check_vma=False)

    return pp, tp, n_slot, inner_cfg, wrap


def pp_layers(x, layers, spec, cache, q_pos, cfg, mesh, per_row_pos=False):
    """Run all L layers across the pp stages; returns (x, k_all, v_all).

    x: (B, T, dim) replicated over pp and tp (dp shards the batch).
    layers: L/pp slot dicts of PpWeight leaves. cache: KVCache whose leaves
    are (pp, B, KVH, S, hs), sharded over pp on the stage axis and tp on
    the kv-head axis (cache_pspec(pp=True)).
    """
    from ..models.transformer import _layer

    pp, tp, n_slot, inner_cfg, wrap = _pp_scaffold(mesh, layers, cfg,
                                                   x.shape[0])

    def body(x_l, q_pos_l, layers_l, k_l, v_l):
        p = lax.axis_index(PP_AXIS)
        k_l = list(k_l)
        v_l = list(v_l)
        for s in range(pp):
            y = x_l
            gate = (p == s)
            for j in range(n_slot):
                lw = {k: _unwrap0(k, w, tp) for k, w in layers_l[j].items()}
                y, k_new, v_new = _layer(
                    y, lw, spec, k_l[j][0], v_l[j][0], q_pos_l, inner_cfg,
                    per_row_pos=per_row_pos, write_gate=gate)
                k_l[j] = k_new[None]
                v_l[j] = v_new[None]
            # live-stage broadcast (manual_psum: f32 transit on CPU only —
            # XLA CPU miscompiles a bf16 all-reduce in a manual region)
            live = jnp.where(gate, y, jnp.zeros_like(y))
            x_l = manual_psum(live, PP_AXIS)
        return x_l, tuple(k_l), tuple(v_l)

    return wrap(body)(x, q_pos, layers, cache.k, cache.v)


def gpipe_microbatches(t: int, pp: int) -> int:
    """Microbatch count for a T-token segment: 1 means "use the all-stages
    scheme". GPipe engages only for flop-bound segments (>= 32 tokens per
    stage — decode and speculative verify stay all-stages, they are
    weight-read-bound and rotation would re-read weights); M is the
    largest divisor of T in [pp, 4*pp] capped at T/32, trading bubble
    fraction (M+pp-1)/M against per-microbatch weight re-reads."""
    if pp <= 1 or t < 32 * pp:
        return 1
    for m in range(min(4 * pp, t // 32), pp - 1, -1):
        if t % m == 0:
            return m
    return 1


def pp_layers_gpipe(x, layers, spec, cache, q_pos, cfg, mesh, n_mb,
                    per_row_pos=False):
    """GPipe sequence-microbatch prefill across the pp stages; same
    signature/contract as pp_layers plus `n_mb` (from gpipe_microbatches,
    > 1, dividing T). Returns (x, k_all, v_all) with x fully assembled
    (B, T, dim) — logits_for_all / logit_index callers read any position.

    Schedule: at step t (static, t in [0, M+pp-1)), the device at stage p
    runs microbatch m = t - p when 0 <= m < M. Stage 0 reads its
    microbatch straight from the embedded input; other stages consume the
    activation ppermute'd from stage p-1 at the end of the previous step;
    stage pp-1 deposits its result into the output buffer. Bubble steps
    (m out of range) compute on stale data with cache writes gated off
    and their results discarded — SPMD-uniform control flow, like
    pp_layers' off-turn iterations, but each device runs only its OWN
    layers, so the wall is (M+pp-1) microbatch-stage computes instead of
    M*pp."""
    from ..models.transformer import _layer

    pp, tp, n_slot, inner_cfg, wrap = _pp_scaffold(mesh, layers, cfg,
                                                   x.shape[0])
    t = x.shape[1]
    assert n_mb > 1 and t % n_mb == 0, (t, n_mb)
    t_mb = t // n_mb
    perm = [(i, i + 1) for i in range(pp - 1)]

    def shift(y):
        # activation hop stage p -> p+1; stage 0 receives zeros (unused —
        # it always reads the embedded input). f32 transit on CPU for the
        # same reason as manual_psum.
        if jax.default_backend() == "cpu" and y.dtype == jnp.bfloat16:
            return lax.ppermute(y.astype(jnp.float32), PP_AXIS,
                                perm).astype(y.dtype)
        return lax.ppermute(y, PP_AXIS, perm)

    def body(x_l, q_pos_l, layers_l, k_l, v_l):
        p = lax.axis_index(PP_AXIS)
        k_l = list(k_l)
        v_l = list(v_l)
        lws = [{k: _unwrap0(k, w, tp) for k, w in layers_l[j].items()}
               for j in range(n_slot)]
        act = jnp.zeros((x_l.shape[0], t_mb, x_l.shape[2]), x_l.dtype)
        out = jnp.zeros_like(x_l)
        for step in range(n_mb + pp - 1):
            m = step - p                # this device's microbatch index
            valid = (m >= 0) & (m < n_mb)
            off = jnp.clip(m, 0, n_mb - 1) * t_mb
            inp = jnp.where(p == 0,
                            lax.dynamic_slice_in_dim(x_l, off, t_mb, 1),
                            act)
            q_mb = lax.dynamic_slice_in_dim(q_pos_l, off, t_mb, 1)
            y = inp
            for j in range(n_slot):
                y, k_new, v_new = _layer(
                    y, lws[j], spec, k_l[j][0], v_l[j][0], q_mb, inner_cfg,
                    per_row_pos=per_row_pos, write_gate=valid)
                k_l[j] = k_new[None]
                v_l[j] = v_new[None]
            # only the last stage's (valid) results reach the output; all
            # other devices keep out == 0, so one psum replicates at the end
            cur = lax.dynamic_slice_in_dim(out, off, t_mb, 1)
            out = lax.dynamic_update_slice_in_dim(
                out, jnp.where((p == pp - 1) & valid, y, cur), off, 1)
            if step < n_mb + pp - 2:  # the last step's hop is dead
                act = shift(y)
        return manual_psum(out, PP_AXIS), tuple(k_l), tuple(v_l)

    return wrap(body)(x, q_pos, layers, cache.k, cache.v)
