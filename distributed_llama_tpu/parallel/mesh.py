"""Device mesh construction.

The reference's cluster shape is `nSlices = nWorkers + 1` CPU nodes in a TCP
star (ref: src/app.cpp:103-132). Here the cluster is a `jax.sharding.Mesh`
with named axes:

  dp — data parallel (batch; net-new vs the reference, which is batch=1)
  sp — sequence/context parallel (ring attention axis)
  ep — expert parallel (MoE experts placed across devices; net-new — the
       reference only TP-slices every expert, ref: grok1-tasks.cpp:56-126)
  tp — tensor parallel (the reference's nSlices axis)

Multi-host TPU slices work transparently: `jax.devices()` spans hosts and
GSPMD collectives ride ICI/DCN — the replacement for the reference's
socket star (SURVEY.md §5.8).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

DP_AXIS = "dp"
SP_AXIS = "sp"
EP_AXIS = "ep"
TP_AXIS = "tp"


def make_mesh(tp: int | None = None, dp: int = 1, sp: int = 1, ep: int = 1,
              devices=None) -> Mesh:
    """Build a (dp, sp, ep, tp) mesh. tp defaults to all remaining devices.
    ep neighbors tp so the MoE partial-sum psum over (ep, tp) rides the
    innermost (fastest) ICI dimension."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if tp is None:
        assert n % (dp * sp * ep) == 0, (n, dp, sp, ep)
        tp = n // (dp * sp * ep)
    need = dp * sp * ep * tp
    assert need <= n, f"mesh {dp}x{sp}x{ep}x{tp} needs {need} devices, have {n}"
    arr = np.array(devices[:need]).reshape(dp, sp, ep, tp)
    return Mesh(arr, (DP_AXIS, SP_AXIS, EP_AXIS, TP_AXIS))
