"""Device mesh construction.

The reference's cluster shape is `nSlices = nWorkers + 1` CPU nodes in a TCP
star (ref: src/app.cpp:103-132). Here the cluster is a `jax.sharding.Mesh`
with named axes:

  dp — data parallel (batch; net-new vs the reference, which is batch=1)
  sp — sequence/context parallel (ring attention axis)
  ep — expert parallel (MoE experts placed across devices; net-new — the
       reference only TP-slices every expert, ref: grok1-tasks.cpp:56-126)
  pp — pipeline parallel (layers placed in stages across devices; net-new —
       every reference node runs every layer, ref: llama2-tasks.cpp:214-220)
  tp — tensor parallel (the reference's nSlices axis)

Multi-host TPU slices work transparently: `jax.devices()` spans hosts and
GSPMD collectives ride ICI/DCN — the replacement for the reference's
socket star (SURVEY.md §5.8).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

DP_AXIS = "dp"
SP_AXIS = "sp"
EP_AXIS = "ep"
PP_AXIS = "pp"
TP_AXIS = "tp"


def make_mesh(tp: int | None = None, dp: int = 1, sp: int = 1, ep: int = 1,
              pp: int = 1, devices=None) -> Mesh:
    """Build a (dp, sp, ep, pp, tp) mesh. tp defaults to all remaining
    devices. ep/pp neighbor tp so the per-layer reduces ride the innermost
    (fastest) ICI dimensions; pp's stage hop is the cheapest collective."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if tp is None:
        assert n % (dp * sp * ep * pp) == 0, (n, dp, sp, ep, pp)
        tp = n // (dp * sp * ep * pp)
    need = dp * sp * ep * pp * tp
    assert need <= n, (
        f"mesh {dp}x{sp}x{ep}x{pp}x{tp} needs {need} devices, have {n}")
    arr = np.array(devices[:need]).reshape(dp, sp, ep, pp, tp)
    return Mesh(arr, (DP_AXIS, SP_AXIS, EP_AXIS, PP_AXIS, TP_AXIS))
