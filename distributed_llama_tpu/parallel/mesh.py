"""Device mesh construction.

The reference's cluster shape is `nSlices = nWorkers + 1` CPU nodes in a TCP
star (ref: src/app.cpp:103-132). Here the cluster is a `jax.sharding.Mesh`
with named axes:

  dp — data parallel (batch; net-new vs the reference, which is batch=1)
  sp — sequence/context parallel (ring attention axis)
  tp — tensor parallel (the reference's nSlices axis)

Multi-host TPU slices work transparently: `jax.devices()` spans hosts and
GSPMD collectives ride ICI/DCN — the replacement for the reference's
socket star (SURVEY.md §5.8).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

DP_AXIS = "dp"
SP_AXIS = "sp"
TP_AXIS = "tp"


def make_mesh(tp: int | None = None, dp: int = 1, sp: int = 1,
              devices=None) -> Mesh:
    """Build a (dp, sp, tp) mesh. tp defaults to all remaining devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if tp is None:
        assert n % (dp * sp) == 0, (n, dp, sp)
        tp = n // (dp * sp)
    need = dp * sp * tp
    assert need <= n, f"mesh {dp}x{sp}x{tp} needs {need} devices, have {n}"
    arr = np.array(devices[:need]).reshape(dp, sp, tp)
    return Mesh(arr, (DP_AXIS, SP_AXIS, TP_AXIS))
