"""Multi-host execution — the reference's root/worker cluster, TPU-native.

The reference scales across machines with a hand-rolled TCP star: one root
process drives generation while N workers each hold a weight shard and
lock-step the per-token task list, triggered by a `pos` broadcast
(ref: src/apps/dllama/dllama.cpp:180-193, src/tasks.cpp:165-182,
src/socket.cpp). Here the cluster is `jax.distributed`: every host runs the
same SPMD program over ONE global `Mesh` whose devices span processes; XLA
routes the collectives over ICI within a slice and DCN across hosts.

Process 0 ("root", the reference's root node) does the tokenize / sample /
print / HTTP I/O. Worker processes (`dllama worker --nnodes N --node-rank
r --coordinator host:port`) join the mesh and follow a small broadcast
protocol carrying exactly what the reference root pushed over its sockets
each run: the prompt tokens, step budget, and sampling params
(ref: src/apps/dllama/dllama.cpp:180-193). Generation itself then needs NO
per-token control traffic: logits are replicated to every host by the jitted
step, and the sampler is a deterministic xorshift stream whose state rides
the run header — each host locally reproduces the root's token choices,
where the reference had to broadcast `pos` every step.

Framing: every root->worker message is one fixed-size int64 header
broadcast, optionally followed by one payload broadcast whose length the
header announced. Uniform framing means a root that dies or exits at ANY
protocol point pairs its final SHUTDOWN header with whatever header read a
worker is blocked in — workers always shut down cleanly instead of
deadlocking in a shape-mismatched collective.

Weights: every host streams only its addressable shards from its own copy
of the `.m` file (models/loader.py places per-device shards) — the
equivalent of the reference root pushing each worker its slice over TCP at
startup (ref: src/transformer.cpp:562-621), minus the network hop.
"""

from __future__ import annotations

import numpy as np

import jax

# message kinds (root -> workers)
MSG_SHUTDOWN = 0
MSG_RUN = 1       # one engine.generate(): tokens + budget + sampling params
MSG_API = 2       # one API request: raw JSON body bytes
MSG_XFER_BENCH = 3  # join a measure_transfer_ms() collective microbench
MSG_SEED = 5      # startup handshake: cluster-wide sampler seed

# [kind, n_payload, payload_is_bytes, max_tokens, seed_lo, seed_hi,
#  temp_bits, topp_bits, reset, lookup]
_HEADER_LEN = 10


def init_multihost(coordinator: str, num_processes: int, process_id: int) -> int:
    """Join the jax.distributed cluster; returns this process's index.

    Call before any JAX backend use. Every process must pass the same
    coordinator address ("host:port", reachable from all hosts) and the
    cluster size; ranks are 0..num_processes-1 with rank 0 the root.
    """
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return jax.process_index()


def is_multihost(mesh) -> bool:
    """Does this mesh span more than one process? (If so, engine outputs
    must be replicated before a host fetch, and host-side drivers must run
    the broadcast protocol.)"""
    if mesh is None:
        return False
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def _bcast(arr: np.ndarray) -> np.ndarray:
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.broadcast_one_to_all(arr))


class RunMsg:
    """One decoded protocol message."""

    def __init__(self, kind: int, tokens=None, body: bytes | None = None,
                 ints=None, max_tokens: int = 0, seed: int = 0,
                 temperature: float = 0.0, topp: float = 0.0,
                 reset: bool = False, lookup: int = 0):
        self.kind = kind
        self.tokens = tokens
        self.body = body
        self.ints = ints
        self.max_tokens = max_tokens
        self.seed = seed
        self.temperature = temperature
        self.topp = topp
        self.lookup = lookup
        self.reset = reset


def _send(kind: int, *, int_payload=None, bytes_payload: bytes | None = None,
          max_tokens: int = 0, seed: int = 0, temperature: float = 0.0,
          topp: float = 0.0, reset: bool = False, lookup: int = 0) -> None:
    assert int_payload is None or bytes_payload is None
    n = (len(int_payload) if int_payload is not None
         else len(bytes_payload) if bytes_payload is not None else 0)
    header = [
        kind, n, int(bytes_payload is not None), max_tokens,
        seed & 0xFFFFFFFF, (seed >> 32) & 0xFFFFFFFF,
        int(np.float32(temperature).view(np.int32)),
        int(np.float32(topp).view(np.int32)),
        int(reset),
        int(lookup),
    ]
    _bcast(np.asarray(header, np.int64))
    if int_payload is not None:
        _bcast(np.asarray(int_payload, np.int64))
    elif bytes_payload is not None:
        _bcast(np.frombuffer(bytes_payload, np.uint8))


def recv_msg() -> RunMsg:
    """Worker: block for the next protocol message."""
    h = _bcast(np.zeros(_HEADER_LEN, np.int64))
    kind, n, is_bytes = int(h[0]), int(h[1]), int(h[2])
    msg = RunMsg(
        kind,
        max_tokens=int(h[3]),
        seed=int(h[4]) | (int(h[5]) << 32),
        temperature=float(np.int32(h[6]).view(np.float32)),
        topp=float(np.int32(h[7]).view(np.float32)),
        reset=bool(h[8]),
        lookup=int(h[9]),
    )
    if n:
        if is_bytes:
            msg.body = _bcast(np.zeros(n, np.uint8)).tobytes()
        else:
            msg.ints = [int(v) for v in _bcast(np.zeros(n, np.int64))]
            if kind == MSG_RUN:
                msg.tokens = msg.ints
    return msg


# -- root-side senders -----------------------------------------------------

def send_run(tokens: list[int], max_tokens: int, seed: int,
             temperature: float, topp: float, reset: bool = False,
             lookup: int = 0) -> None:
    """Root: announce one generate() run. seed carries the root sampler's
    CURRENT rng state, so workers reproduce the token stream even when
    their own sampler flags differ. lookup > 0 = the run speculates with
    that draft length: drafts are mined from the (replicated) token
    stream, so every process mines the SAME drafts and the verify-forward
    shapes stay in lock-step across the cluster."""
    _send(MSG_RUN, int_payload=tokens, max_tokens=max_tokens, seed=seed,
          temperature=temperature, topp=topp, reset=reset, lookup=lookup)


def send_api(body_json: bytes) -> None:
    """Root: announce one API request; workers replay the identical
    completion loop from the raw request body."""
    _send(MSG_API, bytes_payload=body_json)


def send_xfer_bench() -> None:
    _send(MSG_XFER_BENCH)


def send_shutdown() -> None:
    _send(MSG_SHUTDOWN)


# -- startup handshake -----------------------------------------------------

def check_config(fingerprint: list[int]) -> None:
    """Verify every process launched with the same mesh/dtype/sampler config
    (the reference ships its spec as a raw struct memcpy and is silently
    ABI-fragile — ref: src/transformer.cpp:633). All-gathered so EVERY rank
    sees every other rank's fingerprint: a mismatch errors symmetrically and
    immediately on all processes, instead of one side exiting while the
    other hangs in its next collective."""
    from jax.experimental import multihost_utils

    mine = np.asarray(fingerprint, np.int64)
    allfp = np.asarray(multihost_utils.process_allgather(mine))
    bad = [r for r in range(allfp.shape[0]) if list(allfp[r]) != list(allfp[0])]
    if bad:
        raise SystemExit(
            f"cluster config mismatch: rank 0 has {list(allfp[0])}, "
            f"rank(s) {bad} differ (mine: {list(mine)}) — every process "
            "must use the same MODEL (.m) and TOKENIZER (.t) files and the "
            "same --tp/--dp/--sp/--ep/--pp, dtype, seq-len, pallas and "
            "sampler flags")


def bcast_spec(spec, model_fp: int = 0, push: bool = False):
    """Root-push phase 0: rank 0 broadcasts the model spec, weight-content
    fingerprint, and its --push-weights flag so FILE-LESS workers can
    participate in the config check and build their engine without ever
    reading a `.m`. Non-root callers pass spec=None; returns
    (spec, model_fp, push) on every rank.

    Runs UNCONDITIONALLY on every multihost startup (build_engine), not
    only in push mode: the collective sequence must be identical across
    processes regardless of per-process flags, or a --push-weights
    mismatch would deadlock in mismatched collectives BEFORE check_config
    could report it. With the sequence fixed, the flag rides here and the
    fingerprint check turns a mismatch into a symmetric error. Matches the
    reference root shipping its TransformerSpec struct ahead of the weight
    push (ref: src/transformer.cpp:633-644) — explicit fields, not a raw
    memcpy."""
    from ..models.spec import ArchType, HiddenAct, ModelSpec
    from ..quants.types import FloatType

    if spec is not None:
        fields = [int(spec.arch), spec.dim, spec.hidden_dim, spec.n_layers,
                  spec.n_heads, spec.n_kv_heads, spec.vocab_size,
                  spec.seq_len, int(spec.hidden_act),
                  int(np.float32(spec.rope_theta).view(np.int32)),
                  spec.n_experts, spec.n_active_experts,
                  int(spec.weights_float_type), spec.version,
                  model_fp & 0xFFFFFFFF, int(push)]
    else:
        fields = [0] * 16
    f = _bcast(np.asarray(fields, np.int64))
    out = ModelSpec(
        arch=ArchType(int(f[0])), dim=int(f[1]), hidden_dim=int(f[2]),
        n_layers=int(f[3]), n_heads=int(f[4]), n_kv_heads=int(f[5]),
        vocab_size=int(f[6]), seq_len=int(f[7]),
        hidden_act=HiddenAct(int(f[8])),
        rope_theta=float(np.int32(f[9]).view(np.float32)),
        n_experts=int(f[10]), n_active_experts=int(f[11]),
        weights_float_type=FloatType(int(f[12])), version=int(f[13]))
    return out, int(f[14]), bool(f[15])


def bcast_model_tensors(spec, path: str | None):
    """Root-push phase 1: a HostTensor generator on EVERY rank. Rank 0
    streams its `.m` file tensor-by-tensor and broadcasts each tensor's
    raw file bytes; other ranks receive and decode the identical bytes —
    so a worker needs NO local model file (the reference's root pushes
    every worker its slice over TCP the same way,
    ref: src/transformer.cpp:562-591,685-720). One tensor is resident at a
    time on each host (the streamed-loader memory contract holds); feed
    this to models.loader.load_params_streamed(tensors=...), which places
    only this host's shards and drops the rest."""
    from ..io.model_file import (_tensor_bytes, model_tensor_plan, read_spec,
                                 tensor_from_bytes)

    root = jax.process_index() == 0
    f = None
    if root:
        assert path is not None, "--push-weights root needs the model file"
        header_size = getattr(spec, "_header_size", None)
        if header_size is None:
            header_size = getattr(
                read_spec(path, spec.weights_float_type), "_header_size")
        f = open(path, "rb")
        f.seek(header_size)
    try:
        for name, shape, ftype in model_tensor_plan(spec):
            nbytes = _tensor_bytes(shape, ftype)
            if root:
                raw = np.frombuffer(f.read(nbytes), np.uint8)
                if raw.size != nbytes:
                    raise EOFError(f"model file truncated at {name}")
            else:
                raw = np.zeros(nbytes, np.uint8)
            raw = _bcast(raw)
            yield tensor_from_bytes(name, shape, ftype, raw.tobytes())
    finally:
        if f is not None:
            f.close()


def broadcast_seed(seed: int) -> int:
    """Agree on one base sampler seed cluster-wide (the CLI default is
    time-based, which would diverge per host)."""
    if jax.process_index() == 0:
        _send(MSG_SEED, seed=seed)
        return seed
    msg = recv_msg()
    if msg.kind == MSG_SHUTDOWN:
        raise SystemExit("root shut down during startup")
    if msg.kind != MSG_SEED:
        raise SystemExit(f"protocol error: expected seed, got kind={msg.kind}")
    return msg.seed
