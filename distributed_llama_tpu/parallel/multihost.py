"""Multi-host execution — the reference's root/worker cluster, TPU-native.

The reference scales across machines with a hand-rolled TCP star: one root
process drives generation while N workers each hold a weight shard and
lock-step the per-token task list, triggered by a `pos` broadcast
(ref: src/apps/dllama/dllama.cpp:180-193, src/tasks.cpp:165-182,
src/socket.cpp). Here the cluster is split into two planes:

DATA PLANE — `jax.distributed`: every host runs the same SPMD program over
ONE global `Mesh` whose devices span processes; XLA routes the collectives
over ICI within a slice and DCN across hosts. Weights: every host streams
only its addressable shards from its own copy of the `.m` file
(models/loader.py), or receives the root's tensor bytes over collective
broadcast (`bcast_model_tensors` — the reference root pushing each worker
its slice over TCP at startup, ref: src/transformer.cpp:562-621).

CONTROL PLANE — a supervised TCP star (this module): the root listens on
``coordinator_port + 1`` (``DLLAMA_CONTROL_PORT`` overrides) and every
worker connects with retry + exponential backoff bounded by
``--connect-timeout``, then identifies itself with a versioned ``MSG_HELLO``
handshake. All protocol messages (the prompt tokens, step budget, sampling
params, raw API bodies — exactly what the reference root pushed over its
sockets each run, ref: src/apps/dllama/dllama.cpp:180-193) ride length-
prefixed frames with per-socket deadlines on EVERY send and recv. A
root->worker heartbeat (``MSG_PING``/``MSG_PONG`` every
``--heartbeat-interval``) bounds failure detection: a peer that dies (EOF),
wedges (no frame within ``--worker-timeout``), or tears a frame is
*detected* and surfaced as a structured :class:`ClusterPeerLost`
(node_id, last_seen, phase, reason) instead of hanging a collective
forever — the exact raw-TCP fragility the reference ships with (a dead
worker hangs the whole cluster; SURVEY §5.3). The previous revision of
this module framed control messages as `broadcast_one_to_all` collectives,
which pair up cleanly on a CLEAN root exit but block unboundedly in C++
when a peer silently dies — no timeout, heartbeat, or retry was possible
at all.

Generation itself needs NO per-token control traffic: logits are
replicated to every host by the jitted step, and the sampler is a
deterministic xorshift stream whose state rides the run header — each host
locally reproduces the root's token choices, where the reference had to
broadcast `pos` every step.

Fault injection: the frame codec fires the socket-layer sites of
``runtime/faults.py`` (``conn_refused``/``recv_stall``/``frame_truncate``/
``peer_close``) so two-process chaos tests can kill or stall either side
deterministically (tests/test_cluster_chaos.py, the
``parallel/cluster_harness.py`` subprocess driver). All detection is
host-side — no jitted entry point changes under any of it.

Ops runbook: docs/operations.md "Cluster failure modes".
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time

import numpy as np

import jax

from ..runtime.faults import FAULTS

# message kinds (root -> workers, except PONG and TRACE)
MSG_SHUTDOWN = 0
MSG_RUN = 1       # one engine.generate(): tokens + budget + sampling params
MSG_API = 2       # one API request: raw JSON body bytes
MSG_XFER_BENCH = 3  # join the collective microbench sequence (header
#                     carries n_prompt so root and workers run IDENTICAL
#                     measure calls — a mismatch deadlocks the mesh)
MSG_SEED = 5      # startup handshake: cluster-wide sampler seed
MSG_HELLO = 6     # worker -> root: version + rank + pid
MSG_HELLO_ACK = 7  # root -> worker: version/status + adopted timing
MSG_PING = 8      # root -> worker heartbeat
MSG_PONG = 9      # worker -> root heartbeat reply: [seq, worker wall µs]
MSG_TRACE = 10    # worker -> root: flight-recorder span ship (JSON
#                   payload of wall-stamped events; the root rebases them
#                   onto its own timeline via the PING/PONG-midpoint
#                   clock-offset estimate — the cluster twin of the
#                   replica tier's RMSG_TRACE)

# MSG kind -> ledger label (the `kind` label of dllama_wire_bytes_total)
MSG_NAMES = {
    MSG_SHUTDOWN: "SHUTDOWN", MSG_RUN: "RUN", MSG_API: "API",
    MSG_XFER_BENCH: "XFER_BENCH", MSG_SEED: "SEED", MSG_HELLO: "HELLO",
    MSG_HELLO_ACK: "HELLO_ACK", MSG_PING: "PING", MSG_PONG: "PONG",
    MSG_TRACE: "TRACE",
}

# [kind, n_payload, payload_is_bytes, max_tokens, seed_lo, seed_hi,
#  temp_bits, topp_bits, reset, lookup, trace_tid]
_HEADER_LEN = 11

# v2: protocol header grew the trace_tid slot and PONG carries the
# worker's wall clock (dlwire) — mixed builds fail the HELLO symmetric
PROTOCOL_VERSION = 2

# diagnostic exit codes (documented in docs/operations.md): distinct from
# generic failure (1) so operators and supervisors can tell "a peer died
# and we detected it" from "we crashed"
EXIT_PEER_LOST = 43   # bounded detection fired: a peer is dead/wedged
EXIT_FORMATION = 44   # cluster never formed (connect timeout, version/rank
#                       mismatch) — nothing was ever at risk

_FRAME_MAGIC = 0x444C4743  # "DLGC"
_FRAME_HDR = struct.Struct("<IIII")  # magic, kind, n_ints, n_payload_bytes
_MAX_INTS = 1 << 16
_MAX_PAYLOAD = 1 << 31
_HELLO_ACK_OK, _HELLO_ACK_BAD_VERSION, _HELLO_ACK_BAD_RANK = 0, 1, 2


class ClusterPeerLost(RuntimeError):
    """Bounded failure detection fired: ``node_id`` has not produced a
    frame within the heartbeat timeout (or its socket died). ``last_seen``
    is seconds since its last frame at detection time, ``phase`` the
    cluster phase the detecting side was in (formation/load/idle/run/...),
    ``reason`` the detector ("timeout", "eof", "reset", "truncated frame",
    "send failed: ..."). The root surfaces this as a diagnostic exit
    (``EXIT_PEER_LOST``); the api-mode supervisor maps it to the BROKEN
    path (runtime/resilience.EngineSupervisor.trip_cluster); workers exit
    cleanly on root loss."""

    def __init__(self, node_id: int, last_seen: float, phase: str,
                 reason: str = "timeout"):
        self.node_id = int(node_id)
        self.last_seen = float(last_seen)
        self.phase = phase
        self.reason = reason
        super().__init__(
            f"cluster peer lost: node {node_id} ({reason}) — last seen "
            f"{last_seen:.2f}s ago, phase={phase}")

    def summary(self) -> dict:
        """The structured diagnostic shape (logged as one JSON line and
        reported in the /stats cluster block)."""
        return {"event": "cluster_peer_lost", "node_id": self.node_id,
                "last_seen_s": round(self.last_seen, 3),
                "phase": self.phase, "reason": self.reason}


class ClusterProtocolError(RuntimeError):
    """Handshake or framing violation (version/rank mismatch, bad magic,
    truncated frame, formation timeout) — a config/deploy error, not a
    peer death."""


# -- frame codec -----------------------------------------------------------

def frame_bytes(n_ints: int, n_payload: int) -> int:
    """Exact on-the-wire size of one frame — header + 8 bytes per int +
    payload. The reconciliation tests (and the bench cluster row) pin
    the measured ledger against this arithmetic: the codec owns the
    format, so the model lives next to it."""
    return _FRAME_HDR.size + 8 * int(n_ints) + int(n_payload)


def _send_frame(sock: socket.socket, kind: int, ints=(), payload: bytes = b"",
                timeout: float | None = None, acct=None) -> None:
    """One framed send with a per-socket deadline. The caller serializes
    concurrent senders (per-peer send lock). Fault sites: frame_truncate
    (half the bytes then close — the peer sees a torn frame), peer_close
    (close without writing).

    ``acct(kind, nbytes)`` is the wire-ledger hook: called EXACTLY ONCE
    per frame attempt (a finally, so fault paths account too) with the
    bytes actually handed to the kernel — a torn frame counts its
    partial bytes once, a peer_close counts zero, and a sendall that
    raises mid-write counts zero (the kernel's share is unknowable; the
    ledger under-reports rather than guesses)."""
    ints = [int(v) for v in ints]
    buf = _FRAME_HDR.pack(_FRAME_MAGIC, kind, len(ints), len(payload))
    if ints:
        buf += struct.pack(f"<{len(ints)}q", *ints)
    buf += payload
    sock.settimeout(timeout)
    sent = 0
    try:
        if FAULTS.triggered("frame_truncate"):
            part = buf[: max(1, len(buf) // 2)]
            try:
                sock.sendall(part)
                sent = len(part)
            finally:
                sock.close()
            raise ClusterProtocolError("injected frame_truncate")
        if FAULTS.triggered("peer_close"):
            sock.close()
            raise ClusterProtocolError("injected peer_close")
        sock.sendall(buf)
        sent = len(buf)
    finally:
        if acct is not None and sent:
            acct(kind, sent)


def _recv_exact(sock: socket.socket, n: int, deadline: float | None, *,
                allow_eof: bool = False, got_box: list | None = None
                ) -> bytes | None:
    """Read exactly n bytes before an ABSOLUTE monotonic deadline. The
    per-chunk socket timeout is re-armed to the REMAINING budget, so a
    peer trickling one byte per timeout window cannot stretch a frame
    read unboundedly — the whole-frame bound is what the detection
    contract advertises. EOF at a frame boundary returns None when
    allowed (clean close); EOF mid-read is a torn frame and raises.
    ``got_box[0]`` accumulates bytes actually read (the ledger's truth
    even when the read dies mid-frame)."""
    chunks: list[bytes] = []
    got = 0
    while got < n:
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout(
                    f"frame read exceeded its deadline ({got}/{n} bytes)")
            sock.settimeout(remaining)
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            if allow_eof and got == 0:
                return None
            raise ClusterProtocolError(
                f"truncated frame: EOF after {got}/{n} bytes")
        chunks.append(chunk)
        got += len(chunk)
        if got_box is not None:
            got_box[0] += len(chunk)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket, timeout: float | None, acct=None
                ) -> tuple[int, list[int], bytes] | None:
    """One framed recv under ONE whole-frame deadline (header + ints +
    payload share it). Returns None on a clean EOF at a frame boundary;
    raises socket.timeout past the deadline and ClusterProtocolError on a
    torn/garbled frame. Fault site: recv_stall (wedges this reader like a
    hung peer — it stops answering heartbeats, so only the PING/PONG
    timeout on the OTHER side detects it).

    ``acct(kind_or_None, nbytes)`` mirrors the send hook: called exactly
    once per frame attempt with the bytes actually read — a frame torn
    mid-payload counts its partial bytes once, under the parsed kind
    when the header survived (None otherwise)."""
    got = [0]
    kind = None
    try:
        # stall fires BEFORE the deadline is armed (as pre-ledger): the
        # whole-frame bound covers the read, not an injected wedge
        FAULTS.fire("recv_stall")
        deadline = None if timeout is None else time.monotonic() + timeout
        sock.settimeout(timeout)
        hdr = _recv_exact(sock, _FRAME_HDR.size, deadline, allow_eof=True,
                          got_box=got)
        if hdr is None:
            return None
        magic, kind, n_ints, n_pay = _FRAME_HDR.unpack(hdr)
        if magic != _FRAME_MAGIC:
            raise ClusterProtocolError(f"bad frame magic 0x{magic:08x}")
        if n_ints > _MAX_INTS or n_pay > _MAX_PAYLOAD:
            raise ClusterProtocolError(
                f"implausible frame header (ints={n_ints}, payload={n_pay})")
        ints: list[int] = []
        if n_ints:
            raw = _recv_exact(sock, 8 * n_ints, deadline, got_box=got)
            ints = list(struct.unpack(f"<{n_ints}q", raw))
        payload = (_recv_exact(sock, n_pay, deadline, got_box=got)
                   if n_pay else b"")
        return kind, ints, payload
    finally:
        if acct is not None and got[0]:
            acct(kind, got[0])


def control_port(coordinator: str) -> int:
    """The control-plane TCP port: coordinator port + 1 on the same host
    (rank 0 runs on the coordinator host — the jax.distributed coordinator
    lives inside process 0). ``DLLAMA_CONTROL_PORT`` overrides when +1 is
    taken."""
    env = os.environ.get("DLLAMA_CONTROL_PORT")
    if env:
        return int(env)
    return int(coordinator.rsplit(":", 1)[1]) + 1


def _now() -> float:
    return time.monotonic()


class _Peer:
    """Root-side record of one connected worker. The connection is held
    through TWO Python socket objects over the SAME fd (dup): Python
    timeouts live on the socket OBJECT, and the receiver thread re-arms
    its deadline per read while sender threads (heartbeat, broadcast)
    arm worker_timeout per write — on one shared object those
    settimeout() calls race, so a send could run under the receiver's
    near-zero remaining budget (spurious 'send failed' peer-loss) or a
    recv under the sender's full budget (detection bound stretched).
    Distinct objects make each direction's deadline private; the kernel
    socket is one TCP stream either way."""

    def __init__(self, rank: int, sock: socket.socket, pid: int):
        self.rank = rank
        self.sock = sock              # receiver-thread reads
        self.send_sock = sock.dup()   # sender threads, under send_lock
        self.pid = pid
        self.last_seen = _now()
        self.send_lock = threading.Lock()
        self.alive = True
        # wire-ledger hooks (set after _init_stats — formation frames ride
        # before the stats object exists, documented ledger scope)
        self.acct_send = None
        self.acct_recv = None
        # in-flight PING seq -> (mono, wall) send stamps, for the RTT /
        # clock-offset estimate; bounded (stale seqs pruned on insert)
        self.ping_sent: dict[int, tuple] = {}

    def close(self) -> None:
        for s in (self.sock, self.send_sock):
            try:
                s.close()
            except OSError:
                pass


class _LinkBase:
    """State shared by both ends of the control star: heartbeat timing,
    the current phase label (rides every ClusterPeerLost), counters for
    the /stats cluster block, and the peer-lost callback hook."""

    def __init__(self, nnodes: int, rank: int, *,
                 heartbeat_interval: float, worker_timeout: float):
        self.nnodes = int(nnodes)
        self.rank = int(rank)
        self.heartbeat_interval = float(heartbeat_interval)
        self.worker_timeout = float(worker_timeout)
        self.phase = "formation"
        # the trace id the current protocol activity rides (set by the
        # driver — harness root / _announce_run); a ClusterPeerLost
        # casualty span links under it
        self.trace_tid = 0
        self.lost: dict[int, ClusterPeerLost] = {}  # dlrace: guarded-by(self._lock)
        # callback invoked ONCE per lost peer, from the detecting thread
        # (receiver/heartbeat — the main thread may be wedged in a
        # collective and uninterruptible, so the callback is where a
        # diagnostic exit must happen). None = record only; the next
        # send/recv raises.
        self.on_peer_lost = None
        self._lock = threading.Lock()
        self._closing = False  # dlrace: guarded-by(self._lock)
        self.stats = None  # runtime.stats.ClusterStats, set in _init_stats

    def _init_stats(self, connect_retries: int = 0) -> None:
        from ..runtime.stats import ClusterStats

        self.stats = ClusterStats(
            nnodes=self.nnodes, node_rank=self.rank,
            protocol_version=PROTOCOL_VERSION,
            heartbeat_interval_s=self.heartbeat_interval,
            worker_timeout_s=self.worker_timeout,
            connect_retries=connect_retries)

    def set_phase(self, phase: str) -> None:
        self.phase = phase

    def check(self) -> None:
        """Raise the first recorded peer loss (idempotent view — senders
        call this before touching sockets so a loss detected by the
        heartbeat thread surfaces on the driving thread too)."""
        with self._lock:
            if self.lost and not self._closing:
                raise next(iter(self.lost.values()))

    def _mk_acct(self, peer_rank: int, direction: str):
        """One wire-ledger accounting closure for the codec hooks: a
        no-op until _init_stats built the ClusterStats (formation frames
        are out of ledger scope by design)."""
        def acct(kind, nbytes):
            st = self.stats
            if st is not None:
                st.wire.account(peer_rank,
                                MSG_NAMES.get(kind, str(kind)),
                                direction, nbytes)
        return acct

    def _report_lost(self, exc: ClusterPeerLost) -> bool:
        """Record + notify exactly once per peer. Returns True when this
        call was the first detection."""
        with self._lock:
            if self._closing or exc.node_id in self.lost:
                return False
            self.lost[exc.node_id] = exc
        if self.stats is not None:
            self.stats.peers_lost.append(exc.summary())
        from ..runtime.trace import TRACER

        if TRACER.enabled:
            # the casualty span: a lost peer lands on the SAME timeline
            # (and trace id) as the protocol activity it died under —
            # the cluster twin of a SIGKILLed replica's worker_exit event
            TRACER.event("cluster_lost", self.trace_tid,
                         node=exc.node_id, reason=exc.reason,
                         phase=exc.phase,
                         last_seen_s=round(exc.last_seen, 3))
        cb = self.on_peer_lost
        if cb is not None:
            cb(exc)
        return True

    def summary(self) -> dict:
        out = self.stats.summary() if self.stats is not None else {}
        out["phase"] = self.phase
        return out


class RootLink(_LinkBase):
    """Root (rank 0) side of the control star: accepts the versioned
    HELLO handshake from every worker during formation, then runs one
    receiver thread per peer (PONGs update liveness; silence past
    ``worker_timeout`` or a dead socket trips :class:`ClusterPeerLost`)
    and one heartbeat thread PINGing all peers every
    ``heartbeat_interval``."""

    def __init__(self, nnodes: int, bind_host: str, port: int, *,
                 heartbeat_interval: float = 2.0,
                 worker_timeout: float = 10.0,
                 connect_timeout: float = 30.0):
        super().__init__(nnodes, 0, heartbeat_interval=heartbeat_interval,
                         worker_timeout=worker_timeout)
        self.connect_timeout = float(connect_timeout)
        self._bind = (bind_host, int(port))
        self.peers: dict[int, _Peer] = {}
        self._threads: list[threading.Thread] = []

    def form(self) -> None:
        """Bind, accept nnodes-1 HELLOs (each validated for protocol
        version and rank uniqueness, each ACKed with the root's heartbeat
        timing so both sides agree on detection bounds), then start the
        heartbeat machinery. Raises ClusterProtocolError when the cluster
        does not form within ``connect_timeout``."""
        deadline = _now() + self.connect_timeout
        try:
            srv = socket.create_server(self._bind,
                                       backlog=max(self.nnodes, 2),
                                       reuse_port=False)
        except OSError as e:
            raise ClusterProtocolError(
                f"cannot bind the control port {self._bind[1]} "
                f"(coordinator port + 1): {e} — set DLLAMA_CONTROL_PORT "
                "to a free port on every node") from e
        try:
            srv.settimeout(0.2)
            while len(self.peers) < self.nnodes - 1:
                if _now() > deadline:
                    missing = sorted(set(range(1, self.nnodes))
                                     - set(self.peers))
                    raise ClusterProtocolError(
                        f"cluster formation timed out after "
                        f"{self.connect_timeout:.1f}s (--connect-timeout): "
                        f"worker rank(s) {missing} never completed the "
                        f"HELLO handshake on control port {self._bind[1]}")
                try:
                    conn, _addr = srv.accept()
                except socket.timeout:
                    continue
                self._handshake(conn)
        finally:
            srv.close()
        self._init_stats()
        # formation is over: early joiners have been silent BY PROTOCOL
        # while later ranks HELLOed (nothing is sent to a connected peer
        # until every rank is in), so their handshake-time last_seen may
        # be up to connect_timeout stale — liveness clocks start NOW, or
        # a healthy staggered join would false-positive instantly
        for peer in self.peers.values():
            peer.last_seen = _now()
            peer.acct_send = self._mk_acct(peer.rank, "tx")
            peer.acct_recv = self._mk_acct(peer.rank, "rx")
        for peer in self.peers.values():
            t = threading.Thread(target=self._receiver, args=(peer,),
                                 name=f"dllama-cluster-recv-r{peer.rank}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        hb = threading.Thread(target=self._heartbeat,
                              name="dllama-cluster-heartbeat", daemon=True)
        hb.start()
        self._threads.append(hb)

    def _handshake(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            frame = _recv_frame(conn, timeout=5.0)
        except (OSError, ClusterProtocolError):
            conn.close()  # a port-scanner / torn hello: drop, keep waiting
            return
        if frame is None or frame[0] != MSG_HELLO or len(frame[1]) < 3:
            conn.close()
            return
        version, rank, pid = frame[1][:3]
        # the root's timing is authoritative cluster-wide: heartbeat
        # cadence + detection bound AND the formation budget (the
        # worker's pre-first-frame grace must cover the ROOT's formation
        # window, not its own local --connect-timeout)
        ack = [PROTOCOL_VERSION, _HELLO_ACK_OK, self.nnodes,
               int(self.heartbeat_interval * 1e3),
               int(self.worker_timeout * 1e3),
               int(self.connect_timeout * 1e3)]
        if version != PROTOCOL_VERSION:
            ack[1] = _HELLO_ACK_BAD_VERSION
            self._ack_and_close(conn, ack)
            raise ClusterProtocolError(
                f"protocol version mismatch: worker rank {rank} speaks "
                f"v{version}, root speaks v{PROTOCOL_VERSION} — every node "
                "must run the same build")
        if not (1 <= rank < self.nnodes) or rank in self.peers:
            ack[1] = _HELLO_ACK_BAD_RANK
            self._ack_and_close(conn, ack)
            raise ClusterProtocolError(
                f"bad HELLO rank {rank}: expected a unique rank in "
                f"1..{self.nnodes - 1} (already connected: "
                f"{sorted(self.peers)})")
        try:
            _send_frame(conn, MSG_HELLO_ACK, ack, timeout=5.0)
            self.peers[rank] = _Peer(rank, conn, pid)
        except (OSError, ClusterProtocolError):
            # the worker died between its HELLO and our ACK: drop the
            # half-dead connection and keep waiting for that rank's
            # restart inside the formation deadline — a raw BrokenPipe
            # must not crash formation unstructured
            conn.close()

    @staticmethod
    def _ack_and_close(conn: socket.socket, ack: list[int]) -> None:
        try:
            _send_frame(conn, MSG_HELLO_ACK, ack, timeout=5.0)
        except (OSError, ClusterProtocolError):
            pass
        conn.close()

    def _receiver(self, peer: _Peer) -> None:
        """Per-peer read loop: any frame refreshes liveness; silence past
        ``worker_timeout`` (the peer answers PINGs when healthy, so
        silence means dead or wedged), EOF, reset, or a torn frame trips
        ClusterPeerLost with the matching reason."""
        while peer.alive and not self._closing:
            wait = max(0.05,
                       peer.last_seen + self.worker_timeout - _now())
            try:
                frame = _recv_frame(peer.sock, timeout=wait,
                                    acct=peer.acct_recv)
            except socket.timeout:
                self._lost(peer, "timeout")
                return
            except ConnectionResetError:
                self._lost(peer, "reset")
                return
            except ClusterProtocolError as e:
                self._lost(peer, str(e))
                return
            except OSError:
                if self._closing:
                    return
                self._lost(peer, "socket error")
                return
            if frame is None:  # clean EOF: the worker process is gone
                if not self._closing:
                    self._lost(peer, "eof")
                return
            peer.last_seen = _now()
            if self.stats is not None:
                self.stats.frames_received += 1
                if frame[0] == MSG_PONG:
                    self.stats.pongs_received += 1
                    self._note_pong(peer, frame[1])
            if frame[0] == MSG_TRACE:
                self._ingest_trace(peer, frame[2])

    def _note_pong(self, peer: _Peer, ints: list[int]) -> None:
        """One PONG: match it to its PING's send stamps for the RTT
        sample, and — when the worker echoed its wall clock — refresh
        the midpoint clock-offset estimate (offset = worker wall at the
        midpoint of the round trip minus local wall; kept at the best
        i.e. minimum-RTT sample — the NTP pick)."""
        if not ints:
            return
        stamp = peer.ping_sent.pop(int(ints[0]), None)
        if stamp is None:
            return
        mono_send, wall_send = stamp
        rtt_ms = (_now() - mono_send) * 1e3
        offset_s = None
        if len(ints) > 1 and ints[1]:
            wall_mid = (wall_send + time.time()) / 2.0
            offset_s = ints[1] / 1e6 - wall_mid
        self.stats.wire.rtt(peer.rank, rtt_ms, offset_s)

    def _ingest_trace(self, peer: _Peer, payload: bytes) -> None:
        """One MSG_TRACE frame: merge the worker's wall-stamped span
        events onto the local tracer's timeline, shifted by the per-peer
        clock-offset estimate so cross-host events sort to within the
        offset estimate's error (~RTT/2)."""
        from ..runtime.trace import TRACER

        if not TRACER.enabled:
            return
        try:
            import json

            events = json.loads(payload.decode())["events"]
            assert isinstance(events, list)
        except (ValueError, KeyError, AssertionError, UnicodeDecodeError):
            return  # a malformed ship is observability loss, not a fault
        off = (self.stats.wire.clock_offset_s(peer.rank)
               if self.stats is not None else None)
        if off:
            events = [{**e, "ts_wall": e["ts_wall"] - off}
                      for e in events if "ts_wall" in e]
        TRACER.ingest(events, origin=f"node{peer.rank}")

    def _heartbeat(self) -> None:
        # ping FIRST, then sleep: the formation-complete ping reaches
        # every worker immediately, ending the protocol-silent formation
        # window their own liveness clocks must tolerate (WorkerLink
        # _receiver's pre-first-frame grace)
        seq = 0
        while not self._closing:
            seq += 1
            for peer in list(self.peers.values()):
                if not peer.alive:
                    continue
                try:
                    # stamp BEFORE the send: the RTT sample must include
                    # the send syscall (the peer's PONG races the stamp
                    # otherwise); stale seqs (unanswered pings) pruned
                    # so a wedged peer cannot grow the dict unboundedly.
                    # The receiver thread pops matched seqs lock-free
                    # concurrently, so the prune must tolerate losing
                    # the race (default pop; StopIteration/RuntimeError
                    # if the dict empties/mutates under the iterator) —
                    # an uncaught error here would kill the heartbeat
                    # thread and tear the whole cluster down
                    peer.ping_sent[seq] = (_now(), time.time())
                    while len(peer.ping_sent) > 64:
                        try:
                            peer.ping_sent.pop(
                                next(iter(peer.ping_sent)), None)
                        except (StopIteration, RuntimeError):
                            break
                    with peer.send_lock:
                        _send_frame(peer.send_sock, MSG_PING, [seq],
                                    timeout=self.worker_timeout,
                                    acct=peer.acct_send)
                    if self.stats is not None:
                        self.stats.pings_sent += 1
                except (OSError, ClusterProtocolError) as e:
                    self._lost(peer, f"send failed: {e}")
            time.sleep(self.heartbeat_interval)

    def _lost(self, peer: _Peer, reason: str) -> None:
        peer.alive = False
        age = _now() - peer.last_seen
        peer.close()
        self._report_lost(
            ClusterPeerLost(peer.rank, age, self.phase, reason))

    def broadcast(self, kind: int, ints, payload: bytes = b"") -> None:
        """Fan one protocol frame out to every worker (the reference
        root's per-worker socket writes). Raises ClusterPeerLost when a
        peer was, or just turned out to be, lost — except for SHUTDOWN,
        which is best-effort by design (a dying cluster must still be
        tear-down-able)."""
        shutdown = kind == MSG_SHUTDOWN
        if shutdown:
            with self._lock:
                self._closing = True
        else:
            self.check()
        for peer in list(self.peers.values()):
            if not peer.alive:
                continue
            try:
                with peer.send_lock:
                    _send_frame(peer.send_sock, kind, ints, payload,
                                timeout=self.worker_timeout,
                                acct=peer.acct_send)
                if self.stats is not None:
                    self.stats.frames_sent += 1
            except (OSError, ClusterProtocolError) as e:
                if not shutdown:
                    self._lost(peer, f"send failed: {e}")
                    self.check()

    def close(self) -> None:
        with self._lock:
            self._closing = True
        for peer in self.peers.values():
            peer.alive = False
            peer.close()


class WorkerLink(_LinkBase):
    """Worker side: connects with retry + exponential backoff bounded by
    ``connect_timeout``, HELLOs, adopts the root's heartbeat timing from
    the ACK, then runs one receiver thread that answers PINGs with PONGs,
    queues protocol messages for :meth:`recv`, and trips
    :class:`ClusterPeerLost` (node 0) when the root goes silent past
    ``worker_timeout`` or its socket dies."""

    def __init__(self, host: str, port: int, rank: int, nnodes: int, *,
                 heartbeat_interval: float = 2.0,
                 worker_timeout: float = 10.0,
                 connect_timeout: float = 30.0,
                 protocol_version: int = PROTOCOL_VERSION):
        super().__init__(nnodes, rank, heartbeat_interval=heartbeat_interval,
                         worker_timeout=worker_timeout)
        self._addr = (host, int(port))
        self.connect_timeout = float(connect_timeout)
        self._protocol_version = int(protocol_version)
        self.sock: socket.socket | None = None
        self._send_lock = threading.Lock()
        self._acct_send = None
        self._acct_recv = None
        self._queue: list[tuple[int, list[int], bytes]] = []
        self._cond = threading.Condition()
        self._last_seen = _now()
        self._shutdown_seen = False
        self.connect_retries = 0

    def form(self) -> None:
        deadline = _now() + self.connect_timeout
        delay = 0.05
        last_err: Exception | None = None
        while True:
            budget = deadline - _now()
            if budget <= 0:
                raise ClusterProtocolError(
                    f"could not reach root control port "
                    f"{self._addr[0]}:{self._addr[1]} within "
                    f"{self.connect_timeout:.1f}s (--connect-timeout, "
                    f"{self.connect_retries} attempts): {last_err}")
            try:
                FAULTS.fire("conn_refused")
                self.sock = socket.create_connection(
                    self._addr, timeout=min(budget, 5.0))
                break
            except OSError as e:  # refused/unreachable/timeout: back off
                last_err = e
                self.connect_retries += 1
                time.sleep(min(delay, max(deadline - _now(), 0)))
                delay = min(delay * 2, 1.0)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            _send_frame(self.sock, MSG_HELLO,
                        [self._protocol_version, self.rank, os.getpid()],
                        timeout=5.0)
        except OSError as e:
            raise ClusterProtocolError(
                f"control handshake failed sending HELLO: {e}") from e
        try:
            frame = _recv_frame(self.sock, timeout=self.connect_timeout)
        except socket.timeout as e:
            raise ClusterProtocolError(
                "root accepted the connection but never ACKed the HELLO "
                f"within {self.connect_timeout:.1f}s") from e
        except OSError as e:  # reset/aborted mid-ACK: still a structured
            raise ClusterProtocolError(  # formation error, never a raw
                f"control handshake failed awaiting the HELLO ack: {e}"
            ) from e  # traceback with exit 1
        if frame is None or frame[0] != MSG_HELLO_ACK or len(frame[1]) < 6:
            raise ClusterProtocolError(
                f"malformed HELLO_ACK from root: {frame!r}")
        (root_version, status, nnodes, hb_ms, timeout_ms,
         connect_ms) = frame[1][:6]
        if status == _HELLO_ACK_BAD_VERSION or root_version != self._protocol_version:
            raise ClusterProtocolError(
                f"protocol version mismatch: this worker speaks "
                f"v{self._protocol_version}, root speaks v{root_version} — "
                "every node must run the same build")
        if status == _HELLO_ACK_BAD_RANK:
            raise ClusterProtocolError(
                f"root rejected rank {self.rank}: duplicate or out of "
                f"range for an {nnodes}-node cluster — check --node-rank")
        # adopt the ROOT's timing: detection bounds must agree cluster-wide
        # (a worker with a shorter timeout than the root's ping interval
        # would false-positive on a healthy root), and the ROOT's
        # formation budget governs the protocol-silent window this
        # worker's pre-first-frame grace must tolerate — its own local
        # --connect-timeout may be shorter
        self.nnodes = int(nnodes)
        self.heartbeat_interval = hb_ms / 1e3
        self.worker_timeout = timeout_ms / 1e3
        self.connect_timeout = connect_ms / 1e3
        self._last_seen = _now()
        self._init_stats(connect_retries=self.connect_retries)
        self._acct_send = self._mk_acct(0, "tx")
        self._acct_recv = self._mk_acct(0, "rx")
        t = threading.Thread(target=self._receiver,
                             name="dllama-cluster-recv-root", daemon=True)
        t.start()

    def _receiver(self) -> None:
        saw_frame = False
        while not self._closing:
            # pre-first-frame grace: between this worker's HELLO_ACK and
            # formation completing, the root is silent BY PROTOCOL while
            # later ranks join (bounded by connect_timeout; the root's
            # formation-complete ping ends the window) — a staggered but
            # healthy join must not read as a dead root. A root that
            # actually dies in the window still surfaces EOF-fast.
            budget = self.worker_timeout + (
                0.0 if saw_frame else self.connect_timeout)
            wait = max(0.05, self._last_seen + budget - _now())
            try:
                frame = _recv_frame(self.sock, timeout=wait,
                                    acct=self._acct_recv)
            except socket.timeout:
                self._root_lost("timeout")
                return
            except ConnectionResetError:
                self._root_lost("reset")
                return
            except ClusterProtocolError as e:
                self._root_lost(str(e))
                return
            except OSError:
                if not self._closing:
                    self._root_lost("socket error")
                return
            if frame is None:
                if not (self._closing or self._shutdown_seen):
                    self._root_lost("eof")
                return
            saw_frame = True
            self._last_seen = _now()
            kind = frame[0]
            if self.stats is not None:
                self.stats.frames_received += 1
            if kind == MSG_PING:
                try:
                    # echo the seq + this worker's wall clock (µs): the
                    # root's midpoint estimate of the clock offset is
                    # what MSG_TRACE span rebasing rides
                    pong = [frame[1][0] if frame[1] else 0,
                            int(time.time() * 1e6)]
                    with self._send_lock:
                        _send_frame(self.sock, MSG_PONG, pong,
                                    timeout=self.worker_timeout,
                                    acct=self._acct_send)
                    if self.stats is not None:
                        self.stats.pongs_sent += 1
                except (OSError, ClusterProtocolError) as e:
                    if not self._closing:
                        self._root_lost(f"pong send failed: {e}")
                    return
                continue
            if kind == MSG_SHUTDOWN:
                # the root's LAST frame (broadcast(MSG_SHUTDOWN) closes
                # the root side to new sends): deliver it and stop
                # reading — continuing would race the root's socket
                # teardown (a stray PING in flight, our PONG to a closed
                # peer) into a spurious root-lost diagnostic
                self._shutdown_seen = True
                with self._cond:
                    self._queue.append(frame)
                    self._cond.notify_all()
                return
            with self._cond:
                self._queue.append(frame)
                self._cond.notify_all()

    def _root_lost(self, reason: str) -> None:
        age = _now() - self._last_seen
        exc = ClusterPeerLost(0, age, self.phase, reason)
        first = self._report_lost(exc)
        with self._cond:
            self._cond.notify_all()  # wake any recv() waiter to raise
        if first:
            try:
                self.sock.close()
            except OSError:
                pass

    def recv(self, timeout: float | None = None
             ) -> tuple[int, list[int], bytes]:
        """Block for the next protocol frame. NEVER unbounded: the wait
        wakes on root loss (raising the structured ClusterPeerLost) and,
        when ``timeout`` is given, raises socket.timeout past it."""
        deadline = None if timeout is None else _now() + timeout
        with self._cond:
            while not self._queue:
                self.check()
                if deadline is not None and _now() > deadline:
                    raise socket.timeout(
                        f"no protocol frame within {timeout:.1f}s")
                self._cond.wait(timeout=0.1)
            return self._queue.pop(0)

    def ship_trace(self, events: list[dict]) -> bool:
        """Best-effort worker→root span ship (MSG_TRACE): the events are
        ``Tracer.export_span`` output (wall-stamped — monotonic clocks do
        not transfer between hosts; the root rebases via its clock-offset
        estimate for this peer). Returns False instead of raising on any
        failure: a span that cannot ship is observability loss, never a
        reason to take the worker down — the root's casualty machinery
        covers a worker that dies before shipping."""
        if self.sock is None or self._closing or not events:
            return False
        import json

        try:
            payload = json.dumps({"events": events}).encode()
        except (TypeError, ValueError):
            return False
        try:
            with self._send_lock:
                _send_frame(self.sock, MSG_TRACE, [len(events)], payload,
                            timeout=self.worker_timeout,
                            acct=self._acct_send)
            if self.stats is not None:
                self.stats.frames_sent += 1
            return True
        except (OSError, ClusterProtocolError):
            return False

    def close(self) -> None:
        with self._lock:
            self._closing = True
        with self._cond:
            self._cond.notify_all()
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass


# -- module-level link wiring ---------------------------------------------

_LINK: RootLink | WorkerLink | None = None


def get_link() -> RootLink | WorkerLink | None:
    return _LINK


def set_link(link: RootLink | WorkerLink | None) -> None:
    """Install a link explicitly (the chaos harness and in-process tests
    drive links without init_multihost)."""
    global _LINK
    _LINK = link


def set_phase(phase: str) -> None:
    """Label the cluster phase (rides every ClusterPeerLost diagnostic and
    the /stats cluster block). No-op off-cluster."""
    if _LINK is not None:
        _LINK.set_phase(phase)


def cluster_summary() -> dict | None:
    """The /stats ``cluster`` block (None off-cluster)."""
    return None if _LINK is None else _LINK.summary()


def close_link() -> None:
    global _LINK
    if _LINK is not None:
        _LINK.close()
        _LINK = None


def diagnostic_exit(exc: ClusterPeerLost) -> None:
    """The default peer-lost policy for CLI drivers: print the structured
    diagnostic and hard-exit with EXIT_PEER_LOST. os._exit, not
    sys.exit — the detecting thread is a daemon and the main thread may
    be wedged inside an uninterruptible collective; a soft exit would
    hang exactly the way this subsystem exists to prevent."""
    import json

    # deliberate operator-facing host output, not kernel debug leftovers
    print("🔴 cluster: " + json.dumps(exc.summary()),  # dlgrind: ignore[DLG106]
          flush=True)
    os._exit(EXIT_PEER_LOST)


def install_peer_lost_exit(handler=None) -> None:
    """Arm the peer-lost callback on the live link (default:
    :func:`diagnostic_exit`)."""
    if _LINK is not None:
        _LINK.on_peer_lost = handler or diagnostic_exit


def init_multihost(coordinator: str, num_processes: int, process_id: int, *,
                   connect_timeout: float = 30.0,
                   heartbeat_interval: float = 2.0,
                   worker_timeout: float = 10.0) -> int:
    """Form the control-plane star, then join the jax.distributed cluster;
    returns this process's index.

    Call before any JAX backend use. Every process must pass the same
    coordinator address ("host:port", reachable from all hosts) and the
    cluster size; ranks are 0..num_processes-1 with rank 0 the root. The
    control link forms FIRST: version/rank mismatches and unreachable
    roots surface as immediate structured errors with bounded waits,
    instead of a silent hang inside jax.distributed.initialize — and the
    heartbeat covers the (collective-heavy) init/load phases from the
    moment the handshake completes."""
    global _LINK
    if num_processes > 1:
        host = coordinator.rsplit(":", 1)[0]
        port = control_port(coordinator)
        if process_id == 0:
            link = RootLink(num_processes, "", port,
                            heartbeat_interval=heartbeat_interval,
                            worker_timeout=worker_timeout,
                            connect_timeout=connect_timeout)
        else:
            link = WorkerLink(host, port, process_id, num_processes,
                              heartbeat_interval=heartbeat_interval,
                              worker_timeout=worker_timeout,
                              connect_timeout=connect_timeout)
        link.form()
        # the diagnostic-exit policy arms BEFORE the initialize barrier:
        # a peer that dies while everyone blocks inside
        # jax.distributed.initialize (which waits unboundedly for every
        # join) must still produce the bounded structured exit — a
        # record-only detection would leave this very call hanging
        # forever. Drivers may re-install a richer handler afterwards
        # (the api server's supervisor mapping).
        link.on_peer_lost = diagnostic_exit
        _LINK = link
        set_phase("init")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return jax.process_index()


def is_multihost(mesh) -> bool:
    """Does this mesh span more than one process? (If so, engine outputs
    must be replicated before a host fetch, and host-side drivers must run
    the control-plane protocol.)"""
    if mesh is None:
        return False
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def _bcast(arr: np.ndarray) -> np.ndarray:
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.broadcast_one_to_all(arr))


def _note_bcast(what: str, ms: float, nbytes: int = 0) -> None:
    """Record one startup data-plane broadcast into the cluster ledger
    (the bytes ride XLA collectives the socket ledger cannot see — the
    host-side wall and payload size are what this plane CAN measure) and
    onto the trace timeline when the recorder is on."""
    link = _LINK
    if link is not None and link.stats is not None:
        st = link.stats
        if what == "spec":
            st.bcast_spec_ms = round((st.bcast_spec_ms or 0.0) + ms, 3)
        else:
            st.bcast_tensors_ms = round(
                (st.bcast_tensors_ms or 0.0) + ms, 3)
            st.bcast_tensors_bytes += int(nbytes)
    from ..runtime.trace import TRACER

    if TRACER.enabled:
        TRACER.event("bcast", getattr(link, "trace_tid", 0) or 0,
                     what=what, ms=round(ms, 3), bytes=int(nbytes))


class RunMsg:
    """One decoded protocol message."""

    def __init__(self, kind: int, tokens=None, body: bytes | None = None,
                 ints=None, max_tokens: int = 0, seed: int = 0,
                 temperature: float = 0.0, topp: float = 0.0,
                 reset: bool = False, lookup: int = 0,
                 trace_tid: int = 0):
        self.kind = kind
        self.tokens = tokens
        self.body = body
        self.ints = ints
        self.max_tokens = max_tokens
        self.seed = seed
        self.temperature = temperature
        self.topp = topp
        self.lookup = lookup
        self.reset = reset
        self.trace_tid = trace_tid


def _require_link() -> RootLink | WorkerLink:
    if _LINK is None:
        raise RuntimeError(
            "no cluster control link — init_multihost() was never called "
            "in this process (single-process runs have no protocol)")
    return _LINK


def _send(kind: int, *, int_payload=None, bytes_payload: bytes | None = None,
          max_tokens: int = 0, seed: int = 0, temperature: float = 0.0,
          topp: float = 0.0, reset: bool = False, lookup: int = 0,
          trace_tid: int = 0) -> None:
    assert int_payload is None or bytes_payload is None
    n = (len(int_payload) if int_payload is not None
         else len(bytes_payload) if bytes_payload is not None else 0)
    header = [
        kind, n, int(bytes_payload is not None), max_tokens,
        seed & 0xFFFFFFFF, (seed >> 32) & 0xFFFFFFFF,
        int(np.float32(temperature).view(np.int32)),
        int(np.float32(topp).view(np.int32)),
        int(reset),
        int(lookup),
        int(trace_tid),
    ]
    if int_payload is not None:
        payload = np.asarray(int_payload, "<i8").tobytes()
    elif bytes_payload is not None:
        payload = bytes(bytes_payload)
    else:
        payload = b""
    link = _require_link()
    assert isinstance(link, RootLink), "only rank 0 sends protocol messages"
    link.broadcast(kind, header, payload)


def recv_msg(timeout: float | None = None) -> RunMsg:
    """Worker: block for the next protocol message. The wait is supervised
    (root loss raises a structured ClusterPeerLost within the heartbeat
    bound), never an unbounded socket read."""
    link = _require_link()
    assert isinstance(link, WorkerLink), "only workers receive messages"
    kind, h, payload = link.recv(timeout=timeout)
    if len(h) < _HEADER_LEN:
        raise ClusterProtocolError(
            f"short protocol header: {len(h)} ints (kind={kind})")
    n, is_bytes = int(h[1]), int(h[2])
    msg = RunMsg(
        kind,
        max_tokens=int(h[3]),
        seed=int(h[4]) | (int(h[5]) << 32),
        temperature=float(np.int32(h[6]).view(np.float32)),
        topp=float(np.int32(h[7]).view(np.float32)),
        reset=bool(h[8]),
        lookup=int(h[9]),
        trace_tid=int(h[10]),
    )
    if n:
        if is_bytes:
            msg.body = payload
        else:
            msg.ints = [int(v) for v in np.frombuffer(payload, "<i8")]
            if kind == MSG_RUN:
                msg.tokens = msg.ints
    return msg


# -- root-side senders -----------------------------------------------------

def send_run(tokens: list[int], max_tokens: int, seed: int,
             temperature: float, topp: float, reset: bool = False,
             lookup: int = 0, trace_tid: int = 0) -> None:
    """Root: announce one generate() run. seed carries the root sampler's
    CURRENT rng state, so workers reproduce the token stream even when
    their own sampler flags differ. lookup > 0 = the run speculates with
    that draft length: drafts are mined from the (replicated) token
    stream, so every process mines the SAME drafts and the verify-forward
    shapes stay in lock-step across the cluster. trace_tid links the
    workers' span events to the root's timeline (0 = untraced)."""
    _send(MSG_RUN, int_payload=tokens, max_tokens=max_tokens, seed=seed,
          temperature=temperature, topp=topp, reset=reset, lookup=lookup,
          trace_tid=trace_tid)


def send_api(body_json: bytes) -> None:
    """Root: announce one API request; workers replay the identical
    completion loop from the raw request body."""
    _send(MSG_API, bytes_payload=body_json)


def send_xfer_bench(n_prompt: int) -> None:
    """Root: announce the benchmark's collective-microbench sequence.
    ``n_prompt`` rides the header so every worker runs the IDENTICAL
    measure_transfer_ms() + measure_prefill_transfer_ms(n_prompt)
    calls (which execute real collectives over the global mesh, including
    the pp ppermute rotation) — the root running a measure the workers
    skip deadlocks the whole cluster (ADVICE r5 high)."""
    _send(MSG_XFER_BENCH, max_tokens=int(n_prompt))


def send_shutdown() -> None:
    _send(MSG_SHUTDOWN)


# -- startup handshake -----------------------------------------------------

def check_config(fingerprint: list[int]) -> None:
    """Verify every process launched with the same mesh/dtype/sampler config
    (the reference ships its spec as a raw struct memcpy and is silently
    ABI-fragile — ref: src/transformer.cpp:633). All-gathered so EVERY rank
    sees every other rank's fingerprint: a mismatch errors symmetrically and
    immediately on all processes, instead of one side exiting while the
    other hangs in its next collective."""
    from jax.experimental import multihost_utils

    mine = np.asarray(fingerprint, np.int64)
    allfp = np.asarray(multihost_utils.process_allgather(mine))
    bad = [r for r in range(allfp.shape[0]) if list(allfp[r]) != list(allfp[0])]
    if bad:
        raise SystemExit(
            f"cluster config mismatch: rank 0 has {list(allfp[0])}, "
            f"rank(s) {bad} differ (mine: {list(mine)}) — every process "
            "must use the same MODEL (.m) and TOKENIZER (.t) files and the "
            "same --tp/--dp/--sp/--ep/--pp, dtype, seq-len, pallas and "
            "sampler flags")


def bcast_spec(spec, model_fp: int = 0, push: bool = False):
    """Root-push phase 0: rank 0 broadcasts the model spec, weight-content
    fingerprint, and its --push-weights flag so FILE-LESS workers can
    participate in the config check and build their engine without ever
    reading a `.m`. Non-root callers pass spec=None; returns
    (spec, model_fp, push) on every rank.

    Runs UNCONDITIONALLY on every multihost startup (build_engine), not
    only in push mode: the collective sequence must be identical across
    processes regardless of per-process flags, or a --push-weights
    mismatch would deadlock in mismatched collectives BEFORE check_config
    could report it. With the sequence fixed, the flag rides here and the
    fingerprint check turns a mismatch into a symmetric error. Matches the
    reference root shipping its TransformerSpec struct ahead of the weight
    push (ref: src/transformer.cpp:633-644) — explicit fields, not a raw
    memcpy."""
    from ..models.spec import ArchType, HiddenAct, ModelSpec
    from ..quants.types import FloatType

    if spec is not None:
        fields = [int(spec.arch), spec.dim, spec.hidden_dim, spec.n_layers,
                  spec.n_heads, spec.n_kv_heads, spec.vocab_size,
                  spec.seq_len, int(spec.hidden_act),
                  int(np.float32(spec.rope_theta).view(np.int32)),
                  spec.n_experts, spec.n_active_experts,
                  int(spec.weights_float_type), spec.version,
                  model_fp & 0xFFFFFFFF, int(push)]
    else:
        fields = [0] * 16
    t0 = time.perf_counter()
    f = _bcast(np.asarray(fields, np.int64))
    _note_bcast("spec", (time.perf_counter() - t0) * 1e3)
    out = ModelSpec(
        arch=ArchType(int(f[0])), dim=int(f[1]), hidden_dim=int(f[2]),
        n_layers=int(f[3]), n_heads=int(f[4]), n_kv_heads=int(f[5]),
        vocab_size=int(f[6]), seq_len=int(f[7]),
        hidden_act=HiddenAct(int(f[8])),
        rope_theta=float(np.int32(f[9]).view(np.float32)),
        n_experts=int(f[10]), n_active_experts=int(f[11]),
        weights_float_type=FloatType(int(f[12])), version=int(f[13]))
    return out, int(f[14]), bool(f[15])


def bcast_model_tensors(spec, path: str | None):
    """Root-push phase 1: a HostTensor generator on EVERY rank. Rank 0
    streams its `.m` file tensor-by-tensor and broadcasts each tensor's
    raw file bytes; other ranks receive and decode the identical bytes —
    so a worker needs NO local model file (the reference's root pushes
    every worker its slice over TCP the same way,
    ref: src/transformer.cpp:562-591,685-720). One tensor is resident at a
    time on each host (the streamed-loader memory contract holds); feed
    this to models.loader.load_params_streamed(tensors=...), which places
    only this host's shards and drops the rest."""
    from ..io.model_file import (_tensor_bytes, model_tensor_plan, read_spec,
                                 tensor_from_bytes)

    root = jax.process_index() == 0
    f = None
    if root:
        assert path is not None, "--push-weights root needs the model file"
        header_size = getattr(spec, "_header_size", None)
        if header_size is None:
            header_size = getattr(
                read_spec(path, spec.weights_float_type), "_header_size")
        f = open(path, "rb")
        f.seek(header_size)
    total_ms = 0.0
    total_bytes = 0
    try:
        for name, shape, ftype in model_tensor_plan(spec):
            nbytes = _tensor_bytes(shape, ftype)
            if root:
                raw = np.frombuffer(f.read(nbytes), np.uint8)
                if raw.size != nbytes:
                    raise EOFError(f"model file truncated at {name}")
            else:
                raw = np.zeros(nbytes, np.uint8)
            t0 = time.perf_counter()
            raw = _bcast(raw)
            total_ms += (time.perf_counter() - t0) * 1e3
            total_bytes += nbytes
            yield tensor_from_bytes(name, shape, ftype, raw.tobytes())
    finally:
        if f is not None:
            f.close()
        # one ledger note for the whole stream (per-tensor events would
        # be hundreds of lines for one number an operator wants)
        if total_bytes:
            _note_bcast("tensors", total_ms, total_bytes)


def broadcast_seed(seed: int) -> int:
    """Agree on one base sampler seed cluster-wide (the CLI default is
    time-based, which would diverge per host)."""
    if jax.process_index() == 0:
        _send(MSG_SEED, seed=seed)
        return seed
    msg = recv_msg()
    if msg.kind == MSG_SHUTDOWN:
        raise SystemExit("root shut down during startup")
    if msg.kind != MSG_SEED:
        raise SystemExit(f"protocol error: expected seed, got kind={msg.kind}")
    return msg.seed
