"""Partition specs reproducing the reference's tensor-parallel decomposition.

The reference splits weights two ways (ref: src/transformer.cpp:14-76):

  RowMatmulSlice — output-dim split: wq, wk, wv, w1, w3, MoE up/gate/down
  ColMatmulSlice — input-dim split (partial sums reduced at root): wo, w2

Here the same decomposition is a PartitionSpec per tensor; GSPMD turns the
col-split contractions into psum/reduce-scatter over ICI — the reference's
gather+sum-at-root (ref: src/tasks.cpp:67-90, llama2-tasks.cpp:125-131)
with the star topology replaced by all-reduce.

Unsliced tensors (embeddings, norms, router — the reference's root-only set,
ref: src/transformer.cpp:639-673) are replicated. wcls is vocab-sharded (an
improvement: the reference computes all logits on root).

The reference's `nSlices <= nKvHeads` constraint (ref:
src/transformer.cpp:254-257) becomes `n_kv_heads % tp == 0` here; KV-cache
heads shard on tp exactly like KvCacheSlice (ref: src/transformer.cpp:161-171).
Unlike the reference, tp may also EXCEED the kv-head count: the engine then
replicates wk/wv (and the cache) into tp virtual heads
(models/params.kv_replication) and these specs apply unchanged — the relaxed
form of the rule the reference could not support (SURVEY.md §7 step 4).
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.spec import ModelSpec
from ..quants.jax_codec import QuantizedTensor
from .mesh import DP_AXIS, TP_AXIS

# per-param logical split: 'row' = shard output dim, 'col' = shard input dim,
# None = replicate. Axis positions account for leading stacking dims (the
# per-expert E axis on MoE weights; layers are a pytree list, not an axis).
_SPLIT = {
    "tok_emb": None,
    "rms_att": None,
    "rms_ffn": None,
    "rms_moe": None,
    "rms_ffn2": None,
    "rms_final": None,
    "moe_router": None,
    "wq": "row",
    "wk": "row",
    "wv": "row",
    "wqkv": "row",  # fused single-shard variants (models/params.py)
    "w1": "row",
    "w3": "row",
    "w13": "row",
    "moe_up": "row",
    "moe_gate": "row",
    "moe_down": "col",
    "wo": "col",
    "w2": "col",
    "wcls": "row",  # vocab-sharded logits (net-new vs reference root-only wcls)
}


def _pspec_for(name: str, ndim: int, quantized: bool, which: str,
               vocab_axes: tuple | None = None) -> P:
    """PartitionSpec for one array leaf.

    Dense weights are (lead..., d, n). Q40 leaves are packed (lead..., d, m)
    — flattened nibble-position-major, m = 16*nb — and scales (lead..., d, nb).
    Row split shards the d axis for all three forms. Col split shards the
    last axis; for the packed form a contiguous m shard is a nibble-position
    stripe rather than a block stripe, which GSPMD handles transparently
    (the dequant reshape introduces a resharding); the shard_map TP path
    slices at the logical-tensor level instead and stays block-aligned.
    """
    split = _SPLIT[name]
    axes: list = [None] * ndim
    if name in ("tok_emb", "wcls") and vocab_axes is not None:
        # vocab sharding (ops/sharded_vocab.py): the embedding table
        # row-splits its vocab dim — under pp over BOTH (pp, tp), since
        # the gather/head run outside the manual region and every stage
        # would otherwise hold a full copy. wcls keeps its row split but
        # widens to the same axes.
        if name == "tok_emb" or split == "row":
            axes[ndim - 2] = vocab_axes
            return P(*axes)
    if split is None:
        return P(*axes)
    axes[ndim - 2 if split == "row" else ndim - 1] = TP_AXIS
    return P(*axes)


def _leaf_spec(name: str, w, vocab_axes: tuple | None = None):
    from .ep_moe import EpColWeight, EpRowWeight, ep_pspec
    from .mesh import PP_AXIS
    from .pp import PpWeight
    from .tp_q80 import TpColWeight, TpRowWeight, tp_col_pspec, tp_row_pspec

    if isinstance(w, PpWeight):
        # pipeline mode: stage axis on pp, the weight's usual tp split (or
        # its Tp wrapper's stack layout) on the remaining dims — the ONE
        # spec source shared with the manual region's in_specs, so entering
        # the region moves no bytes (parallel/pp.py)
        from .pp import _leaf_in_spec

        return _leaf_in_spec(name, w, TP_AXIS)
    if isinstance(w, (EpRowWeight, EpColWeight)):
        # expert-parallel mode: expert axis on ep (parallel/ep_moe.py)
        return ep_pspec(w)
    if isinstance(w, TpColWeight):
        # q80-collective mode: col weights are pre-stacked (tp, ..., d, n/tp)
        return tp_col_pspec(w)
    if isinstance(w, TpRowWeight):
        # shard_map-kernel mode: output rows on tp, matching the in_specs of
        # tp_row_matmul so entering the shard_map moves no bytes
        return tp_row_pspec(w)
    if isinstance(w, QuantizedTensor):
        return QuantizedTensor(  # pytree-shaped specs
            _pspec_for(name, w.packed.ndim, True, "packed", vocab_axes),
            _pspec_for(name, w.scales.ndim, True, "scales", vocab_axes),
        )
    return _pspec_for(name, w.ndim, False, "dense", vocab_axes)


def param_pspecs(params: dict, vocab_axes: tuple | None = None) -> dict:
    """Pytree of PartitionSpecs matching the params pytree
    ({"tok_emb", "rms_final", "wcls", "layers": [{...}, ...]}).
    vocab_axes: mesh axes row-splitting the vocab dim of tok_emb/wcls
    (ops/sharded_vocab.vocab_shard_axes; None keeps them replicated/
    tp-split as before)."""
    out = {}
    for name, w in params.items():
        if name == "layers":
            out[name] = [{k: _leaf_spec(k, v) for k, v in lw.items()} for lw in w]
        else:
            out[name] = _leaf_spec(name, w, vocab_axes)
    return out


def cache_pspec(sp: bool = False, pp: bool = False) -> P:
    """Per-layer KV cache leaf (B, KVH, S, hs): batch on dp, kv-heads on tp
    (ref: KvCacheSlice, src/transformer.cpp:161-171). With sp=True the
    sequence dim also shards over sp — per-device cache memory becomes
    seq_len/sp, the long-context scaling axis the reference lacks
    (SURVEY.md §5.7); decode then attends via sp_cache_attention. With
    pp=True the leaf is stage-stacked (pp, B, KVH, S, hs) and the stage
    axis shards over pp — each device holds only its layers' cache
    (parallel/pp.py)."""
    from .mesh import PP_AXIS, SP_AXIS

    spec = (DP_AXIS, TP_AXIS, SP_AXIS if sp else None, None)
    return P(PP_AXIS, *spec) if pp else P(*spec)


def check_tp_constraints(spec: ModelSpec, tp: int, q40: bool = False) -> None:
    """Divisibility rules; the reference asserts the same invariants
    (ref: src/transformer.cpp:15,49,254-257,78-96). The engine calls this
    with its COMPUTE spec: when tp > the file's n_kv_heads it has already
    replicated kv heads to tp virtual heads (models/params.kv_replication),
    so the reference's nSlices <= nKvHeads bound is relaxed upstream."""
    if tp == 1:
        return
    assert spec.n_kv_heads % tp == 0, (
        f"tp={tp} must divide n_kv_heads={spec.n_kv_heads} "
        "(reference constraint nSlices <= nKvHeads, transformer.cpp:254-257; "
        "for tp > n_kv_heads the engine replicates kv heads first)")
    assert spec.n_heads % tp == 0
    assert spec.hidden_dim % tp == 0 and spec.dim % tp == 0
    if q40:
        # col-split shards must keep whole 32-value blocks
        assert spec.hidden_dim % (32 * tp) == 0
        assert spec.dim % (32 * tp) == 0


COL_SPLIT_NAMES = tuple(k for k, v in _SPLIT.items() if v == "col")


def repack_col_weights(params: dict, tp: int) -> dict:
    """Repack every col-split weight into the TpColWeight stacked form used
    by the q80-collective shard_map path (parallel/tp_q80.py). Non-mutating
    (callers may keep using the original pytree, e.g. to compare modes).

    Note: on device-resident weights this transiently duplicates each col
    weight on the default device before shard_params distributes it; the
    streamed loader (models/loader.py) repacks host-side per tensor and
    places shards directly, avoiding the spike — prefer it at 70B scale."""
    from .tp_q80 import TpColWeight, repack_col_tp

    def repack(v):
        from .ep_moe import EpColWeight
        from .pp import PpWeight

        # already repacked (streamed loader) or owned by the ep path; a
        # PpWeight is the streamed loader's stage stack, whose q40 col
        # leaves it repacked at build time (models/loader._PpStacker)
        if isinstance(v, (TpColWeight, EpColWeight, PpWeight)):
            return v
        return repack_col_tp(v, tp)

    out = dict(params)
    out["layers"] = [
        {k: (repack(v) if k in COL_SPLIT_NAMES else v) for k, v in lw.items()}
        for lw in params["layers"]
    ]
    return out


def wrap_row_weights(params: dict) -> dict:
    """Mark every remaining Q40 matmul weight as TpRowWeight so matmul()
    routes it through the shard_map Pallas path (parallel/tp_q80.py). Run
    AFTER repack_col_weights when tp > 1 — col-split weights must already be
    TpColWeight stacks; with tp == 1 (dp-only meshes) col weights are
    unsplit and row-wrapping them is correct (marker only, no sharding)."""
    from .tp_q80 import TpRowWeight

    def wrap(name, v):
        if (name in _SPLIT and _SPLIT[name] is not None
                and isinstance(v, QuantizedTensor)):
            return TpRowWeight(v)
        return v

    out = dict(params)
    out["layers"] = [
        {k: wrap(k, v) for k, v in lw.items()} for lw in params["layers"]
    ]
    if isinstance(out.get("wcls"), QuantizedTensor):
        out["wcls"] = TpRowWeight(out["wcls"])
    return out


def shard_params(params: dict, mesh, vocab_axes: tuple | None = None) -> dict:
    """device_put every leaf with its NamedSharding (sharded weight placement —
    the analogue of the reference's per-worker weight push at load,
    ref: src/transformer.cpp:562-591)."""
    specs = param_pspecs(params, vocab_axes)

    def put(w, s):
        return jax.device_put(w, NamedSharding(mesh, s))

    def put_entry(w, sp):
        from .wrappers import WeightWrapper

        if isinstance(w, WeightWrapper):
            return type(w)(put_entry(w.w, sp.w))
        if isinstance(w, QuantizedTensor):
            return QuantizedTensor(put(w.packed, sp.packed), put(w.scales, sp.scales))
        return put(w, sp)

    out = {}
    for name, w in params.items():
        if name == "layers":
            out[name] = [
                {k: put_entry(v, specs[name][i][k]) for k, v in lw.items()}
                for i, lw in enumerate(w)
            ]
        else:
            out[name] = put_entry(w, specs[name])
    return out
