"""Ring attention: sequence/context-parallel causal attention over the sp axis.

Net-new relative to the reference, which has no sequence parallelism at all
(SURVEY.md §2.5: seqLen capped by one node's KV memory, serial O(pos) loop —
ref: src/llama2-tasks.cpp:54-94). Here the sequence is sharded over the mesh's
`sp` axis: each device holds one contiguous Q/K/V chunk, K/V blocks rotate
around the ring via `ppermute` (ICI neighbor exchange), and each device
accumulates its chunk's attention with numerically stable online-softmax
merging — the blockwise/flash decomposition, so no device ever materializes
the full (T, T) score matrix or the full K/V.

Wall-clock per layer: sp steps of (local block attention + neighbor ppermute),
with the K/V transfer overlapping compute when XLA schedules it; KV memory per
device is seq_len/sp — the sequence-length scaling axis the reference lacked.

Layout convention matches ops/attention.py: q/k/v are (B, T, H, hs) with GQA
via n_kv_heads <= n_heads; causal masking uses absolute positions.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .mesh import SP_AXIS


def _block_attn(q, k, v, q_pos, k_pos, scale):
    """One (Tq x Tk) causal block: returns (acc, m, l) flash-style stats.

    q: (B, Tq, H, hs); k/v: (B, Tk, KVH, hs); positions absolute.
    acc: (B, Tq, H, hs) unnormalized sum of softmax-weighted V;
    m: (B, Tq, H) running max; l: (B, Tq, H) running normalizer.
    """
    b, tq, h, hs = q.shape
    kvh = k.shape[2]
    group = h // kvh

    qf = q.astype(jnp.float32).reshape(b, tq, kvh, group, hs)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    scores = jnp.einsum("bqkgd,bskd->bqkgs", qf, kf) * scale  # s = Tk
    mask = q_pos[:, :, None] >= k_pos[:, None, :]             # (B, Tq, Tk)
    scores = jnp.where(mask[:, :, None, None, :], scores, -jnp.inf)

    m = jnp.max(scores, axis=-1)                              # (B, Tq, KVH, G)
    # fully masked rows (no visible keys in this block) contribute nothing
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(scores - m_safe[..., None])
    p = jnp.where(mask[:, :, None, None, :], p, 0.0)
    l = jnp.sum(p, axis=-1)                                   # (B, Tq, KVH, G)
    acc = jnp.einsum("bqkgs,bskd->bqkgd", p, vf)              # (B, Tq, KVH, G, hs)

    m = jnp.where(jnp.isfinite(m), m, -jnp.inf)
    return (acc.reshape(b, tq, h, hs), m.reshape(b, tq, h), l.reshape(b, tq, h))


def _merge(acc1, m1, l1, acc2, m2, l2):
    """Merge two flash-stat triples (online softmax combination)."""
    m = jnp.maximum(m1, m2)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    a1 = jnp.where(jnp.isfinite(m1), jnp.exp(m1 - m_safe), 0.0)
    a2 = jnp.where(jnp.isfinite(m2), jnp.exp(m2 - m_safe), 0.0)
    acc = acc1 * a1[..., None] + acc2 * a2[..., None]
    l = l1 * a1 + l2 * a2
    return acc, m, l


def ring_attention_local(q, k, v, chunk_pos0, axis_name: str = SP_AXIS):
    """Per-shard body: causal attention of the local Q chunk against the full
    (ring-distributed) K/V. Call under shard_map with q/k/v sharded on the
    sequence axis over `axis_name`.

    q, k, v: (B, T_local, H|KVH, hs) — this device's chunk.
    chunk_pos0: scalar int32 — absolute position of this chunk's first token
      (normally sp_index * T_local; passed in so prefill offsets compose).
    Returns (B, T_local, H, hs) attention output for the local chunk.
    """
    from .compat import axis_size

    n = axis_size(axis_name)  # static at trace time
    idx = lax.axis_index(axis_name)
    b, t, h, hs = q.shape
    scale = 1.0 / (hs ** 0.5)

    q_pos = chunk_pos0 + jnp.arange(t, dtype=jnp.int32)[None, :]
    q_pos = jnp.broadcast_to(q_pos, (b, t))

    acc = jnp.zeros((b, t, h, hs), jnp.float32)
    m = jnp.full((b, t, h), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, t, h), jnp.float32)
    # k/v rotate in their input dtype (bf16 halves ppermute bytes); _block_attn
    # casts to f32 per block
    k_cur, v_cur = k, v
    perm = [(i, (i + 1) % n) for i in range(n)]

    # k/v blocks travel the ring: at step s this device holds the chunk that
    # started on device (idx - s) mod n, whose first absolute position is
    # derived from its origin index. Unrolled (n is the static sp size) so the
    # final rotate can be skipped and XLA can overlap transfer with compute.
    for s in range(n):
        src = (idx - s) % n
        k_pos0 = (chunk_pos0 - idx * t) + src * t  # origin chunk's first pos
        k_pos = k_pos0 + jnp.arange(t, dtype=jnp.int32)[None, :]
        k_pos = jnp.broadcast_to(k_pos, (b, t))

        if s + 1 < n:  # start the next rotation before consuming this block
            k_nxt = lax.ppermute(k_cur, axis_name, perm)
            v_nxt = lax.ppermute(v_cur, axis_name, perm)

        acc2, m2, l2 = _block_attn(q, k_cur, v_cur, q_pos, k_pos, scale)
        acc, m, l = _merge(acc, m, l, acc2, m2, l2)
        if s + 1 < n:
            k_cur, v_cur = k_nxt, v_nxt

    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def sp_cache_attention(q, k_cache, v_cache, q_pos, mesh, axis_name: str = SP_AXIS):
    """Decode/continuation attention over an sp-sharded KV cache.

    The counterpart of ring_attention for steps AFTER the sequence-parallel
    prefill: the cache's sequence dim is sharded over sp (cache_pspec(sp=True))
    while the new queries are replicated over sp, so each device computes
    flash stats (acc, m, l) of the full query block against its local cache
    chunk and the stats merge exactly with a pmax/psum online-softmax
    combination — no device ever materializes the full-sequence cache.

    q: (B, T, H, hs); k_cache/v_cache: (B, KVH, S, hs) with S sharded over sp;
    q_pos: (B, T) absolute positions (cache slots > q_pos are masked, so
    not-yet-written positions never contribute). Returns (B, T, H, hs).
    """
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    from .mesh import DP_AXIS, TP_AXIS

    n = mesh.shape[axis_name]
    assert k_cache.shape[2] % n == 0, (k_cache.shape, n)
    tp = TP_AXIS if TP_AXIS in mesh.axis_names else None

    q_spec = P(DP_AXIS, None, tp, None)
    cache_spec = P(DP_AXIS, tp, axis_name, None)
    pos_spec = P(DP_AXIS, None)

    def body(q_l, k_l, v_l, qp_l):
        return sp_cache_attention_local(q_l, k_l, v_l, qp_l,
                                        axis_name=axis_name)

    fn = shard_map(body, mesh=mesh,
                   in_specs=(q_spec, cache_spec, cache_spec, pos_spec),
                   out_specs=q_spec, check_vma=False)
    return fn(q, k_cache, v_cache, q_pos)


def sp_cache_attention_local(q_l, k_l, v_l, qp_l, axis_name: str = SP_AXIS):
    """The per-shard body of sp_cache_attention (local shapes: the cache's
    sequence dim is this device's S/sp chunk, queries replicated): local
    flash stats + the exact pmax/psum online-softmax merge. Called from
    sp_cache_attention's shard_map AND directly inside the fully-manual pp
    region (parallel/pp.py — shard_map cannot nest, so sp under pp runs
    manually exactly like tp and ep do)."""
    s_local = k_l.shape[2]
    hs = q_l.shape[-1]
    scale = 1.0 / (hs ** 0.5)
    idx = lax.axis_index(axis_name)
    bl = q_l.shape[0]
    k_pos = idx * s_local + jnp.arange(s_local, dtype=jnp.int32)[None, :]
    k_pos = jnp.broadcast_to(k_pos, (bl, s_local))
    kt = k_l.transpose(0, 2, 1, 3)  # (B, S_l, KVH, hs) — _block_attn layout
    vt = v_l.transpose(0, 2, 1, 3)
    acc, m, l = _block_attn(q_l, kt, vt, qp_l, k_pos, scale)
    # exact online-softmax merge across the sp chunks
    m_max = lax.pmax(m, axis_name)
    m_safe = jnp.where(jnp.isfinite(m_max), m_max, 0.0)
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    num = lax.psum(acc * alpha[..., None], axis_name)
    den = lax.psum(l * alpha, axis_name)
    return (num / jnp.maximum(den, 1e-30)[..., None]).astype(q_l.dtype)


def ring_attention(q, k, v, mesh, pos0: int = 0, axis_name: str = SP_AXIS):
    """Sequence-parallel causal attention over a mesh's sp axis.

    q, k, v: (B, T, H|KVH, hs) global arrays; T must divide by mesh sp size.
    Returns (B, T, H, hs). Entry point for tests and the sp-prefill path;
    sharding: sequence axis over sp, everything else replicated.
    """
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    from .mesh import TP_AXIS

    n = mesh.shape[axis_name]
    t = q.shape[1]
    assert t % n == 0, (t, n)
    t_local = t // n

    # heads stay tp-sharded through the ring (wq/wk/wv are row-split on tp —
    # parallel/sharding.py), so attention keeps its tensor parallelism; the
    # GQA group math is unaffected because h and kvh shard identically
    tp = TP_AXIS if TP_AXIS in mesh.axis_names else None
    spec = P(None, axis_name, tp, None)

    def body(q_l, k_l, v_l):
        idx = lax.axis_index(axis_name)
        chunk_pos0 = pos0 + idx * t_local
        return ring_attention_local(q_l, k_l, v_l, chunk_pos0, axis_name)

    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)
