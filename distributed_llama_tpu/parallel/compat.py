"""JAX version compatibility for shard_map.

`shard_map` graduated from `jax.experimental.shard_map` to the `jax`
top level, and its replication-check kwarg was renamed `check_rep` ->
`check_vma` in the move. The package targets the new spelling; this shim
keeps the explicit-sharding layer importable on the older jaxlib the CPU
CI / test image pins (0.4.x), where the top-level import does not exist.

Usage: `from ..parallel.compat import shard_map` and call with the NEW
kwarg name (`check_vma=`); the shim translates for old versions.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map  # jax >= 0.6
    _NEEDS_RENAME = False
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map
    _NEEDS_RENAME = True


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool | None = None,
              **kwargs):
    if check_vma is not None:
        kwargs["check_rep" if _NEEDS_RENAME else "check_vma"] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)


def axis_size(axis_name: str) -> int:
    """Static mesh-axis size from inside a manual region. `lax.axis_size`
    arrived after 0.4.x; there, `psum(1, axis)` of the Python literal
    constant-folds to the same static int."""
    import jax

    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)
