"""Two-process control-plane chaos harness.

Drives the multihost control star (parallel/multihost.py RootLink /
WorkerLink) WITHOUT a model, mesh, or jax.distributed cluster — pure
host-side protocol — so the chaos tests (tests/test_cluster_chaos.py) and
the bench cluster row (bench.py BENCH_CHAOS) can kill, stall, or corrupt
either side of a real two-OS-process cluster and assert bounded detection
in the NON-SLOW tier (no compiles, no fixtures; subprocess startup is the
only cost).

Every observable is one JSON line on stdout:

  {"event": "formed", ...}            link up (worker reports its backoff
                                      retry count)
  {"event": "tick", "phase": ...}     worker received a phase-tick frame
  {"event": "dying", "t_wall": ...}   worker about to os._exit(9)
                                      (--die-after; the SIGKILL shape)
  {"event": "cluster_peer_lost", ...} bounded detection fired
                                      (ClusterPeerLost.summary() +
                                      "t_wall") — process exits
                                      EXIT_PEER_LOST (43)
  {"event": "formation_failed", ...}  handshake/connect failure — exits
                                      EXIT_FORMATION (44)
  {"event": "complete" | "shutdown"}  clean end (root | worker), exit 0

Faults are armed via DLLAMA_FAULTS in the child's environment (the
registry loads it at import — runtime/faults.py): e.g.
``recv_stall:after=2;times=0`` wedges a worker's receiver so it stops
answering heartbeats, ``conn_refused:times=2`` fails the first two connect
attempts to exercise the formation backoff.

``--trace`` arms the flight recorder on either side (dlwire): the root
mints ONE trace id for the session and rides it in every phase frame's
header; the worker records a ``cluster_tick`` span event per frame and
ships the new events root-ward in ``MSG_TRACE`` frames, which the root
rebases (clock-offset estimate) onto its own timeline. The root then
emits a ``trace_dump`` JSON line — its merged ring, wall-stamped — on
completion AND on a peer loss (the casualty path: the dump carries the
root-side ``cluster_lost`` event linked under the same id, exactly what
``/admin/trace?id=`` would serve on an api root). The ``complete`` /
``shutdown`` stats now carry the measured wire ledger (bytes + frames
per peer/kind/direction, heartbeat RTT, clock offset).

Usage:
  python -m distributed_llama_tpu.parallel.cluster_harness root \
      --port 19000 --nnodes 2 --heartbeat-interval 0.2 --worker-timeout 1.5 \
      --phases formation:0.2,prefill:8
  python -m distributed_llama_tpu.parallel.cluster_harness worker \
      --host 127.0.0.1 --port 19000 --rank 1 --nnodes 2 [--die-after 0.8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

from . import multihost as mh


def _emit(event: str, **fields) -> None:
    # the harness's whole OUTPUT is these JSON lines — host CLI, not
    # kernel debug leftovers
    print(json.dumps({"event": event, "t_wall": time.time(), **fields}),  # dlgrind: ignore[DLG106]
          flush=True)


def _emit_trace_dump(tid: int) -> None:
    """Dump the tracer's ring, wall-stamped, as one JSON line — the
    harness's stand-in for GET /admin/trace (same event shape, same
    anchor): cross-node linkage asserts read this from stdout, which
    survives the os._exit a peer loss takes."""
    from ..runtime.trace import TRACER

    if not TRACER.enabled:
        return
    events = [{**e, "ts_wall": TRACER.to_wall(e["ts"])}
              for e in TRACER.recent(0)]
    _emit("trace_dump", tid=tid, anchor_wall=TRACER.anchor_wall,
          events=events)


_TRACE_TID = [0]  # the session's minted id, readable from the lost path


def _exit_on_peer_lost(exc: mh.ClusterPeerLost) -> None:
    _emit(**exc.summary())
    # the casualty event (multihost._report_lost) is already in the ring
    _emit_trace_dump(_TRACE_TID[0])
    os._exit(mh.EXIT_PEER_LOST)


def _parse_phases(spec: str) -> list[tuple[str, float]]:
    out = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        name, _, secs = part.partition(":")
        out.append((name, float(secs or 1.0)))
    return out


def run_root(args) -> int:
    from ..runtime.trace import TRACER

    link = mh.RootLink(args.nnodes, "", args.port,
                       heartbeat_interval=args.heartbeat_interval,
                       worker_timeout=args.worker_timeout,
                       connect_timeout=args.connect_timeout)
    tid = 0
    if args.trace:
        # arm BEFORE form() so the casualty path can always link, then
        # mint ONE id for the whole session — every phase frame carries
        # it, so root ticks, worker ticks (shipped back via MSG_TRACE),
        # and a peer-loss casualty all land under one span
        TRACER.configure(enabled=True)
        tid = TRACER.new_id()
        _TRACE_TID[0] = tid
        link.trace_tid = tid
    try:
        link.form()
    except mh.ClusterProtocolError as e:
        _emit("formation_failed", error=str(e))
        return mh.EXIT_FORMATION
    mh.set_link(link)
    link.on_peer_lost = _exit_on_peer_lost
    if tid:
        TRACER.event("handshake", tid, role="root",
                     peers=sorted(link.peers))
    _emit("formed", role="root", peers=sorted(link.peers))
    for name, secs in _parse_phases(args.phases):
        link.set_phase(name)
        # a real protocol frame per phase so the broadcast path (and its
        # lost-peer raise) is exercised, not just the heartbeat — the
        # payload carries the phase name so the worker's diagnostics
        # agree with the root's, and the header carries the trace id
        mh._send(mh.MSG_RUN, bytes_payload=name.encode(), trace_tid=tid)
        if tid:
            TRACER.event("cluster_tick", tid, phase=name, role="root",
                         rank=0)
        time.sleep(secs)
    mh.send_shutdown()
    _emit("complete", stats=link.summary(), tid=tid)
    _emit_trace_dump(tid)
    link.close()
    return 0


def run_worker(args) -> int:
    from ..runtime.trace import TRACER

    link = mh.WorkerLink(args.host, args.port, args.rank, args.nnodes,
                         heartbeat_interval=args.heartbeat_interval,
                         worker_timeout=args.worker_timeout,
                         connect_timeout=args.connect_timeout,
                         protocol_version=args.protocol_version)
    if args.trace:
        TRACER.configure(enabled=True)
    try:
        link.form()
    except mh.ClusterProtocolError as e:
        _emit("formation_failed", error=str(e))
        return mh.EXIT_FORMATION
    mh.set_link(link)
    link.on_peer_lost = _exit_on_peer_lost
    _emit("formed", role="worker", rank=args.rank,
          retries=link.connect_retries,
          heartbeat_interval=link.heartbeat_interval,
          worker_timeout=link.worker_timeout)
    if args.die_after is not None:
        def die():
            time.sleep(args.die_after)
            _emit("dying")
            os._exit(9)  # abrupt, like a SIGKILL/OOM — no FIN handshake code
        threading.Thread(target=die, daemon=True).start()
    shipped = 0  # span events already shipped root-ward (delta ships —
    #              re-sending the whole span would duplicate on ingest)
    while True:
        msg = mh.recv_msg()
        if msg.kind == mh.MSG_SHUTDOWN:
            _emit("shutdown", stats=link.summary())
            link.close()
            return 0
        if msg.kind == mh.MSG_RUN:
            phase = (msg.body or b"?").decode()
            link.set_phase(phase)
            tid = msg.trace_tid
            if TRACER.enabled and tid:
                TRACER.reserve(tid)  # root-minted id: keep local mints
                #                      disjoint (Tracer.reserve)
                link.trace_tid = tid
                _TRACE_TID[0] = tid
                TRACER.event("cluster_tick", tid, phase=phase,
                             role="worker", rank=args.rank)
                # ship per tick, not at shutdown: the root stops reading
                # after its SHUTDOWN broadcast, and a worker that DIES
                # mid-session has at least its earlier ticks on the
                # root's timeline (the casualty span covers the rest)
                span = TRACER.export_span(tid)
                if len(span) > shipped and link.ship_trace(span[shipped:]):
                    shipped = len(span)
            _emit("tick", phase=phase)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cluster_harness")
    p.add_argument("role", choices=["root", "worker"])
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--nnodes", type=int, default=2)
    p.add_argument("--rank", type=int, default=1)
    p.add_argument("--heartbeat-interval", type=float, default=0.25)
    p.add_argument("--worker-timeout", type=float, default=2.0)
    p.add_argument("--connect-timeout", type=float, default=10.0)
    p.add_argument("--protocol-version", type=int,
                   default=mh.PROTOCOL_VERSION,
                   help="override to exercise the version-mismatch path")
    p.add_argument("--phases", default="formation:0.2,idle:2.0",
                   help="root: comma list of name:seconds cluster phases")
    p.add_argument("--die-after", type=float, default=None,
                   help="worker: os._exit(9) after this many seconds")
    p.add_argument("--trace", action="store_true",
                   help="arm the flight recorder: root mints one trace "
                        "id, workers ship cluster_tick spans back via "
                        "MSG_TRACE, both dump the merged ring as a "
                        "trace_dump JSON line")
    args = p.parse_args(argv)
    try:
        return run_root(args) if args.role == "root" else run_worker(args)
    except mh.ClusterPeerLost as exc:  # surfaced on the driving thread
        _emit(**exc.summary())
        return mh.EXIT_PEER_LOST


if __name__ == "__main__":
    sys.exit(main())
