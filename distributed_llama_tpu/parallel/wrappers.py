"""Shared base for the weight-marker pytree wrappers.

The explicit shard_map execution paths mark weights with one-field wrapper
dataclasses (TpRowWeight/TpColWeight in tp_q80.py, EpRowWeight/EpColWeight
in ep_moe.py, PpWeight in pp.py) so matmul()/forward() dispatch on type.
They all share the same shape — `w` holding a dense array or
QuantizedTensor — so the pytree boilerplate and the generic "unwrap, place,
rewrap" handling (sharding.shard_params) live here once; only the
PartitionSpec layout differs per marker.
"""

from __future__ import annotations

import dataclasses

import jax


class WeightWrapper:
    """Base for one-field weight markers; subclasses add only semantics."""

    def tree_flatten(self):
        return (self.w,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def weight_marker(cls):
    """Class decorator: dataclass + pytree registration for a WeightWrapper
    subclass declaring the single `w` field."""
    return jax.tree_util.register_pytree_node_class(dataclasses.dataclass(cls))
