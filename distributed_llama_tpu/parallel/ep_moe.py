"""Expert parallelism: MoE experts PLACED across devices (shard_map path).

Net-new vs the reference, which only tensor-slices every expert — each node
holds a shard of all E experts and computes every active expert
(ref: src/grok1-tasks.cpp:56-143; SURVEY.md §2.5 marks placement-EP absent).
Here the expert axis itself shards over the mesh's `ep` axis: each device
stores E/ep experts (the memory-scaling axis that lets Mixtral/Grok-class
models fit small-HBM chips) and computes only its local experts, masked by
the replicated routing weights; expert contributions and tp partial sums
reduce in a single psum over (ep, tp).

The dataflow inside one shard_map body (all shapes local):

    for each local expert le (E/ep of them, static unroll):
        w_e  = routing_weights[..., ep_index*E/ep + le]   # 0 if not in top-k
        hb   = act(x @ gate_le^T) * (x @ up_le^T)         # hidden/tp local
        acc += w_e * (hb @ down_le^T)                     # dim partial sum
    out = psum(acc, (ep, tp))

Compute cost per device is E/ep dense experts regardless of top-k — at
ep >= E/k this matches the active-only cost of the unsharded decode path
while cutting per-device expert memory by ep. ep composes with tp: within
each expert, up/gate stay row-split and down col-split exactly like the
dense FFN (parallel/tp_q80.py layouts).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.matmul import local_matmul
from ..quants.jax_codec import QuantizedTensor
from .collectives import q80_psum_2shot
from .mesh import EP_AXIS, TP_AXIS
from .tp_q80 import TpColWeight, _batch_axes, repack_col_tp
from .wrappers import WeightWrapper, weight_marker


@weight_marker
class EpRowWeight(WeightWrapper):
    """A stacked (E, d, n) MoE row weight (moe_up / moe_gate): experts on
    ep, output rows on tp. No repacking — both axes shard contiguously."""

    w: QuantizedTensor | jax.Array


@weight_marker
class EpColWeight(WeightWrapper):
    """A stacked MoE col weight (moe_down) in TpColWeight layout
    (tp, E, d, n/tp): tp stack on tp, experts on ep. The tp restacking keeps
    Q40 blocks contiguous per shard (see tp_q80.repack_col_tp)."""

    w: QuantizedTensor | jax.Array


def repack_moe_ep(lw: dict, tp: int) -> dict:
    """Mark one layer's MoE weights for the ep shard_map path: up/gate as-is
    (EpRowWeight), down restacked block-aligned for tp (EpColWeight). A
    moe_down already in TpColWeight stack form (the streamed loader's q80
    mode pre-repacks col weights) is re-marked without touching bytes."""
    down = lw["moe_down"]
    if isinstance(down, TpColWeight):
        down = EpColWeight(down.w)
    elif not isinstance(down, EpColWeight):
        down = EpColWeight(repack_col_tp(down, tp).w)
    out = dict(lw)
    out["moe_up"] = EpRowWeight(lw["moe_up"])
    out["moe_gate"] = EpRowWeight(lw["moe_gate"])
    out["moe_down"] = down
    return out


def ep_row_pspec(ndim: int) -> P:
    """(E, d, m/nb/n): experts -> ep, output rows -> tp. The single source
    of the EpRowWeight layout (the streamed loader places with it too)."""
    return P(EP_AXIS, TP_AXIS, *([None] * (ndim - 2)))


def ep_col_pspec(ndim: int) -> P:
    """(tp, E, d, ...): tp stack -> tp, experts -> ep (EpColWeight layout)."""
    return P(TP_AXIS, EP_AXIS, *([None] * (ndim - 2)))


def _row_pspec(w: EpRowWeight) -> EpRowWeight:
    if isinstance(w.w, QuantizedTensor):
        return EpRowWeight(QuantizedTensor(ep_row_pspec(w.w.packed.ndim),
                                           ep_row_pspec(w.w.scales.ndim)))
    return EpRowWeight(ep_row_pspec(w.w.ndim))


def _col_pspec(w: EpColWeight) -> EpColWeight:
    if isinstance(w.w, QuantizedTensor):
        return EpColWeight(QuantizedTensor(ep_col_pspec(w.w.packed.ndim),
                                           ep_col_pspec(w.w.scales.ndim)))
    return EpColWeight(ep_col_pspec(w.w.ndim))


def ep_pspec(w):
    """PartitionSpec pytree for an Ep wrapper (sharding._leaf_spec hook)."""
    return _row_pspec(w) if isinstance(w, EpRowWeight) else _col_pspec(w)


def _take2(w, le):
    """Static-index one local expert out of a local (E_l, d, ...) leaf."""
    if isinstance(w, QuantizedTensor):
        return QuantizedTensor(w.packed[le], w.scales[le])
    return w[le]


def ep_moe_ffn(
    xb: jnp.ndarray,         # (B, T, dim) — post-norm activations
    e_weights: jnp.ndarray,  # (B, T, E) normalized routing weights, 0 if inactive
    lw: dict,                # layer weights with Ep-wrapped moe_{up,gate,down}
    mesh,
    *,
    act_fn,
    compute_dtype,
    use_pallas: bool = False,
    interpret: bool = False,
    reduce: str = "exact",
) -> jnp.ndarray:
    """Expert-parallel MoE FFN; returns (B, T, dim) replicated over (ep, tp).

    reduce="q80" compresses the tp partial-sum hop (the wire-heavy one —
    dim bytes per expert stack) via the quantized two-shot exchange; the ep
    expert-sum hop stays exact.
    """
    from .compat import shard_map

    ep = mesh.shape.get(EP_AXIS, 1)
    tp = mesh.shape.get(TP_AXIS, 1)
    e_total = e_weights.shape[-1]
    assert e_total % ep == 0, (e_total, ep)
    dp_ax, sp_ax = _batch_axes(mesh, xb)
    x_spec = P(dp_ax, sp_ax, None)

    def body(x_l, ew_l, up_l, gate_l, down_l):
        return _ep_body(x_l, ew_l, up_l.w, gate_l.w, down_l.w,
                        ep=ep, tp=tp, act_fn=act_fn,
                        compute_dtype=compute_dtype, use_pallas=use_pallas,
                        interpret=interpret, reduce=reduce)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(x_spec, x_spec, _row_pspec(lw["moe_up"]),
                  _row_pspec(lw["moe_gate"]), _col_pspec(lw["moe_down"])),
        out_specs=x_spec, check_vma=False)
    return fn(xb, e_weights, lw["moe_up"], lw["moe_gate"], lw["moe_down"])


def _ep_body(x_l, ew_l, up_w, gate_w, down_w, *, ep, tp, act_fn,
             compute_dtype, use_pallas, interpret, reduce):
    """The per-shard expert-parallel MoE computation (local shapes): each
    device runs its E/ep local experts masked by the replicated routing
    weights and the partial sums reduce over (ep, tp). Called from
    ep_moe_ffn's shard_map body AND directly inside the fully-manual pp
    region (parallel/pp.py — shard_map cannot nest, so ep under pp must be
    manual exactly like tp is)."""
    e_total = ew_l.shape[-1]
    e_local = e_total // ep
    ep_idx = lax.axis_index(EP_AXIS) if ep > 1 else 0
    acc = jnp.zeros(x_l.shape[:-1] + (down_w.packed.shape[-2]
                    if isinstance(down_w, QuantizedTensor)
                    else down_w.shape[-2],), compute_dtype)
    for le in range(e_local):
        ge = ep_idx * e_local + le
        w_e = lax.dynamic_index_in_dim(ew_l, ge, axis=-1, keepdims=True)
        gate = local_matmul(x_l, _take2(gate_w, le),
                            compute_dtype=compute_dtype,
                            use_pallas=use_pallas, interpret=interpret)
        up = local_matmul(x_l, _take2(up_w, le),
                          compute_dtype=compute_dtype,
                          use_pallas=use_pallas, interpret=interpret)
        hb = act_fn(gate) * up
        down_le = _take2(down_w, 0)       # drop the tp stack axis
        down_le = _take2(down_le, le)     # then the local expert axis
        out = local_matmul(hb, down_le, compute_dtype=compute_dtype,
                           use_pallas=use_pallas, interpret=interpret)
        acc = acc + w_e.astype(out.dtype) * out
    from .tp_q80 import manual_psum

    # manual_psum: f32 transit for bf16 payloads on the CPU backend (the
    # same XLA CPU manual-region miscompile the pp stage broadcast hits)
    if reduce == "q80" and tp > 1:
        acc = q80_psum_2shot(acc, TP_AXIS, tp)
        return manual_psum(acc, EP_AXIS) if ep > 1 else acc
    axes = tuple(ax for ax, n in ((EP_AXIS, ep), (TP_AXIS, tp)) if n > 1)
    return manual_psum(acc, axes) if axes else acc
