from .mesh import make_mesh, TP_AXIS, DP_AXIS, SP_AXIS
from .sharding import (
    param_pspecs,
    shard_params,
    cache_pspec,
    check_tp_constraints,
    repack_col_weights,
)
from .collectives import q80_psum, q80_all_gather, q80_psum_2shot

__all__ = [
    "make_mesh",
    "TP_AXIS",
    "DP_AXIS",
    "SP_AXIS",
    "param_pspecs",
    "shard_params",
    "cache_pspec",
    "check_tp_constraints",
    "repack_col_weights",
    "q80_psum",
    "q80_all_gather",
    "q80_psum_2shot",
]
