"""Quantized collectives — the reference's signature wire optimization.

The reference quantizes activations to Q80 before every socket transfer and
dequantizes after receive, cutting traffic ~4x (ref: src/tasks.cpp:124-163;
README measures 2048 kB -> 544 kB per token). The TPU equivalent: inside a
`shard_map`, quantize the local partial sum to int8 blocks, all-gather the
(int8, f16-scale) pair over the mesh axis, dequantize and reduce locally.

Use `q80_psum` in place of `jax.lax.psum` when trading exactness for ICI/DCN
bandwidth (most valuable across DCN in multi-slice deployments; on-slice ICI
rarely needs it — which is why it is a flag, not the default).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..quants.jax_codec import quantize_q80_jax, dequantize_q80_jax


def q80_all_gather(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """All-gather with int8 block-quantized payload.

    x: (..., n) local array -> (shards, ..., n) gathered, dequantized f32.
    """
    q, scales = quantize_q80_jax(x)
    qg = jax.lax.all_gather(q, axis_name)
    sg = jax.lax.all_gather(scales, axis_name)
    return dequantize_q80_jax(qg, sg)


def q80_psum(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """All-reduce of partial sums with Q80-compressed exchange.

    Equivalent of the reference's quantize -> gather -> dequantize -> sum
    (ref: src/tasks.cpp:67-90,149-163 + llama2-tasks.cpp:125-131), with the
    star topology replaced by an all-gather so every shard gets the result.

    Per-device wire bytes: (n-1) * 1.0625*|x| — fine at n=2, beaten by
    `q80_psum_2shot` for larger meshes (which stays ~2*1.0625*|x|).
    """
    gathered = q80_all_gather(x, axis_name)  # (shards, ..., n)
    return jnp.sum(gathered, axis=0).astype(x.dtype)


def q80_psum_2shot(x: jnp.ndarray, axis_name: str, n: int) -> jnp.ndarray:
    """Two-shot quantized all-reduce: int8 all-to-all of per-destination
    chunks -> local dequant + f32 sum -> re-quantize -> int8 all-gather.

    The distributed form of the reference's gather-at-root + sum + rebroadcast
    (ref: src/tasks.cpp:67-163) with the root role rotated: device i owns the
    reduction of chunk i. Per-device wire bytes 2*(n-1)/n * 1.0625*|x| vs
    2*(n-1)/n * 4*|x| for an f32 ring all-reduce — the reference's ~4x wire
    cut (ref README.md:96-110) at every mesh size, where the one-shot
    `q80_psum` degrades past n=4. Values are quantized twice (partial sums,
    then the reduced chunk) — the same double quantization the reference's
    Q80 buffer performs per hop.

    `n` must be the static size of `axis_name`; the last dim of x must split
    into n chunks of whole 32-element blocks (fall back to q80_psum if not).
    """
    d = x.shape[-1]
    if n == 1:
        return x
    if d % (32 * n) != 0:
        return q80_psum(x, axis_name)
    lead = x.shape[:-1]
    xc = jnp.moveaxis(x.reshape(*lead, n, d // n), -2, 0)   # (n, ..., d/n)
    q, s = quantize_q80_jax(xc)
    q = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    s = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)
    red = jnp.sum(dequantize_q80_jax(q, s), axis=0)         # my chunk, reduced
    q2, s2 = quantize_q80_jax(red)
    qg = jax.lax.all_gather(q2, axis_name)
    sg = jax.lax.all_gather(s2, axis_name)
    out = jnp.moveaxis(dequantize_q80_jax(qg, sg), 0, -2).reshape(*lead, d)
    return out.astype(x.dtype)
