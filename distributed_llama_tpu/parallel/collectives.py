"""Quantized collectives — the reference's signature wire optimization.

The reference quantizes activations to Q80 before every socket transfer and
dequantizes after receive, cutting traffic ~4x (ref: src/tasks.cpp:124-163;
README measures 2048 kB -> 544 kB per token). The TPU equivalent: inside a
`shard_map`, quantize the local partial sum to int8 blocks, all-gather the
(int8, f16-scale) pair over the mesh axis, dequantize and reduce locally.

Use `q80_psum` in place of `jax.lax.psum` when trading exactness for ICI/DCN
bandwidth (most valuable across DCN in multi-slice deployments; on-slice ICI
rarely needs it — which is why it is a flag, not the default).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..quants.jax_codec import quantize_q80_jax, dequantize_q80_jax


def q80_all_gather(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """All-gather with int8 block-quantized payload.

    x: (..., n) local array -> (shards, ..., n) gathered, dequantized f32.
    """
    q, scales = quantize_q80_jax(x)
    qg = jax.lax.all_gather(q, axis_name)
    sg = jax.lax.all_gather(scales, axis_name)
    return dequantize_q80_jax(qg, sg)


def q80_psum(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """All-reduce of partial sums with Q80-compressed exchange.

    Equivalent of the reference's quantize -> gather -> dequantize -> sum
    (ref: src/tasks.cpp:67-90,149-163 + llama2-tasks.cpp:125-131), with the
    star topology replaced by an all-gather so every shard gets the result.
    """
    gathered = q80_all_gather(x, axis_name)  # (shards, ..., n)
    return jnp.sum(gathered, axis=0).astype(x.dtype)
