"""SentencePiece `.model` -> reference `.t` tokenizer file.

Equivalent of the reference converter (ref:
converter/convert-tokenizer-sentencepiece.py): vocab pieces + scores with the
llama2.c conventions — SPM's meta symbol U+2581 becomes a leading space and
`<0xXX>` byte pieces are kept verbatim.

The sentencepiece package is not available in this image, so the ModelProto
is read with a minimal protobuf wire-format parser (the file is just
`repeated SentencePiece pieces = 1` where SentencePiece has
`piece = 1 (string), score = 2 (float), type = 3 (enum)` — see the public
sentencepiece_model.proto).
"""

from __future__ import annotations

import argparse
import struct

from ..io.tokenizer_file import TokenizerData, write_tokenizer_file

NORMAL, UNKNOWN, CONTROL, USER_DEFINED, BYTE, UNUSED = 1, 2, 3, 4, 5, 6


def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) for one protobuf message."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:          # varint
            val, i = _read_varint(buf, i)
        elif wire == 1:        # 64-bit
            val, i = buf[i:i + 8], i + 8
        elif wire == 2:        # length-delimited
            ln, i = _read_varint(buf, i)
            val, i = buf[i:i + ln], i + ln
        elif wire == 5:        # 32-bit
            val, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def parse_spm_model(path: str) -> list[tuple[bytes, float, int]]:
    """-> [(piece_bytes, score, type)] in vocab order."""
    with open(path, "rb") as f:
        raw = f.read()
    pieces: list[tuple[bytes, float, int]] = []
    for field, wire, val in _fields(raw):
        if field == 1 and wire == 2:  # repeated SentencePiece pieces
            piece = b""
            score = 0.0
            ptype = NORMAL
            for pf, pw, pv in _fields(val):
                if pf == 1:
                    piece = pv
                elif pf == 2:
                    score = struct.unpack("<f", pv)[0]
                elif pf == 3:
                    ptype = pv
            pieces.append((piece, score, ptype))
    if not pieces:
        raise ValueError(f"{path}: no sentencepiece pieces found")
    return pieces


def spm_to_tokenizer_data(path: str, bos_id: int = 1, eos_id: int = 2) -> TokenizerData:
    pieces = parse_spm_model(path)
    vocab: list[bytes] = []
    scores: list[float] = []
    for i, (piece, score, ptype) in enumerate(pieces):
        text = piece.decode("utf-8", errors="replace")
        # bos/eos pieces are rewritten to the llama2.c display convention the
        # reference exporter uses, keeping .t files byte-compatible with its
        # output (ref: convert-tokenizer-sentencepiece.py:42-45)
        if i == bos_id:
            text = "\n<s>\n"
        elif i == eos_id:
            text = "\n</s>\n"
        # SPM word-boundary marker U+2581 -> leading space (llama2.c convention)
        text = text.replace("▁", " ")
        vocab.append(text.encode("utf-8"))
        scores.append(score)
    return TokenizerData(vocab=vocab, scores=scores, bos_id=bos_id, eos_id=eos_id)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="Convert a sentencepiece .model to .t")
    ap.add_argument("model")
    ap.add_argument("output")
    ap.add_argument("--bos-id", type=int, default=1)
    ap.add_argument("--eos-id", type=int, default=2)
    args = ap.parse_args(argv)
    data = spm_to_tokenizer_data(args.model, args.bos_id, args.eos_id)
    write_tokenizer_file(args.output, data)
    print(f"✅ wrote {args.output}: vocab={data.vocab_size} "
          f"bos={data.bos_id} eos={data.eos_id}")


if __name__ == "__main__":
    main()
