"""Llama-3 tiktoken vocab -> reference `.t` tokenizer file.

Equivalent of the reference converter (ref:
converter/convert-tokenizer-llama3.py): the input is the tiktoken text format
(one `base64token rank` pair per line); merge priority is encoded as a
negative-rank score so the engine's greedy highest-score merge reproduces BPE
rank order, and the 256 llama-3 special tokens are appended after the base
vocab (ref: convert-tokenizer-llama3.py:13-79).
"""

from __future__ import annotations

import argparse
import base64

from ..io.tokenizer_file import TokenizerData, write_tokenizer_file

N_SPECIAL = 256
SPECIAL_TOKENS = [
    "<|begin_of_text|>",
    "<|end_of_text|>",
    "<|reserved_special_token_0|>",
    "<|reserved_special_token_1|>",
    "<|finetune_right_pad_id|>",
    "<|step_id|>",
    "<|start_header_id|>",
    "<|end_header_id|>",
    "<|eom_id|>",
    "<|eot_id|>",
    "<|python_tag|>",
]


def load_tiktoken_vocab(path: str) -> list[bytes]:
    vocab: list[bytes] = []
    with open(path, "rb") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            token_b64, rank = line.split()
            tok = base64.b64decode(token_b64)
            assert int(rank) == len(vocab), "ranks must be dense and ordered"
            vocab.append(tok)
    return vocab


def llama3_to_tokenizer_data(path: str) -> TokenizerData:
    base = load_tiktoken_vocab(path)
    specials = list(SPECIAL_TOKENS)
    specials += [f"<|reserved_special_token_{i}|>"
                 for i in range(2, 2 + N_SPECIAL - len(specials))]
    vocab = base + [s.encode() for s in specials]
    # negative-rank scores: higher-priority merges (lower rank) score higher;
    # specials get -inf-ish so they never merge
    scores = [-float(i) for i in range(len(base))]
    scores += [-1e9] * len(specials)
    bos = vocab.index(b"<|begin_of_text|>")
    eos = vocab.index(b"<|eot_id|>")
    return TokenizerData(vocab=vocab, scores=scores, bos_id=bos, eos_id=eos)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="Convert a llama-3 tiktoken vocab to .t")
    ap.add_argument("model", help="tiktoken file (tokenizer.model)")
    ap.add_argument("output")
    args = ap.parse_args(argv)
    data = llama3_to_tokenizer_data(args.model)
    write_tokenizer_file(args.output, data)
    print(f"✅ wrote {args.output}: vocab={data.vocab_size} "
          f"bos={data.bos_id} eos={data.eos_id}")


if __name__ == "__main__":
    main()
