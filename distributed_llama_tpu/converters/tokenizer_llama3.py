"""Llama-3 tiktoken vocab -> reference `.t` tokenizer file.

Equivalent of the reference converter (ref:
converter/convert-tokenizer-llama3.py): the input is the tiktoken text format
(one `base64token rank` pair per line); merge priority is encoded as a
negative-rank score so the engine's greedy highest-score merge reproduces BPE
rank order, and the 256 llama-3 special tokens are appended after the base
vocab (ref: convert-tokenizer-llama3.py:13-79).
"""

from __future__ import annotations

import argparse
import base64

from ..io.tokenizer_file import TokenizerData, write_tokenizer_file

N_SPECIAL = 256
# the reference's (Llama-3.0) special-token name table (ref:
# convert-tokenizer-llama3.py:14-27) — kept identical so produced .t files
# are interchangeable with the reference's published dllama_tokenizer_llama3.t
SPECIAL_TOKENS = [
    "<|begin_of_text|>",
    "<|end_of_text|>",
    "<|reserved_special_token_0|>",
    "<|reserved_special_token_1|>",
    "<|reserved_special_token_2|>",
    "<|reserved_special_token_3|>",
    "<|start_header_id|>",
    "<|end_header_id|>",
    "<|reserved_special_token_4|>",
    "<|eot_id|>",
]


def load_tiktoken_vocab(path: str) -> list[bytes]:
    vocab: list[bytes] = []
    with open(path, "rb") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            token_b64, rank = line.split()
            tok = base64.b64decode(token_b64)
            assert int(rank) == len(vocab), "ranks must be dense and ordered"
            vocab.append(tok)
    return vocab


def llama3_to_tokenizer_data(path: str, bos_id: int | None = None,
                             eos_id: int | None = None) -> TokenizerData:
    """bos/eos default to the reference's ids: bos=<|begin_of_text|> (128000),
    eos=<|end_of_text|> (128001) — what a base model emits; instruct chat
    stops on <|eot_id|> because generation stops on the whole
    Tokenizer.stop_token_ids() set, not eos_id alone
    (ref: convert-tokenizer-llama3.py:29-30)."""
    base = load_tiktoken_vocab(path)
    specials = list(SPECIAL_TOKENS)
    specials += [f"<|reserved_special_token_{i}|>"
                 for i in range(5, 5 + N_SPECIAL - len(specials))]
    vocab = base + [s.encode() for s in specials]
    # negative-rank scores: higher-priority merges (lower rank) score higher;
    # specials continue the -rank sequence (ref: convert-tokenizer-llama3.py:52-58)
    scores = [-float(i) for i in range(len(vocab))]
    bos = vocab.index(b"<|begin_of_text|>") if bos_id is None else bos_id
    eos = vocab.index(b"<|end_of_text|>") if eos_id is None else eos_id
    return TokenizerData(vocab=vocab, scores=scores, bos_id=bos, eos_id=eos)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="Convert a llama-3 tiktoken vocab to .t")
    ap.add_argument("model", help="tiktoken file (tokenizer.model)")
    ap.add_argument("output")
    ap.add_argument("--bos-id", type=int, default=None,
                    help="override bos id (default: <|begin_of_text|>)")
    ap.add_argument("--eos-id", type=int, default=None,
                    help="override eos id (default: <|end_of_text|>; pass the "
                         "<|eot_id|> index for instruct-tuned chat models)")
    args = ap.parse_args(argv)
    data = llama3_to_tokenizer_data(args.model, args.bos_id, args.eos_id)
    write_tokenizer_file(args.output, data)
    print(f"✅ wrote {args.output}: vocab={data.vocab_size} "
          f"bos={data.bos_id} eos={data.eos_id}")


if __name__ == "__main__":
    main()
