"""Meta Llama checkpoint (consolidated.*.pth) -> reference-format `.m`.

Equivalent of the reference Meta converter (ref: converter/convert-llama.py):
the N checkpoint shards are Meta's column/row-parallel splits, re-concatenated
per tensor role — axis 1 for tok_embeddings / wo / w2, axis 0 otherwise
(ref: convert-llama.py:73-90). hidden_dim is derived from w1's shard shape x
n_shards (ref: convert-llama.py:64-66). No rotary permutation: Meta's layout
is already the interleaved form rope_llama expects.

Tensors are streamed chunk-by-chunk so peak host memory stays bounded
(ref: convert-llama.py:49-67 chunks for the same reason).

Usage:
  python -m distributed_llama_tpu.converters.meta_llama <dir> out.m \
      --weights-float-type q40 [--seq-len 4096]
"""

from __future__ import annotations

import argparse
import json
import os
from pathlib import Path

import numpy as np

from ..io.model_file import model_tensor_plan, write_header, write_tensor
from ..models.spec import ArchType, HiddenAct, ModelSpec
from ..quants.types import FloatType

# our plan name -> (meta name pattern, concat axis)
_AXIS1 = {"tok_emb", "wo", "w2"}
_META = {
    "tok_emb": "tok_embeddings.weight",
    "wq": "layers.{l}.attention.wq.weight",
    "wk": "layers.{l}.attention.wk.weight",
    "wv": "layers.{l}.attention.wv.weight",
    "wo": "layers.{l}.attention.wo.weight",
    "w1": "layers.{l}.feed_forward.w1.weight",
    "w2": "layers.{l}.feed_forward.w2.weight",
    "w3": "layers.{l}.feed_forward.w3.weight",
    "rms_att": "layers.{l}.attention_norm.weight",
    "rms_ffn": "layers.{l}.ffn_norm.weight",
    "rms_final": "norm.weight",
    "wcls": "output.weight",
}


def _meta_name(plan_name: str) -> str:
    if plan_name.startswith("layers."):
        _, l, rest = plan_name.split(".", 2)
        return _META[rest].format(l=l)
    return _META[plan_name]


def convert_meta(folder: str, out_path: str, weights_float_type: FloatType,
                 seq_len: int | None = None, progress: bool = True) -> ModelSpec:
    """seq_len=None reads max_seq_len from params.json (the reference
    converter requires and uses it — ref: convert-llama.py:59-62), falling
    back to 2048 for checkpoints that omit it; pass a value to override."""
    import torch

    with open(os.path.join(folder, "params.json")) as f:
        params = json.load(f)
    if seq_len is None:
        seq_len = int(params.get("max_seq_len", 2048))

    shard_paths = sorted(Path(folder).glob("consolidated.*.pth"))
    if not shard_paths:
        raise FileNotFoundError(f"no consolidated.*.pth under {folder}")
    shards = [torch.load(p, map_location="cpu", mmap=True) for p in shard_paths]

    def fetch(plan_name: str) -> np.ndarray:
        meta = _meta_name(plan_name)
        parts = [s[meta] for s in shards]
        if len(parts) == 1 or parts[0].dim() == 1:
            t = parts[0]
        else:
            base = plan_name.split(".")[-1]
            t = torch.cat(parts, dim=1 if base in _AXIS1 else 0)
        return t.to(torch.float32).numpy()

    n_heads = params["n_heads"]
    hidden_dim = shards[0]["layers.0.feed_forward.w1.weight"].shape[0] * len(shards)
    vocab_size = params.get("vocab_size", -1)
    if vocab_size <= 0:
        # tok_embeddings shards are column-split (axis 1 = dim), so the vocab
        # dimension is shape[0] regardless of shard count
        vocab_size = shards[0]["tok_embeddings.weight"].shape[0]

    spec = ModelSpec(
        arch=ArchType.LLAMA,
        dim=params["dim"],
        hidden_dim=hidden_dim,
        n_layers=params["n_layers"],
        n_heads=n_heads,
        n_kv_heads=params.get("n_kv_heads", n_heads),
        vocab_size=vocab_size,
        seq_len=seq_len,
        hidden_act=HiddenAct.SILU,
        rope_theta=float(params.get("rope_theta", 10000.0)),
        weights_float_type=weights_float_type,
    )

    with open(out_path, "wb") as f:
        write_header(f, spec)
        for name, shape, ftype in model_tensor_plan(spec):
            x = fetch(name)
            assert x.shape == tuple(shape), (name, x.shape, shape)
            write_tensor(f, x, ftype)
            if progress:
                print(f"🔶 {name} {tuple(shape)} -> {ftype.name}", flush=True)
    return spec


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="Convert a Meta llama checkpoint "
                                             "folder to .m")
    ap.add_argument("folder")
    ap.add_argument("output")
    ap.add_argument("--weights-float-type", default="q40",
                    choices=["f32", "f16", "q40", "q80"])
    ap.add_argument("--seq-len", type=int, default=None,
                    help="context length written to the header (default: "
                         "params.json max_seq_len, else 2048)")
    args = ap.parse_args(argv)
    spec = convert_meta(args.folder, args.output,
                        FloatType[args.weights_float_type.upper()], args.seq_len)
    print(f"✅ wrote {args.output}: {spec.arch.name} dim={spec.dim} "
          f"layers={spec.n_layers}")


if __name__ == "__main__":
    main()
