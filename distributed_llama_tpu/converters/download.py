"""Pre-converted model downloader / launcher.

Equivalent of the reference's download-model.py: a catalog of pre-converted
`.m`/`.t` files hosted on Hugging Face (the reference publishes these under
https://huggingface.co/b4rtaz — ref: download-model.py:5-27), downloaded in
parts and concatenated, then a ready-to-run command is printed
(ref: download-model.py:55-100).

Usage:
  python -m distributed_llama_tpu.converters.download tinyllama
  python -m distributed_llama_tpu.converters.download --list
"""

from __future__ import annotations

import argparse
import os
import sys
import urllib.error
import urllib.request

_HF = "https://huggingface.co"

# name -> (model url parts, tokenizer url)  (catalog mirrors download-model.py:5-27)
CATALOG: dict[str, dict] = {
    "tinyllama_1_1b_3t_q40": {
        "model": [f"{_HF}/b4rtaz/TinyLlama-1.1B-3T-Distributed-Llama/resolve/main/dllama_model_tinylama_1.1b_3t_q40.m?download=true"],
        "tokenizer": f"{_HF}/b4rtaz/TinyLlama-1.1B-3T-Distributed-Llama/resolve/main/dllama_tokenizer_tinylama_1.1b_3t.t?download=true",
    },
    "llama3_8b_q40": {
        "model": [f"{_HF}/b4rtaz/Llama-3-8B-Q40-Distributed-Llama/resolve/main/dllama_model_meta-llama-3-8b_q40.m?download=true"],
        "tokenizer": f"{_HF}/b4rtaz/Llama-3-8B-Q40-Distributed-Llama/resolve/main/dllama_tokenizer_llama3.t?download=true",
    },
    "llama3_8b_instruct_q40": {
        "model": [f"{_HF}/b4rtaz/Llama-3-8B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_model_lama3_instruct_q40.m?download=true"],
        "tokenizer": f"{_HF}/b4rtaz/Llama-3-8B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_tokenizer_llama3.t?download=true",
    },
}
ALIASES = {"tinyllama": "tinyllama_1_1b_3t_q40", "llama3_8b": "llama3_8b_q40"}


def download(url: str, dest: str, progress: bool = True) -> None:
    def hook(blocks, bs, total):
        if progress and total > 0 and blocks % 256 == 0:
            done = min(blocks * bs, total)
            print(f"\r📥 {dest}: {done / 1e6:.0f}/{total / 1e6:.0f} MB",
                  end="", flush=True)

    # download to a temp name so an interrupted run never leaves a truncated
    # file at the final path (the existence check would treat it as complete)
    tmp = dest + ".download"
    urllib.request.urlretrieve(url, tmp, reporthook=hook)
    os.replace(tmp, dest)
    if progress:
        print()


def fetch_model(name: str, out_dir: str = "models") -> tuple[str, str]:
    key = ALIASES.get(name, name)
    if key not in CATALOG:
        raise KeyError(f"unknown model '{name}' — use --list")
    entry = CATALOG[key]
    folder = os.path.join(out_dir, key)
    os.makedirs(folder, exist_ok=True)

    model_path = os.path.join(folder, f"dllama_model_{key}.m")
    tok_path = os.path.join(folder, f"dllama_tokenizer_{key}.t")

    if not os.path.exists(model_path):
        parts = []
        for i, url in enumerate(entry["model"]):
            part = model_path + (f".part{i}" if len(entry["model"]) > 1 else "")
            download(url, part)
            parts.append(part)
        if len(parts) > 1:  # concatenate split archives (ref: download-model.py:40-52)
            with open(model_path, "wb") as out:
                for part in parts:
                    with open(part, "rb") as pf:
                        while chunk := pf.read(1 << 24):
                            out.write(chunk)
                    os.remove(part)
    if not os.path.exists(tok_path):
        download(entry["tokenizer"], tok_path)
    return model_path, tok_path


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="Download a pre-converted model")
    ap.add_argument("name", nargs="?", help="catalog name or alias")
    ap.add_argument("--list", action="store_true")
    ap.add_argument("--out-dir", default="models")
    args = ap.parse_args(argv)
    if args.list or not args.name:
        for key in CATALOG:
            print(key)
        return
    try:
        model, tok = fetch_model(args.name, args.out_dir)
    except KeyError as e:
        sys.exit(str(e.args[0]))
    except (urllib.error.URLError, OSError) as e:
        sys.exit(f"download failed (no network egress?): {e}")
    print("✅ downloaded. Run:")
    print(f"  python -m distributed_llama_tpu.apps.dllama inference "
          f"--model {model} --tokenizer {tok} --prompt \"Hello world\" --steps 64")


if __name__ == "__main__":
    main()
