"""HF safetensors checkpoint -> reference-format `.m`.

Equivalent of the reference HF converter (ref: converter/convert-hf.py):
llama / mistral / mixtral from config.json + *.safetensors, streamed
tensor-by-tensor so peak memory is one tensor.

Layout decisions mirror the reference:
  * llama/mistral q/k projections are permuted from HF's half-split rotary
    layout to the interleaved layout our rope_llama expects
    (ref: converter/convert-hf.py:12-15,46-50): within each head,
    new_row[2j] = old_row[j], new_row[2j+1] = old_row[j + hs/2].
  * mixtral keeps HF's native layout — the MIXTRAL arch applies half-rotation
    RoPE (rope_falcon), matching HF semantics without permutation.
  * MoE expert tensor order is up(w3), gate(w1), down(w2)
    (ref: converter/convert-hf.py:67-74).

Usage:
  python -m distributed_llama_tpu.converters.hf <hf_dir> out.m --weights-float-type q40
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

from ..io.model_file import model_tensor_plan, write_header, write_tensor
from ..models.spec import ArchType, HiddenAct, ModelSpec
from ..quants.types import FloatType


def permute_rotary(w: np.ndarray, n_heads: int) -> np.ndarray:
    """HF half-split -> interleaved rotary row order, per head."""
    d, n = w.shape
    hs = d // n_heads
    return (w.reshape(n_heads, 2, hs // 2, n)
             .swapaxes(1, 2)
             .reshape(d, n))


def spec_from_config(config: dict, weights_float_type: FloatType,
                     max_seq_len: int | None = None) -> ModelSpec:
    model_type = config.get("model_type", "llama")
    if model_type not in ("llama", "mistral", "mixtral"):
        raise ValueError(
            f"unsupported model_type '{model_type}' — this converter handles "
            "llama/mistral/mixtral (ref: converter/convert-hf.py:146-181)")
    scaling = config.get("rope_scaling")
    if scaling and scaling.get("rope_type", scaling.get("type")) not in (None, "default"):
        raise ValueError(
            f"rope_scaling {scaling!r} cannot be represented in the .m spec; "
            "converting would silently produce wrong rotary frequencies")
    n_experts = config.get("num_local_experts", 0) or 0
    arch = ArchType.MIXTRAL if n_experts > 0 else ArchType.LLAMA
    seq_len = config.get("max_position_embeddings", 2048)
    if max_seq_len:
        seq_len = min(seq_len, max_seq_len)
    act = config.get("hidden_act", "silu")
    return ModelSpec(
        arch=arch,
        dim=config["hidden_size"],
        hidden_dim=config["intermediate_size"],
        n_layers=config["num_hidden_layers"],
        n_heads=config["num_attention_heads"],
        n_kv_heads=config.get("num_key_value_heads", config["num_attention_heads"]),
        vocab_size=config["vocab_size"],
        seq_len=seq_len,
        hidden_act=HiddenAct.GELU if act.startswith("gelu") else HiddenAct.SILU,
        rope_theta=float(config.get("rope_theta", 10000.0)),
        n_experts=n_experts,
        n_active_experts=config.get("num_experts_per_tok", 0) or 0,
        weights_float_type=weights_float_type,
        version=0,
    )


class SafetensorsIndex:
    """Lazy multi-file safetensors reader: name -> f32 numpy array."""

    def __init__(self, folder: str):
        from safetensors import safe_open

        self._safe_open = safe_open
        self.folder = folder
        self.file_for: dict[str, str] = {}
        index_path = os.path.join(folder, "model.safetensors.index.json")
        if os.path.exists(index_path):
            with open(index_path) as f:
                weight_map = json.load(f)["weight_map"]
            for name, fname in weight_map.items():
                self.file_for[name] = os.path.join(folder, fname)
        else:
            for fname in sorted(os.listdir(folder)):
                if fname.endswith(".safetensors"):
                    path = os.path.join(folder, fname)
                    with safe_open(path, framework="np") as f:
                        for name in f.keys():
                            self.file_for[name] = path
        if not self.file_for:
            raise FileNotFoundError(f"no .safetensors files under {folder}")

    def __contains__(self, name: str) -> bool:
        return name in self.file_for

    def get(self, name: str) -> np.ndarray:
        import torch

        path = self.file_for[name]
        with self._safe_open(path, framework="pt") as f:
            t = f.get_tensor(name)  # torch handles bf16, np does not
        return t.to(torch.float32).numpy()


def _hf_name(plan_name: str, spec: ModelSpec) -> tuple[str, bool]:
    """Map our plan tensor name -> (HF tensor name, needs_rotary_permute)."""
    if plan_name == "tok_emb":
        return "model.embed_tokens.weight", False
    if plan_name == "rms_final":
        return "model.norm.weight", False
    if plan_name == "wcls":
        return "lm_head.weight", False
    assert plan_name.startswith("layers.")
    _, l, rest = plan_name.split(".", 2)
    p = f"model.layers.{l}."
    permute = spec.arch == ArchType.LLAMA
    table = {
        "wq": (p + "self_attn.q_proj.weight", permute),
        "wk": (p + "self_attn.k_proj.weight", permute),
        "wv": (p + "self_attn.v_proj.weight", False),
        "wo": (p + "self_attn.o_proj.weight", False),
        "w1": (p + "mlp.gate_proj.weight", False),
        "w2": (p + "mlp.down_proj.weight", False),
        "w3": (p + "mlp.up_proj.weight", False),
        "moe_router": (p + "block_sparse_moe.gate.weight", False),
        "rms_att": (p + "input_layernorm.weight", False),
        "rms_ffn": (p + "post_attention_layernorm.weight", False),
    }
    if rest in table:
        return table[rest]
    # experts.{e}.{up|gate|down} -> HF w3/w1/w2 (ref: convert-hf.py:67-74)
    _, e, role = rest.split(".")
    hf_w = {"up": "w3", "gate": "w1", "down": "w2"}[role]
    return p + f"block_sparse_moe.experts.{e}.{hf_w}.weight", False


def convert_hf(folder: str, out_path: str, weights_float_type: FloatType,
               max_seq_len: int | None = None, progress: bool = True) -> ModelSpec:
    with open(os.path.join(folder, "config.json")) as f:
        config = json.load(f)
    spec = spec_from_config(config, weights_float_type, max_seq_len)
    idx = SafetensorsIndex(folder)

    def fetch(plan_name: str, shape) -> np.ndarray:
        hf, permute = _hf_name(plan_name, spec)
        if hf == "lm_head.weight" and hf not in idx:
            hf = "model.embed_tokens.weight"  # tied embeddings
        x = idx.get(hf)
        if permute:
            n_heads = spec.n_heads if plan_name.endswith("wq") else spec.n_kv_heads
            x = permute_rotary(x, n_heads)
        assert x.shape == tuple(shape), (plan_name, x.shape, shape)
        return x

    t0 = time.time()
    with open(out_path, "wb") as f:
        write_header(f, spec)
        for name, shape, ftype in model_tensor_plan(spec):
            write_tensor(f, fetch(name, shape), ftype)
            if progress:
                print(f"🔶 {name} {tuple(shape)} -> {ftype.name} "
                      f"({time.time()-t0:.0f}s)", flush=True)
    return spec


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="Convert a HF llama/mistral/mixtral "
                                             "checkpoint folder to .m")
    ap.add_argument("folder")
    ap.add_argument("output")
    ap.add_argument("--weights-float-type", default="q40",
                    choices=["f32", "f16", "q40", "q80"])
    ap.add_argument("--max-seq-len", type=int, default=None)
    args = ap.parse_args(argv)
    spec = convert_hf(args.folder, args.output,
                      FloatType[args.weights_float_type.upper()],
                      args.max_seq_len)
    print(f"✅ wrote {args.output}: {spec.arch.name} dim={spec.dim} "
          f"layers={spec.n_layers}")


if __name__ == "__main__":
    main()
