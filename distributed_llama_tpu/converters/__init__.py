"""Offline tooling: checkpoint and tokenizer converters.

TPU-native equivalents of the reference converter suite (SURVEY.md §2.4):

  hf.py                 HF safetensors -> .m   (ref: converter/convert-hf.py)
  meta_llama.py         Meta consolidated.pth -> .m (ref: converter/convert-llama.py)
  grok1.py              Grok-1 torch bins -> .m (ref: converter/convert-grok-1.py)
  tokenizer_spm.py      sentencepiece .model -> .t (ref: converter/convert-tokenizer-sentencepiece.py)
  tokenizer_llama3.py   tiktoken base64 vocab -> .t (ref: converter/convert-tokenizer-llama3.py)
  download.py           pre-converted model catalog + launcher (ref: download-model.py)

All writers stream tensor-by-tensor through io.model_file.write_header/
write_tensor in the exact reference file order, so outputs load in both this
framework and the reference engine.
"""
