"""Grok-1 (keyfan/grok-1-hf torch dump) -> reference-format `.m`.

Equivalent of the reference Grok converter (ref: converter/convert-grok-1.py):
hardcoded 64-layer / 8-expert / top-2 spec (ref: convert-grok-1.py:59-70), the
19-file `pytorch_model-000NN-of-00019.bin` walk with one file resident at a
time, and the tensor-name mapping:

  transformer.in_out_embed.weight                      -> tok_emb
  ...decoder_layer.{l}.multi_head_attention.query/key/value/linear -> wq/wk/wv/wo
  ...decoder_layer.{l}.router.weight                   -> moe_router
  ...decoder_layer.{l}.moe.{e}.linear_v/linear/linear_1 -> expert up/gate/down
  ...decoder_layer.{l}.rms_norm{,_1,_2,_3}             -> rms_att/rms_ffn/rms_moe/rms_ffn2
  transformer.rms_norm.weight                          -> rms_final
  lm_head.weight                                       -> wcls

Usage:
  python -m distributed_llama_tpu.converters.grok1 <dir> out.m --weights-float-type q40
"""

from __future__ import annotations

import argparse
import gc
import os

import numpy as np

from ..io.model_file import model_tensor_plan, write_header, write_tensor
from ..models.spec import ArchType, HiddenAct, ModelSpec
from ..quants.types import FloatType

GROK1_SPEC = dict(
    arch=ArchType.GROK1, dim=6144, hidden_dim=32768, n_layers=64, n_heads=48,
    n_kv_heads=8, n_experts=8, n_active_experts=2, vocab_size=131072,
    seq_len=8192, hidden_act=HiddenAct.GELU, rope_theta=10000.0,
)
N_FILES = 19


def _grok_name(plan_name: str) -> str:
    if plan_name == "tok_emb":
        return "transformer.in_out_embed.weight"
    if plan_name == "rms_final":
        return "transformer.rms_norm.weight"
    if plan_name == "wcls":
        return "lm_head.weight"
    _, l, rest = plan_name.split(".", 2)
    p = f"transformer.decoder_layer.{l}."
    table = {
        "wq": p + "multi_head_attention.query.weight",
        "wk": p + "multi_head_attention.key.weight",
        "wv": p + "multi_head_attention.value.weight",
        "wo": p + "multi_head_attention.linear.weight",
        "moe_router": p + "router.weight",
        "rms_att": p + "rms_norm.weight",
        "rms_ffn": p + "rms_norm_1.weight",
        "rms_moe": p + "rms_norm_2.weight",
        "rms_ffn2": p + "rms_norm_3.weight",
    }
    if rest in table:
        return table[rest]
    _, e, role = rest.split(".")
    suffix = {"up": "linear_v", "gate": "linear", "down": "linear_1"}[role]
    return p + f"moe.{e}.{suffix}.weight"


class _ShardWalker:
    """One torch shard resident at a time, with a name->file index built
    lazily (ref: convert-grok-1.py:20-52)."""

    def __init__(self, folder: str, n_files: int = N_FILES):
        self.folder = folder
        self.n_files = n_files
        self.index: dict[str, int] = {}
        self.current: dict | None = None
        self.current_idx = 0

    def _load(self, idx: int) -> None:
        import torch

        if self.current_idx == idx and self.current is not None:
            return
        self.current = None
        # memory relief between checkpoint shards — but respect a session
        # that disabled cyclic GC (tests/conftest.py does: collecting jax
        # objects segfaults on the pinned jaxlib/CPython; refcounting
        # already frees the dropped shard's tensors)
        if gc.isenabled():
            gc.collect()
        path = os.path.join(
            self.folder, f"pytorch_model-{idx:05d}-of-{self.n_files:05d}.bin")
        print(f"💿 loading {os.path.basename(path)}", flush=True)
        self.current = torch.load(path, map_location="cpu")
        for k in self.current:
            self.index[k] = idx
        self.current_idx = idx

    def get(self, name: str) -> np.ndarray:
        import torch

        if self.current is None:
            self._load(1)
        while name not in self.current:
            if name in self.index:
                self._load(self.index[name])
            elif self.current_idx < self.n_files:
                self._load(self.current_idx + 1)
            else:
                raise KeyError(name)
        return self.current[name].to(torch.float32).numpy()


def convert_grok1(folder: str, out_path: str, weights_float_type: FloatType,
                  progress: bool = True, spec: ModelSpec | None = None,
                  n_files: int = N_FILES) -> ModelSpec:
    """spec/n_files default to the production Grok-1 dump (ref:
    convert-grok-1.py:59-70); overridable for shrunken test checkpoints."""
    if spec is None:
        spec = ModelSpec(weights_float_type=weights_float_type, **GROK1_SPEC)
    walker = _ShardWalker(folder, n_files)
    with open(out_path, "wb") as f:
        write_header(f, spec)
        for name, shape, ftype in model_tensor_plan(spec):
            x = walker.get(_grok_name(name))
            assert x.shape == tuple(shape), (name, x.shape, shape)
            write_tensor(f, x, ftype)
            if progress:
                print(f"🔶 {name} {tuple(shape)} -> {ftype.name}", flush=True)
    return spec


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="Convert a Grok-1 torch dump to .m")
    ap.add_argument("folder")
    ap.add_argument("output")
    ap.add_argument("--weights-float-type", default="q40",
                    choices=["f32", "f16", "q40", "q80"])
    args = ap.parse_args(argv)
    spec = convert_grok1(args.folder, args.output,
                         FloatType[args.weights_float_type.upper()])
    print(f"✅ wrote {args.output}: {spec.arch.name} dim={spec.dim} "
          f"layers={spec.n_layers}")


if __name__ == "__main__":
    main()
