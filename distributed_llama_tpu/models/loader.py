"""Streamed sharded weight loading — the 70B path.

The reference root streams the mmap'd file tensor-by-tensor, splitting each
matrix and pushing every worker its shard over the socket while only the
current tensor is resident (ref: src/transformer.cpp:562-621, 623-683). The
TPU equivalent: iterate the file in plan order, convert each tensor to its
device layout on the host, `jax.device_put` it with its NamedSharding (each
device receives only its shard), and free the host buffer before the next
tensor. Peak host memory is one fusion group (~3 tensors, or one layer's
expert stack for MoE), never the whole model — `load_params_streamed`
returns the measured peak so callers/tests can hold it to that bound.

The result pytree is final: QKV/w1|w3 pre-fused when tp == 1, col weights
pre-repacked to TpColWeight stacks when q80 collectives are on, every leaf
already placed/sharded. Engine's own transforms detect and skip
already-transformed params, so this feeds Engine(...) directly.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..io.model_file import HostTensor, iter_model_tensors
from ..quants.jax_codec import QuantizedTensor
from ..quants.numpy_codec import quantize_q40
from ..quants.types import FloatType
from ..parallel.sharding import COL_SPLIT_NAMES, _pspec_for
from ..parallel.mesh import EP_AXIS, PP_AXIS, TP_AXIS
from .spec import ArchType, ModelSpec

_MOE_EP_KEYS = ("moe_up", "moe_gate", "moe_down")


class LoadStats(NamedTuple):
    peak_host_bytes: int   # max bytes of file tensors resident at once
    total_bytes: int       # total tensor bytes streamed


def _host_bytes(t: HostTensor) -> int:
    n = 0
    for a in (t.data, t.scales, t.packed):
        if a is not None:
            n += a.nbytes
    return n


def _leaf_key(plan_name: str) -> str:
    """'layers.3.wq' -> 'wq'; 'layers.0.experts.2.up' -> 'moe_up'."""
    parts = plan_name.split(".")
    if parts[0] != "layers":
        return plan_name
    if parts[2] == "experts":
        return "moe_" + parts[4]
    return parts[2]


def _to_q40_host(x: np.ndarray) -> HostTensor:
    scales, packed = quantize_q40(x.reshape(-1, x.shape[-1]))
    return HostTensor("", FloatType.Q40, x.shape, scales=scales, packed=packed)


def _replicate_kv_host(t: HostTensor, kvh: int, r: int) -> HostTensor:
    """Repeat a kv projection's per-head row blocks r times (axis 0, row
    order: virtual head j = real head j//r) — the host-side half of
    models/params.replicate_kv_heads, done before placement so each device
    receives only its virtual head's shard."""

    def rep(a):
        if a is None:
            return None
        per = a.shape[0] // kvh
        return np.repeat(a.reshape(kvh, per, *a.shape[1:]), r,
                         axis=0).reshape(kvh * r * per, *a.shape[1:])

    return HostTensor(t.name, t.ftype, (t.shape[0] * r, *t.shape[1:]),
                      data=rep(t.data), scales=rep(t.scales),
                      packed=rep(t.packed))


def _q40_raw_stack(ts: list[HostTensor]) -> tuple[np.ndarray, np.ndarray]:
    """(packed, scales) in raw block layout for one tensor or an E-stacked
    expert list — the single host-side Q40 pipeline every load path uses."""
    qs = [t if t.ftype == FloatType.Q40 else _to_q40_host(t.to_f32())
          for t in ts]
    packed = np.stack([q.packed for q in qs]) if len(ts) > 1 else qs[0].packed
    scales = np.stack([q.scales for q in qs]) if len(ts) > 1 else qs[0].scales
    return packed, scales


def _q40_host_stack(ts: list[HostTensor]) -> tuple[np.ndarray, np.ndarray]:
    """Like _q40_raw_stack but in the flattened device layout."""
    packed, scales = _q40_raw_stack(ts)
    return QuantizedTensor.host_layout(scales, packed)


def _dense_host_stack(ts: list[HostTensor]) -> np.ndarray:
    return (np.stack([t.to_f32() for t in ts]) if len(ts) > 1
            else ts[0].to_f32())


class _Placer:
    """Converts one host tensor (or fusion group) to device arrays with the
    right NamedSharding, tracking q80-collective col repacking and
    expert-parallel (ep) placement — each device receives only its E/ep
    experts' shards directly, so peak per-device expert memory at load is
    E/(ep*tp), never full-E (the point of placement-EP)."""

    def __init__(self, mesh, mode: str, dtype, tp: int, q80_collectives: bool,
                 ep: int = 1, vocab_axes: tuple | None = None):
        self.mesh = mesh
        self.mode = mode
        self.dtype = dtype
        self.tp = tp
        self.q80 = q80_collectives and tp > 1
        self.ep = ep
        # vocab sharding (ops/sharded_vocab.py): tok_emb/wcls place
        # row-split over these axes AT LOAD — the 70B-scale path must
        # never hold a replicated 524 MB table per device only for the
        # engine to reshard it
        self.vocab_axes = vocab_axes

    def _put(self, x: np.ndarray, pspec):
        if self.mesh is None:
            return jnp.asarray(x)
        return jax.device_put(x, NamedSharding(self.mesh, pspec))

    def dense(self, key: str, x: np.ndarray):
        return self._put(x, _pspec_for(key, x.ndim, False, "dense",
                                       self.vocab_axes))

    def weight(self, key: str, ts: list[HostTensor]):
        """A matmul weight: single tensor, or an E-stacked expert list.
        Applies mode (dense/q40), col repack for q80 collectives, ep
        placement for MoE expert stacks, sharding."""
        moe_ep = self.ep > 1 and key in _MOE_EP_KEYS
        if self.mode != "q40":
            x = _dense_host_stack(ts)
            x = x.astype(np.dtype(self.dtype) if self.dtype != jnp.bfloat16
                         else np.float32)
            if (self.q80 or moe_ep) and key in COL_SPLIT_NAMES:
                n = x.shape[-1]
                xs = x.reshape(*x.shape[:-1], self.tp, n // self.tp)
                xs = np.moveaxis(xs, -2, 0)
                from ..parallel.ep_moe import EpColWeight
                from ..parallel.tp_q80 import TpColWeight

                wrap = EpColWeight if moe_ep else TpColWeight
                arr = self._put(np.ascontiguousarray(xs),
                                _col_stack_pspec(xs.ndim, ep=moe_ep))
                return wrap(
                    arr if self.dtype != jnp.bfloat16
                    else arr.astype(jnp.bfloat16))
            if moe_ep:
                from ..parallel.ep_moe import EpRowWeight

                from ..parallel.ep_moe import ep_row_pspec

                arr = self._put(x, ep_row_pspec(x.ndim))
                return EpRowWeight(
                    arr.astype(self.dtype) if self.dtype == jnp.bfloat16
                    else arr)
            arr = self._put(x, _pspec_for(key, x.ndim, False, "dense",
                                          self.vocab_axes))
            return arr.astype(self.dtype) if self.dtype == jnp.bfloat16 else arr

        packed, scales = _q40_raw_stack(ts)
        if (self.q80 or moe_ep) and key in COL_SPLIT_NAMES:
            return self._col_q40(packed, scales, ep=moe_ep)
        pk, sc = QuantizedTensor.host_layout(scales, packed)
        if moe_ep:
            from ..parallel.ep_moe import EpRowWeight, ep_row_pspec

            return EpRowWeight(QuantizedTensor(
                self._put(pk, ep_row_pspec(pk.ndim)),
                self._put(sc, ep_row_pspec(sc.ndim)),
            ))
        return QuantizedTensor(
            self._put(pk, _pspec_for(key, pk.ndim, True, "packed",
                                     self.vocab_axes)),
            self._put(sc, _pspec_for(key, sc.ndim, True, "scales",
                                     self.vocab_axes)),
        )

    def _col_q40(self, packed: np.ndarray, scales: np.ndarray,
                 ep: bool = False):
        """Host-side block-aligned col repack -> TpColWeight stack (or
        EpColWeight for ep-placed expert stacks), placed shard-per-device
        (no transient full copy on one device — the repack the engine-side
        path cannot avoid, parallel/sharding.py)."""
        from ..parallel.ep_moe import EpColWeight
        from ..parallel.tp_q80 import TpColWeight

        pk_dev, sc_dev = _col_q40_host(packed, scales, self.tp)
        wrap = EpColWeight if ep else TpColWeight
        return wrap(QuantizedTensor(
            self._put(pk_dev, _col_stack_pspec(pk_dev.ndim, ep=ep)),
            self._put(sc_dev, _col_stack_pspec(sc_dev.ndim, ep=ep)),
        ))


def _col_q40_host(packed: np.ndarray, scales: np.ndarray, tp: int
                  ) -> tuple[np.ndarray, np.ndarray]:
    """Raw-layout Q40 (…, nb, 16) -> block-aligned (tp, …, nb/tp…) col stack
    in the flattened device layout (parallel/tp_q80.repack_col_tp semantics,
    host-side)."""
    nb = packed.shape[-2]
    assert nb % tp == 0, (nb, tp)
    lead = packed.shape[:-2]
    pk = np.moveaxis(packed.reshape(*lead, tp, nb // tp, 16), -3, 0)
    sc = np.moveaxis(scales.reshape(*lead, tp, nb // tp), -2, 0)
    return QuantizedTensor.host_layout(
        np.ascontiguousarray(sc), np.ascontiguousarray(pk))


def _col_stack_pspec(ndim: int, ep: bool = False):
    if ep:  # EpColWeight layout — single source in parallel/ep_moe.py
        from ..parallel.ep_moe import ep_col_pspec

        return ep_col_pspec(ndim)
    return P(TP_AXIS, *([None] * (ndim - 1)))


class _PpStacker:
    """Builds stage-stacked PpWeight leaves (parallel/pp.py) one layer
    tensor at a time: a zero-initialized (pp, ...) buffer sharded over pp
    receives each stage's row via a donated dynamic_update_slice, so the
    per-device footprint is the final L/pp share plus one transient host
    tensor — never the full-L restack the engine-side path pays."""

    def __init__(self, mesh, pp: int, tp: int = 1, ep: int = 1):
        self.mesh = mesh
        self.pp = pp
        self.tp = tp
        self.ep = ep

        @functools.partial(jax.jit, donate_argnums=0, static_argnums=3)
        def update(buf, row, stage, sharding):
            row = row.astype(buf.dtype)[None]
            start = (stage,) + (0,) * (buf.ndim - 1)
            out = jax.lax.dynamic_update_slice(buf, row, start)
            return jax.lax.with_sharding_constraint(out, sharding)

        @functools.partial(jax.jit, static_argnums=(0, 1, 2))
        def zeros(shape, dtype, sharding):
            return jax.lax.with_sharding_constraint(
                jnp.zeros(shape, dtype), sharding)

        self._update = update
        self._zeros = zeros  # one jit each — cache hits per distinct shape

    def _row(self, buf, arr: np.ndarray, stage: int, inner_pspec, dtype):
        sh = NamedSharding(self.mesh, P(PP_AXIS, *inner_pspec))
        if buf is None:
            buf = self._zeros((self.pp,) + arr.shape, jnp.dtype(dtype), sh)
        return self._update(buf, jnp.asarray(arr), stage, sh)

    def add(self, slot: dict, key: str, stage: int, mode: str, dtype,
            ts: list[HostTensor], *, keep_f32: bool = False):
        """Fold one layer tensor (or fused/expert-stacked group) into the
        slot's stage-stacked leaf."""
        from ..parallel.ep_moe import (EpColWeight, EpRowWeight, ep_col_pspec,
                                       ep_row_pspec)
        from ..parallel.pp import PpWeight
        from ..parallel.tp_q80 import TpColWeight

        cur = slot.get(key)
        moe_ep = self.ep > 1 and key in _MOE_EP_KEYS
        if mode != "q40" or keep_f32:
            x = _dense_host_stack(ts)
            leaf_dtype = jnp.float32 if keep_f32 else dtype
            if moe_ep and key in COL_SPLIT_NAMES:
                # ep x pp dense moe_down: (tp, E, d, n/tp) col stack per
                # stage — PpWeight(EpColWeight(...)), mirroring _Placer
                n = x.shape[-1]
                xs = np.ascontiguousarray(np.moveaxis(
                    x.reshape(*x.shape[:-1], self.tp, n // self.tp), -2, 0))
                old = cur.w.w if cur is not None else None
                slot[key] = PpWeight(EpColWeight(self._row(
                    old, xs, stage, ep_col_pspec(xs.ndim), leaf_dtype)))
                return
            if moe_ep:
                old = cur.w.w if cur is not None else None
                slot[key] = PpWeight(EpRowWeight(self._row(
                    old, x, stage, ep_row_pspec(x.ndim), leaf_dtype)))
                return
            spec = _pspec_for(key, x.ndim, False, "dense")
            slot[key] = PpWeight(self._row(
                cur.w if cur is not None else None, x, stage, spec,
                leaf_dtype))
            return
        if moe_ep and key in COL_SPLIT_NAMES:
            # ep x pp q40 moe_down: block-aligned (tp, E, d, ...) col
            # stack, stage-stacked — PpWeight(EpColWeight(QuantizedTensor))
            packed, scales = _q40_raw_stack(ts)
            pk, sc = _col_q40_host(packed, scales, self.tp)
            old = cur.w.w if cur is not None else None
            slot[key] = PpWeight(EpColWeight(QuantizedTensor(
                self._row(old.packed if old is not None else None, pk,
                          stage, ep_col_pspec(pk.ndim), pk.dtype),
                self._row(old.scales if old is not None else None, sc,
                          stage, ep_col_pspec(sc.ndim), sc.dtype),
            )))
            return
        if moe_ep:
            # ep x pp q40 moe_up/moe_gate: expert-stacked rows, experts on
            # ep — PpWeight(EpRowWeight(QuantizedTensor))
            pk, sc = _q40_host_stack(ts)
            old = cur.w.w if cur is not None else None
            slot[key] = PpWeight(EpRowWeight(QuantizedTensor(
                self._row(old.packed if old is not None else None, pk,
                          stage, ep_row_pspec(pk.ndim), pk.dtype),
                self._row(old.scales if old is not None else None, sc,
                          stage, ep_row_pspec(sc.ndim), sc.dtype),
            )))
            return
        if key in COL_SPLIT_NAMES and self.tp > 1:
            # pp's fully-manual region slices weights at placement: q40 col
            # shards must be block-aligned TpColWeight stacks, stage-stacked
            # to (pp, tp, ..., d, m/tp) — PpWeight(TpColWeight(...))
            packed, scales = _q40_raw_stack(ts)
            pk, sc = _col_q40_host(packed, scales, self.tp)
            inner = P(TP_AXIS, *([None] * (pk.ndim - 1)))
            old = cur.w.w if cur is not None else None
            slot[key] = PpWeight(TpColWeight(QuantizedTensor(
                self._row(old.packed if old is not None else None, pk,
                          stage, inner, pk.dtype),
                self._row(old.scales if old is not None else None, sc,
                          stage, P(TP_AXIS, *([None] * (sc.ndim - 1))),
                          sc.dtype),
            )))
            return
        pk, sc = _q40_host_stack(ts)
        old = cur.w if cur is not None else None
        slot[key] = PpWeight(QuantizedTensor(
            self._row(old.packed if old is not None else None, pk, stage,
                      _pspec_for(key, pk.ndim, True, "packed"), pk.dtype),
            self._row(old.scales if old is not None else None, sc, stage,
                      _pspec_for(key, sc.ndim, True, "scales"), sc.dtype),
        ))


def _fuse_group(key: str) -> str | None:
    """Which single-shard fusion group a leaf belongs to (models/params.py:
    fuse_layer_weights semantics, streamed)."""
    if key in ("wq", "wk", "wv"):
        return "wqkv"
    if key in ("w1", "w3"):
        return "w13"
    return None


def _concat_host(ts: list[HostTensor], mode: str) -> list[HostTensor]:
    """Concatenate a fusion group along the output dim on the host."""
    if mode == "q40":
        qs = [t if t.ftype == FloatType.Q40 else _to_q40_host(t.to_f32())
              for t in ts]
        return [HostTensor("", FloatType.Q40,
                           (sum(t.shape[0] for t in ts), ts[0].shape[1]),
                           scales=np.concatenate([q.scales for q in qs]),
                           packed=np.concatenate([q.packed for q in qs]))]
    x = np.concatenate([t.to_f32() for t in ts], axis=0)
    return [HostTensor("", FloatType.F32, x.shape, data=x)]


def load_params_streamed(
    spec: ModelSpec,
    path: str | None,
    mesh=None,
    *,
    mode: str = "q40",
    dtype=jnp.bfloat16,
    q80_collectives: bool = False,
    fuse: bool | None = None,
    tensors=None,
    shard_vocab: bool | None = None,
) -> tuple[dict, LoadStats]:
    """Stream the `.m` file into a final, placed params pytree.

    fuse defaults to tp == 1 (matching Engine's single-shard fast path).
    Returns (params, LoadStats) — peak_host_bytes is the loader's measured
    high-water mark of resident file-tensor bytes.

    tensors: optional HostTensor iterator replacing the file read — the
    multihost root-push path feeds parallel.multihost.bcast_model_tensors
    here so a worker WITHOUT the `.m` places shards straight from the
    root's broadcast (path may then be None on workers).
    """
    assert mode in ("dense", "q40")
    tp = mesh.shape.get(TP_AXIS, 1) if mesh is not None else 1
    ep = mesh.shape.get(EP_AXIS, 1) if mesh is not None else 1
    pp = mesh.shape.get(PP_AXIS, 1) if mesh is not None else 1
    kv_rep = 1
    if tp > spec.n_kv_heads:
        # tp beyond the kv-head count: wk/wv rows replicate host-side into
        # tp virtual heads BEFORE placement, so each device still receives
        # exactly its shard (models/params.kv_replication)
        from .params import kv_replication

        kv_rep = kv_replication(spec, tp)
    if fuse is None:
        fuse = tp == 1
    if pp > 1:
        assert spec.n_layers % pp == 0, (spec.n_layers, pp)
        assert not q80_collectives, (
            "pp loading uses exact reduces (matching Engine)")
    n_slot = spec.n_layers // pp
    # vocab sharding (ops/sharded_vocab.py): place tok_emb/wcls row-split
    # at load — same auto rule as the Engine, so the arrays arrive in the
    # layout shard_params expects and nothing reshards (a replicated 70B
    # table would otherwise cost 524 MB on EVERY device just to be thrown
    # away). shard_vocab=False pins the replicated parity placement.
    from ..ops.sharded_vocab import vocab_shard_axes

    vocab_axes: tuple | None = None
    if shard_vocab is not False:
        vocab_axes = vocab_shard_axes(mesh, spec.vocab_size) or None
        if shard_vocab and vocab_axes is None:
            raise ValueError(
                f"shard_vocab: mesh tp axes cannot split vocab="
                f"{spec.vocab_size} evenly")
    placer = _Placer(mesh, mode, dtype, tp, q80_collectives, ep=ep,
                     vocab_axes=vocab_axes)
    pp_stack = _PpStacker(mesh, pp, tp=tp, ep=ep) if pp > 1 else None

    p: dict = {"layers": [dict() for _ in range(n_slot if pp > 1
                                                else spec.n_layers)]}
    pending: dict[str, list[HostTensor]] = {}
    peak = 0
    total = 0
    live = 0

    def target(plan_name: str):
        """(dest dict, stage) — stage is None for non-layer tensors; under
        pp layer l maps to slot l % n_slot at stage l // n_slot."""
        parts = plan_name.split(".")
        if parts[0] != "layers":
            return p, None
        l = int(parts[1])
        if pp > 1:
            return p["layers"][l % n_slot], l // n_slot
        return p["layers"][l], None

    if tensors is None:
        tensors = iter_model_tensors(path, spec)
    for t in tensors:
        key = _leaf_key(t.name)
        if kv_rep > 1 and key in ("wk", "wv"):
            # replicate BEFORE accounting so live/peak measure the r-fold
            # bytes actually resident during placement
            t = _replicate_kv_host(t, spec.n_kv_heads, kv_rep)
        b = _host_bytes(t)
        total += b
        live += b
        peak = max(peak, live)
        dest, stage = target(t.name)
        group = _fuse_group(key) if fuse else None

        if group is not None:
            gk = f"{t.name.rsplit('.', 1)[0]}.{group}"
            pending.setdefault(gk, []).append(t)
            want = 3 if group == "wqkv" else 2
            if len(pending[gk]) == want:
                ts = pending.pop(gk)
                cts = _concat_host(ts, mode)
                if stage is not None:
                    pp_stack.add(dest, group, stage, mode, dtype, cts)
                else:
                    dest[group] = placer.weight(group, cts)
                live -= sum(_host_bytes(x) for x in ts)
            continue

        if key.startswith("moe_") and key != "moe_router":
            # experts stream in (up, gate, down) x E order; stack per role
            gk = f"{t.name.rsplit('.', 2)[0]}.{key}"
            pending.setdefault(gk, []).append(t)
            if len(pending[gk]) == spec.n_experts:
                ts = pending.pop(gk)
                if stage is not None:
                    pp_stack.add(dest, key, stage, mode, dtype, ts)
                else:
                    dest[key] = placer.weight(key, ts)
                live -= sum(_host_bytes(x) for x in ts)
            continue

        if key in ("rms_att", "rms_ffn", "rms_moe", "rms_ffn2", "rms_final"):
            if stage is not None:  # per-layer norms stack too, kept f32
                pp_stack.add(dest, key, stage, "dense", dtype, [t],
                             keep_f32=True)
            else:
                dest[key] = placer.dense(key, t.to_f32())  # norms stay f32
        elif key in ("tok_emb", "moe_router"):
            if stage is not None:  # moe_router is a per-layer dense leaf
                pp_stack.add(dest, key, stage, "dense", dtype, [t])
            else:
                arr = placer.dense(key, t.to_f32())
                dest[key] = arr.astype(dtype) if dtype != jnp.float32 else arr
        else:
            if stage is not None:
                pp_stack.add(dest, key, stage, mode, dtype, [t])
            else:
                dest[key] = placer.weight(key, [t])
        live -= b

    assert not pending, f"incomplete fusion groups: {list(pending)}"
    return p, LoadStats(peak_host_bytes=peak, total_bytes=total)
