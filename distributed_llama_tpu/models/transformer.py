"""Unified transformer forward for LLAMA / MIXTRAL / GROK1.

One jittable segment-forward covers both prefill (T tokens at once — net-new
vs the reference, which feeds the prompt token-by-token) and decode (T=1).
The per-layer dataflow reproduces the reference task pipelines:

  * LLAMA dense block  — ref: src/llama2-tasks.cpp:249-275
  * MIXTRAL MoE block  — ref: src/mixtral-tasks.cpp:5-51
  * GROK1 extra norms, input/logit scalings — ref: src/grok1-tasks.cpp:11-41,
    244-272, 274-326

but the reference's broadcast/gather/merge sync tasks vanish: the row/col
weight sharding is expressed as PartitionSpecs (parallel/sharding.py) and
GSPMD inserts the equivalent ICI collectives.

Layers are statically unrolled — the TPU analogue of the reference's flat
per-layer task list (ref: src/tasks.hpp:27-37). An earlier `lax.scan` over
stacked (L, ...) weights/cache was profiled at ~3x the decode cost of the
actual math: every scan step dynamic-sliced the layer's KV cache out of the
stacked array and back in (two 16 MB copies per layer per token at 7B), and
copied+re-laid-out the packed weights before each Pallas call. Unrolling
makes each layer's weights and cache standalone buffers: weights feed the
kernel in place, and the per-layer cache arrays are donated and updated
in place via dynamic_update_slice (the functional form of the reference's
in-place cache write at src/llama2-tasks.cpp:38-44).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.activations import apply_hidden_act
from ..ops.attention import decode_attention
from ..ops.matmul import matmul
from ..ops.norms import rmsnorm
from ..ops.rope import apply_rope
from ..quants.jax_codec import QuantizedTensor
from .spec import ArchType, ModelSpec

GROK_INPUT_SCALE = 78.38367176906169      # ref: src/grok1-tasks.cpp:13
GROK_LOGIT_SCALE = 0.5773502691896257     # ref: src/grok1-tasks.cpp:271


def _flash_ok(t: int, h: int, kvh: int) -> bool:
    from ..ops.pallas_attention import flash_supported

    return flash_supported(t, h, kvh)


class KVCache(NamedTuple):
    """Per-layer KV cache: tuples of L arrays, each (B, KVH, S, hs).

    Separate per-layer buffers (not one stacked (L, ...) array) so that a
    donated cache is updated strictly in place — profiling showed XLA copies
    stacked caches wholesale through scan/while carries. Head-major (KVH
    before S) so decode attention reads each head's keys sequentially;
    with S-major XLA picked a head-minor layout that ran the per-layer
    score contraction at ~75 GB/s instead of ~600."""

    k: tuple
    v: tuple

    @classmethod
    def create(cls, spec: ModelSpec, batch: int, seq_len: int | None = None,
               dtype=jnp.float32, pp: int = 1) -> "KVCache":
        """pp > 1: stage-stacked layout — n_layers/pp leaves of
        (pp, B, KVH, S, hs), the stage axis sharded over pp so each device
        stores only its own layers' cache (parallel/pp.py)."""
        s = seq_len or spec.seq_len
        shape = (batch, spec.n_kv_heads, s, spec.head_size)
        n = spec.n_layers
        if pp > 1:
            assert n % pp == 0, (n, pp)
            shape = (pp,) + shape
            n = n // pp
        return cls(
            tuple(jnp.zeros(shape, dtype) for _ in range(n)),
            tuple(jnp.zeros(shape, dtype) for _ in range(n)),
        )


def _to_cache_dtype(x, dtype):
    """Cast k/v to the cache dtype; sub-bf16 caches (fp8 e4m3) saturate at
    the format's max first — the jax cast is non-saturating and |v| > 448
    would become NaN, permanently poisoning every later attention read
    (read-side counterpart: ops/attention.is_narrow_cache)."""
    from ..ops.attention import is_narrow_cache

    if is_narrow_cache(dtype):
        lim = float(jnp.finfo(dtype).max)
        x = jnp.clip(x, -lim, lim)
    return x.astype(dtype)


def _scatter_cache_write(k_cache, v_cache, k, v, idx, write_gate):
    """Drop-mode scatter of (B, T, KVH, hs) K/V at per-position indices
    (B, T) into (B, KVH, S, hs) caches. write_gate (traced bool) pushes
    gated-off writes to the out-of-bounds slot S, which scatter drops —
    shared by the batched per-row write path and the manual-sp chunk-local
    write path so the OOB-gating idiom cannot diverge."""
    oob = k_cache.shape[2]
    if write_gate is not None:
        idx = jnp.where(write_gate, idx, oob)
    bidx = jnp.arange(k_cache.shape[0], dtype=jnp.int32)[:, None]
    k_cache = k_cache.at[bidx, :, idx].set(
        _to_cache_dtype(k, k_cache.dtype), mode="drop")
    v_cache = v_cache.at[bidx, :, idx].set(
        _to_cache_dtype(v, v_cache.dtype), mode="drop")
    return k_cache, v_cache


def _attention_block(x, lw, spec: ModelSpec, k_cache, v_cache, q_pos, cfg,
                     sp_mesh=None, sp_cache_mesh=None, per_row_pos=False,
                     write_gate=None):
    """Norm -> QKV -> RoPE -> cache update -> attention -> output proj.

    Returns (attn_out, new_k_cache, new_v_cache). attn_out is the wo
    projection NOT yet added to the residual (archs differ there).
    write_gate: optional traced bool — when False the cache update re-writes
    the existing values (pipeline parallelism runs every stage's layers on
    every device each iteration, but only the live stage may write its
    cache — parallel/pp.py).
    """
    b, t, d = x.shape
    h, kvh, hs = spec.n_heads, spec.n_kv_heads, spec.head_size
    f = cfg.get("manual_tp") or 1
    if f > 1:
        # fully-manual pp region: this shard computes h/tp query heads and
        # kvh/tp kv heads (row-split projections, head-sharded cache) — the
        # same per-shard shapes tp_q80's shard_map bodies see. RoPE and
        # attention are per-head, so only the reshape bookkeeping changes.
        h, kvh = h // f, kvh // f

    xb = rmsnorm(x, lw["rms_att"])  # ref: llama2-tasks.cpp:10-21
    if "wqkv" in lw:
        # fused QKV projection (single-shard path): one kernel call, one
        # shared activation prep, deeper DMA pipeline
        qkv = matmul(xb, lw["wqkv"], **cfg)
        q = qkv[..., : h * hs].reshape(b, t, h, hs)
        k = qkv[..., h * hs: (h + kvh) * hs].reshape(b, t, kvh, hs)
        v = qkv[..., (h + kvh) * hs:].reshape(b, t, kvh, hs)
    else:
        q = matmul(xb, lw["wq"], **cfg).reshape(b, t, h, hs)
        k = matmul(xb, lw["wk"], **cfg).reshape(b, t, kvh, hs)
        v = matmul(xb, lw["wv"], **cfg).reshape(b, t, kvh, hs)

    q = apply_rope(q, q_pos, spec.rope_theta, spec.arch)
    k = apply_rope(k, q_pos, spec.rope_theta, spec.arch)

    # functional cache update at positions q_pos (contiguous per row:
    # pos[b]..pos[b]+T); cache is head-major (B, KVH, S, hs) — see KVCache
    sp_n = cfg.get("manual_sp") or 1
    if sp_n > 1:
        # fully-manual pp region with an sp-sharded cache: this device
        # holds the S/sp chunk starting at sp_index * s_local. Writes go
        # through a per-position scatter at chunk-LOCAL indices; positions
        # owned by other devices (and bubble-step writes, write_gate) are
        # pushed to the OOB slot — scatter drops them. Negative local
        # indices would WRAP, not drop, so they are clamped to OOB first.
        from ..parallel.mesh import SP_AXIS as _SP
        from ..parallel.ring_attention import sp_cache_attention_local

        s_local = k_cache.shape[2]
        local = q_pos - lax.axis_index(_SP) * s_local
        local = jnp.where(local < 0, s_local, local)
        k_cache, v_cache = _scatter_cache_write(k_cache, v_cache, k, v,
                                                local, write_gate)
        att = sp_cache_attention_local(q, k_cache, v_cache, q_pos)
        out = matmul(att.reshape(b, t, h * hs), lw["wo"], **cfg)
        return out, k_cache, v_cache
    if per_row_pos:
        # batched generation: each sequence writes at its own position
        # (net-new vs the reference's batch=1 — SURVEY.md §2.5 DP row).
        # Gated (pp off-turn) writes are pushed out of bounds and dropped
        # by the scatter — cheaper than a read-modify-write, and XLA's
        # partitioner handles the scatter where it miscompiles the
        # equivalent gather under manual pp.
        k_cache, v_cache = _scatter_cache_write(k_cache, v_cache, k, v,
                                                q_pos, write_gate)
    else:
        pos0 = q_pos[:, 0]
        k_w = _to_cache_dtype(k.transpose(0, 2, 1, 3), k_cache.dtype)
        v_w = _to_cache_dtype(v.transpose(0, 2, 1, 3), v_cache.dtype)
        # index literals pinned to the position dtype: bare Python 0s trace
        # as int64 under x64 and dynamic_(update_)slice rejects mixed index
        # dtypes — int32 everywhere keeps the program x64-proof (dlgrind
        # DLG202 traces entry points under enable_x64)
        zero = jnp.int32(0)
        start = (zero, zero, pos0[0], zero)
        if write_gate is not None:
            k_w = jnp.where(write_gate, k_w,
                            lax.dynamic_slice(k_cache, start, k_w.shape))
            v_w = jnp.where(write_gate, v_w,
                            lax.dynamic_slice(v_cache, start, v_w.shape))
        k_cache = lax.dynamic_update_slice(k_cache, k_w, start)
        v_cache = lax.dynamic_update_slice(v_cache, v_w, start)
    if sp_cache_mesh is not None:
        # keep the cache sp-sharded through the functional update: during ring
        # prefill the T-sharded K/V reshards into the S-sharded cache (one
        # K/V-sized shuffle per layer); decode's single-position write lands
        # in the owning shard. Per-device cache stays seq_len/sp.
        from jax.sharding import NamedSharding

        from ..parallel.sharding import cache_pspec

        cs = NamedSharding(sp_cache_mesh, cache_pspec(sp=True))
        k_cache = jax.lax.with_sharding_constraint(k_cache, cs)
        v_cache = jax.lax.with_sharding_constraint(v_cache, cs)

    if sp_mesh is not None:
        # sequence-parallel prefill: the segment starts at pos 0 and IS the
        # whole context so far, so attention runs q-chunk vs ring-rotating
        # k/v chunks instead of against the cache (net-new vs the reference —
        # SURVEY.md §5.7)
        from ..parallel.ring_attention import ring_attention

        att = ring_attention(q, k, v, sp_mesh, pos0=0)
    elif sp_cache_mesh is not None:
        # sp-sharded cache: per-chunk flash stats + exact psum merge. Must
        # outrank the pallas branch — the pallas kernel is not shard_map'd,
        # so routing it an sp-sharded cache would all-gather the full
        # sequence per layer and void the seq_len/sp memory scaling.
        from ..parallel.ring_attention import sp_cache_attention

        att = sp_cache_attention(q, k_cache, v_cache, q_pos, sp_cache_mesh)
    elif cfg.get("use_pallas") and _flash_ok(t, h, kvh):
        # decode (T=1) and chunked prefill (T>1) both take the flash kernel:
        # online-softmax in VMEM instead of the dense path's (B,T,KVH,G,S)
        # score materialization in HBM (ops/pallas_attention.py)
        if cfg.get("manual_tp"):
            # already inside the fully-manual pp region: heads are local,
            # call the kernel directly (no shard_map entry)
            from ..ops.pallas_attention import flash_attention

            att = flash_attention(
                q, k_cache, v_cache, q_pos,
                interpret=cfg.get("pallas_interpret", False))
        elif cfg.get("tp_mesh") is not None:
            # multi-device mesh: GSPMD can't partition a pallas_call, so the
            # kernel runs per-shard inside shard_map (dp on batch, tp on
            # kv-heads — head-local, no collective)
            from ..parallel.tp_q80 import tp_flash_attention

            att = tp_flash_attention(
                q, k_cache, v_cache, q_pos, cfg["tp_mesh"],
                interpret=cfg.get("pallas_interpret", False))
        else:
            from ..ops.pallas_attention import flash_attention

            att = flash_attention(
                q, k_cache, v_cache, q_pos,
                interpret=cfg.get("pallas_interpret", False))
    else:
        att = decode_attention(q, k_cache, v_cache, q_pos)  # (B, T, H, hs)
    out = matmul(att.reshape(b, t, h * hs), lw["wo"], **cfg)
    return out, k_cache, v_cache


def _dense_ffn(xb, lw, spec: ModelSpec, cfg):
    """SwiGLU FFN (ref: src/llama2-tasks.cpp:158-189)."""
    if "w13" in lw:
        h13 = matmul(xb, lw["w13"], **cfg)  # fused gate|up (single-shard path)
        hd = h13.shape[-1] // 2
        gate, up = h13[..., :hd], h13[..., hd:]
    else:
        gate = matmul(xb, lw["w1"], **cfg)
        up = matmul(xb, lw["w3"], **cfg)
    hb = apply_hidden_act(gate, spec.hidden_act) * up
    return matmul(hb, lw["w2"], **cfg)


def _moe_ffn(xb, lw, spec: ModelSpec, cfg):
    """Top-k routed expert FFN (ref: src/grok1-tasks.cpp:56-227).

    Router/top-k runs replicated (the reference runs it root-only and
    broadcasts — ref: grok1-tasks.cpp:121-126). Decode (T==1) gathers only
    the active experts' weights; prefill computes all experts densely and
    masks — both compile to static shapes.
    """
    b, t, d = xb.shape
    k_active = spec.n_active_experts

    router_logits = matmul(xb, lw["moe_router"], **cfg)  # (B, T, E)
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    top_p, top_idx = lax.top_k(probs, k_active)           # (B, T, K)
    weights = top_p / top_p.sum(axis=-1, keepdims=True)   # ref: grok1-tasks.cpp:99-114

    def scatter_weights():
        # (B, T, E) dense scatter of the normalized top-k weights (0 for
        # inactive experts) — shared by the ep and dense-prefill paths
        return jnp.zeros_like(probs).at[
            jnp.arange(b)[:, None, None],
            jnp.arange(t)[None, :, None],
            top_idx,
        ].set(weights)

    from ..parallel.ep_moe import EpRowWeight, ep_moe_ffn

    if isinstance(lw["moe_up"], EpRowWeight):
        # expert-parallel placement (ep mesh axis): each ep shard computes
        # only its local experts, masked by the scattered routing weights
        e_weights = scatter_weights()
        if cfg.get("manual_tp"):
            # already inside a fully-manual region (pp — parallel/pp.py):
            # shard_map cannot nest, so the ep body runs directly with the
            # region's manual (ep, tp) axes
            from ..parallel.ep_moe import _ep_body

            return _ep_body(
                xb, e_weights, lw["moe_up"].w, lw["moe_gate"].w,
                lw["moe_down"].w,
                ep=cfg.get("manual_ep") or 1, tp=cfg["manual_tp"],
                act_fn=lambda g: apply_hidden_act(g, spec.hidden_act),
                compute_dtype=cfg["compute_dtype"],
                use_pallas=cfg.get("use_pallas", False),
                interpret=cfg.get("pallas_interpret", False),
                reduce=cfg.get("tp_reduce", "exact"),
            ).astype(xb.dtype)
        return ep_moe_ffn(
            xb, e_weights, lw, cfg["tp_mesh"],
            act_fn=lambda g: apply_hidden_act(g, spec.hidden_act),
            compute_dtype=cfg["compute_dtype"],
            use_pallas=cfg.get("use_pallas", False),
            interpret=cfg.get("pallas_interpret", False),
            reduce=cfg.get("tp_reduce", "exact"),
        ).astype(xb.dtype)

    def expert_apply(w_up, w_gate, w_down, x_tok):
        gate = matmul(x_tok, w_gate, **cfg)
        up = matmul(x_tok, w_up, **cfg)
        hb = apply_hidden_act(gate, spec.hidden_act) * up
        return matmul(hb, w_down, **cfg)

    if t == 1 and b == 1:
        # decode: gather only the K active experts' weights (the reference
        # likewise computes just the active experts — grok1-tasks.cpp:128-143)
        from ..ops.matmul import fused_expert_matmul

        idx = top_idx.reshape(k_active)
        acc = jnp.zeros((b, t, d), xb.dtype)
        for ae in range(k_active):  # K is tiny and static — unrolled
            e = idx[ae]
            # expert-indexed fused kernel when eligible: the kernel reads the
            # active expert's packed bytes in place instead of paying a
            # dynamic-slice HBM copy per expert per layer (pallas_q40.py)
            out = None
            gate = fused_expert_matmul(xb, lw["moe_gate"], e, **cfg)
            up = (fused_expert_matmul(xb, lw["moe_up"], e, **cfg)
                  if gate is not None else None)
            if gate is not None and up is not None:
                hb = apply_hidden_act(gate, spec.hidden_act) * up
                out = fused_expert_matmul(hb, lw["moe_down"], e, **cfg)
            if out is None:
                out = expert_apply(
                    _take_expert(lw["moe_up"], e),
                    _take_expert(lw["moe_gate"], e),
                    _take_expert(lw["moe_down"], e),
                    xb,
                )
            acc = acc + weights[..., ae, None].astype(out.dtype) * out
        return acc

    # prefill: dense all-expert compute, mask by routing weights
    e_weights = scatter_weights()

    def all_experts(e, acc):
        up_e = _take_expert(lw["moe_up"], e)
        gate_e = _take_expert(lw["moe_gate"], e)
        down_e = _take_expert(lw["moe_down"], e)
        out = expert_apply(up_e, gate_e, down_e, xb)
        return acc + e_weights[..., e, None].astype(out.dtype) * out

    acc = jnp.zeros((b, t, d), xb.dtype)
    for e in range(spec.n_experts):
        acc = all_experts(e, acc)
    return acc


def _take_expert(w, e):
    """Select expert e from a stacked (E, ...) weight (dense or Q40; for
    TpColWeight the expert axis sits behind the tp stack axis)."""
    from ..parallel.tp_q80 import TpColWeight, TpRowWeight, take_expert_col

    if isinstance(w, TpColWeight):
        return take_expert_col(w, e)
    if isinstance(w, TpRowWeight):
        return TpRowWeight(_take_expert(w.w, e))
    if isinstance(w, QuantizedTensor):
        return QuantizedTensor(
            lax.dynamic_index_in_dim(w.packed, e, axis=0, keepdims=False),
            lax.dynamic_index_in_dim(w.scales, e, axis=0, keepdims=False),
        )
    return lax.dynamic_index_in_dim(w, e, axis=0, keepdims=False)


def _layer(x, lw, spec: ModelSpec, k_cache, v_cache, q_pos, cfg, sp_mesh=None,
           sp_cache_mesh=None, per_row_pos=False, write_gate=None):
    attn_out, k_cache, v_cache = _attention_block(
        x, lw, spec, k_cache, v_cache, q_pos, cfg, sp_mesh=sp_mesh,
        sp_cache_mesh=sp_cache_mesh, per_row_pos=per_row_pos,
        write_gate=write_gate)

    if spec.arch == ArchType.GROK1:
        # post-attention norm BEFORE residual add (ref: grok1-tasks.cpp:16-41)
        x = x + rmsnorm(attn_out, lw["rms_ffn"]).astype(x.dtype)
        xb = rmsnorm(x, lw["rms_moe"])          # ref: grok1-tasks.cpp:43-54
        moe_out = _moe_ffn(xb, lw, spec, cfg)
        moe_out = rmsnorm(moe_out, lw["rms_ffn2"])  # ref: grok1-tasks.cpp:244-256
        x = x + moe_out.astype(x.dtype)
    elif spec.arch == ArchType.MIXTRAL:
        x = x + attn_out.astype(x.dtype)        # ref: mixtral-tasks.cpp:24
        xb = rmsnorm(x, lw["rms_ffn"])
        x = x + _moe_ffn(xb, lw, spec, cfg).astype(x.dtype)
    else:
        x = x + attn_out.astype(x.dtype)        # ref: llama2-tasks.cpp:125-131
        xb = rmsnorm(x, lw["rms_ffn"])
        x = x + _dense_ffn(xb, lw, spec, cfg).astype(x.dtype)
    return x, k_cache, v_cache


def forward(
    params: dict,
    spec: ModelSpec,
    tokens: jnp.ndarray,   # (B, T) int32
    pos0: jnp.ndarray,     # int32 first absolute position of the segment —
                           # scalar (shared) or (B,) per-sequence (batched
                           # generation with ragged prompt lengths)
    cache: KVCache,
    *,
    activation_q80: bool = False,
    compute_dtype=jnp.float32,
    logits_for_all: bool = False,
    use_pallas: bool = False,
    sp_mesh=None,
    tp_mesh=None,
    tp_reduce: str = "exact",
    pallas_interpret: bool = False,
    sp_cache_mesh=None,
    pp_mesh=None,
    pp_gpipe: bool = True,
    logit_index=None,
    vocab_mesh=None,
    vocab_axes: tuple = ("tp",),
) -> tuple[jnp.ndarray, KVCache]:
    """Run T tokens through the model; returns (logits, updated cache).

    logits: (B, vocab) for the last token (or position `logit_index` if
    given — scalar or (B,) per-sequence, used when the segment is
    right-padded), or (B, T, vocab) if logits_for_all.
    sp_mesh: a Mesh whose sp axis shards this segment's sequence — enables the
    ring-attention prefill path (segment must start at pos 0).
    tp_mesh: a Mesh for the explicit shard_map TP paths (weights marked as
    TpRowWeight/TpColWeight; Pallas kernels per shard, col partial sums
    reduced per tp_reduce — see parallel/tp_q80.py).
    sp_cache_mesh: a Mesh whose sp axis shards the KV cache's sequence dim
    (cache_pspec(sp=True)) — cache writes keep that sharding and attention
    reads it chunk-wise (parallel/ring_attention.py:sp_cache_attention).
    pp_mesh: a Mesh whose pp axis places the layers in stages — params
    "layers" must be stage-stacked (parallel/pp.py:stack_stages) and the
    cache stage-stacked (KVCache.create(pp=...)).
    vocab_mesh: a Mesh whose `vocab_axes` row-split the embedding table's
    vocab dim (ops/sharded_vocab.py) — the lookup becomes a masked local
    gather + all-reduce, bit-identical to the replicated gather (zeros +
    one real contribution add exactly). The head (wcls) is row-split by
    its PartitionSpec independently of this knob.
    """
    cfg = dict(activation_q80=activation_q80, compute_dtype=compute_dtype,
               use_pallas=use_pallas, tp_mesh=tp_mesh, tp_reduce=tp_reduce,
               pallas_interpret=pallas_interpret)
    b, t = tokens.shape

    if vocab_mesh is not None:
        from ..ops.sharded_vocab import embed_tokens_sharded

        x = embed_tokens_sharded(params["tok_emb"], tokens, vocab_mesh,
                                 tuple(vocab_axes), compute_dtype)
    else:
        x = params["tok_emb"][tokens].astype(compute_dtype)  # ref: tasks.cpp:202-203
    if spec.arch == ArchType.GROK1:
        x = x * GROK_INPUT_SCALE

    per_row_pos = getattr(pos0, "ndim", 0) == 1
    if per_row_pos:
        q_pos = pos0[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    else:
        q_pos = pos0 + jnp.arange(t, dtype=jnp.int32)[None, :]
        q_pos = jnp.broadcast_to(q_pos, (b, t))

    if pp_mesh is not None:
        # layers placed in stages over pp (parallel/pp.py): long segments
        # (prefill chunks) take the GPipe sequence-microbatch schedule —
        # flop-bound, wall ~ 1/pp of the all-stages scheme; decode/verify
        # segments (weight-read-bound) keep all-stages
        from ..parallel.pp import (gpipe_microbatches, pp_layers,
                                   pp_layers_gpipe)

        n_mb = (gpipe_microbatches(t, pp_mesh.shape["pp"])
                if pp_gpipe else 1)
        if n_mb > 1:
            x, k_all, v_all = pp_layers_gpipe(
                x, params["layers"], spec, cache, q_pos, cfg, pp_mesh,
                n_mb, per_row_pos=per_row_pos)
        else:
            x, k_all, v_all = pp_layers(x, params["layers"], spec, cache,
                                        q_pos, cfg, pp_mesh,
                                        per_row_pos=per_row_pos)
        k_all, v_all = list(k_all), list(v_all)
    else:
        # statically unrolled layer loop (see module docstring for why not
        # scan)
        k_all = []
        v_all = []
        for l in range(spec.n_layers):
            x, k_new, v_new = _layer(x, params["layers"][l], spec,
                                     cache.k[l], cache.v[l], q_pos, cfg,
                                     sp_mesh=sp_mesh,
                                     sp_cache_mesh=sp_cache_mesh,
                                     per_row_pos=per_row_pos)
            k_all.append(k_new)
            v_all.append(v_new)

    x = rmsnorm(x, params["rms_final"])  # ref: llama2-tasks.cpp:222-234
    if not logits_for_all:
        if logit_index is None:
            x = x[:, -1, :]
        else:
            x = jnp.take_along_axis(
                x, jnp.broadcast_to(logit_index.reshape(-1, 1, 1),
                                    (x.shape[0], 1, x.shape[-1])), axis=1)[:, 0]
    logits = matmul(x, params["wcls"], **cfg).astype(jnp.float32)
    if spec.arch == ArchType.GROK1:
        logits = logits * GROK_LOGIT_SCALE  # ref: grok1-tasks.cpp:269-272
    return logits, KVCache(tuple(k_all), tuple(v_all))
