from .spec import ArchType, HiddenAct, ModelSpec

__all__ = ["ArchType", "HiddenAct", "ModelSpec"]
