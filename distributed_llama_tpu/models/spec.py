"""Model hyperparameter spec.

TPU-native analogue of TransformerSpec (ref: src/transformer.hpp:82-104).
Values and enum encodings are file-compatible with the reference `.m` header
(ref: src/transformer.hpp:42-80).
"""

from __future__ import annotations

import dataclasses
import enum

from ..quants.types import FloatType


class ArchType(enum.IntEnum):
    """ref: src/transformer.hpp:71-75 (values double as legacy file magics)."""

    LLAMA = 0xABCD00
    GROK1 = 0xABCD01
    MIXTRAL = 0xABCD02


class HiddenAct(enum.IntEnum):
    """ref: src/transformer.hpp:77-80."""

    GELU = 0
    SILU = 1


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    arch: ArchType
    dim: int
    hidden_dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    vocab_size: int
    seq_len: int
    hidden_act: HiddenAct = HiddenAct.SILU
    rope_theta: float = 10000.0
    n_experts: int = 0
    n_active_experts: int = 0
    weights_float_type: FloatType = FloatType.F32
    version: int = 0

    @property
    def head_size(self) -> int:
        # ref: src/transformer.cpp:248
        return self.dim // self.n_heads

    @property
    def kv_dim(self) -> int:
        # ref: src/transformer.cpp:249
        return (self.dim * self.n_kv_heads) // self.n_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    def validate(self) -> None:
        assert self.dim % self.n_heads == 0
        assert (self.dim * self.n_kv_heads) % self.n_heads == 0
        if self.arch in (ArchType.GROK1, ArchType.MIXTRAL):
            # MoE archs without experts would fail deep inside the forward
            # (missing moe_router); reject at spec level instead
            assert self.is_moe, f"{self.arch.name} requires n_experts > 0"
        if self.is_moe:
            assert 0 < self.n_active_experts <= self.n_experts
