"""Model parameters as a JAX pytree.

Weights are stacked over layers (leading L axis) so the forward pass can
`lax.scan` over layers — one compiled layer body instead of the reference's
flat per-layer task list (ref: src/llama2-tasks.cpp:249-275).

Two storage modes:
  * dense  — weights dequantized to `dtype` (bf16 on TPU) at load
  * q40    — weights kept as packed QuantizedTensor in HBM (4.5 bits/weight),
             dequantized inside the consuming matmul (ref keeps Q40 in RAM
             and fuses dequant into the kernel: src/funcs.cpp:286-385)

Unsliced tensors (embeddings, norms, wcls, MoE router) mirror the reference's
root-only tensors (ref: src/transformer.cpp:639-673) by being replicated
across the mesh.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import numpy as np

from ..io.model_file import HostTensor, model_tensor_plan
from ..quants.jax_codec import QuantizedTensor
from ..quants.numpy_codec import quantize_q40
from ..quants.types import FloatType
from .spec import ArchType, ModelSpec


def _stack_q40(tensors: list[HostTensor]) -> QuantizedTensor:
    packed = np.stack([t.packed for t in tensors])
    scales = np.stack([t.scales for t in tensors])
    return QuantizedTensor.from_numpy(scales, packed)


def _to_q40_host(x: np.ndarray) -> HostTensor:
    scales, packed = quantize_q40(x.reshape(-1, x.shape[-1]))
    t = HostTensor("", FloatType.Q40, x.shape, scales=scales, packed=packed)
    return t


def load_params(
    spec: ModelSpec,
    tensors: dict[str, HostTensor],
    mode: str = "dense",
    dtype=jnp.float32,
    put: Callable | None = None,
) -> dict:
    """Build the params pytree from file tensors.

    `put` optionally maps (name, np/QuantizedTensor host arrays) -> device
    arrays with a sharding (used by parallel.loader for sharded placement);
    defaults to plain jnp.asarray.
    """
    assert mode in ("dense", "q40")
    dev = put or (lambda name, x: x if isinstance(x, QuantizedTensor) else jnp.asarray(x))

    def weight(names: list[str], shape_hint: str):
        """Stack per-layer (or per-layer-per-expert) matmul weights."""
        ts = [tensors[n] for n in names]
        if mode == "q40":
            qs = []
            for t in ts:
                if t.ftype == FloatType.Q40:
                    qs.append(t)
                else:
                    qs.append(_to_q40_host(t.to_f32()))
            packed = np.stack([q.packed for q in qs])
            scales = np.stack([q.scales for q in qs])
            return dev(shape_hint, QuantizedTensor.from_numpy(scales, packed))
        dense = np.stack([t.to_f32() for t in ts]).astype(dtype)
        return dev(shape_hint, dense)

    L = spec.n_layers
    p: dict = {}
    p["tok_emb"] = dev("tok_emb", tensors["tok_emb"].to_f32().astype(dtype))
    p["rms_att"] = dev("rms_att", np.stack([tensors[f"layers.{l}.rms_att"].to_f32() for l in range(L)]))
    p["rms_ffn"] = dev("rms_ffn", np.stack([tensors[f"layers.{l}.rms_ffn"].to_f32() for l in range(L)]))
    if spec.arch == ArchType.GROK1:
        p["rms_moe"] = dev("rms_moe", np.stack([tensors[f"layers.{l}.rms_moe"].to_f32() for l in range(L)]))
        p["rms_ffn2"] = dev("rms_ffn2", np.stack([tensors[f"layers.{l}.rms_ffn2"].to_f32() for l in range(L)]))
    for w in ("wq", "wk", "wv", "wo"):
        p[w] = weight([f"layers.{l}.{w}" for l in range(L)], w)
    if spec.is_moe:
        p["moe_router"] = dev(
            "moe_router",
            np.stack([tensors[f"layers.{l}.moe_router"].to_f32() for l in range(L)]).astype(dtype),
        )
        for w in ("up", "gate", "down"):
            names = [f"layers.{l}.experts.{e}.{w}" for l in range(L) for e in range(spec.n_experts)]
            ts = [tensors[n] for n in names]
            if mode == "q40":
                qs = [t if t.ftype == FloatType.Q40 else _to_q40_host(t.to_f32()) for t in ts]
                E = spec.n_experts
                packed = np.stack([q.packed for q in qs]).reshape(L, E, *qs[0].packed.shape)
                scales = np.stack([q.scales for q in qs]).reshape(L, E, *qs[0].scales.shape)
                p[f"moe_{w}"] = dev(f"moe_{w}", QuantizedTensor.from_numpy(scales, packed))
            else:
                dense = np.stack([t.to_f32() for t in ts]).astype(dtype)
                p[f"moe_{w}"] = dev(f"moe_{w}", dense.reshape(L, spec.n_experts, *dense.shape[1:]))
    else:
        for w in ("w1", "w2", "w3"):
            p[w] = weight([f"layers.{l}.{w}" for l in range(L)], w)
    p["rms_final"] = dev("rms_final", tensors["rms_final"].to_f32())
    p["wcls"] = weight(["wcls"], "wcls")  # stacked with leading dim 1
    return p


def random_tensors(spec: ModelSpec, seed: int = 0, scale: float = 0.02) -> dict[str, HostTensor]:
    """Synthetic host tensors for tests/benchmarks (numpy RNG, not xorshift —
    speed matters at 8B scale)."""
    rng = np.random.default_rng(seed)
    out = {}
    for name, shape, ftype in model_tensor_plan(spec):
        x = (rng.standard_normal(shape, dtype=np.float32) * scale)
        if ftype == FloatType.Q40:
            out[name] = _to_q40_host(x)
            out[name].name = name
            out[name].shape = shape
        else:
            out[name] = HostTensor(name, FloatType.F32, shape, data=x)
    return out
