"""Model parameters as a JAX pytree.

Structure: {"tok_emb", "rms_final", "wcls", "layers": [<per-layer dict>, ...]}
— each layer's weights are standalone device arrays (no stacked (L, ...)
axis). The forward pass statically unrolls over `layers` (the TPU analogue
of the reference's flat per-layer task list, ref: src/llama2-tasks.cpp:
249-275); standalone buffers feed the fused Q40 kernel in place, with no
per-step slice/copy, and per-layer loading never materializes a stacked
host copy (important for the 70B path — each tensor moves host -> device
individually via the `put` hook).

Two storage modes:
  * dense  — weights dequantized to `dtype` (bf16 on TPU) at load
  * q40    — weights kept as packed QuantizedTensor in HBM (4.5 bits/weight),
             dequantized inside the consuming matmul (ref keeps Q40 in RAM
             and fuses dequant into the kernel: src/funcs.cpp:286-385)

Unsliced tensors (embeddings, norms, wcls, MoE router) mirror the reference's
root-only tensors (ref: src/transformer.cpp:639-673) by being replicated
across the mesh.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import numpy as np

from ..io.model_file import HostTensor, model_tensor_plan
from ..quants.jax_codec import QuantizedTensor
from ..quants.numpy_codec import quantize_q40
from ..quants.types import FloatType
from .spec import ArchType, ModelSpec


def _to_q40_host(x: np.ndarray) -> HostTensor:
    scales, packed = quantize_q40(x.reshape(-1, x.shape[-1]))
    t = HostTensor("", FloatType.Q40, x.shape, scales=scales, packed=packed)
    return t


def load_params(
    spec: ModelSpec,
    tensors: dict[str, HostTensor],
    mode: str = "dense",
    dtype=jnp.float32,
    put: Callable | None = None,
) -> dict:
    """Build the params pytree from file tensors.

    `put` optionally maps (name, np array | host QuantizedTensor) -> device
    array — the hook a sharded streaming loader uses for direct multi-chip
    placement; defaults to plain jnp.asarray.
    """
    assert mode in ("dense", "q40")
    dev = put or (lambda name, x: x if isinstance(x, QuantizedTensor) else jnp.asarray(x))

    def weight(t: HostTensor, name: str):
        """One matmul weight in the requested storage mode."""
        if mode == "q40":
            if t.ftype != FloatType.Q40:
                t = _to_q40_host(t.to_f32())
            return dev(name, QuantizedTensor.from_numpy(t.scales, t.packed))
        return dev(name, t.to_f32().astype(dtype))

    def moe_weight(ts: list[HostTensor], name: str):
        """Stacked (E, ...) expert weight (experts stay stacked so decode can
        dynamic-gather the active ones)."""
        if mode == "q40":
            qs = [t if t.ftype == FloatType.Q40 else _to_q40_host(t.to_f32()) for t in ts]
            packed = np.stack([q.packed for q in qs])
            scales = np.stack([q.scales for q in qs])
            return dev(name, QuantizedTensor.from_numpy(scales, packed))
        dense = np.stack([t.to_f32() for t in ts]).astype(dtype)
        return dev(name, dense)

    p: dict = {}
    p["tok_emb"] = dev("tok_emb", tensors["tok_emb"].to_f32().astype(dtype))
    layers = []
    for l in range(spec.n_layers):
        lw: dict = {}
        lw["rms_att"] = dev(f"layers.{l}.rms_att", tensors[f"layers.{l}.rms_att"].to_f32())
        lw["rms_ffn"] = dev(f"layers.{l}.rms_ffn", tensors[f"layers.{l}.rms_ffn"].to_f32())
        if spec.arch == ArchType.GROK1:
            lw["rms_moe"] = dev(f"layers.{l}.rms_moe", tensors[f"layers.{l}.rms_moe"].to_f32())
            lw["rms_ffn2"] = dev(f"layers.{l}.rms_ffn2", tensors[f"layers.{l}.rms_ffn2"].to_f32())
        for w in ("wq", "wk", "wv", "wo"):
            lw[w] = weight(tensors[f"layers.{l}.{w}"], f"layers.{l}.{w}")
        if spec.is_moe:
            lw["moe_router"] = dev(
                f"layers.{l}.moe_router",
                tensors[f"layers.{l}.moe_router"].to_f32().astype(dtype))
            for w in ("up", "gate", "down"):
                ts = [tensors[f"layers.{l}.experts.{e}.{w}"] for e in range(spec.n_experts)]
                lw[f"moe_{w}"] = moe_weight(ts, f"layers.{l}.moe_{w}")
        else:
            for w in ("w1", "w2", "w3"):
                lw[w] = weight(tensors[f"layers.{l}.{w}"], f"layers.{l}.{w}")
        layers.append(lw)
    p["layers"] = layers
    p["rms_final"] = dev("rms_final", tensors["rms_final"].to_f32())
    p["wcls"] = weight(tensors["wcls"], "wcls")
    return p


def _concat_weights(ws: list):
    """Concatenate matmul weights along the output dim (device-side)."""
    if isinstance(ws[0], QuantizedTensor):
        return QuantizedTensor(
            jnp.concatenate([w.packed for w in ws], axis=0),
            jnp.concatenate([w.scales for w in ws], axis=0),
        )
    return jnp.concatenate(ws, axis=0)


def fuse_layer_weights(params: dict) -> dict:
    """Fuse QKV -> wqkv and w1|w3 -> w13 along the output dim, IN PLACE.

    Single-shard (tp == 1) fast path: decode is DMA-latency-bound per kernel
    call, so 3 calls sharing one input become 1 call with a 3x deeper grid
    (measured win on v5e). Not applied under tensor parallelism: the fused
    output dim would shard across the q|k|v segment boundaries, breaking the
    reference's RowMatmulSlice semantics (ref: src/transformer.cpp:14-46).
    Mutates the layer dicts so the superseded per-projection device buffers
    are actually freed even while the caller still holds the params dict
    (at 7B Q40 they are ~2.5 GB of HBM)."""
    for lw in params["layers"]:
        if "wq" in lw:
            lw["wqkv"] = _concat_weights([lw.pop("wq"), lw.pop("wk"), lw.pop("wv")])
        if "w1" in lw:
            lw["w13"] = _concat_weights([lw.pop("w1"), lw.pop("w3")])
    return params


def _split_rows(w, cuts: list[int]) -> list:
    """Split a matmul weight back along the output dim at `cuts`."""
    if isinstance(w, QuantizedTensor):
        return [QuantizedTensor(w.packed[a:b], w.scales[a:b])
                for a, b in zip([0] + cuts, cuts + [w.packed.shape[0]])]
    return [w[a:b] for a, b in zip([0] + cuts, cuts + [w.shape[0]])]


def unfuse_layer_weights(params: dict, spec: ModelSpec) -> dict:
    """Inverse of fuse_layer_weights (exact row slices), for engines built
    at tp > 1 from a params dict another (tp == 1) engine already fused —
    fuse mutates in place, and a row split of the fused [q|k|v] output dim
    does not align with the projection boundaries, which the fully-manual
    pp region (unlike GSPMD, whose sharding never changes semantics) would
    silently miscompute. No-op when nothing is fused."""
    if not any("wqkv" in lw or "w13" in lw for lw in params["layers"]):
        return params
    d, kv, h = spec.dim, spec.kv_dim, spec.hidden_dim
    params = dict(params)
    params["layers"] = [dict(lw) for lw in params["layers"]]
    for lw in params["layers"]:
        if "wqkv" in lw:
            lw["wq"], lw["wk"], lw["wv"] = _split_rows(
                lw.pop("wqkv"), [d, d + kv])
        if "w13" in lw:
            lw["w1"], lw["w3"] = _split_rows(lw.pop("w13"), [h])
    return params


def kv_replication(spec: ModelSpec, tp: int) -> int:
    """Replication factor r for tp > n_kv_heads, validating the config.

    Relaxes the reference's hard `nSlices <= nKvHeads` constraint
    (ref: src/transformer.cpp:254-257) — the planned extension the reference
    could not do (SURVEY.md §7 step 4): GQA models with few kv heads (e.g.
    70B's 8) can now shard over more chips (tp=16) by replicating each kv
    head's projections and cache r = tp/n_kv_heads times as tp "virtual"
    heads (virtual head j holds real head j//r). Query heads stay
    contiguously sharded — shard s's H/tp query heads all belong to virtual
    head s, so attention remains head-local like the reference's
    MultiHeadAttSlice. Aggregate kv projection + cache memory grows r-fold,
    but PER-DEVICE cache stays one head's worth — the same as at
    tp = n_kv_heads — while per-device weights and FLOPs keep shrinking.
    """
    kvh = spec.n_kv_heads
    assert tp % kvh == 0, (
        f"tp={tp} must be a multiple of n_kv_heads={kvh} to replicate")
    assert spec.n_heads % tp == 0, (
        f"tp={tp} must divide n_heads={spec.n_heads}")
    return tp // kvh


def _repeat_head_rows(a, kvh: int, r: int):
    """Repeat row-blocks of axis 0 (grouped per kv head) r times, so virtual
    head j = real head j // r. Works for dense (kv_dim, n), Q40 packed
    (kv_dim, m) and scales (kv_dim, nb)."""
    per = a.shape[0] // kvh
    rep = jnp.repeat(jnp.asarray(a).reshape(kvh, per, *a.shape[1:]), r, axis=0)
    return rep.reshape(kvh * r * per, *a.shape[1:])


def replicate_kv_heads(params: dict, spec: ModelSpec, tp: int) -> dict:
    """Expand wk/wv to tp virtual heads (see kv_replication). Non-mutating
    (fresh layer dicts, like repack_col_weights — callers may keep using
    the original pytree); idempotent (already-expanded leaves are detected
    by their row count, so loader-expanded params pass through)."""
    r = kv_replication(spec, tp)
    if r == 1:
        return params
    kvh = spec.n_kv_heads
    params = dict(params)
    params["layers"] = [dict(lw) for lw in params["layers"]]
    for lw in params["layers"]:
        for key in ("wk", "wv"):
            w = lw.get(key)
            if w is None:
                continue  # fused wqkv exists only on the tp==1 path
            if isinstance(w, QuantizedTensor):
                if w.packed.shape[0] == spec.kv_dim * r:
                    continue
                assert w.packed.shape[0] == spec.kv_dim, w.packed.shape
                lw[key] = QuantizedTensor(
                    _repeat_head_rows(w.packed, kvh, r),
                    _repeat_head_rows(w.scales, kvh, r))
            else:
                if w.shape[0] == spec.kv_dim * r:
                    continue
                assert w.shape[0] == spec.kv_dim, w.shape
                lw[key] = _repeat_head_rows(w, kvh, r)
    return params


def random_tensors(spec: ModelSpec, seed: int = 0, scale: float = 0.02) -> dict[str, HostTensor]:
    """Synthetic host tensors for tests/benchmarks (numpy RNG, not xorshift —
    speed matters at 8B scale)."""
    rng = np.random.default_rng(seed)
    out = {}
    for name, shape, ftype in model_tensor_plan(spec):
        x = (rng.standard_normal(shape, dtype=np.float32) * scale)
        if ftype == FloatType.Q40:
            out[name] = _to_q40_host(x)
            out[name].name = name
            out[name].shape = shape
        else:
            out[name] = HostTensor(name, FloatType.F32, shape, data=x)
    return out
