"""Tiny fixture model/tokenizer writers shared by tests and examples.

One place for the end-to-end fixture the suite uses everywhere: a small
random-weight Llama spec written to a real `.m` file plus a llama2.c-style
byte-fallback tokenizer `.t` (vocab 288 = 3 specials + 256 byte tokens +
fillers; byte b maps to token b+3), so CLI/API/cluster paths exercise the
same file formats the reference consumes.
"""

from __future__ import annotations

import numpy as np

from .io import (TokenizerData, model_tensor_plan, write_model,
                 write_tokenizer_file)
from .models import ArchType, HiddenAct, ModelSpec
from .quants import FloatType


def tiny_spec(weights_float_type: FloatType = FloatType.Q40,
              **overrides) -> ModelSpec:
    base = dict(
        arch=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2, n_heads=4,
        n_kv_heads=2, vocab_size=288, seq_len=160, hidden_act=HiddenAct.SILU,
        weights_float_type=weights_float_type)
    base.update(overrides)
    return ModelSpec(**base)


def free_port() -> int:
    """An OS-assigned free TCP port (shared by the cluster tests, the
    chaos harness spawners, and bench's cluster row — one home for the
    bind-port-0 idiom)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def byte_fallback_vocab(vocab_size: int) -> list[bytes]:
    vocab = [b"<unk>", b"<s>", b"</s>"]
    vocab += [f"<0x{b:02X}>".encode() for b in range(256)]
    vocab += [f"<fill{i}>".encode() for i in range(len(vocab), vocab_size)]
    return vocab


def write_fixture(dirpath, seed: int = 77, rng=None,
                  spec: ModelSpec | None = None,
                  **spec_overrides) -> tuple[str, str]:
    """Write model.m + tok.t under dirpath; returns their paths.

    Weights are `rng.standard_normal * 0.05` from `rng` (or a fresh
    default_rng(seed)) in plan order — tests that pin golden outputs must
    keep their seed/spec stable.
    """
    if spec is None:
        spec = tiny_spec(**spec_overrides)
    if rng is None:
        rng = np.random.default_rng(seed)
    tensors = {name: rng.standard_normal(shape).astype(np.float32) * 0.05
               for name, shape, _ in model_tensor_plan(spec)}
    mpath = f"{dirpath}/model.m"
    write_model(mpath, spec, tensors)
    tpath = f"{dirpath}/tok.t"
    write_tokenizer_file(tpath, TokenizerData(
        vocab=byte_fallback_vocab(spec.vocab_size),
        scores=[0.0] * spec.vocab_size, bos_id=1, eos_id=2))
    return mpath, tpath
