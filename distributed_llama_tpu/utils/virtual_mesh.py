"""Virtual CPU mesh bootstrap — the ONE place the device-count convention
lives.

Three consumers need "N virtual CPU devices" before jax initializes a
backend: tests/conftest.py (the 8-device SPMD test mesh), the dlgrind
jaxpr audit (analysis/__main__.py traces mesh entry points), and
__graft_entry__.py's multichip dryrun fallback on jax 0.4.x. XLA parses
XLA_FLAGS once per process, so all of them must append the flag the same
way and early; hand-rolled copies of this logic drifted — hence this
module, which imports nothing heavy (NO jax) so it is safe to call before
backend selection.
"""

from __future__ import annotations

import os

VIRTUAL_MESH_DEVICES = 8  # the CI/test convention (tests/conftest.py)


def ensure_virtual_cpu_devices(n: int = VIRTUAL_MESH_DEVICES) -> None:
    """Idempotently request `n` host-platform devices via XLA_FLAGS.

    Takes effect only if no XLA backend has materialized yet (flags are
    parsed once per process); callers that can verify afterwards should
    (see __graft_entry__.dryrun_multichip).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}")
