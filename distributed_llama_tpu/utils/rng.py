"""Bit-exact port of the reference xorshift* RNG.

The reference seeds synthetic test weights and the sampler coin flips from a
64-bit xorshift* generator (ref: src/utils.cpp:53-64). Reproducing it bit-for-
bit lets us replay the reference's golden-weight integration tests and get
identical sampling traces for a given seed.
"""

from __future__ import annotations

import numpy as np

_MASK64 = (1 << 64) - 1
_MULT = 0x2545F4914F6CDD1D


def xorshift_u32(state: int) -> tuple[int, int]:
    """One step of xorshift*; returns (new_state, u32 sample).

    Mirrors randomU32 (ref: src/utils.cpp:53-59).
    """
    state &= _MASK64
    state ^= state >> 12
    state ^= (state << 25) & _MASK64
    state ^= state >> 27
    sample = ((state * _MULT) & _MASK64) >> 32
    return state, sample & 0xFFFFFFFF


def xorshift_f32(state: int) -> tuple[int, float]:
    """Random float32 in [0, 1) (ref: src/utils.cpp:61-64)."""
    state, u = xorshift_u32(state)
    return state, np.float32((u >> 8) / 16777216.0).item()


class XorshiftRng:
    """Stateful wrapper used for synthetic weights and sampler parity."""

    def __init__(self, seed: int):
        self.state = seed & _MASK64

    def u32(self) -> int:
        self.state, v = xorshift_u32(self.state)
        return v

    def f32(self) -> float:
        self.state, v = xorshift_f32(self.state)
        return v

    def random_f32_array(self, n: int, scale: float = 1.0, offset: float = 0.0) -> np.ndarray:
        """n floats in [offset, offset + scale) drawn sequentially."""
        out = np.empty(n, dtype=np.float32)
        state = self.state
        for i in range(n):
            state, v = xorshift_f32(state)
            out[i] = v
        self.state = state
        return out * np.float32(scale) + np.float32(offset)
