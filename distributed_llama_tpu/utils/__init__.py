from .rng import xorshift_u32, xorshift_f32, XorshiftRng

__all__ = ["xorshift_u32", "xorshift_f32", "XorshiftRng"]
