"""Per-step timing stats.

Parity with the reference's benchmark surface: per-token G/I/T lines and
end-of-run averages (ref: src/apps/dllama/dllama.cpp:47-91, tasks.cpp:212-215,
socket.cpp:266-271). On TPU the compute/transfer split inside one jitted step
is XLA's business, so we report: generation wall ms (G), device-step ms (I,
the blocking device time), and host overhead ms (sampling + bookkeeping).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class StepStats:
    generation_ms: float = 0.0  # wall time of the whole token step (G)
    device_ms: float = 0.0      # device execution + logits D2H transfer (I) —
                                # the transfer is the sync point, so it cannot
                                # be separated from device time
    host_ms: float = 0.0        # host-side sampling/bookkeeping


@dataclasses.dataclass
class RunStats:
    steps: list[StepStats] = dataclasses.field(default_factory=list)

    def add(self, s: StepStats) -> None:
        self.steps.append(s)

    def averages(self, skip_first: int = 1) -> StepStats:
        """Average over steps, skipping warmup/compile steps (the reference
        averages all 16 samples; we exclude the compile step)."""
        body = self.steps[skip_first:] or self.steps
        n = len(body)
        return StepStats(
            generation_ms=sum(s.generation_ms for s in body) / n,
            device_ms=sum(s.device_ms for s in body) / n,
            host_ms=sum(s.host_ms for s in body) / n,
        )
