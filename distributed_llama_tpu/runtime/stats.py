"""Per-step timing stats.

Parity with the reference's benchmark surface: per-token G/I/T lines and
end-of-run averages (ref: src/apps/dllama/dllama.cpp:47-91, tasks.cpp:212-215,
socket.cpp:266-271). On TPU the compute/transfer split inside one jitted step
is XLA's business, so we report: generation wall ms (G), device-step ms (I,
the blocking device time), and host overhead ms (sampling + bookkeeping).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class StepStats:
    generation_ms: float = 0.0  # wall time of the whole token step (G)
    device_ms: float = 0.0      # device execution + logits D2H transfer (I) —
                                # the transfer is the sync point, so it cannot
                                # be separated from device time
    host_ms: float = 0.0        # host-side sampling/bookkeeping


@dataclasses.dataclass
class RunStats:
    steps: list[StepStats] = dataclasses.field(default_factory=list)

    def add(self, s: StepStats) -> None:
        self.steps.append(s)

    def averages(self, skip_first: int = 1) -> StepStats:
        """Average over steps, skipping warmup/compile steps (the reference
        averages all 16 samples; we exclude the compile step)."""
        body = self.steps[skip_first:] or self.steps
        n = len(body)
        return StepStats(
            generation_ms=sum(s.generation_ms for s in body) / n,
            device_ms=sum(s.device_ms for s in body) / n,
            host_ms=sum(s.host_ms for s in body) / n,
        )


# -- serving (continuous-batching scheduler) counters ----------------------


def percentile(xs: list, p: float):
    """Nearest-rank percentile over a small sample (None when empty) —
    TTFT/ITL distributions are tens of requests, not enough to justify
    interpolation (deliberately NO linear interpolation: p50 of [1, 2]
    is one of the observed values, never an invented 1.5). p is clamped
    to [0, 100]: p0 is the min, p100 the max, a single element answers
    every p. Backs every reported p50/p99 in this module —
    tests/test_stats.py pins the edge cases."""
    if not xs:
        return None
    xs = sorted(xs)
    k = min(len(xs) - 1, max(0, round(p / 100.0 * (len(xs) - 1))))
    return xs[k]


@dataclasses.dataclass
class RequestStats:
    """Per-request serving latency record (runtime/scheduler.py): TTFT is
    submit -> first emitted token (queue wait + prefill included — the
    number a client actually experiences), ITL the mean gap between
    subsequent tokens of the request."""

    n_prompt: int = 0
    n_out: int = 0
    t_submit: float = 0.0
    t_first: float | None = None
    t_done: float | None = None
    # per-request speculative-decoding accounting (runtime/draft.py):
    # verify forwards this request rode, draft tokens proposed for it,
    # and how many were accepted — the HONEST per-request accept record
    # the VERDICT #6 reporting debt asked for (aggregate twin: SpecStats)
    spec_forwards: int = 0
    spec_drafted: int = 0
    spec_accepted: int = 0

    @property
    def ttft_ms(self) -> float | None:
        if self.t_first is None:
            return None
        return (self.t_first - self.t_submit) * 1e3

    @property
    def itl_ms(self) -> float | None:
        if self.t_first is None or self.t_done is None or self.n_out < 2:
            return None
        return (self.t_done - self.t_first) / (self.n_out - 1) * 1e3


@dataclasses.dataclass
class SpecStats:
    """Aggregate speculative-decoding counters owned by the Scheduler
    (runtime/scheduler.py) — the honest accept-rate record every tier
    exports (the `spec` /stats block + the dllama_spec_* /metrics
    family). Attached even with drafting OFF (mode "off", all zeros):
    a tier must never lose a metric family to a launch flag. Lifetime =
    one scheduler generation, like ServeStats."""

    mode: str = "off"          # off | self<d> | model
    draft_len: int = 0
    verify_forwards: int = 0   # fixed-width verify steps dispatched
    draft_forwards: int = 0    # draft dispatches (one scan == one)
    drafted: int = 0           # draft tokens proposed (speculating rows)
    accepted: int = 0          # draft tokens the verify confirmed
    emitted_spec: int = 0      # tokens emitted by speculating rows
    # the SLO actuator ("degrade — no speculation"): iterations where
    # the admission policy had drafting disabled while a draft was armed
    degraded_steps: int = 0

    def summary(self) -> dict:
        return {
            "mode": self.mode,
            "draft_len": self.draft_len,
            "verify_forwards": self.verify_forwards,
            "draft_forwards": self.draft_forwards,
            "drafted": self.drafted,
            "accepted": self.accepted,
            "emitted_spec": self.emitted_spec,
            "accept_rate": (round(self.accepted / self.drafted, 4)
                            if self.drafted else None),
            "tokens_per_verify": (round(self.emitted_spec
                                        / self.verify_forwards, 3)
                                  if self.verify_forwards else None),
            "degraded_steps": self.degraded_steps,
        }


@dataclasses.dataclass
class PrefixCacheStats:
    """Counters owned by runtime/prefix_cache.PrefixCache. Lifetime = one
    (engine, scheduler) generation: the arena dies with the engine, so a
    supervisor rebuild starts these at zero (the /stats `prefix_cache`
    block is per-generation by design — a fresh empty tree SHOULD read
    as a 0% hit rate until it re-warms)."""

    num_blocks: int = 0
    block_len: int = 0
    lookups: int = 0           # admissions checked against the tree
    hits: int = 0              # admissions seeded from >= 1 cached block
    tokens_saved: int = 0      # prompt tokens seeded instead of prefilled
    tokens_prefilled: int = 0  # prompt tokens actually prefilled
    blocks_published: int = 0
    evictions: int = 0         # unreferenced LRU leaves freed for reuse
    publish_drops: int = 0     # publishes skipped: pool full of
    # referenced/live blocks (eviction must never free a pinned block)
    invalidations: int = 0     # whole-tree resets (abort/rebuild/close)
    blocks_in_use: int = 0     # gauge: pool slots the tree references

    def summary(self) -> dict:
        rnd = lambda v: None if v is None else round(v, 4)  # noqa: E731
        seen = self.tokens_saved + self.tokens_prefilled
        return {
            "num_blocks": self.num_blocks,
            "block_len": self.block_len,
            "blocks_in_use": self.blocks_in_use,
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_rate": rnd(self.hits / self.lookups) if self.lookups
            else None,
            "tokens_saved": self.tokens_saved,
            "prefill_saved_frac": rnd(self.tokens_saved / seen) if seen
            else None,
            "blocks_published": self.blocks_published,
            "evictions": self.evictions,
            "publish_drops": self.publish_drops,
            "invalidations": self.invalidations,
        }


class StepTimelineStats:
    """Per-batch-composition step-duration histograms (owned by
    runtime/trace.Tracer): every scheduler iteration records its wall ms
    keyed by (decode_rows, prefill_rows, chunk) — the raw measurement the
    batch-knee search (ROADMAP item 1) needs, the ``dllama_step_ms``
    /metrics family, and the bench rows' ``step_timeline`` block.
    Bounded: ``window`` samples per composition, at most ``max_keys``
    distinct compositions (the composition space is small by
    construction — decode_rows and prefill_rows are <= batch, chunk is
    one fixed width — but a bound beats trusting that)."""

    def __init__(self, window: int = 4096, max_keys: int = 256):
        import threading
        from collections import deque

        self.window = int(window)
        self.max_keys = int(max_keys)
        self._lock = threading.Lock()
        self._hist: dict[tuple, object] = {}  # dlrace: guarded-by(self._lock)
        self.overflow = 0  # samples dropped past max_keys

    def record(self, decode_rows: int, prefill_rows: int, chunk: int,
               wall_ms: float) -> None:
        from collections import deque

        key = (int(decode_rows), int(prefill_rows), int(chunk))
        with self._lock:
            d = self._hist.get(key)
            if d is None:
                if len(self._hist) >= self.max_keys:
                    self.overflow += 1
                    return
                d = self._hist[key] = deque(maxlen=self.window)
            d.append(wall_ms)

    def summary(self) -> dict:
        """{(dec, pre, chunk): {n, p50_ms, p99_ms, mean_ms}} over the
        sliding windows, busiest composition first."""
        with self._lock:
            items = [(k, list(d)) for k, d in self._hist.items()]
        out = {}
        for key, xs in sorted(items, key=lambda kv: -len(kv[1])):
            out[key] = {
                "n": len(xs),
                "p50_ms": round(percentile(xs, 50), 4),
                "p99_ms": round(percentile(xs, 99), 4),
                "mean_ms": round(sum(xs) / len(xs), 4),
            }
        return out

    def summary_json(self) -> dict:
        """summary() with string keys ("dec4_pre1_c16") — the BENCH json
        block (tuple keys do not survive json.dumps)."""
        return {f"dec{k[0]}_pre{k[1]}_c{k[2]}": v
                for k, v in self.summary().items()}


@dataclasses.dataclass
class ServeStats:
    """Scheduler-level serving counters: running totals plus BOUNDED
    sliding windows (`window` most-recent entries) of per-iteration
    occupancy/queue-depth samples and per-request latency records — a
    long-running server must not grow a list per step forever, and the
    percentile sort on GET /stats must stay O(window). The
    aggregate-throughput denominators (wall clock) belong to the caller —
    this object only owns what the scheduler alone can observe."""

    window: int = 10_000
    requests_submitted: int = 0
    requests_finished: int = 0
    tokens_out: int = 0
    steps: int = 0
    # resilience counters (requests_failed/expired also count toward
    # requests_finished — every submitted request gets exactly one
    # terminal event): failed = structured error frames (crash/abort),
    # expired = deadline or queue-time budget kills, rejected = refused
    # at submit() (queue bound) and therefore NOT in requests_submitted
    requests_failed: int = 0
    requests_expired: int = 0
    requests_rejected: int = 0
    # attached by the Scheduler when the radix prefix cache is on — its
    # summary rides the same /stats payload as a `prefix_cache` block
    prefix: PrefixCacheStats | None = None
    # attached by the Scheduler when the SLO-aware admission policy is on
    # (runtime/scheduler.AdmissionPolicy) — current chunk width, EWMAs,
    # and transition counters ride /stats as an `admission` block
    admission: object | None = None
    # ALWAYS attached by the Scheduler (mode "off" when no draft is
    # armed): the speculative-decoding accept record, runtime/draft.py
    spec: SpecStats | None = None

    def __post_init__(self):
        from collections import deque

        self.requests = deque(maxlen=self.window)   # RequestStats records
        self.occupancy = deque(maxlen=self.window)  # live slots, per step
        self.queue_depth = deque(maxlen=self.window)

    def summary(self) -> dict:
        """JSON-ready snapshot (the API server's GET /stats and the bench's
        Poisson-arrival row both emit this). Percentiles and occupancy
        cover the sliding window; the totals are lifetime counters."""
        ttfts = [r.ttft_ms for r in self.requests if r.ttft_ms is not None]
        itls = [r.itl_ms for r in self.requests if r.itl_ms is not None]
        rnd = lambda v: None if v is None else round(v, 3)  # noqa: E731
        out = {
            "requests_submitted": self.requests_submitted,
            "requests_finished": self.requests_finished,
            "requests_failed": self.requests_failed,
            "requests_expired": self.requests_expired,
            "requests_rejected": self.requests_rejected,
            "tokens_out": self.tokens_out,
            "ttft_p50_ms": rnd(percentile(ttfts, 50)),
            "ttft_p99_ms": rnd(percentile(ttfts, 99)),
            "itl_p50_ms": rnd(percentile(itls, 50)),
            "itl_p99_ms": rnd(percentile(itls, 99)),
            "mean_slot_occupancy": rnd(sum(self.occupancy)
                                       / len(self.occupancy))
            if self.occupancy else 0.0,
            "max_queue_depth": max(self.queue_depth, default=0),
            "steps": self.steps,
        }
        if self.prefix is not None:
            out["prefix_cache"] = self.prefix.summary()
        if self.admission is not None:
            out["admission"] = self.admission.summary()
        if self.spec is not None:
            out["spec"] = self.spec.summary()
        return out


class WireStats:
    """Measured control-plane wire ledger (dlwire): bytes and frames per
    (peer, MSG kind, direction) plus per-peer PING→PONG round-trip
    histograms and the midpoint clock-offset estimate — owned by
    parallel/multihost's link objects, which account every codec
    send/recv through :meth:`account`. MEASURED, not modeled: a torn
    frame counts exactly the bytes that actually crossed the socket
    (the fault sites fire inside the codec, so the ledger sees the same
    partial writes the peer does). Kind labels are the MSG_* names (a
    small closed set), peers are ranks — cardinality is bounded by
    protocol design, but a ``max_keys`` bound backs that up. Rendered
    as the ``wire`` block of the cluster /stats summary and the
    ``dllama_wire_bytes_total{peer,kind,dir}`` /
    ``dllama_heartbeat_rtt_ms{peer}`` /metrics families."""

    def __init__(self, window: int = 512, max_keys: int = 64,
                 recent: int = 32):
        import threading
        from collections import deque  # noqa: F401 — used in rtt()

        self.window = int(window)
        self.max_keys = int(max_keys)
        self.recent = int(recent)
        self._lock = threading.Lock()
        # peer -> {"tx"|"rx" -> {kind_name -> [frames, bytes]}}
        self._counts: dict[int, dict] = {}  # dlrace: guarded-by(self._lock)
        self._rtt: dict[int, object] = {}  # dlrace: guarded-by(self._lock)
        self._offset: dict[int, float] = {}  # dlrace: guarded-by(self._lock)
        self._best_rtt: dict[int, float] = {}  # dlrace: guarded-by(self._lock)
        self.key_overflow = 0

    def account(self, peer: int, kind: str, direction: str,
                nbytes: int, frames: int = 1) -> None:
        """One codec send/recv: ``nbytes`` actually moved (0 is skipped —
        nothing crossed the wire). Cheap by design: a dict walk and two
        int adds under one lock, on control-plane frames only (heartbeat
        cadence, never per decoded token)."""
        if nbytes <= 0:
            return
        with self._lock:
            dirs = self._counts.setdefault(int(peer), {})
            kinds = dirs.setdefault(direction, {})
            rec = kinds.get(kind)
            if rec is None:
                if len(kinds) >= self.max_keys:
                    self.key_overflow += 1
                    return
                rec = kinds[kind] = [0, 0]
            rec[0] += int(frames)
            rec[1] += int(nbytes)

    def rtt(self, peer: int, ms: float,
            offset_s: float | None = None) -> None:
        """One PING→PONG round trip. The clock offset rides the BEST
        (minimum-RTT) sample seen so far — the standard NTP-style pick:
        the smaller the round trip, the tighter the midpoint bounds the
        remote clock."""
        from collections import deque

        with self._lock:
            d = self._rtt.get(int(peer))
            if d is None:
                d = self._rtt[int(peer)] = deque(maxlen=self.window)
            d.append(float(ms))
            if offset_s is not None:
                best = self._best_rtt.get(int(peer))
                if best is None or ms <= best:
                    self._best_rtt[int(peer)] = float(ms)
                    self._offset[int(peer)] = float(offset_s)

    def clock_offset_s(self, peer: int) -> float | None:
        """Best-sample estimate of (peer wall clock − local wall clock),
        seconds — what MSG_TRACE ingestion subtracts to rebase a worker's
        wall-stamped span onto the root timeline."""
        with self._lock:
            return self._offset.get(int(peer))

    def total_bytes(self, direction: str) -> int:
        with self._lock:
            return sum(rec[1]
                       for dirs in self._counts.values()
                       for kind in (dirs.get(direction) or {},)
                       for rec in kind.values())

    def peer_bytes(self, peer: int, kind: str, direction: str) -> int:
        """Exact measured bytes for one (peer, kind, dir) — the
        reconciliation tests compare this against frame-size
        arithmetic."""
        with self._lock:
            rec = ((self._counts.get(int(peer)) or {})
                   .get(direction) or {}).get(kind)
            return rec[1] if rec else 0

    def summary(self) -> dict:
        with self._lock:
            peers = {}
            for peer in sorted(set(self._counts) | set(self._rtt)):
                rec: dict = {}
                dirs = self._counts.get(peer) or {}
                for d in ("tx", "rx"):
                    kinds = dirs.get(d)
                    if kinds:
                        rec[d] = {k: {"frames": v[0], "bytes": v[1]}
                                  for k, v in sorted(kinds.items())}
                rtts = list(self._rtt.get(peer) or ())
                if rtts:
                    rec["rtt_ms"] = {
                        "n": len(rtts),
                        "p50_ms": round(percentile(rtts, 50), 4),
                        "p99_ms": round(percentile(rtts, 99), 4),
                        "mean_ms": round(sum(rtts) / len(rtts), 4),
                        # a short raw tail so offline consumers (the bench
                        # cluster row's step_timeline) can re-histogram
                        "recent": [round(v, 4) for v in rtts[-self.recent:]],
                    }
                off = self._offset.get(peer)
                if off is not None:
                    rec["clock_offset_ms"] = round(off * 1e3, 4)
                    rec["best_rtt_ms"] = round(self._best_rtt[peer], 4)
                peers[str(peer)] = rec
            out = {"peers": peers, "key_overflow": self.key_overflow}
        out["tx_bytes"] = self.total_bytes("tx")
        out["rx_bytes"] = self.total_bytes("rx")
        return out


@dataclasses.dataclass
class ClusterStats:
    """Control-plane counters owned by parallel/multihost's link objects
    (RootLink / WorkerLink): heartbeat traffic, formation retries, the
    measured wire ledger (:class:`WireStats`), startup data-plane
    broadcast timings, and the structured record of every peer loss.
    Surfaced as the ``cluster`` block of GET /stats on a multihost api
    root, and by the chaos harness (parallel/cluster_harness.py). The
    phase label is attached live by ``multihost.cluster_summary()`` — it
    belongs to the link, not here."""

    nnodes: int = 1
    node_rank: int = 0
    protocol_version: int = 0
    heartbeat_interval_s: float = 0.0
    worker_timeout_s: float = 0.0
    connect_retries: int = 0   # worker side: backoff attempts at formation
    pings_sent: int = 0        # root side
    pongs_received: int = 0    # root side
    pongs_sent: int = 0        # worker side
    frames_sent: int = 0       # protocol frames (excl. pings)
    frames_received: int = 0   # every frame (incl. heartbeat traffic)
    # startup data-plane timings (parallel/multihost.bcast_spec /
    # bcast_model_tensors — the collective weight push the heartbeat
    # covers but the wire ledger cannot count, XLA owns those bytes):
    # wall ms per phase, plus the tensor bytes rank 0 streamed
    bcast_spec_ms: float | None = None
    bcast_tensors_ms: float | None = None
    bcast_tensors_bytes: int = 0

    def __post_init__(self):
        # ClusterPeerLost.summary() dicts, in detection order
        self.peers_lost: list = []
        self.wire = WireStats()

    def summary(self) -> dict:
        return {
            "nnodes": self.nnodes,
            "node_rank": self.node_rank,
            "protocol_version": self.protocol_version,
            "heartbeat_interval_s": self.heartbeat_interval_s,
            "worker_timeout_s": self.worker_timeout_s,
            "connect_retries": self.connect_retries,
            "pings_sent": self.pings_sent,
            "pongs_received": self.pongs_received,
            "pongs_sent": self.pongs_sent,
            "frames_sent": self.frames_sent,
            "frames_received": self.frames_received,
            "bcast_spec_ms": self.bcast_spec_ms,
            "bcast_tensors_ms": self.bcast_tensors_ms,
            "bcast_tensors_bytes": self.bcast_tensors_bytes,
            "wire": self.wire.summary(),
            "peers_lost": list(self.peers_lost),
        }


@dataclasses.dataclass
class KVTransferStats:
    """Counters for the cross-replica KV block transfer plane
    (runtime/kv_transfer.py): cache FILLs on miss (a replica imports a
    sibling's published arena blocks instead of re-prefilling), the
    donor-side export serving, and the router's prefill/decode
    disaggregation handoffs. Owned by the party that does the work —
    the Router for thread-tier fills + disaggregation decisions, each
    worker's ReplicaServer for its own wire serving/fills — and surfaced
    as the ``kv_transfer`` /stats block + the ``dllama_kv_transfer_*``
    /metrics family in EVERY tier incl. idle (enabled=False, zeros:
    a tier must never lose a metric family to a launch flag).

    ``wire`` is a :class:`WireStats` ledger accounting the RMSG_BLOCK_*
    frames per (peer, kind, dir) — the same measured-bytes discipline as
    the cluster control plane (dlwire), so ``netstats.reconcile_wire``
    can close measured-vs-modeled over block transfers too."""

    enabled: bool = False
    tier: str = "mixed"        # this party's role: prefill|decode|mixed
    block_len: int = 0
    block_bytes: int = 0       # one block's K+V payload bytes (exact)
    # importer side (cache FILL on miss)
    fills_requested: int = 0   # fill decisions / attempts
    fills_ok: int = 0          # >= 1 block actually imported
    fill_fallbacks: int = 0    # error/timeout/donor death -> re-prefill
    fill_misses: int = 0       # donor answered shorter than expected
    tokens_filled: int = 0     # prompt tokens imported instead of prefilled
    blocks_filled: int = 0
    bytes_rx: int = 0          # block payload bytes received
    # donor side (export serving)
    queries_served: int = 0
    query_misses: int = 0      # QUERY answered with nothing fetchable
    blocks_exported: int = 0
    bytes_tx: int = 0          # block payload bytes sent
    donor_aborts: int = 0      # exports cut short (peer death, error)
    # prefill/decode disaggregation (router-side)
    prefill_passes: int = 0          # prefill-tier passes completed
    prefill_pass_fallbacks: int = 0  # no prefill worker / pass failed ->
    #                                  unified mixed path
    shadow_truncates: int = 0        # stale shadow entries cleared by a
    #                                  QUERY miss answer (donor eviction)

    def __post_init__(self):
        import threading
        from collections import deque

        # whole-fill wall ms (connect -> last block imported)
        self.transfer_ms = deque(maxlen=1000)  # dlrace: guarded-by(self.lock)
        self.wire = WireStats()
        # counter mutations ride this lock (concurrent fills/donor
        # connections all write here; += on a dataclass int is a
        # read-modify-write that can drop counts under contention —
        # the same discipline RouterStats keeps via the router lock)
        self.lock = threading.Lock()

    def note_transfer_ms(self, ms: float) -> None:
        with self.lock:
            self.transfer_ms.append(float(ms))

    def summary(self) -> dict:
        rnd = lambda v: None if v is None else round(v, 3)  # noqa: E731
        xs = list(self.transfer_ms)
        out = {
            "enabled": self.enabled,
            "tier": self.tier,
            "block_len": self.block_len,
            "block_bytes": self.block_bytes,
            "fills_requested": self.fills_requested,
            "fills_ok": self.fills_ok,
            "fill_fallbacks": self.fill_fallbacks,
            "fill_misses": self.fill_misses,
            "tokens_filled": self.tokens_filled,
            "blocks_filled": self.blocks_filled,
            "bytes_rx": self.bytes_rx,
            "queries_served": self.queries_served,
            "query_misses": self.query_misses,
            "blocks_exported": self.blocks_exported,
            "bytes_tx": self.bytes_tx,
            "donor_aborts": self.donor_aborts,
            "prefill_passes": self.prefill_passes,
            "prefill_pass_fallbacks": self.prefill_pass_fallbacks,
            "shadow_truncates": self.shadow_truncates,
            "transfer_p50_ms": rnd(percentile(xs, 50)),
            "transfer_p99_ms": rnd(percentile(xs, 99)),
        }
        wire = self.wire.summary()
        if wire.get("tx_bytes") or wire.get("rx_bytes"):
            out["wire"] = wire
        return out

    @staticmethod
    def merge(blocks: list) -> dict:
        """Sum a list of summary() dicts into one aggregate (the router's
        top-level block over its own counters + every worker's). Counters
        add; enabled/tier describe the aggregate; percentiles are not
        mergeable and report None unless exactly one side has them."""
        keys = ("fills_requested", "fills_ok", "fill_fallbacks",
                "fill_misses", "tokens_filled", "blocks_filled",
                "bytes_rx", "queries_served", "query_misses",
                "blocks_exported", "bytes_tx", "donor_aborts",
                "prefill_passes", "prefill_pass_fallbacks",
                "shadow_truncates")
        blocks = [b for b in blocks if isinstance(b, dict)]
        out = {k: sum(int(b.get(k) or 0) for b in blocks) for k in keys}
        out["enabled"] = any(b.get("enabled") for b in blocks)
        out["tier"] = "aggregate"
        out["block_len"] = max((int(b.get("block_len") or 0)
                                for b in blocks), default=0)
        out["block_bytes"] = max((int(b.get("block_bytes") or 0)
                                  for b in blocks), default=0)
        with_ms = [b for b in blocks
                   if b.get("transfer_p50_ms") is not None]
        out["transfer_p50_ms"] = (with_ms[0]["transfer_p50_ms"]
                                  if len(with_ms) == 1 else None)
        out["transfer_p99_ms"] = (with_ms[0].get("transfer_p99_ms")
                                  if len(with_ms) == 1 else None)
        return out


@dataclasses.dataclass
class FleetStats:
    """Counters owned by runtime/fleet.FleetController — the
    measurement→decision loop over the serving fleet: autoscale
    decisions (spawns, reaps, HBM-blocked refusals, spawn failures +
    backoff), the overload ladder's position, and door-level sheds by
    reason. Surfaced as the ``fleet`` /stats block + the
    ``dllama_fleet_*`` /metrics family in EVERY tier incl. idle
    (enabled=False, zeros: a tier must never lose a metric family to a
    launch flag); the per-tenant admitted/shed/budget ledger rides the
    same block from the controller's TenantLedger."""

    enabled: bool = False
    ticks: int = 0             # controller observation rounds
    pressure: float = 0.0      # last observed serve-tier pressure
    rung: int = 0              # overload ladder position (0 = healthy)
    target_replicas: int = 0   # what the controller wants
    scale_ups: int = 0         # replicas spawned into rotation
    scale_downs: int = 0       # replicas drained + reaped
    scale_blocked_hbm: int = 0  # spawns refused by the HBM ceiling
    spawn_failures: int = 0    # scale-up spawns that died (→ backoff)
    warm_fills: int = 0        # sibling KV fills into fresh replicas
    sheds: int = 0             # door rejections by the ladder
    clamped: int = 0           # admissions with max_tokens clamped

    def __post_init__(self):
        import threading

        # shed rejections keyed by ladder reason ("shed"/"prefix_only")
        self.sheds_by_reason: dict[str, int] = {}
        # counter mutations ride this lock (the controller thread, its
        # spawn/reap worker threads, and the API door all write here)
        self.lock = threading.Lock()

    def summary(self) -> dict:
        return {
            "enabled": self.enabled,
            "ticks": self.ticks,
            "pressure": self.pressure,
            "rung": self.rung,
            "target_replicas": self.target_replicas,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "scale_blocked_hbm": self.scale_blocked_hbm,
            "spawn_failures": self.spawn_failures,
            "warm_fills": self.warm_fills,
            "sheds": self.sheds,
            "clamped": self.clamped,
            "sheds_by_reason": dict(self.sheds_by_reason),
        }


@dataclasses.dataclass
class RouterStats:
    """Counters owned by runtime/router.Router — placement decisions,
    failover retries, and per-replica breaker events, surfaced as the
    ``router`` block of GET /stats (the per-replica supervisor summaries
    ride the same payload as a ``replicas`` list)."""

    replicas: int = 0
    policy: str = ""
    routed: int = 0             # successful placements (incl. retries)
    routed_cache_hit: int = 0   # placements won by a radix prefix match
    routed_affinity: int = 0    # placements won by session stickiness
    routed_fallback: int = 0    # least-loaded / round-robin placements
    retries: int = 0            # failover resubmits (pre-first-token)
    failovers_ok: int = 0       # retried requests that then completed
    midstream_failures: int = 0  # streams killed after >= 1 token: the
    # structured NON-retryable frame the client saw (the router never
    # silently replays a partially-delivered stream)
    breaker_trips: int = 0      # router-level circuit opens
    breaker_probes: int = 0     # half-open probe placements
    drains: int = 0             # per-replica drains (rolling restart)
    restarts: int = 0           # per-replica supervisor rebuilds
    no_replica_rejections: int = 0  # submits with NO routable replica

    def summary(self) -> dict:
        return {k: getattr(self, k) for k in (
            "replicas", "policy", "routed", "routed_cache_hit",
            "routed_affinity", "routed_fallback", "retries",
            "failovers_ok", "midstream_failures", "breaker_trips",
            "breaker_probes", "drains", "restarts",
            "no_replica_rejections")}


@dataclasses.dataclass
class ProcStats:
    """Process-supervision counters owned by
    runtime/router.RemoteReplicaHandle (local-spawn mode): every worker
    exit is CLASSIFIED (``classify_exit`` — ``signal:SIGKILL``,
    ``config_error``, ``fault_exit``, ...) and the respawn-to-routable
    latency distribution is what the process-kill chaos tests and the
    ``BENCH_ROUTER=1`` process row assert their bound against. Surfaced
    as the ``proc`` block of each replica's /stats summary."""

    respawns: int = 0         # successful respawn-to-routable cycles
    spawn_failures: int = 0   # spawn attempts that died/hung pre-ready
    exits: int = 0            # deaths of READY (post-handshake) workers

    def __post_init__(self):
        from collections import deque

        # death-detected -> port-handshake-complete (warmed) latency
        self.respawn_ms = deque(maxlen=1000)
        # classes of ALL process deaths — ready-worker exits AND failed
        # spawn attempts (a crash-looping `config_error` shows up here
        # even though it never got far enough to count as an `exit`)
        self.exit_classes: dict[str, int] = {}

    def note_exit(self, cls: str) -> None:
        self.exits += 1
        self.exit_classes[cls] = self.exit_classes.get(cls, 0) + 1

    def note_spawn_failure(self, cls: str | None) -> None:
        self.spawn_failures += 1
        if cls is not None:
            self.exit_classes[cls] = self.exit_classes.get(cls, 0) + 1

    def summary(self) -> dict:
        rnd = lambda v: None if v is None else round(v, 3)  # noqa: E731
        return {
            "exits": self.exits,
            "exit_classes": dict(self.exit_classes),
            "respawns": self.respawns,
            "spawn_failures": self.spawn_failures,
            "respawn_p50_ms": rnd(percentile(list(self.respawn_ms), 50)),
            "respawn_p99_ms": rnd(percentile(list(self.respawn_ms), 99)),
        }


@dataclasses.dataclass
class SupervisorStats:
    """Resilience counters owned by runtime/resilience.EngineSupervisor —
    they survive scheduler rebuilds (each recovery mints a fresh
    Scheduler/ServeStats; these accumulate across generations)."""

    crashes: int = 0          # step-loop exceptions caught
    watchdog_trips: int = 0   # stalls detected by the watchdog
    recoveries: int = 0       # successful rebuilds back to ready
    consecutive_failures: int = 0
    rejected_unready: int = 0  # submits refused while recovering/broken
    cluster_losses: int = 0    # ClusterPeerLost escalations (trip_cluster):
    # straight to BROKEN — no rebuild resurrects a remote worker

    def __post_init__(self):
        from collections import deque

        # failure-detected -> ready-again latency, the recovery-time
        # distribution the bench chaos row reports
        self.recovery_ms = deque(maxlen=1000)

    def summary(self) -> dict:
        rnd = lambda v: None if v is None else round(v, 3)  # noqa: E731
        return {
            "crashes": self.crashes,
            "watchdog_trips": self.watchdog_trips,
            "recoveries": self.recoveries,
            "consecutive_failures": self.consecutive_failures,
            "rejected_unready": self.rejected_unready,
            "cluster_losses": self.cluster_losses,
            "recovery_p50_ms": rnd(percentile(list(self.recovery_ms), 50)),
            "recovery_p99_ms": rnd(percentile(list(self.recovery_ms), 99)),
        }
