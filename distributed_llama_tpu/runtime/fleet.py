"""The fleet brain: load-adaptive autoscaling, SLO-aware overload
shedding, and multi-tenant fairness (ROADMAP item 1's closed loop).

Every actuator this module drives already exists — PR-7's spawn/drain/
respawn supervision, PR-8/10's queue/occupancy/goodput signals, PR-10/
11's HBM-ledger headroom + SLO admission ladder, PR-13's "degrade — no
speculation" knob, PR-14's ``--tier`` roles + KV warm-fill, PR-3/6's
structured 429/``Retry-After``/drain. What was missing is the brain
that reads the signals and drives the actuators:

  * ``FleetController`` — a host-side control loop (one thread, riding
    the same cadence discipline as the replica monitors) that scales
    ``--replica-procs`` between ``--min-replicas``/``--max-replicas``
    from OBSERVED load: spawn on sustained queue growth / occupancy
    EWMA, drain+reap on sustained idle, the HBM ledger's
    ``slots_addable`` as the hard ceiling. Freshly spawned replicas
    warm via PR-14 KV block fills from siblings instead of starting
    cold, and prefill vs decode tiers resize independently from their
    own saturation signals. Every decision is a trace event
    (``scale_up``/``scale_down``) and one structured log line.
  * ``ShedLadder`` — the door-level overload ladder, armed by the SLO
    flags and walked IN ORDER before any rejection: speculation off →
    ``max_tokens`` clamp → prefix-cache-only admission → structured
    429 + ``Retry-After`` derived from the live drain rate. Monotone
    degradation, rung-by-rung recovery with hysteresis (consecutive
    observation counts, not wall time — so every transition is
    count-deterministic under test).
  * ``WFQueue``/``TenantLedger`` — priority classes and per-tenant
    token budgets (``--tenant-budgets``; tenant from the request body
    or ``X-Tenant`` header) with start-time weighted-fair queueing
    replacing the FIFO admission deque, so a hog tenant's overage can
    never move a victim's p99: over-budget tenants are served only
    when no in-budget tenant waits, and within a budget class the
    virtual-time tags bound any tenant's lead by one request's cost
    over its weighted share.

Everything here is host-side bookkeeping and thread scheduling: zero
new jitted entry points, so the dlgrind entry-point fingerprints are
unchanged by construction, and spawned replicas warm their executables
before becoming routable (``--freeze-compiles`` holds through
scale-up, degrade, and recovery).

Chaos surface (runtime/faults.py): ``spawn_stall`` (key ``rK``) slows
the controller's replica-K spawn deterministically; ``scale_flap``
replaces the measured pressure with a synthetic oscillation for as
many ticks as it is armed — the anti-flap hysteresis bars in
tests/test_fleet.py count fires, not wall time.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

from .faults import FAULTS
from .trace import TRACER

# priority classes, highest first: the WFQ serves a lower band only
# when every higher band is empty (strict priority ACROSS bands,
# weighted fairness WITHIN a band)
PRIORITIES = ("high", "normal", "low")
DEFAULT_TENANT = "anon"

# the shed/degrade ladder, walked top (healthy) to bottom (shed) one
# rung at a time — docs/operations.md "Overload and autoscaling" is the
# operator-facing table of these names
LADDER_RUNGS = ("healthy", "no_spec", "clamp", "prefix_only", "shed")


def parse_tenant_budgets(spec: str | None) -> dict:
    """Parse ``--tenant-budgets``: comma-separated
    ``name=weight[:tokens_per_sec]`` entries, e.g.
    ``"acme=3:5000,free=1:200"`` — weight is the WFQ share, the
    optional rate is the token-bucket refill (absent/0 = unlimited
    budget, fairness by weight only). Unknown tenants get weight 1,
    unlimited. Raises ValueError on malformed entries (the CLI refuses
    at parse time, never at serve time)."""
    out: dict[str, tuple[float, float]] = {}
    if not spec:
        return out
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise ValueError(f"tenant budget {item!r}: expected "
                             "name=weight[:tokens_per_sec]")
        name, _, val = item.partition("=")
        w, _, rate = val.partition(":")
        try:
            weight = float(w)
            per_sec = float(rate) if rate else 0.0
        except ValueError:
            raise ValueError(f"tenant budget {item!r}: weight and rate "
                             "must be numbers") from None
        if weight <= 0 or per_sec < 0:
            raise ValueError(f"tenant budget {item!r}: weight must be "
                             "> 0 and rate >= 0")
        out[name.strip()] = (weight, per_sec)
    return out


class TenantLedger:
    """Per-tenant WFQ weights + token-bucket budgets, held OUTSIDE the
    scheduler so budgets survive supervisor rebuilds (each generation's
    fresh ``WFQueue`` shares this one ledger — the same externally-held
    discipline as the supervisor's counter carry).

    The bucket refills at ``tokens_per_sec`` up to ``burst_secs`` worth
    of credit; a request charges its COST (prompt + max_tokens — the
    service estimate the WFQ tags use) when it is admitted off the
    queue. ``in_budget`` going False never rejects by itself: it only
    demotes the tenant behind every in-budget sibling (work-conserving
    — overage is served from idle capacity, never from a victim's
    share). The injectable ``clock`` makes refill count-deterministic
    under test."""

    def __init__(self, budgets: dict | None = None, *,
                 burst_secs: float = 10.0, clock=time.monotonic):
        self._clock = clock
        self._burst = float(burst_secs)
        self.lock = threading.Lock()
        # name -> (weight, tokens_per_sec); absent tenants default (1, 0)
        self._spec: dict[str, tuple[float, float]] = dict(budgets or {})
        self._balance: dict[str, float] = {}  # dlrace: guarded-by(self.lock)
        self._last_refill = clock()  # dlrace: guarded-by(self.lock)
        # lifetime per-tenant accounting (the fleet /stats block)
        self._admitted: dict[str, int] = {}  # dlrace: guarded-by(self.lock)
        self._shed: dict[str, int] = {}  # dlrace: guarded-by(self.lock)
        self._charged: dict[str, int] = {}  # dlrace: guarded-by(self.lock)
        with self.lock:
            for name, (_, rate) in self._spec.items():
                if rate > 0:
                    self._balance[name] = rate * self._burst

    def weight(self, tenant: str) -> float:
        return self._spec.get(tenant, (1.0, 0.0))[0]

    def limited(self, tenant: str) -> bool:
        return self._spec.get(tenant, (1.0, 0.0))[1] > 0

    def _refill_locked(self, now: float) -> None:
        dt = max(now - self._last_refill, 0.0)
        self._last_refill = now
        for name, (_, rate) in self._spec.items():
            if rate > 0:
                cap = rate * self._burst
                self._balance[name] = min(
                    self._balance.get(name, cap) + rate * dt, cap)

    def in_budget(self, tenant: str) -> bool:
        """True when this tenant's bucket has credit (or it is not
        budget-limited at all)."""
        rate = self._spec.get(tenant, (1.0, 0.0))[1]
        if rate <= 0:
            return True
        with self.lock:
            self._refill_locked(self._clock())
            return self._balance.get(tenant, 0.0) > 0.0

    def charge(self, tenant: str, tokens: int) -> None:
        """Debit an admitted request's cost (the bucket may go negative
        — overage is repaid by refill before the tenant is in-budget
        again)."""
        with self.lock:
            self._refill_locked(self._clock())
            self._charged[tenant] = self._charged.get(tenant, 0) + int(tokens)
            self._admitted[tenant] = self._admitted.get(tenant, 0) + 1
            rate = self._spec.get(tenant, (1.0, 0.0))[1]
            if rate > 0:
                self._balance[tenant] = (
                    self._balance.get(tenant, rate * self._burst)
                    - float(tokens))

    def note_shed(self, tenant: str) -> None:
        with self.lock:
            self._shed[tenant] = self._shed.get(tenant, 0) + 1

    def summary(self) -> dict:
        """Per-tenant block of the fleet /stats payload: every tenant
        ever seen (configured or not), with weight, budget remaining
        (None = unlimited), admitted/shed/charged totals."""
        with self.lock:
            self._refill_locked(self._clock())
            names = (set(self._spec) | set(self._admitted)
                     | set(self._shed))
            out = {}
            for name in sorted(names):
                weight, rate = self._spec.get(name, (1.0, 0.0))
                out[name] = {
                    "weight": weight,
                    "tokens_per_sec": rate or None,
                    "budget_remaining": (round(self._balance.get(name, 0.0), 1)
                                         if rate > 0 else None),
                    "admitted": self._admitted.get(name, 0),
                    "shed": self._shed.get(name, 0),
                    "tokens_charged": self._charged.get(name, 0),
                }
            return out


class WFQueue:
    """Start-time weighted-fair admission queue, duck-typing the slice
    of ``collections.deque`` the scheduler uses (``append`` /
    ``popleft`` / ``len`` / truthiness) so it drops into
    ``Scheduler._queue`` unchanged.

    Within a priority band, requests carry virtual-time tags in the
    SFQ style: a request's start tag is max(band virtual time, its
    tenant's last finish tag), its finish tag start + cost/weight
    (cost = prompt + max_tokens — the service estimate). ``popleft``
    serves the smallest head finish tag among tenants, which bounds any
    tenant's lead over its weighted share by one request's cost — the
    two-tenant starvation bound tests/test_fleet.py pins. Bands are
    strict priority (high before normal before low); over-budget
    tenants (TenantLedger) are eligible only when NO in-budget tenant
    waits in any band, so a hog's overage rides idle capacity and never
    moves a victim.

    Locking: ``append``/``popleft`` take a tiny internal lock never
    held across a forward — the submit path stays as cheap as the
    deque it replaces (the measured constraint: mutex-taking submits
    once stalled a 2.8 s arrival trace to 8.5 s). ``__len__``/
    ``__bool__`` read one int lock-free, preserving the scheduler's
    and supervisor's lock-free busy checks."""

    def __init__(self, ledger: TenantLedger | None = None):
        self.ledger = ledger
        self._lock = threading.Lock()
        # band index -> tenant -> deque[(finish_tag, start_tag, req)]
        self._bands: dict[int, dict[str, deque]] = {
            i: {} for i in range(len(PRIORITIES))}  # dlrace: guarded-by(self._lock)
        self._vt = [0.0] * len(PRIORITIES)  # dlrace: guarded-by(self._lock)
        # (band, tenant) -> last finish tag handed out
        self._finish: dict[tuple, float] = {}  # dlrace: guarded-by(self._lock)
        self._n = 0  # dlrace: guarded-by(self._lock)

    def __len__(self) -> int:
        return self._n  # lock-free: int read is atomic under the GIL

    def __bool__(self) -> bool:
        return self._n > 0

    @staticmethod
    def _band_of(req) -> int:
        p = getattr(req, "priority", "normal")
        try:
            return PRIORITIES.index(p)
        except ValueError:
            return PRIORITIES.index("normal")

    @staticmethod
    def _cost_of(req) -> float:
        return float(len(getattr(req, "prompt", ()) or ())
                     + max(int(getattr(req, "max_tokens", 0) or 0), 1))

    def append(self, req) -> None:
        tenant = getattr(req, "tenant", None) or DEFAULT_TENANT
        band = self._band_of(req)
        weight = self.ledger.weight(tenant) if self.ledger else 1.0
        cost = self._cost_of(req)
        with self._lock:
            start = max(self._vt[band],
                        self._finish.get((band, tenant), 0.0))
            finish = start + cost / max(weight, 1e-9)
            self._finish[(band, tenant)] = finish
            self._bands[band].setdefault(tenant, deque()).append(
                (finish, start, req))
            self._n += 1

    def popleft(self):
        """The next request to admit (IndexError when empty — the
        deque contract ``Scheduler._abort_all`` relies on). Charges the
        winner's cost to its tenant's budget."""
        with self._lock:
            if self._n == 0:
                raise IndexError("pop from an empty WFQueue")
            pick = self._pick_locked(budgeted=True)
            if pick is None:
                # every waiting tenant is over budget: work-conserving
                # fallback — serve the overage by the same tags
                pick = self._pick_locked(budgeted=False)
            band, tenant, dq = pick
            finish, start, req = dq.popleft()
            if not dq:
                del self._bands[band][tenant]
            self._vt[band] = max(self._vt[band], start)
            self._n -= 1
        if self.ledger is not None:
            self.ledger.charge(tenant, int(self._cost_of(req)))
        return req

    def _pick_locked(self, budgeted: bool):  # dlrace: holds(self._lock)
        for band in range(len(PRIORITIES)):
            tenants = self._bands[band]
            best = None
            for tenant, dq in tenants.items():
                if not dq:
                    continue
                if budgeted and self.ledger is not None \
                        and not self.ledger.in_budget(tenant):
                    continue
                head = dq[0][0]
                if best is None or head < best[0]:
                    best = (head, tenant, dq)
            if best is not None:
                return (band, best[1], best[2])
        return None

    def snapshot_depths(self) -> dict:
        """{priority: queued} — the fleet /stats block's queue shape."""
        with self._lock:
            return {PRIORITIES[b]: sum(len(dq) for dq in t.values())
                    for b, t in self._bands.items()}


class ShedLadder:
    """The door-level overload ladder (rungs in ``LADDER_RUNGS``),
    walked monotonically one rung at a time with count-based hysteresis
    — ``up_after`` consecutive observations above ``hi`` escalate,
    ``down_after`` consecutive below ``lo`` recover, with ``cooldown``
    observations of dead time after every move so one noisy tick cannot
    thrash the ladder (the same discipline as AdmissionPolicy's chunk
    walk, which remains the rung BELOW this ladder: ``no_spec`` here
    composes with the policy's own spec actuator — either may turn
    drafting off, both must agree to turn it on).

    The pressure signal is the caller's (the FleetController feeds
    queue depth per slot of routable capacity); the drain rate feeds
    ``retry_after`` so a 429's Retry-After is derived from how fast the
    queue is ACTUALLY draining, not a constant."""

    def __init__(self, *, hi: float = 0.8, lo: float = 0.3,
                 up_after: int = 2, down_after: int = 4,
                 cooldown: int = 2, clamp_tokens: int = 64):
        self.hi = float(hi)
        self.lo = float(lo)
        self.up_after = int(up_after)
        self.down_after = int(down_after)
        self.cooldown = int(cooldown)
        self.clamp_tokens = int(clamp_tokens)
        self.rung = 0
        self.escalations = 0
        self.recoveries = 0
        self._above = 0
        self._below = 0
        self._since_move = self.cooldown  # first move is eligible
        self._drain_rate = 0.0   # requests/sec, EWMA
        self._queued = 0

    @property
    def name(self) -> str:
        return LADDER_RUNGS[self.rung]

    @property
    def spec_degraded(self) -> bool:
        return self.rung >= LADDER_RUNGS.index("no_spec")

    def observe(self, pressure: float, *, queued: int = 0,
                drained: float = 0.0) -> int:
        """One controller tick's observation: pressure in [0, inf),
        queued requests, and requests drained since the last tick
        (already per-second). Returns the rung AFTER the walk."""
        self._queued = int(queued)
        self._drain_rate = 0.5 * self._drain_rate + 0.5 * max(drained, 0.0)
        self._since_move += 1
        if pressure > self.hi:
            self._above += 1
            self._below = 0
        elif pressure < self.lo:
            self._below += 1
            self._above = 0
        else:
            self._above = 0
            self._below = 0
        if self._since_move < self.cooldown:
            return self.rung
        if (self._above >= self.up_after
                and self.rung + 1 < len(LADDER_RUNGS)):
            self.rung += 1
            self.escalations += 1
            self._above = 0
            self._since_move = 0
            if TRACER.enabled:
                TRACER.event("degrade", 0, rung=self.rung,
                             name=self.name, pressure=round(pressure, 3))
        elif self._below >= self.down_after and self.rung > 0:
            self.rung -= 1
            self.recoveries += 1
            self._below = 0
            self._since_move = 0
            if TRACER.enabled:
                TRACER.event("degrade", 0, rung=self.rung,
                             name=self.name, pressure=round(pressure, 3),
                             recovered=True)
        return self.rung

    def retry_after(self) -> float:
        """A shed 429's Retry-After: queued work over the live drain
        rate, clamped to [0.5, 30] s — the time until the queue has
        actually made room, not a constant guess."""
        if self._drain_rate <= 1e-6:
            return 30.0
        return min(max(self._queued / self._drain_rate, 0.5), 30.0)

    def admit(self, *, max_tokens: int, prefix_hit: bool) -> tuple:
        """Walk the ladder for ONE arriving request. Returns
        ``(allowed, max_tokens, reason)`` — reason is None when nothing
        degraded, else the rung name that acted. The shed decision
        raises nothing itself: the door owns the structured 429."""
        if self.rung >= LADDER_RUNGS.index("shed"):
            return (False, max_tokens, "shed")
        if self.rung >= LADDER_RUNGS.index("prefix_only") and not prefix_hit:
            return (False, max_tokens, "prefix_only")
        if self.rung >= LADDER_RUNGS.index("clamp") \
                and (max_tokens <= 0 or max_tokens > self.clamp_tokens):
            return (True, self.clamp_tokens, "clamp")
        return (True, max_tokens, None)

    def summary(self) -> dict:
        return {
            "rung": self.rung,
            "name": self.name,
            "escalations": self.escalations,
            "recoveries": self.recoveries,
            "drain_rate": round(self._drain_rate, 3),
            "retry_after_s": round(self.retry_after(), 3),
        }


class ShedReject(Exception):
    """A request shed by the overload ladder — the door maps it to a
    structured 429 with the drain-rate-derived Retry-After."""

    def __init__(self, reason: str, retry_after: float):
        super().__init__(f"overload: {reason}")
        self.reason = reason
        self.retry_after = float(retry_after)


class FleetConfig:
    """Anti-flap knobs, all count-based (ticks of the controller's
    ``poll`` cadence) so every bar in tests/test_fleet.py is
    deterministic under a driven ``tick()`` (docs/operations.md
    "Overload and autoscaling" documents each knob)."""

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 1,
                 poll: float = 0.5, up_pressure: float = 0.75,
                 down_pressure: float = 0.15, up_after: int = 3,
                 down_after: int = 8, cooldown_ticks: int = 4,
                 spawn_backoff_ticks: int = 6, ewma_alpha: float = 0.4,
                 warm_prompts: int = 4):
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.poll = float(poll)
        self.up_pressure = float(up_pressure)
        self.down_pressure = float(down_pressure)
        self.up_after = int(up_after)
        self.down_after = int(down_after)
        self.cooldown_ticks = int(cooldown_ticks)
        self.spawn_backoff_ticks = int(spawn_backoff_ticks)
        self.ewma_alpha = float(ewma_alpha)
        self.warm_prompts = int(warm_prompts)


class FleetController:
    """The measurement→decision loop over one serving front door
    (Router or EngineSupervisor — autoscaling needs a Router with a
    spawn factory; the ladder and fairness work on every tier).

    One controller thread ticks every ``config.poll`` seconds; each
    ``tick()`` is also a public, synchronous entry point so tests drive
    the loop count-deterministically with zero sleeps. A tick:

      1. observes per-tier load (queued + running per routable slot,
         EWMA-smoothed) and feeds the shed ladder;
      2. applies the ladder's ``no_spec`` rung to every local
         scheduler (process workers run their own AdmissionPolicy
         actuator worker-side — the parent's ladder governs the door);
      3. walks the scale decision per tier (prefill and decode/mixed
         resize independently): sustained pressure spawns (bounded by
         ``max_replicas`` and the HBM ledger's ``slots_addable``),
         sustained idle drains + reaps (never below ``min_replicas``).

    Chaos-proofing: the spawn runs on a worker thread (a SIGKILL of the
    half-built replica lands in that thread's failure fold, counted as
    ``spawn_failures`` + backoff ticks — never a confused respawn);
    the reap path marks the victim ``reap=True`` BEFORE draining so
    ``/readyz`` and ``Router.state`` report ``scaling_down`` instead of
    a health problem, and closes the handle (which retires its monitor)
    before removing it from rotation. A freshly spawned replica is
    warmed twice over: its supervisor/worker warms every compile key
    before it reports ready (zero post-warmup compiles), and the
    controller replays the router's recent prompts through the PR-14
    fill path so its CACHE starts warm too."""

    def __init__(self, door, *, config: FleetConfig | None = None,
                 ladder: ShedLadder | None = None,
                 ledger: TenantLedger | None = None,
                 stats=None, clock=time.monotonic):
        from .stats import FleetStats

        self.door = door
        self.config = config or FleetConfig()
        self.ladder = ladder
        self.ledger = ledger
        self.stats = stats or FleetStats(enabled=True)
        self._clock = clock
        self._lock = threading.Lock()
        self._closed = False
        # per-tier ("prefill" vs "serve" = decode+mixed) decision state
        self._load_ewma: dict[str, float] = {}  # dlrace: guarded-by(self._lock)
        self._above: dict[str, int] = {}  # dlrace: guarded-by(self._lock)
        self._idle: dict[str, int] = {}  # dlrace: guarded-by(self._lock)
        self._cooldown: dict[str, int] = {}  # dlrace: guarded-by(self._lock)
        self._backoff = 0  # dlrace: guarded-by(self._lock)
        # replica ids reserved by in-flight spawn threads (a spawn can
        # take minutes; the walk counts these toward max_replicas and
        # the next decision mints a DISTINCT id)
        self._pending: set[int] = set()  # dlrace: guarded-by(self._lock)
        self._scaling_threads: list[threading.Thread] = []
        self._flap_phase = False  # scale_flap fault toggle
        self._finished_last = 0
        self._last_tick = clock()
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run,
                                        name="dllama-fleet", daemon=True)
        self._thread.start()

    def close(self, timeout: float = 10.0) -> None:
        self._closed = True
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=timeout)
        for t in list(self._scaling_threads):
            if t.is_alive():
                t.join(timeout=timeout)

    def _run(self) -> None:
        while not self._closed:
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the brain must outlive a
                # transiently unreadable signal (a replica mid-respawn
                # answering half a summary); decisions resume next tick
                pass
            time.sleep(self.config.poll)

    # -- the door-side admission walk -------------------------------------

    def admit(self, *, tenant: str | None, n_prompt: int,
              max_tokens: int, prefix_hit: bool = False) -> int:
        """Run ONE arriving request through the shed ladder (called by
        the API door before submit). Returns the possibly-clamped
        max_tokens; raises ShedReject with the drain-rate Retry-After
        when the request must be turned away. Sheds are accounted per
        tenant and traced."""
        if self.ladder is None:
            return max_tokens
        allowed, mt, reason = self.ladder.admit(max_tokens=max_tokens,
                                                prefix_hit=prefix_hit)
        if allowed:
            if reason == "clamp":
                with self._lock:
                    self.stats.clamped += 1
            return mt
        name = tenant or DEFAULT_TENANT
        if self.ledger is not None:
            self.ledger.note_shed(name)
        with self._lock:
            self.stats.sheds += 1
            self.stats.sheds_by_reason[reason] = (
                self.stats.sheds_by_reason.get(reason, 0) + 1)
        retry = self.ladder.retry_after()
        if TRACER.enabled:
            TRACER.event("shed", 0, tenant=name, reason=reason,
                         retry_after=round(retry, 3),
                         n_prompt=int(n_prompt))
        raise ShedReject(reason, retry)

    # -- observation -------------------------------------------------------

    def _handles(self) -> list:
        return getattr(self.door, "replicas", None) or []

    def _serve_group(self, h) -> str:
        """Which saturation signal a replica feeds: the prefill tier
        resizes from its own load, decode and mixed replicas share the
        request-serving signal."""
        return "prefill" if getattr(h, "tier", "mixed") == "prefill" \
            else "serve"

    def _capacity(self) -> int:
        """KV slots per replica — the pressure denominator. The door's
        engine template knows its batch; a process tier that has not
        handshaken yet falls back to 1 (pressure reads high, which only
        delays scale-down — the safe direction)."""
        try:
            return max(int(self.door.engine.batch), 1)
        except Exception:  # noqa: BLE001 — EngineUnready pre-handshake
            return 1

    def _observe(self) -> dict:
        """{group: (pressure, n_routable)} over the live handles (or
        the single supervisor)."""
        handles = self._handles()
        cap = self._capacity()
        if not handles:
            sup = self.door
            try:
                sched = sup._sched
                load = (len(sched._queue)
                        + sum(1 for s in sched.slots if s.req is not None))
            except Exception:  # noqa: BLE001 — mid-rebuild
                load = 0
            return {"serve": (load / cap, 1)}
        out: dict[str, list] = {}
        for h in handles:
            if getattr(h, "reap", False):
                continue  # a draining-for-reap replica is not capacity
            g = self._serve_group(h)
            acc = out.setdefault(g, [0.0, 0])
            try:
                if h.sup is not None and h.sup.ready and not h.draining:
                    acc[0] += float(h.load())
                    acc[1] += 1
            except Exception:  # noqa: BLE001 — a dying replica's health
                continue
        return {g: ((load / (n * cap)) if n else 1.0, n)
                for g, (load, n) in out.items()}

    def _queued_total(self) -> int:
        handles = self._handles()
        if not handles:
            try:
                return len(self.door._sched._queue)
            except Exception:  # noqa: BLE001
                return 0
        total = 0
        for h in handles:
            try:
                total += max(int(h.load()) - self._capacity(), 0)
            except Exception:  # noqa: BLE001
                continue
        return total

    def _finished_total(self) -> int:
        try:
            return int(self.door.summary().get("requests_finished") or 0)
        except Exception:  # noqa: BLE001
            return self._finished_last

    def _hbm_headroom_ok(self) -> bool:
        """The hard ceiling: one more replica costs ``capacity`` KV
        slots — refuse the spawn when the HBM ledger says they do not
        fit. Unknown headroom (CPU backends report no limit) allows."""
        try:
            summary = self.door.summary()
            reps = summary.get("replicas") or [summary]
            for rep in reps:
                hbm = rep.get("hbm") if isinstance(rep, dict) else None
                if not isinstance(hbm, dict):
                    continue
                addable = hbm.get("slots_addable")
                if addable is not None:
                    return int(addable) >= self._capacity()
        except Exception:  # noqa: BLE001 — no signal = no ceiling
            return True
        return True

    # -- the decision loop -------------------------------------------------

    def tick(self) -> dict:
        """One observation + decision round (the thread's body and the
        tests' deterministic driver). Returns the observation so chaos
        tests can assert on the exact signal a decision saw."""
        now = self._clock()
        dt = max(now - self._last_tick, 1e-6)
        self._last_tick = now
        obs = self._observe()
        # scale_flap (runtime/faults.py): replace the measured pressure
        # with a synthetic oscillation for exactly as many ticks as the
        # site is armed — the anti-flap bars count fires
        if FAULTS.triggered("scale_flap"):
            self._flap_phase = not self._flap_phase
            flap = 1.0 if self._flap_phase else 0.0
            obs = {g: (flap, n) for g, (n_p, n) in
                   zip(obs.keys(), obs.values())} or {"serve": (flap, 1)}
        finished = self._finished_total()
        drained = max(finished - self._finished_last, 0) / dt
        self._finished_last = finished
        queued = self._queued_total()
        serve_pressure = obs.get("serve", (0.0, 0))[0]
        if self.ladder is not None:
            rung = self.ladder.observe(serve_pressure, queued=queued,
                                       drained=drained)
            self._apply_degrade(rung)
        with self._lock:
            self.stats.ticks += 1
            self.stats.pressure = round(serve_pressure, 4)
            if self._backoff > 0:
                self._backoff -= 1
        if self._scalable():
            for group, (pressure, n) in obs.items():
                self._walk_scale(group, pressure, n)
        return {"obs": obs, "queued": queued, "drained": drained}

    def _apply_degrade(self, rung: int) -> None:
        """Rung >= no_spec turns per-slot drafting off on every LOCAL
        scheduler (thread replicas + the single supervisor; process
        workers keep their own worker-side AdmissionPolicy actuator —
        the parent's ladder acts at the door it owns). Re-applied every
        tick so a supervisor rebuild (fresh scheduler) re-learns the
        current rung within one poll."""
        degraded = self.ladder.spec_degraded if self.ladder else False
        with self._lock:
            self.stats.rung = self.ladder.rung if self.ladder else 0
        sups = ([h.sup for h in self._handles()
                 if getattr(h, "has_local_engine", True)
                 and h.sup is not None]
                or ([self.door] if not self._handles() else []))
        for sup in sups:
            try:
                sup._sched.spec_degraded = degraded
            except Exception:  # noqa: BLE001 — mid-rebuild: the fresh
                continue      # scheduler picks the rung up next tick

    def _scalable(self) -> bool:
        return (self.config.max_replicas > self.config.min_replicas
                or self.config.max_replicas > 1) \
            and getattr(self.door, "_spawn_factory", None) is not None

    def _tier_handles(self, group: str) -> list:
        return [h for h in self._handles()
                if self._serve_group(h) == group
                and not getattr(h, "reap", False)]

    def _walk_scale(self, group: str, pressure: float, n: int) -> None:
        cfg = self.config
        with self._lock:
            a = self.config.ewma_alpha
            prev = self._load_ewma.get(group, pressure)
            ewma = a * pressure + (1.0 - a) * prev
            self._load_ewma[group] = ewma
            cd = self._cooldown.get(group, 0)
            if cd > 0:
                self._cooldown[group] = cd - 1
                return
            if ewma > cfg.up_pressure:
                self._above[group] = self._above.get(group, 0) + 1
                self._idle[group] = 0
            elif ewma < cfg.down_pressure:
                self._idle[group] = self._idle.get(group, 0) + 1
                self._above[group] = 0
            else:
                self._above[group] = 0
                self._idle[group] = 0
            want_up = (self._above.get(group, 0) >= cfg.up_after
                       and self._backoff == 0)
            want_down = self._idle.get(group, 0) >= cfg.down_after
            pending = len(self._pending)
        # in-flight spawns count toward the ceiling: a spawn can take
        # minutes, and a second decision inside that window must not
        # double-mint the same replica id (or overshoot max_replicas)
        total = len([h for h in self._handles()
                     if not getattr(h, "reap", False)]) + pending
        if want_up:
            if total >= cfg.max_replicas:
                return
            if not self._hbm_headroom_ok():
                with self._lock:
                    self.stats.scale_blocked_hbm += 1
                return
            with self._lock:
                self._above[group] = 0
                self._cooldown[group] = cfg.cooldown_ticks
            self._scale_up(group, pressure)
        elif want_down:
            if total <= max(cfg.min_replicas, 1) or n <= 1:
                return
            with self._lock:
                self._idle[group] = 0
                self._cooldown[group] = cfg.cooldown_ticks
            self._scale_down(group, pressure)

    # -- scale-up ----------------------------------------------------------

    def _scale_up(self, group: str, pressure: float) -> None:
        router = self.door
        tier = "prefill" if group == "prefill" else "mixed"
        with self._lock:
            # reserve the id against concurrent/in-flight spawns: the
            # next decision sees it in _pending and mints rid + 1
            rid = max((h.id for h in self._handles()),
                      default=-1) + 1
            while rid in self._pending:
                rid += 1
            self._pending.add(rid)
            self.stats.target_replicas = (len(self._handles())
                                          + len(self._pending))
        router.scaling = "scaling_up"
        print(f"🧠 fleet: scale_up tier={tier} replica=r{rid} "
              f"pressure={pressure:.2f} "
              f"actual={len(self._handles())} "
              f"target={self.stats.target_replicas}", flush=True)
        t = threading.Thread(target=self._spawn_one, args=(rid, tier),
                             name=f"dllama-fleet-spawn-r{rid}",
                             daemon=True)
        self._scaling_threads.append(t)
        t.start()

    def _spawn_one(self, rid: int, tier: str) -> None:
        """Worker-thread body: build one replica handle (blocks on the
        spawn handshake + warmup — possibly minutes), enter it into
        rotation, warm its cache from siblings. A failure at ANY point
        folds into spawn_failures + backoff ticks — never a half-entered
        handle (the handle only joins ``router.replicas`` after its own
        constructor proved it routable-warm)."""
        router = self.door
        t0 = time.perf_counter()
        handle = None
        try:
            # slow-spawn chaos site: key-filtered so ONE scale-up can be
            # stalled deterministically while siblings spawn clean
            FAULTS.fire("spawn_stall", key=f"r{rid}")
            handle = router._spawn_factory(rid, tier)
            self._warm_from_siblings(handle)
            router.add_replica(handle)
            with self._lock:
                self.stats.scale_ups += 1
                self.stats.target_replicas = (len(self._handles())
                                              + len(self._pending) - 1)
            ms = (time.perf_counter() - t0) * 1e3
            if TRACER.enabled:
                TRACER.event("scale_up", 0, replica=rid, tier=tier,
                             ms=round(ms, 1))
            print(f"🧠 fleet: scale_up DONE replica=r{rid} tier={tier} "
                  f"ms={ms:.0f}", flush=True)
        except Exception as e:  # noqa: BLE001 — spawn died (or the entry
            # was refused): count + back off, and CLOSE a built handle —
            # a live worker process must never outlive a failed entry
            if handle is not None:
                try:
                    handle.close()
                except Exception:  # noqa: BLE001
                    pass
            with self._lock:
                self.stats.spawn_failures += 1
                self._backoff = self.config.spawn_backoff_ticks
            print(f"🧠 fleet: scale_up FAILED replica=r{rid} ({e}) — "
                  f"backing off {self.config.spawn_backoff_ticks} ticks",
                  flush=True)
        finally:
            with self._lock:
                self._pending.discard(rid)
            router.scaling = None

    def _warm_from_siblings(self, handle) -> None:
        """PR-14 cache warmup for a fresh replica: replay the router's
        recent prompts as max_tokens=0 prefills, each with a fill from
        the warmest sibling — the new cache seeds from donors instead
        of starting cold. Best-effort by design: every failure shape
        degrades to a cold start, never an error (the handle is already
        COMPILE-warm from its own constructor)."""
        router = self.door
        if not getattr(router, "_kv_transfer", False):
            return
        prompts = list(getattr(router, "_recent_prompts", ()) or ())
        if not prompts:
            return
        from ..sampler import Sampler

        filled = 0
        for prompt in prompts[-self.config.warm_prompts:]:
            try:
                donor = router._pick_donor(handle, prompt)
                if donor is None:
                    continue
                dh, dn = donor
                fill = None
                if hasattr(handle, "client") and hasattr(dh, "client"):
                    addr = dh.client.addr
                    fill = (addr[0], addr[1], dn, dh.id)
                elif not hasattr(handle, "client") \
                        and not hasattr(dh, "client"):
                    from .kv_transfer import local_fill

                    local_fill(dh.sup, handle.sup, prompt,
                               stats=getattr(router, "kvx", None))
                    handle.note_routed(prompt)
                    filled += 1
                    continue
                vocab = max(int(max(prompt)) + 1, 2)
                sampler = Sampler(vocab, temperature=0.0, topp=1.0, seed=1)
                inner = handle.sup.submit(prompt, 0, sampler, fill=fill)
                for _ in inner.tokens(timeout=30.0):
                    pass
                handle.note_routed(prompt)
                filled += 1
            except Exception:  # noqa: BLE001 — cold start, not an error
                continue
        with self._lock:
            self.stats.warm_fills += filled

    # -- scale-down --------------------------------------------------------

    def _scale_down(self, group: str, pressure: float) -> None:
        """Reap the highest-id idle replica of the group: mark it
        ``reap`` FIRST (readiness and state reporting exclude it from
        that moment — satellite: a draining-for-reap replica must not
        flip fleet readiness), drain it, close it (retiring its monitor
        so a respawn can never resurrect it — the close-before-remove
        ordering RemoteReplicaHandle.close guarantees), then drop it
        from rotation."""
        router = self.door
        victims = sorted(self._tier_handles(group), key=lambda h: -h.id)
        victim = None
        for h in victims:
            try:
                if not h.draining and h.load() == 0:
                    victim = h
                    break
            except Exception:  # noqa: BLE001
                continue
        if victim is None or len(self._tier_handles(group)) <= 1:
            return
        with self._lock:
            self.stats.target_replicas = len(self._handles()) - 1
        victim.reap = True
        router.scaling = "scaling_down"
        print(f"🧠 fleet: scale_down tier={group} replica=r{victim.id} "
              f"pressure={pressure:.2f} "
              f"target={self.stats.target_replicas}", flush=True)
        t = threading.Thread(target=self._reap_one, args=(victim,),
                             name=f"dllama-fleet-reap-r{victim.id}",
                             daemon=True)
        self._scaling_threads.append(t)
        t.start()

    def _reap_one(self, victim) -> None:
        router = self.door
        t0 = time.perf_counter()
        try:
            victim.drain(timeout=30.0)
            router.reap_replica(victim.id)
            with self._lock:
                self.stats.scale_downs += 1
            if TRACER.enabled:
                TRACER.event("scale_down", 0, replica=victim.id,
                             ms=round((time.perf_counter() - t0) * 1e3, 1))
            print(f"🧠 fleet: scale_down DONE replica=r{victim.id}",
                  flush=True)
        except Exception:  # noqa: BLE001 — victim died mid-drain: its
            # monitor (already told to close via reap_replica next tick)
            # or the next tick's walk owns the retry
            victim.reap = False
        finally:
            router.scaling = None

    # -- observability -----------------------------------------------------

    def summary(self) -> dict:
        out = self.stats.summary()
        handles = self._handles()
        out["actual_replicas"] = (len([h for h in handles
                                       if not getattr(h, "reap", False)])
                                  if handles else 1)
        if out.get("target_replicas", 0) == 0:
            out["target_replicas"] = out["actual_replicas"]
        out["min_replicas"] = self.config.min_replicas
        out["max_replicas"] = self.config.max_replicas
        out["autoscaling"] = self._scalable()
        if self.ladder is not None:
            out["ladder"] = self.ladder.summary()
        if self.ledger is not None:
            out["tenants"] = self.ledger.summary()
        return out
