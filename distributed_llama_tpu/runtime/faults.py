"""Deterministic fault injection for the serving stack.

The reference engine has no fault tolerance (SURVEY §"no fault tolerance"),
and the real failure shapes this repo has hit are not reproducible at will:
the TPU plugin HANGS (not errors) when its tunnel is down, and a mid-decode
crash poisons the donated KV cache. This registry makes every one of those
shapes a one-line, count-deterministic trigger so the whole resilience
layer (runtime/resilience.py) is testable in CI on CPU.

Named sites, fired host-side BEFORE any device dispatch (so arming a fault
never changes a jitted program — the dlgrind entry-point fingerprints are
invariant under injection):

  * ``step_raise``    — scheduler step loop, start of an iteration: raises
                        ``FaultError`` (the crash shape)
  * ``step_stall``    — same place: blocks for ``ms`` milliseconds or until
                        ``release()`` (the axon-hang shape — a watchdog must
                        detect it, nothing else will)
  * ``prefill_raise`` — Engine.slot_prefill_chunk entry: raises
                        ``FaultError`` mid-admission
  * ``slow_step``     — scheduler step loop: sleeps ``ms`` per fire (the
                        degraded-but-alive shape deadlines must catch)

Replica-level sites, fired in the scheduler step loop of schedulers that
carry a ``fault_key`` (the router's replicas — runtime/router.py names
replica i's scheduler ``r{i}``), so multi-replica chaos tests can kill or
wedge ONE replica deterministically while its siblings keep serving:

  * ``replica_raise`` — like ``step_raise``, but an armed ``key=rK`` spec
                        only counts and fires on replica K's steps (the
                        kill-one-mid-trace shape: the router must retry
                        not-yet-streamed requests on a survivor)
  * ``replica_stall`` — like ``step_stall`` with the same key filter (one
                        replica wedges; only ITS watchdog may trip)
  * ``worker_exit``   — replica-worker PROCESS token stream
                        (runtime/replica_worker.py): the worker queries
                        ``triggered()`` before each token frame and
                        ``os._exit``s hard — the in-process stand-in for
                        SIGKILL/OOM, count-deterministic and key-filtered
                        like the other replica sites (armed via
                        ``DLLAMA_FAULTS`` in the worker's environment)

Socket-layer sites, fired inside the multihost control-plane frame codec
(parallel/multihost.py) so two-process chaos tests can kill or stall either
side of the root<->worker star and assert bounded detection
(tests/test_cluster_chaos.py):

KV-transfer sites, fired in the donor's block-export loop
(runtime/kv_transfer.py) so chaos tests can kill or wedge a transfer at
an exact BLOCK_DATA frame (key-filtered like the replica sites — the
donor worker's fault_key):

  * ``kvx_stall``      — donor export loop, before a BLOCK_DATA send:
                         blocks like ``step_stall`` (wedged donor — the
                         importer's per-transfer deadline must fire and
                         degrade to a local re-prefill)
  * ``kvx_exit``       — same place, ``triggered()`` form: the donor
                         ``os._exit``s hard mid-stream (the SIGKILL/OOM
                         shape landing exactly between two block frames)

Fleet-controller sites, fired in the autoscaler's decision loop
(runtime/fleet.py) so anti-flap hysteresis and spawn backoff are
count-deterministically testable (tests/test_fleet.py):

  * ``spawn_stall``    — controller scale-up path, before the replica
                         spawn: blocks like ``step_stall`` (a slow
                         container/TPU grant — the controller must keep
                         serving and keep its ``scaling_up`` state
                         truthful while one spawn crawls; key-filtered
                         by the new replica's ``rK`` so ONE scale-up
                         stalls while siblings spawn clean)
  * ``scale_flap``     — controller tick, ``triggered()`` form: each
                         fire flips a synthetic full/empty load signal
                         (the oscillating-traffic shape — the
                         controller's EWMA + cooldown must NOT flap
                         replicas up and down; the test counts fires,
                         not wall time)

  * ``conn_refused``   — worker connect attempt: raises
                         ``ConnectionRefusedError`` (exercises the
                         cluster-formation retry/backoff path; ``times=K``
                         fails the first K attempts deterministically)
  * ``recv_stall``     — frame receive entry: blocks like ``step_stall``
                         (a wedged peer that holds its socket open but
                         stops reading — and so stops answering
                         heartbeats; only the PING/PONG timeout detects it)
  * ``frame_truncate`` — frame send: writes half the frame then closes the
                         socket (the peer sees a mid-frame EOF — the
                         torn-write shape)
  * ``peer_close``     — frame send: closes the socket without writing
                         (the abrupt-death shape at a protocol point)

Arming is test-driven (``FAULTS.arm(...)``) or env-driven for subprocess
harnesses (bench chaos rows, CI):

    DLLAMA_FAULTS="step_raise:after=40;times=1,slow_step:ms=50;times=0"

``after=N`` skips the first N invocations of the site, ``times=K`` fires on
the next K (K=0 → every invocation), ``ms=F`` sets the stall/sleep length,
``key=S`` restricts a replica-level site to the scheduler whose
``fault_key`` is S (invocations from other keys are not even counted, so
``after`` stays deterministic per replica).
Counters are per-site and monotonically increasing, so a given arm spec
fires at exactly the same invocations on every run — crashes land on the
same scheduler iteration every time.
"""

from __future__ import annotations

import dataclasses
import os
import threading

from .trace import TRACER

SITES = ("step_raise", "step_stall", "prefill_raise", "slow_step",
         "replica_raise", "replica_stall", "worker_exit",
         "conn_refused", "recv_stall", "frame_truncate", "peer_close",
         "kvx_stall", "kvx_exit", "spawn_stall", "scale_flap")


class FaultError(RuntimeError):
    """The injected failure (distinct type so tests can tell an injected
    crash from a real one)."""


@dataclasses.dataclass
class _Armed:
    site: str
    after: int = 0     # skip this many invocations of the site first
    times: int = 1     # then fire on this many (0 = every one from there on)
    ms: float = 0.0    # stall/sleep milliseconds (step_stall / slow_step)
    key: str | None = None  # replica filter: only fire() calls carrying
    # this key count or fire (None = any caller)
    hits: int = 0      # invocations seen
    fired: int = 0     # invocations that actually fired

    def should_fire(self) -> bool:
        self.hits += 1
        if self.hits <= self.after:
            return False
        if self.times and self.fired >= self.times:
            return False
        self.fired += 1
        return True


class FaultRegistry:
    """Thread-safe, count-deterministic fault trigger store. One process
    singleton (``FAULTS``); the scheduler/engine call ``fire(site)`` at the
    named sites and pay one dict lookup when nothing is armed."""

    def __init__(self):
        self._lock = threading.Lock()
        self._armed: dict[str, _Armed] = {}  # dlrace: guarded-by(self._lock)
        # a stalled site blocks on this event, so tests can release a
        # "hung" thread instead of leaking it for the stall duration
        self._release = threading.Event()

    def arm(self, site: str, *, after: int = 0, times: int = 1,
            ms: float = 0.0, key: str | None = None) -> None:
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r} (have {SITES})")
        with self._lock:
            self._release.clear()
            self._armed[site] = _Armed(site, after=after, times=times, ms=ms,
                                       key=key)

    def clear(self, site: str | None = None) -> None:
        """Disarm (one site or everything) and release any in-progress
        stall — test teardown must never leave a thread blocked."""
        with self._lock:
            if site is None:
                self._armed.clear()
            else:
                self._armed.pop(site, None)
            self._release.set()

    def release(self) -> None:
        """Unblock any thread currently inside a ``step_stall``."""
        self._release.set()

    def armed(self, site: str) -> bool:
        with self._lock:
            return site in self._armed

    def fired(self, site: str) -> int:
        with self._lock:
            a = self._armed.get(site)
            return a.fired if a else 0

    def fire(self, site: str, key: str | None = None) -> None:
        """Called at the named site. No-op unless armed; otherwise raises
        (``*_raise``), stalls (``step_stall``) or sleeps (``slow_step``)
        per the armed spec. ``key`` identifies the caller for the
        replica-level sites: an armed spec carrying a key neither fires
        NOR counts a hit for any other caller, so ``after=N`` lands on
        replica K's N+1-th step regardless of what its siblings do."""
        with self._lock:
            a = self._armed.get(site)
            if a is None or (a.key is not None and key != a.key):
                return
            if not a.should_fire():
                return
            ms = a.ms
            fired = a.fired
        if TRACER.enabled:
            # the flight recorder sees every fault that actually FIRED —
            # a chaos timeline must show the injected kill next to the
            # spans it killed (runtime/trace.py)
            TRACER.event("fault", 0, site=site, key=key, n=fired)
        if site == "conn_refused":
            # the REAL exception type the connect retry path handles — an
            # injected refusal must walk the same backoff code as a root
            # that is not up yet
            raise ConnectionRefusedError(f"injected {site} (fire #{a.fired})")
        if site.endswith("_raise"):
            raise FaultError(f"injected {site} (fire #{a.fired})")
        if site in ("step_stall", "recv_stall", "replica_stall",
                    "kvx_stall", "spawn_stall"):
            # block like the real hang: until released or ms elapses
            # (default: effectively forever — the watchdog's / the peer
            # heartbeat timeout's job)
            self._release.wait(timeout=(ms / 1e3) if ms else 3600.0)
            return
        if site == "slow_step" and ms:
            import time

            time.sleep(ms / 1e3)

    def triggered(self, site: str, key: str | None = None) -> bool:
        """Count-deterministic QUERY form of ``fire()`` for sites whose
        effect the CALLER performs rather than this registry raising or
        stalling (``frame_truncate``/``peer_close`` — the codec owns the
        socket and performs the mangle itself; ``worker_exit`` — the
        replica worker os._exits). Consumes one invocation count, with
        the same key filter as ``fire()``: an armed spec carrying a key
        neither triggers nor counts for callers with a different key."""
        with self._lock:
            a = self._armed.get(site)
            if a is None or (a.key is not None and key != a.key):
                return False
            fire = a.should_fire()
            fired = a.fired
        if fire and TRACER.enabled:
            TRACER.event("fault", 0, site=site, key=key, n=fired)
        return fire

    def load_env(self, env=None) -> None:
        """Parse ``DLLAMA_FAULTS`` (see module docstring). Malformed specs
        raise ValueError loudly — a typo'd chaos run must not silently
        measure a healthy system."""
        spec = (env if env is not None else os.environ).get(
            "DLLAMA_FAULTS", "")
        for part in filter(None, (p.strip() for p in spec.split(","))):
            site, _, opts = part.partition(":")
            kw: dict = {}
            for opt in filter(None, (o.strip() for o in opts.split(";"))):
                name, _, val = opt.partition("=")
                if name not in ("after", "times", "ms", "key"):
                    raise ValueError(
                        f"bad DLLAMA_FAULTS option {opt!r} in {part!r}")
                kw[name] = (float(val) if name == "ms"
                            else val if name == "key" else int(val))
            self.arm(site, **kw)


FAULTS = FaultRegistry()
FAULTS.load_env()
