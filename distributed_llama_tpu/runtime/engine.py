"""Inference engine: compiled prefill/decode over an optional mesh.

TPU-native replacement for the reference's Inference driver + generation
loops (ref: src/tasks.cpp:184-256, src/apps/dllama/dllama.cpp:14-91):

  * one jitted segment-forward instead of the per-token task list; the KV
    cache is donated so decode updates in place (no realloc per token)
  * chunked prefill (the reference feeds the prompt token-by-token)
  * sharded execution: params/cache placed with NamedShardings over a
    (dp, sp, tp) mesh; GSPMD emits the ICI collectives that replace the
    reference's socket broadcast/gather choreography
  * greedy sampling on device (argmax fused into the step); full
    temperature/top-p sampling on host with reference-parity RNG
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable, Iterator, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.params import fuse_layer_weights
from ..models.spec import ModelSpec
from ..models.transformer import KVCache, forward
from ..parallel.mesh import DP_AXIS, SP_AXIS
from ..parallel.sharding import cache_pspec, check_tp_constraints, shard_params
from ..sampler import Sampler
from .stats import RunStats, StepStats


class GenerationResult(NamedTuple):
    tokens: list[int]
    stats: RunStats


def seed_rows_from_blocks(cache: KVCache, arena_k, arena_v, row, block_ids
                          ) -> KVCache:
    """Seed cache row ``row``'s leading positions from prefix-arena blocks
    — the traced body of ``Engine.slot_seed_prefix`` (module-level so
    analysis/entrypoints.py fingerprints the SAME program the engine jits;
    dlgrind's DLG204 gate covers the serving seed path by construction).

    arena_k/arena_v: (num_blocks, layers, kv_heads, block_len, head_size)
    — block-major with the per-layer block laid out exactly like a cache
    slice (KVH before the sequence dim), so seed and publish are pure
    gathers/reshapes, never transposed HBM traffic against the cache's
    head-major layout. block_ids is the FIXED-width
    (seq_len // block_len,) int32 vector the scheduler always pads (with
    block 0) — the pad keeps ONE compilation key for every admission
    (the same discipline as slot_prefill_chunk's fixed C). Padded
    blocks' writes land beyond the real seeded prefix and are
    overwritten position-by-position (suffix prefill, then decode)
    before any query can attend them — the same invariant decode
    overruns rely on everywhere in the engine.

    Blocks pass through the f8 NaN-code guard
    (ops/pallas_attention.saturate_f8_nan_codes): arena bytes written by
    this engine's own forwards are saturated already, but the seeding
    boundary must not TRUST its producer — see Engine._seed_guard."""
    from ..ops.pallas_attention import saturate_f8_nan_codes

    mb = block_ids.shape[0]
    _, _, kvh, bl, hs = arena_k.shape
    z = jnp.int32(0)
    row = jnp.asarray(row, jnp.int32)
    k_all, v_all = [], []
    for l in range(len(cache.k)):
        new = []
        for arena, leaf in ((arena_k, cache.k[l]), (arena_v, cache.v[l])):
            seg = arena[block_ids, l]                  # (MB, KVH, bl, hs)
            seg = seg.transpose(1, 0, 2, 3).reshape(1, kvh, mb * bl, hs)
            seg = saturate_f8_nan_codes(seg.astype(leaf.dtype))
            new.append(lax.dynamic_update_slice(leaf, seg, (row, z, z, z)))
        k_all.append(new[0])
        v_all.append(new[1])
    return KVCache(tuple(k_all), tuple(v_all))


def export_arena_block(arena_k, arena_v, src):
    """Gather ONE published arena block pair for the cross-replica KV
    transfer plane (runtime/kv_transfer.py) — the traced body of
    ``Engine.block_export`` (module-level so analysis/entrypoints.py
    fingerprints the SAME program the engine jits). The arenas are only
    READ (never donated: the block stays published locally); the caller
    fetches the returned (layers, kv_heads, block_len, head_size) pair
    to host and ships the raw bytes."""
    src = jnp.asarray(src, jnp.int32)
    return (lax.dynamic_index_in_dim(arena_k, src, 0, keepdims=False),
            lax.dynamic_index_in_dim(arena_v, src, 0, keepdims=False))


def import_arena_block(arena_k, arena_v, k_blk, v_blk, dst):
    """Write one fetched block pair into arena slot ``dst`` — the traced
    body of ``Engine.slot_import_block``. The arenas are donated
    (in-place block write, same discipline as slot_publish_block). The
    bytes are written RAW: the seeding boundary's f8 NaN-code guard
    (seed_rows_from_blocks -> saturate_f8_nan_codes) runs when a slot is
    SEEDED from the block, so foreign bytes can never decode as finite
    480 in an attention read whatever their producer did."""
    z = jnp.int32(0)
    dst = jnp.asarray(dst, jnp.int32)
    return (lax.dynamic_update_slice(arena_k, k_blk[None],
                                     (dst, z, z, z, z)),
            lax.dynamic_update_slice(arena_v, v_blk[None],
                                     (dst, z, z, z, z)))


class Engine:
    def __init__(
        self,
        spec: ModelSpec,
        params: dict,
        mesh: Mesh | None = None,
        *,
        batch: int = 1,
        max_seq_len: int | None = None,
        compute_dtype=jnp.bfloat16,
        cache_dtype=jnp.bfloat16,
        activation_q80: bool = False,
        q80_collectives: bool | None = None,
        prefill_chunk: int = 256,  # = pallas MAX_T: fewest whole-weight
        # passes that still take the fused kernel (A/B on v5e: 3009 tok/s
        # prefill vs 1899 at 128; 512+ would fall to the XLA dequant path
        # and measured slower)
        use_pallas: bool | None = None,
        pallas_interpret: bool = False,
        pp_gpipe: bool = True,  # GPipe sequence-microbatch prefill on pp
        # meshes (parallel/pp.py:pp_layers_gpipe); False pins the
        # all-stages scheme everywhere (A/B knob)
        model_fingerprint: int = 0,  # content hash of the weights the
        # session fingerprint folds in (io.model_file.content_fingerprint);
        # 0 = unknown (in-memory params) — such sessions only check shapes
        force_mesh_kernels: bool = False,  # engage the shard_map kernel
        # path even on a 1-device mesh: the Pallas kernels then compile and
        # run INSIDE manual regions on whatever silicon is present — the
        # single-chip proof of the multi-chip kernel path (VERDICT r4 #1;
        # bench.py's shardmap variant row)
        shard_vocab: bool | None = None,  # row-split tok_emb/wcls over the
        # vocab dim (ops/sharded_vocab.py): None = auto (on whenever the
        # mesh's tp axes divide the vocab — the replicated table was
        # 533 MB/chip at 70B widths, VERDICT weak #3); True asserts the
        # mesh can; False pins the replicated parity oracle
        vocab_topk: int = 32,  # per-shard candidate count for the sharded
        # sampled path (k·S candidates provably contain the global top-k;
        # a nucleus larger than the guard allows falls back to one
        # replicated row fetch — docs/parallelism.md "Vocab sharding")
    ):
        self.mesh = mesh
        self.batch = batch
        self.pp_gpipe = pp_gpipe
        self.model_fingerprint = int(model_fingerprint)
        self.seq_len = min(max_seq_len or spec.seq_len, spec.seq_len)
        self.compute_dtype = compute_dtype
        self.cache_dtype = cache_dtype
        self.activation_q80 = activation_q80
        self.prefill_chunk = prefill_chunk
        tp = mesh.shape.get("tp", 1) if mesh is not None else 1
        if tp > spec.n_kv_heads:
            # kv-head replication: tp exceeds the kv-head count, so wk/wv
            # expand to tp virtual heads and the spec the engine computes
            # with reflects that (models/params.kv_replication — the relaxed
            # form of the reference's nSlices <= nKvHeads rule)
            import dataclasses

            from ..models.params import replicate_kv_heads

            params = replicate_kv_heads(params, spec, tp)
            spec = dataclasses.replace(spec, n_kv_heads=tp)
        self.spec = spec
        # --buffer-float-type q80 with tp>1 => wo/w2 partial sums exchange
        # int8 blocks over ICI instead of the GSPMD-exact f32 all-reduce
        # (the reference's wire compression, ref: src/tasks.cpp:124-163)
        if q80_collectives is None:
            q80_collectives = activation_q80 and tp > 1
        self.q80_collectives = q80_collectives and tp > 1
        self._tp_mesh = mesh if self.q80_collectives else None
        # sp > 1: the KV cache's sequence dim shards over sp (per-device
        # cache = seq_len/sp) and every step attends via sp_cache_attention
        sp = mesh.shape.get(SP_AXIS, 1) if mesh is not None else 1
        if sp > 1:
            assert self.seq_len % sp == 0, (
                f"sp={sp} must divide max_seq_len={self.seq_len} "
                "(sp-sharded KV cache)")
        self._sp_cache_mesh = mesh if sp > 1 else None
        if use_pallas is None:
            # default ON for TPU: the fused kernel reads only packed bytes and
            # keeps the unpack at ~6 VPU ops/byte (measured v5e: 2.4 ms vs
            # 5.0 ms XLA-dequant for the same 0.81 GB packed weight set);
            # prefill segments longer than pallas_q40.MAX_T fall back to the
            # FLOPs-amortized XLA dequant path automatically. On CPU (tests,
            # virtual meshes) Mosaic can't compile — use the XLA path unless
            # pallas_interpret forces the interpreted kernel (tests).
            use_pallas = jax.default_backend() != "cpu" or pallas_interpret
        self.use_pallas = use_pallas
        self.pallas_interpret = pallas_interpret
        # GSPMD cannot auto-partition a pallas_call over sharded operands, so
        # multi-device meshes run the kernels per-shard via shard_map
        # (parallel/tp_q80.py): Q40 weights are marked TpRowWeight/TpColWeight
        # and attention shards over (dp, kv-heads). The col partial-sum
        # reduce is exact unless q80 collectives are on.
        mesh_kernels = use_pallas and mesh is not None and (
            mesh.size > 1 or force_mesh_kernels)
        self.tp_reduce = "q80" if self.q80_collectives else "exact"
        if mesh_kernels:
            self._tp_mesh = mesh
        # ep > 1: MoE experts are PLACED across the ep axis (E/ep experts per
        # device — net-new vs the reference's TP-only expert slicing); the
        # MoE block always runs the shard_map path then (parallel/ep_moe.py)
        from ..parallel.mesh import EP_AXIS

        ep = mesh.shape.get(EP_AXIS, 1) if mesh is not None else 1
        if ep > 1:
            assert spec.is_moe, "--ep requires a MoE model (experts to place)"
            assert spec.n_experts % ep == 0, (
                f"ep={ep} must divide n_experts={spec.n_experts}")
            self._tp_mesh = mesh
        # pp > 1: layers are PLACED in stages across the pp axis (L/pp layers
        # + their KV cache per device — net-new vs the reference, where every
        # node runs every layer). The layer loop runs inside a FULLY-manual
        # shard_map (parallel/pp.py) — tp is manual there too, so the fused
        # Pallas kernels run per shard exactly like the tp path (no 2x
        # XLA-dequant penalty; VERDICT r2 weak #1).
        from ..parallel.mesh import PP_AXIS

        pp = mesh.shape.get(PP_AXIS, 1) if mesh is not None else 1
        self._pp = pp
        self._pp_mesh = mesh if pp > 1 else None
        if pp > 1:
            assert spec.n_layers % pp == 0, (
                f"pp={pp} must divide n_layers={spec.n_layers}")
            # ep composes: experts placed across ep INSIDE the manual pp
            # region (each device holds L/pp stages x E/ep experts — the
            # Grok-class scaling layout; parallel/pp.py + ep_moe._ep_body).
            # sp composes too: the cache's sequence dim shards over sp
            # inside the region (scatter writes at chunk-local slots, flash
            # stats merged over sp — transformer._attention_block manual_sp)
            assert not self.q80_collectives, (
                "pp uses exact tp reduces; --buffer-float-type q80 "
                "is not supported with --pp")

        # vocab sharding (ops/sharded_vocab.py): tok_emb becomes a local
        # (vocab/S, dim) shard with a masked gather + all-reduce; wcls
        # keeps its row split (widened over pp when present). Auto-on for
        # tp > 1 whenever the vocab divides; the replicated path stays as
        # the parity oracle (--shard-vocab off / shard_vocab=False).
        from ..ops.sharded_vocab import vocab_shard_axes

        axes = (vocab_shard_axes(mesh, spec.vocab_size)
                if mesh is not None else ())
        if shard_vocab is None:
            self._vocab_axes = axes
        elif shard_vocab:
            assert axes, (
                f"shard_vocab: mesh tp axes cannot split vocab="
                f"{spec.vocab_size} evenly (tp="
                f"{mesh.shape.get('tp', 1) if mesh is not None else 1})")
            self._vocab_axes = axes
        else:
            self._vocab_axes = ()
        self.shard_vocab = bool(self._vocab_axes)
        self.vocab_topk = int(vocab_topk)
        # counters the /stats + bench rows surface: how often the sharded
        # fast path served a sample vs the replicated-row parity fallback
        self.vocab_sample_stats = {"sharded": 0, "fallback": 0}

        if tp == 1:
            # single-shard fast path: fused QKV / w1|w3 kernel calls
            params = fuse_layer_weights(params)
        else:
            # a tp == 1 engine sharing this params dict may have fused it in
            # place; row splits of the fused dims cross the q|k|v boundaries
            from ..models.params import unfuse_layer_weights

            params = unfuse_layer_weights(params, spec)
        if mesh is not None:
            from ..quants.jax_codec import QuantizedTensor

            from ..parallel.wrappers import WeightWrapper

            def _leaf(v):  # loader-marked leaves wrap the quantized tensor
                return v.w if isinstance(v, WeightWrapper) else v

            q40 = any(isinstance(_leaf(v), QuantizedTensor)
                      for lw in params["layers"] for v in lw.values())
            check_tp_constraints(spec, tp, q40=q40)
            if ep > 1:
                from ..parallel.ep_moe import EpRowWeight, repack_moe_ep
                from ..parallel.pp import PpWeight

                params = dict(params)
                params["layers"] = [
                    # PpWeight = the streamed loader's stage stack, whose
                    # ep mode already built PpWeight(Ep...) leaves
                    lw if isinstance(lw.get("moe_up"),
                                     (EpRowWeight, PpWeight))
                    else repack_moe_ep(lw, tp)
                    for lw in params["layers"]
                ]
            if (self.q80_collectives or (mesh_kernels and tp > 1 and q40)
                    or (pp > 1 and tp > 1 and q40)):
                # pp x tp always repacks q40 cols: the manual region slices
                # weights AT PLACEMENT, and a contiguous packed-byte stripe
                # is a nibble-position stripe, not a valid local Q40 tensor
                # (the GSPMD path reshards transparently; manual cannot)
                from ..parallel.sharding import repack_col_weights

                params = repack_col_weights(params, tp)
            if mesh_kernels and q40:
                from ..parallel.sharding import wrap_row_weights

                params = wrap_row_weights(params)
            if pp > 1:
                from ..parallel.pp import stack_stages

                params = stack_stages(params, pp)
            self.params = shard_params(params, mesh,
                                       self._vocab_axes or None)
            self._cache_sharding = NamedSharding(
                mesh, cache_pspec(sp=sp > 1, pp=pp > 1))
            self._token_sharding = NamedSharding(mesh, P(DP_AXIS, None))
        else:
            self.params = params
            self._cache_sharding = None
            self._token_sharding = None

        # mesh spanning >1 process (jax.distributed): host code may only
        # fetch fully-replicated arrays, so logits are all-gathered to every
        # host before sampling (parallel/multihost.py)
        from ..parallel.multihost import is_multihost

        self._multihost = is_multihost(mesh)
        self._replicator = None

        # compile-cache + ledger state BEFORE the first mint (_new_cache
        # below jits the cache maker): every executable this engine ever
        # builds routes through _mint, and _compile_warm arms the
        # recompile sentinel once Scheduler.warmup() has compiled the
        # serving set (runtime/profiler.py)
        self._steps: dict[int | tuple[str, int], Callable] = {}
        self._compile_warm = False
        self.cache = self._new_cache()
        self.pos = 0

    # -- compile ledger ----------------------------------------------------

    def _mint(self, key, fn: Callable) -> Callable:
        """Register one freshly-jitted executable under `key`, routed
        through the compile ledger (runtime/profiler.py): the first call
        is timed as the compile (entry key, wall ms) and — on a warm
        engine — trips the recompile sentinel (a structured error under
        --freeze-compiles, BEFORE the compile runs). The watch swaps the
        raw jitted callable back into _steps after that first call, so
        the steady-state hot path is byte-for-byte the pre-ledger one.
        Host-side bookkeeping only: the jitted program (and dlgrind's
        fingerprint of it) is untouched."""
        from .profiler import COMPILES

        wrapped = COMPILES.watch(self, key, fn)
        self._steps[key] = wrapped
        return wrapped

    def mark_compile_warm(self) -> None:
        """Arm the recompile sentinel: the serving set is compiled
        (Scheduler.warmup calls this last), so from here every new
        compile key is a `compile_after_warmup` event — and, frozen, a
        structured refusal. Per ENGINE: a supervisor rebuild mints a
        fresh engine whose own warmup legitimately recompiles."""
        self._compile_warm = True

    # -- cache ------------------------------------------------------------

    def _new_cache(self) -> KVCache:
        if self._cache_sharding is None:
            return KVCache.create(self.spec, self.batch, self.seq_len,
                                  self.cache_dtype)
        # allocate directly into the sharded layout (out_shardings) — no
        # transient full-size cache on one device (matters for sp-sharded
        # long-context caches). The jitted maker is built once: reset() is a
        # server hot path (per-request) and must not retrace.
        if "cache_maker" not in self._steps:
            n_l = self.spec.n_layers
            if self._pp > 1:  # stage-stacked: n_layers/pp leaves (pp, ...)
                n_l //= self._pp
            shardings = KVCache((self._cache_sharding,) * n_l,
                                (self._cache_sharding,) * n_l)
            self._mint("cache_maker", jax.jit(
                lambda: KVCache.create(self.spec, self.batch, self.seq_len,
                                       self.cache_dtype, pp=self._pp),
                out_shardings=shardings))
        return self._steps["cache_maker"]()

    def reset(self) -> None:
        """New session: rewind position (the API server resets per request,
        ref: src/apps/dllama-api/dllama-api.cpp:236-249)."""
        self.cache = self._new_cache()
        self.pos = 0

    # -- session persistence ----------------------------------------------

    def save_session(self, path: str, tokens: list[int] | None = None) -> None:
        """Persist the generation session — pos and the FILLED cache prefix
        (positions < pos) — to an .npz. Net-new vs the reference, which has
        no KV-cache persistence or session resume (SURVEY.md §5.4): a chat
        can continue across process restarts without re-prefilling its
        history. Narrow dtypes (bf16/fp8) are stored as raw bit patterns
        (numpy's format cannot describe them).

        tokens: optional token history to carry alongside the cache (the
        chat CLI stores its conversation so a resumed session can keep
        mining speculative drafts from pre-restart turns)."""
        assert self._pp == 1, "session save/restore does not support --pp"
        data: dict = {
            "pos": np.int64(self.pos),
            "cache_dtype": np.str_(jnp.dtype(self.cache_dtype).name),
            "config": np.asarray(self._session_fingerprint(), np.int64),
            "tokens": np.asarray(tokens if tokens is not None else [],
                                 np.int32),
        }
        for l in range(self.spec.n_layers):
            for name, leaf in (("k", self.cache.k[l]), ("v", self.cache.v[l])):
                arr = np.asarray(leaf[:, :, : self.pos, :])
                if arr.dtype.itemsize == 1:
                    arr = arr.view(np.uint8)
                elif arr.dtype not in (np.float32, np.float64):
                    arr = arr.view(np.uint16)
                data[f"{name}{l}"] = arr
        # write-then-rename: the cache fetch makes this a seconds-long write
        # for big models, and a signal landing mid-write must never leave a
        # truncated file where a good session stood (chat saves every turn).
        # Open handle: np.savez(str_path) appends ".npz" to extension-less
        # names, which load_session/os.path.exists would then never find.
        import os

        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **data)
        os.replace(tmp, path)

    def load_session(self, path: str) -> list[int]:
        """Restore a save_session() file: refuses a mismatched model/engine
        config, rebuilds the cache with the saved prefix in place (sharded
        placement included) and sets pos. Returns the saved token history
        ([] for files saved without one)."""
        assert self._pp == 1, "session save/restore does not support --pp"
        z = np.load(path)
        saved, mine = list(z["config"]), self._session_fingerprint()
        # the weight-content element compares only when BOTH sides know it:
        # 0 means in-memory params (and 4-element files predate the field)
        # — those degrade to the shape-only check
        content_ok = (len(saved) < 5 or saved[4] == mine[4]
                      or 0 in (saved[4], mine[4]))
        if saved[:4] != mine[:4] or not content_ok:
            raise ValueError(
                "session file does not match this engine's model/config "
                f"(saved {saved}, engine {mine})")
        pos = int(z["pos"])
        assert pos <= self.seq_len
        self.reset()
        dt = jnp.dtype(self.cache_dtype)
        # cache rows are built ON DEVICE through the shared seeding
        # helper (_seed_jit / _seed_guard — one home for the
        # donation-safety fix and the f8 NaN-code guard)
        shape = (self.batch, self.spec.n_kv_heads, self.seq_len,
                 self.spec.head_size)
        # ledger-watched but NOT cached in _steps: each restore builds a
        # fresh closure (no reuse across calls is possible), so storing
        # it would only pin one dead executable per distinct pos for the
        # engine's lifetime — the watch alone records the compile
        from .profiler import COMPILES

        build = COMPILES.watch(self, ("session_restore", pos),
                               self._seed_jit(
            lambda pfx: jnp.zeros(shape, dt).at[:, :, :pos, :].set(
                self._seed_guard(pfx)),
            out_tree=0))
        k_all, v_all = [], []
        for l in range(self.spec.n_layers):
            k_all.append(build(z[f"k{l}"].view(dt)))
            v_all.append(build(z[f"v{l}"].view(dt)))
        self.cache = KVCache(tuple(k_all), tuple(v_all))
        self.pos = pos
        return z["tokens"].tolist() if "tokens" in z.files else []

    # -- cache seeding (session restore + prefix-cache arena) -------------

    def _seed_guard(self, x):
        """Sanitize bytes entering the cache from OUTSIDE a forward (the
        cache-SEEDING boundary: load_session's npz prefix, the prefix
        arena's blocks). In-engine writes saturate
        (models/transformer._to_cache_dtype), so the flash kernel's
        _f8_bits_to never sees an e4m3 NaN code — but a session file or
        arena did not necessarily come from a saturating producer, and
        one 0x7F byte would decode as a finite 480.0 and poison every
        later attention read (ADVICE r5). Non-f8 dtypes pass through."""
        from ..ops.pallas_attention import saturate_f8_nan_codes

        return saturate_f8_nan_codes(x)

    def _seed_jit(self, fn, *, out_tree, donate: tuple = ()):
        """The ONE jit wrapper for every path that builds cache rows on
        device (Engine.load_session, Engine.slot_seed_prefix) — the
        single home of the PR 3 donation-safety fix:

          * the result is COMPUTED on device (fresh zeros + scatter, or a
            gather from the arena), never a device_put/asarray of a host
            temporary — a computed output cannot alias host staging
            memory, so donating it into the first jitted step is safe
            (wholesale device_put here produced intermittent NaN-poisoned
            logits: use-after-free of the host buffer after donation);
          * out_shardings pins every cache output to the engine's cache
            layout, so sharded meshes materialize the full-seq_len result
            straight into the sharded placement — no device ever holds a
            whole unsharded row (only transient prefix inputs replicate).

        `out_tree` is any pytree matching the output structure (its leaf
        values are ignored — one cache sharding per leaf)."""
        if self._cache_sharding is None:
            return jax.jit(fn, donate_argnums=donate)
        shardings = jax.tree_util.tree_map(lambda _: self._cache_sharding,
                                           out_tree)
        return jax.jit(fn, donate_argnums=donate, out_shardings=shardings)

    def _session_fingerprint(self) -> list[int]:
        # architecture dims + cache shape/dtype + the WEIGHT CONTENT hash:
        # a session saved from a same-shape different-weight model (a
        # fine-tune, a requant) would otherwise resume against a KV cache
        # the loaded weights never produced — garbage continuations with
        # no error (ADVICE r3; the multihost cluster fingerprint guards
        # the same hazard). model_fingerprint == 0 (in-memory params)
        # degrades to the shape-only check.
        import zlib

        sp = self.spec
        return [zlib.crc32(repr((sp.arch, sp.dim, sp.hidden_dim, sp.n_layers,
                                 sp.n_heads, sp.n_kv_heads,
                                 sp.head_size)).encode()),
                self.batch, self.seq_len,
                zlib.crc32(jnp.dtype(self.cache_dtype).name.encode()),
                self.model_fingerprint]

    # -- observability -----------------------------------------------------

    def wire_estimate(self):
        """Modeled per-token per-device collective bytes for this engine's
        mesh/config (the reference's S/R columns, ref: socket.cpp:266-271)."""
        from .netstats import estimate_decode_wire

        return estimate_decode_wire(
            self.spec, self.mesh,
            q80=self.q80_collectives,
            act_bytes=jnp.dtype(self.compute_dtype).itemsize,
            batch=self.batch,
            shard_vocab=self.shard_vocab,
            vocab_topk=self.vocab_topk)

    def measure_transfer_ms(self) -> float:
        """Measured per-token DECODE transfer estimate: times activation-
        sized collectives on the mesh and scales by the exact per-token
        collective count of the decode schedule (the reference's T column,
        measured not modeled). Mirrors the collective structure
        netstats.estimate_decode_wire models: per-layer tp reduces, plus the
        single (ep, tp)-group MoE reduce when experts are ep-placed, plus —
        for pp meshes — the all-stages scheme's per-stage live broadcast
        (pp psums over the pp axis per token, parallel/pp.py pp_layers;
        decode never runs the GPipe ppermute rotation, see
        measure_prefill_transfer_ms for that schedule). Payloads carry the
        batch dimension: a decode-step activation is (B, 1, dim)."""
        return self._segment_reduce_ms(1) + self._segment_pp_ms(1)

    def measure_prefill_transfer_ms(self, n_prompt: int) -> float:
        """Measured transfer estimate for prefilling an n_prompt-token
        prompt, following the schedules forward() actually runs (VERDICT
        r4 #9 — the pp cost is the real per-microbatch ppermute structure,
        not a psum approximation). prefill() feeds the prompt in
        prefill_chunk-sized segments and forward() picks the schedule PER
        SEGMENT, so the estimate sums per-segment costs: a segment where
        gpipe_microbatches(t, pp) returns M > 1 does (M + pp - 2)
        activation hops of (B, t/M, dim) over the pp ring plus ONE final
        output psum of (B, t, dim) (pp_layers_gpipe); shorter segments take
        the all-stages scheme's pp psums of (B, t, dim). tp/ep reduces
        scale with t like the decode model. Returns total ms."""
        if self.mesh is None:
            return 0.0
        # measure once per DISTINCT segment length (at most two: the full
        # chunk and the tail) — the microbench compiles + times real
        # collectives, so a per-segment loop would redo that ~n_chunks
        # times for identical numbers
        n_full, tail = divmod(n_prompt, self.prefill_chunk)
        total = 0.0
        if n_full:
            t = self.prefill_chunk
            total += (self._segment_reduce_ms(t)
                      + self._segment_pp_ms(t)) * n_full
        if tail:
            total += self._segment_reduce_ms(tail) + self._segment_pp_ms(tail)
        return total

    def _segment_reduce_ms(self, t: int) -> float:
        """tp/ep per-layer reduce cost for one T-token forward segment —
        the shared collective structure of the decode and prefill
        estimates (payload (B, T, dim); netstats.estimate_decode_wire
        models the same shape)."""
        from .netstats import measure_allreduce_ms

        if self.mesh is None:
            return 0.0
        tp = self.mesh.shape.get("tp", 1)
        ep = self.mesh.shape.get("ep", 1)
        elems = self.batch * t * self.spec.dim
        total = 0.0
        if self.spec.is_moe and ep > 1:
            if tp > 1:  # attention wo reduce stays tp-only
                total += (measure_allreduce_ms(self.mesh, elems)
                          * self.spec.n_layers)
            total += (measure_allreduce_ms(self.mesh, elems,
                                           axes=("ep", "tp"))
                      * self.spec.n_layers)
        elif tp > 1:
            per = measure_allreduce_ms(self.mesh, elems)
            reduces = (1 + self.spec.n_active_experts) if self.spec.is_moe else 2
            total += per * reduces * self.spec.n_layers
        return total

    def _segment_pp_ms(self, t: int) -> float:
        """pp collective cost for one T-token forward segment, following
        the schedule forward() picks for that length: GPipe microbatch
        rotation (long segments) or the all-stages per-stage psum."""
        from ..parallel.pp import gpipe_microbatches
        from .netstats import measure_allreduce_ms, measure_ppermute_ms

        pp = (self.mesh.shape.get("pp", 1) if self.mesh is not None else 1)
        if pp <= 1:
            return 0.0
        elems = self.batch * t * self.spec.dim
        n_mb = gpipe_microbatches(t, pp) if self.pp_gpipe else 1
        if n_mb > 1:
            hops = n_mb + pp - 2
            return (measure_ppermute_ms(
                self.mesh, self.batch * (t // n_mb) * self.spec.dim) * hops
                + measure_allreduce_ms(self.mesh, elems, axes=("pp",)))
        return measure_allreduce_ms(self.mesh, elems, axes=("pp",)) * pp

    # -- compiled steps ---------------------------------------------------

    def _forward_kwargs(self) -> dict:
        """The engine's forward() configuration, in exactly one place — every
        execution path (compiled steps, the on-device greedy scan) must build
        its kwargs here so a new forward() knob is threaded once."""
        return dict(
            activation_q80=self.activation_q80,
            compute_dtype=self.compute_dtype,
            use_pallas=self.use_pallas,
            tp_mesh=self._tp_mesh,
            tp_reduce=self.tp_reduce,
            pallas_interpret=self.pallas_interpret,
            sp_cache_mesh=self._sp_cache_mesh,
            pp_mesh=self._pp_mesh,
            pp_gpipe=self.pp_gpipe,
            vocab_mesh=self.mesh if self.shard_vocab else None,
            vocab_axes=self._vocab_axes or ("tp",),
        )

    def _compiled_step(self, key, *, sp_mesh=None,
                       with_logit_index: bool = False,
                       logits_for_all: bool = False) -> Callable:
        """One cached jitted forward wrapper for every execution path.

        Three shapes share it: (params, tokens, pos, cache) with pos scalar
        (step) or (B,) vector (batched decode), the same with per-position
        logits (logits_for_all — the speculative verify forward), and
        (params, tokens, logit_index, cache) for whole-segment prefill from
        pos 0 (right-padded batch; ring when sp_mesh is set). Single builder
        so a new forward() knob is threaded exactly once."""
        if key in self._steps:
            return self._steps[key]

        common = self._forward_kwargs()
        if with_logit_index:
            def run(params, tokens, logit_index, cache):
                return forward(params, self.spec, tokens, jnp.int32(0), cache,
                               sp_mesh=sp_mesh, logit_index=logit_index,
                               **common)
        else:
            def run(params, tokens, pos0, cache):
                return forward(params, self.spec, tokens, pos0, cache,
                               logits_for_all=logits_for_all, **common)

        # role-specific wrapper names so profiler traces can attribute XLA
        # module executions: with every wrapper named 'run', per-step T
        # alignment mis-attributed whenever extra modules ran inside the
        # trace window (ADVICE r3). decode_step is uniquely the 1-token
        # host-loop step the benchmark hints on.
        run.__name__ = (
            "prefill_seg" if with_logit_index
            else "decode_step" if key == 1
            else f"prefill_chunk_{key}" if isinstance(key, int)
            else f"prefill_chunk_{key[1]}" if key[0] == "prefill"
            else "verify_step" if key[0] == "lookup"
            else "batch_decode_step")
        return self._mint(key, jax.jit(run, donate_argnums=(3,)))

    def _step_fn(self, t: int) -> Callable:
        return self._compiled_step(t)

    def step(self, tokens: np.ndarray, pos0: int, *,
             _key=None) -> jax.Array:
        """Run a (B, T) segment from absolute position pos0; returns last-token
        logits (B, vocab) on device. Advances cache/pos.

        _key overrides the compile-cache key — prefill() routes a width-1
        trailing chunk through ("prefill", 1) so its trace module is named
        prefill_chunk_1, not decode_step (the benchmark counts decode
        executions exactly)."""
        b, t = tokens.shape
        assert b == self.batch
        assert pos0 + t <= self.seq_len, "context overflow"
        tok = jnp.asarray(tokens, jnp.int32)
        if self._token_sharding is not None:
            tok = jax.device_put(tok, self._token_sharding)
        logits, self.cache = self._compiled_step(_key if _key is not None
                                                 else t)(
            self.params, tok, jnp.int32(pos0), self.cache)
        self.pos = pos0 + t
        return logits

    def fetch_logits(self, logits: jax.Array) -> np.ndarray:
        """Bring step() logits to the host. On a multi-process mesh the
        array may be sharded over non-addressable devices; replicate first
        (every host then samples the same logits — the protocol's
        lock-step invariant, parallel/multihost.py)."""
        if self._multihost and not logits.is_fully_replicated:
            if self._replicator is None:
                self._replicator = self._mint("replicator", jax.jit(
                    lambda l: l,
                    out_shardings=NamedSharding(self.mesh, P())))
            logits = self._replicator(logits)
        return np.asarray(logits)

    # -- sharded sampling (ops/sharded_vocab.py) ---------------------------

    @property
    def shard_sampling(self) -> bool:
        """Whether sample_view serves the sharded fast path: vocab is
        sharded and the host can fetch the tiny summaries directly
        (multi-process meshes keep the replicated fetch_logits oracle —
        their serving tiers are single-host anyway)."""
        return self.shard_vocab and not self._multihost

    def sample_view(self, logits, temps: np.ndarray | None, n_vocab: int):
        """Sampling access to one step's (B, vocab) logits. Replicated
        engines return a FullLogitsView (the fetch_logits + host-Sampler
        oracle, exactly the pre-sharding path). Vocab-sharded engines
        run the sharded_sample_prep executable — device argmax +
        per-shard top-k candidates — and fetch ~(B, S·k) floats instead
        of (B, vocab): greedy rows are BIT-IDENTICAL to np.argmax,
        sampled rows are distribution-exact (candidate scheme, guarded;
        anything unprovable fetches ONE replicated row through the
        warmed "vrow" executable — the per-row parity oracle).

        temps: (B,) float32 per-row temperatures (greedy rows pass 1.0 —
        a traced input, never a compile key). n_vocab: the tokenizer
        vocab the candidates/argmax truncate at (one compile key per
        distinct value; rows whose sampler vocab differs fall back)."""
        from ..ops.sharded_vocab import sharded_sample_prep
        from .sampling import FullLogitsView, ShardedLogitsView

        if not self.shard_sampling:
            return FullLogitsView(self.fetch_logits(logits))
        b = logits.shape[0]
        n_shards = 1
        for a in self._vocab_axes:
            n_shards *= self.mesh.shape[a]
        k = max(1, min(self.vocab_topk, self.spec.vocab_size // n_shards))
        key = ("vprep", b, k, int(n_vocab))
        if key not in self._steps:
            mesh, axes = self.mesh, self._vocab_axes

            def run(logits, temps, nv=int(n_vocab), kk=k):
                return sharded_sample_prep(logits, temps, mesh, axes,
                                           nv, kk)

            run.__name__ = "sharded_sample_prep"
            self._mint(key, jax.jit(run))
        if temps is None:
            temps = np.ones((b,), np.float32)
        amax, cand_p, cand_id, guard = self._steps[key](
            logits, jnp.asarray(temps, jnp.float32))
        return ShardedLogitsView(
            np.asarray(amax), np.asarray(cand_p), np.asarray(cand_id),
            np.asarray(guard), int(n_vocab),
            self._row_fetcher(logits), stats=self.vocab_sample_stats)

    def _row_fetcher(self, logits):
        """One replicated (vocab,) row off the sharded logits — the
        sampled path's parity-oracle fallback. A single warmed key per
        batch shape; the row gather is the ONLY place the serving path
        may materialize a full-vocab vector, and only one row at a
        time."""
        key = ("vrow", logits.shape[0])
        if key not in self._steps:
            out_s = (NamedSharding(self.mesh, P()) if self.mesh is not None
                     else None)
            self._mint(key, jax.jit(
                lambda l, i: lax.dynamic_index_in_dim(l, i, 0,
                                                      keepdims=False),
                out_shardings=out_s))
        fn = self._steps[key]

        def fetch(row: int) -> np.ndarray:
            return np.asarray(fn(logits, jnp.int32(row)))

        return fetch

    def warm_sample_ops(self, logits, n_vocab: int) -> None:
        """Compile the sharded-sampling executables (prep + row gather)
        against one step's logits — Scheduler.warmup calls this so
        sampled traffic mints ZERO post-warmup keys (the vprep key set
        is bounded: one per (batch, k, vocab))."""
        if not self.shard_sampling:
            return
        view = self.sample_view(logits, None, n_vocab)
        view.row(0)  # warms the "vrow" fallback executable too

    # -- generation -------------------------------------------------------

    def prefill(self, prompt: list[int]) -> jax.Array:
        """Feed the prompt in fixed-size chunks; returns last logits.

        When the mesh has an sp axis > 1 and this is the start of a session,
        the whole prompt runs as ONE ring-attention segment with the sequence
        sharded over sp (long-context path, net-new vs the reference)."""
        assert self.batch == 1, "prefill() is single-sequence; use step() for batches"
        sp = self.mesh.shape.get(SP_AXIS, 1) if self.mesh is not None else 1
        if (sp > 1 and self._pp == 1 and self.pos == 0 and len(prompt) > 1
                and len(prompt) + (-len(prompt)) % sp <= self.seq_len):
            # (under pp, prefill goes through the GPipe microbatch schedule
            # instead; the sp-sharded cache is written chunk-locally there)
            return self._prefill_ring(prompt, sp)
        logits = None
        i = 0
        n = len(prompt)
        while i < n:
            chunk = min(self.prefill_chunk, n - i)
            seg = np.asarray(prompt[i:i + chunk], np.int32)[None, :]
            logits = self.step(seg, self.pos,
                               _key=("prefill", 1) if chunk == 1 else None)
            i += chunk
        return logits

    def _prefill_ring(self, prompt: list[int], sp: int) -> jax.Array:
        """Whole-prompt sequence-parallel prefill: pad to a multiple of sp,
        shard tokens over the sp axis, attend via ring attention, sample at
        the true last prompt position. Padded positions land in the cache at
        indices >= pos and are therefore never attended by later decode."""
        n = len(prompt)
        pad = (-n) % sp
        t = n + pad
        assert t <= self.seq_len, "context overflow"  # caller checked padding fits

        fn = self._compiled_step(("ring", t), sp_mesh=self.mesh,
                                 with_logit_index=True)
        seg = np.zeros((1, t), np.int32)
        seg[0, :n] = prompt
        tok = jax.device_put(jnp.asarray(seg),
                             NamedSharding(self.mesh, P(DP_AXIS, SP_AXIS)))
        logits, self.cache = fn(self.params, tok, jnp.int32(n - 1), self.cache)
        self.pos = n
        return logits

    def generate(
        self,
        prompt: list[int],
        max_tokens: int,
        sampler: Sampler,
        eos_id: int | set[int] | None = None,
        on_token: Callable[[int], None] | None = None,
    ) -> GenerationResult:
        """Prefill + decode loop (ref: src/apps/dllama/dllama.cpp:14-91).

        eos_id: stop token id, or a set of them (instruct models often end
        turns with a marker token distinct from the header eos).

        max_tokens is a HARD cap on emitted tokens — max_tokens <= 0 emits
        nothing (prefill still advances the cache), exactly like the
        lookup/batch iterator paths (one contract, VERDICT r4 #9)."""
        stop_ids = ({eos_id} if isinstance(eos_id, int) else eos_id) or set()
        stats = RunStats()
        out: list[int] = []

        if max_tokens <= 0:
            self.prefill(prompt)
            return GenerationResult(out, stats)

        t0 = time.perf_counter()
        logits = self.prefill(prompt)
        logits_np = self.fetch_logits(logits)  # D2H is the only true sync on tunneled platforms
        t1 = time.perf_counter()
        stats.add(StepStats(generation_ms=(t1 - t0) * 1e3, device_ms=(t1 - t0) * 1e3))

        token = sampler.sample(logits_np[0])
        out.append(token)
        if on_token:
            on_token(token)

        while len(out) < max_tokens and self.pos < self.seq_len:
            if token in stop_ids:
                break
            g0 = time.perf_counter()
            logits = self.step(np.asarray([[token]], np.int32), self.pos)
            logits_np = self.fetch_logits(logits)
            g1 = time.perf_counter()
            token = sampler.sample(logits_np[0])
            g2 = time.perf_counter()
            stats.add(StepStats(
                generation_ms=(g2 - g0) * 1e3,
                device_ms=(g1 - g0) * 1e3,
                host_ms=(g2 - g1) * 1e3,
            ))
            out.append(token)
            if on_token:
                on_token(token)
        return GenerationResult(out, stats)

    # -- speculative (prompt-lookup) greedy generation --------------------

    def generate_lookup_stream(
        self,
        prompt: list[int],
        max_tokens: int,
        eos_id: int | set[int] | None = None,
        *,
        draft_len: int = 7,
        max_ngram: int = 3,
        history: list[int] | None = None,
        stats: RunStats | None = None,
        vocab_size: int | None = None,
    ) -> Iterator[int]:
        """Token iterator for prompt-lookup speculative decoding
        (runtime/speculative.py): each forward feeds the last emitted token
        PLUS a draft continuation mined from the context's own n-grams and
        emits one token per confirmed position — decode is weight-read-
        bound, so the t = 1 + k verify forward costs ~one token's HBM time
        and every accepted draft token is nearly free. The yielded stream
        is EXACTLY generate()'s greedy stream (drafts only batch the
        confirmation); `last_accept_stats` records (forwards, tokens) and
        updates per forward, so an abandoned iterator leaves it accurate.

        `prompt` is fed from the current self.pos (the API server's prefix
        reuse passes only the suffix); `history` is the full token context
        drafts are mined from (defaults to `prompt`); `vocab_size` caps the
        argmax at the TOKENIZER's vocab like the host Sampler does — a
        padded model head would otherwise emit undecodable ids and break
        the exact-greedy-parity contract. Greedy only: sampled speculation
        needs rejection resampling to stay distribution-exact — the sampled
        paths keep 1 token/forward."""
        from .speculative import count_accepted

        spec_v = min(vocab_size or self.spec.vocab_size,
                     self.spec.vocab_size)

        def first(row: np.ndarray) -> int:
            return int(np.argmax(row[:spec_v]))

        def verify(seg_logits: np.ndarray, draft: list[int]) -> list[int]:
            greedy = np.argmax(seg_logits[:, :spec_v], axis=-1)
            m = count_accepted(draft, greedy)
            return [int(g) for g in greedy[: m + 1]]

        return self._lookup_loop(prompt, max_tokens, eos_id,
                                 draft_len=draft_len, max_ngram=max_ngram,
                                 history=history, stats=stats,
                                 first_fn=first, verify_fn=verify)

    def _lookup_loop(
        self,
        prompt: list[int],
        max_tokens: int,
        eos_id: int | set[int] | None,
        *,
        draft_len: int,
        max_ngram: int,
        history: list[int] | None,
        stats: RunStats | None,
        first_fn: Callable[[np.ndarray], int],
        verify_fn: Callable[[np.ndarray, list[int]], list[int]],
        draft_fn: Callable | None = None,
    ) -> Iterator[int]:
        """The verify-forward skeleton every speculative mode shares —
        draft sizing, the compiled verify step, eos/budget truncation,
        cache-position bookkeeping, accept stats and timing live HERE
        exactly once. Modes differ only in their callbacks:
        first_fn(logits row) -> first token,
        verify_fn(seg_logits (T, V), draft) -> emitted tokens, where
        emitted = the accepted draft prefix plus exactly one more token
        (emitted[i] must be a valid continuation of segment position i —
        its K/V slot holds the fed token stream), and — for REAL-draft
        modes (runtime/draft.py) — draft_fn(hist, k, token, pos0) ->
        draft token list, replacing the default prompt-lookup n-gram
        miner (the draft model owns its KV state inside the closure)."""
        stop_ids = ({eos_id} if isinstance(eos_id, int) else eos_id) or set()

        from .speculative import find_draft

        if max_tokens <= 0:
            # budget-0 emits nothing (prefill still advances the cache) —
            # the same hard-cap contract as Engine.generate() and the API
            # server's plain token iterator at n_gen == 0
            self.prefill(prompt)
            self.last_accept_stats = (1, 0)
            self.last_spec = {"forwards": 1, "drafted": 0, "accepted": 0,
                              "emitted": 0}
            return

        t0 = time.perf_counter()
        logits = self.prefill(prompt)
        logits_np = self.fetch_logits(logits)
        t1 = time.perf_counter()
        if stats is not None:
            stats.add(StepStats(generation_ms=(t1 - t0) * 1e3,
                                device_ms=(t1 - t0) * 1e3))

        token = first_fn(logits_np[0])
        n_out = 1
        self.last_accept_stats = (1, 1)
        # the richer accept record the legacy API tier aggregates into
        # its `spec` /stats block (accepted counts tokens actually USED
        # after eos/budget truncation — the honest numerator)
        self.last_spec = {"forwards": 1, "drafted": 0, "accepted": 0,
                          "emitted": 1}
        hist = np.asarray((history if history is not None else prompt)
                          + [token], np.int32)
        yield token

        while (n_out < max_tokens and self.pos < self.seq_len
               and token not in stop_ids):
            # draft sized to the remaining budget/context (the +1 below is
            # the fed token itself; its K/V write needs a free slot)
            g0 = time.perf_counter()
            k = min(draft_len, self.seq_len - self.pos - 1,
                    max_tokens - n_out - 1)
            pos0 = self.pos
            if draft_fn is not None:
                draft = draft_fn(hist, k, token, pos0) if k > 0 else []
            else:
                draft = (find_draft(hist, k, max_ngram=max_ngram)
                         if k > 0 else [])
            seg = np.asarray([[token] + draft], np.int32)

            # device_ms covers only the verify forward + the logits D2H
            # (like generate()'s step timing); draft mining and the host
            # accept work are host_ms — benchmark 'Avg inference time'
            # would otherwise overstate device time for lookup runs
            # (ADVICE r3)
            d0 = time.perf_counter()
            fn = self._compiled_step(("lookup", seg.shape[1]),
                                     logits_for_all=True)
            tok_dev = jnp.asarray(seg)
            if self._token_sharding is not None:
                tok_dev = jax.device_put(tok_dev, self._token_sharding)
            logits, self.cache = fn(
                self.params, tok_dev, jnp.int32(pos0), self.cache)
            logits_np = self.fetch_logits(logits)
            d1 = time.perf_counter()

            emitted = verify_fn(logits_np[0], draft)
            # stop token: emit it (generate() parity), drop the rest
            for i, t in enumerate(emitted):
                if t in stop_ids:
                    emitted = emitted[: i + 1]
                    break
            emitted = emitted[: max_tokens - n_out]
            # positions pos0..pos0+a hold [token] + the confirmed draft
            # prefix; unconfirmed draft writes beyond that are overwritten
            # position-by-position before any later query attends them
            # (the same invariant decode overruns rely on)
            a = len(emitted) - 1
            self.pos = pos0 + 1 + a
            n_out += len(emitted)
            self.last_accept_stats = (self.last_accept_stats[0] + 1, n_out)
            self.last_spec["forwards"] += 1
            self.last_spec["drafted"] += len(draft)
            self.last_spec["accepted"] += max(a, 0)
            self.last_spec["emitted"] += len(emitted)
            hist = np.concatenate([hist, np.asarray(emitted, np.int32)])
            token = emitted[-1]
            g1 = time.perf_counter()
            if stats is not None:
                stats.add(StepStats(generation_ms=(g1 - g0) * 1e3,
                                    device_ms=(d1 - d0) * 1e3,
                                    host_ms=(g1 - g0 - (d1 - d0)) * 1e3))
            for t in emitted:
                yield t

    def generate_lookup(
        self,
        prompt: list[int],
        max_tokens: int,
        eos_id: int | set[int] | None = None,
        *,
        draft_len: int = 7,
        max_ngram: int = 3,
        on_token: Callable[[int], None] | None = None,
        vocab_size: int | None = None,
        history: list[int] | None = None,
    ) -> GenerationResult:
        """Collecting wrapper over generate_lookup_stream (the CLI path)."""
        stats = RunStats()
        out: list[int] = []
        for t in self.generate_lookup_stream(prompt, max_tokens, eos_id,
                                             draft_len=draft_len,
                                             max_ngram=max_ngram,
                                             stats=stats,
                                             vocab_size=vocab_size,
                                             history=history):
            out.append(t)
            if on_token:
                on_token(t)
        return GenerationResult(out, stats)

    def generate_lookup_sampled(
        self,
        prompt: list[int],
        max_tokens: int,
        *,
        temperature: float,
        topp: float,
        seed: int,
        eos_id: int | set[int] | None = None,
        draft_len: int = 7,
        max_ngram: int = 3,
        on_token: Callable[[int], None] | None = None,
        vocab_size: int | None = None,
        history: list[int] | None = None,
    ) -> GenerationResult:
        """Speculative decoding at temperature > 0 via rejection
        resampling (VERDICT r3 weak #5) — a SEPARATE mode from the
        parity-exact greedy stream: every emitted token is distributed
        exactly as the host Sampler's draw on the same logits
        (speculative.target_dist materializes that distribution;
        speculative.accept_or_resample is marginal-exact), but the RNG
        stream differs (acceptance consumes a data-dependent number of
        uniforms, so xorshift coin parity with Sampler is impossible by
        construction — numpy PCG64 seeded from `seed` instead).

        Drafts are point masses (prompt-lookup mines the context, there is
        no draft model), so accept(token d) = p(d) and the residual is p
        with d removed, renormalized. One verify forward confirms
        accepted-prefix + 1 tokens exactly like the greedy path; the
        accept RATE is content- and temperature-dependent (peaked
        distributions on repetitive text accept most drafts).
        `last_accept_stats` updates per forward like the greedy mode."""
        stats = RunStats()
        out: list[int] = []
        for t in self.generate_lookup_sampled_stream(
                prompt, max_tokens, temperature=temperature, topp=topp,
                seed=seed, eos_id=eos_id, draft_len=draft_len,
                max_ngram=max_ngram, vocab_size=vocab_size,
                history=history, stats=stats):
            out.append(t)
            if on_token:
                on_token(t)
        return GenerationResult(out, stats)

    def generate_lookup_sampled_stream(
        self,
        prompt: list[int],
        max_tokens: int,
        *,
        temperature: float,
        topp: float,
        seed: int,
        eos_id: int | set[int] | None = None,
        draft_len: int = 7,
        max_ngram: int = 3,
        vocab_size: int | None = None,
        history: list[int] | None = None,
        stats: RunStats | None = None,
    ) -> Iterator[int]:
        """Token-iterator form of generate_lookup_sampled — the shape the
        API server streams from (mirrors generate_lookup_stream's greedy
        iterator; the K/V bookkeeping contract is identical, so a consumer
        appends emitted tokens to its history as they arrive). The stream
        is deterministic in (seed, logits, drafts): replicated multihost
        processes that derive the same seed (Sampler.next_seed) draw the
        same uniforms, accept the same widths, and keep their collectives
        in lock-step."""
        from .speculative import accept_or_resample, draw, target_dist

        assert temperature > 0, "temperature 0 is the parity-exact greedy mode"
        spec_v = min(vocab_size or self.spec.vocab_size,
                     self.spec.vocab_size)
        rng = np.random.default_rng(seed)

        def first(row: np.ndarray) -> int:
            return draw(target_dist(row, temperature, topp, spec_v),
                        rng.random())

        def verify(seg_logits: np.ndarray, draft: list[int]) -> list[int]:
            # position i's logits condition on [token] + draft[:i]; accept
            # draft[i] with prob p_i(draft[i]), resample the residual on
            # the first reject; a fully-accepted draft earns a bonus draw
            # from the last position (a "free" token, exactly like the
            # greedy path's final argmax)
            emitted: list[int] = []
            for i, d in enumerate(draft):
                p_i = target_dist(seg_logits[i], temperature, topp, spec_v)
                ok, t = accept_or_resample(p_i, int(d), rng.random(),
                                           rng.random())
                emitted.append(t)
                if not ok:
                    return emitted
            p_k = target_dist(seg_logits[len(draft)], temperature, topp,
                              spec_v)
            emitted.append(draw(p_k, rng.random()))
            return emitted

        return self._lookup_loop(prompt, max_tokens, eos_id,
                                 draft_len=draft_len, max_ngram=max_ngram,
                                 history=history, stats=stats,
                                 first_fn=first, verify_fn=verify)

    # -- real-draft speculative generation (runtime/draft.py) -------------

    def _draft_catchup(self, draft, state: dict, hist: np.ndarray,
                       target_pos: int) -> None:
        """Bring a draft's KV cache frontier up to ``target_pos`` by
        prefilling the token stream it missed (hist[i] is the token at
        absolute position i). Chunks pad to ONE fixed width (pad writes
        land beyond the real frontier and are overwritten before the
        draft attends them — the engine-wide overrun invariant), so
        catch-up adds no compile keys however ragged the gaps are. Gaps
        happen at start (the whole prompt) and whenever a round skipped
        drafting (k == 0 at a budget edge)."""
        c = min(self.prefill_chunk, self.seq_len)
        target = min(int(target_pos), len(hist))
        while state["pos"] < target:
            dp = state["pos"]
            n = min(c, target - dp)
            tok = np.zeros((self.batch, c), np.int32)
            tok[0, :n] = hist[dp:dp + n]
            pos = np.full((self.batch,), self.seq_len, np.int32)
            pos[0] = dp
            state["cache"] = draft.prefill_chunk(state["cache"], tok, pos)
            state["pos"] = dp + n

    def generate_draft_stream(
        self,
        prompt: list[int],
        max_tokens: int,
        eos_id: int | set[int] | None = None,
        *,
        draft,
        draft_len: int = 7,
        history: list[int] | None = None,
        stats: RunStats | None = None,
        vocab_size: int | None = None,
    ) -> Iterator[int]:
        """Greedy REAL-draft speculative decoding (runtime/draft.py): the
        draft model (`DraftModel` — the target's own truncated-depth
        prefix, or a separate draft .m) proposes k tokens in ONE
        dispatched scan, the verify forward confirms accepted-prefix + 1
        exactly like the lookup path, and the emitted stream is EXACTLY
        generate()'s greedy stream — drafts only batch the confirmation,
        on ANY text (prompt lookup needs repetitive text to propose at
        all). The draft keeps its own d-layer KV cache inside this
        stream's closure, walking the same absolute positions as the
        target; rejected draft positions are overwritten by the next
        round's feed (the engine-wide overrun invariant), and a stale
        draft cache can only lower the accept rate, never change a
        token. `last_accept_stats` updates per forward like the lookup
        modes. batch must be 1 (the scheduler owns the batched path)."""
        assert self.batch == 1, "use the scheduler for batched drafting"
        from .speculative import count_accepted

        spec_v = min(vocab_size or self.spec.vocab_size,
                     self.spec.vocab_size)
        state = {"cache": draft.new_cache(), "pos": 0}

        def first(row: np.ndarray) -> int:
            return int(np.argmax(row[:spec_v]))

        def verify(seg_logits: np.ndarray, dr: list[int]) -> list[int]:
            greedy = np.argmax(seg_logits[:, :spec_v], axis=-1)
            m = count_accepted(dr, greedy)
            return [int(g) for g in greedy[: m + 1]]

        def draft_fn(hist, k, token, pos0):
            self._draft_catchup(draft, state, hist, pos0)
            # always scan the FULL draft_len (one compile key) and
            # truncate to k: the extra steps are d/L-cheap and their
            # writes sit beyond the frontier. state["pos"] may then
            # exceed the VERIFIED frontier past a rejection — safe
            # HERE because every next round's scan re-feeds
            # contiguously from the new pos0, overwriting each stale
            # position before its own query attends it (the scheduler
            # path must clamp instead: plain rounds can interleave
            # there — Scheduler._decode_spec)
            toks, state["cache"] = draft.propose(
                state["cache"], np.asarray([token], np.int32),
                np.asarray([pos0], np.int32), draft_len, n_vocab=spec_v)
            state["pos"] = pos0 + draft_len
            return [int(t) for t in toks[0][:k]]

        return self._lookup_loop(prompt, max_tokens, eos_id,
                                 draft_len=draft_len, max_ngram=0,
                                 history=history, stats=stats,
                                 first_fn=first, verify_fn=verify,
                                 draft_fn=draft_fn)

    def generate_draft(
        self,
        prompt: list[int],
        max_tokens: int,
        eos_id: int | set[int] | None = None,
        *,
        draft,
        draft_len: int = 7,
        on_token: Callable[[int], None] | None = None,
        vocab_size: int | None = None,
        history: list[int] | None = None,
    ) -> GenerationResult:
        """Collecting wrapper over generate_draft_stream (the CLI path)."""
        stats = RunStats()
        out: list[int] = []
        for t in self.generate_draft_stream(prompt, max_tokens, eos_id,
                                            draft=draft,
                                            draft_len=draft_len,
                                            stats=stats,
                                            vocab_size=vocab_size,
                                            history=history):
            out.append(t)
            if on_token:
                on_token(t)
        return GenerationResult(out, stats)

    def generate_draft_sampled_stream(
        self,
        prompt: list[int],
        max_tokens: int,
        *,
        draft,
        temperature: float,
        topp: float,
        seed: int,
        eos_id: int | set[int] | None = None,
        draft_len: int = 7,
        vocab_size: int | None = None,
        history: list[int] | None = None,
        stats: RunStats | None = None,
    ) -> Iterator[int]:
        """Sampled REAL-draft speculation via GENERAL rejection
        resampling (speculative.accept_or_resample_q): the draft SAMPLES
        each proposal from its own temperature/top-p distribution q (a
        real, non-point-mass proposal — unlike prompt-lookup's onehot
        drafts), and the target accepts with min(1, p/q), resampling the
        normalized residual max(p - q, 0) on the first reject. Every
        emitted token is distributed exactly as a host-Sampler draw on
        the same logits; the RNG stream is a derived numpy PCG64 like
        the sampled lookup mode (coin parity with the plain path is
        impossible by construction). The draft loop here is host-paced
        (one d-layer forward per proposal — sampling is data-dependent,
        so it cannot fuse into the greedy scan); the greedy mode is the
        latency headline."""
        from .speculative import (accept_or_resample_q, draw, target_dist)

        assert self.batch == 1, "use the scheduler for batched drafting"
        assert temperature > 0, "temperature 0 is the parity-exact greedy mode"
        spec_v = min(vocab_size or self.spec.vocab_size,
                     self.spec.vocab_size)
        rng = np.random.default_rng(seed)
        state = {"cache": draft.new_cache(), "pos": 0, "q": []}

        def first(row: np.ndarray) -> int:
            return draw(target_dist(row, temperature, topp, spec_v),
                        rng.random())

        def draft_fn(hist, k, token, pos0):
            self._draft_catchup(draft, state, hist, pos0)
            toks: list[int] = []
            qs: list[np.ndarray] = []
            cur, p, cache = int(token), int(pos0), state["cache"]
            for _ in range(k):
                lg, cache = draft.step_logits(
                    cache, np.asarray([[cur]], np.int32),
                    np.asarray([p], np.int32))
                qd = target_dist(lg[0], temperature, topp, spec_v)
                cur = draw(qd, rng.random())
                toks.append(cur)
                qs.append(qd)
                p += 1
            state["cache"], state["pos"], state["q"] = cache, p, qs
            return toks

        def verify(seg_logits: np.ndarray, dr: list[int]) -> list[int]:
            emitted: list[int] = []
            for i, d in enumerate(dr):
                p_i = target_dist(seg_logits[i], temperature, topp, spec_v)
                ok, t = accept_or_resample_q(p_i, state["q"][i], int(d),
                                             rng.random(), rng.random())
                emitted.append(t)
                if not ok:
                    return emitted
            p_k = target_dist(seg_logits[len(dr)], temperature, topp,
                              spec_v)
            emitted.append(draw(p_k, rng.random()))
            return emitted

        return self._lookup_loop(prompt, max_tokens, eos_id,
                                 draft_len=draft_len, max_ngram=0,
                                 history=history, stats=stats,
                                 first_fn=first, verify_fn=verify,
                                 draft_fn=draft_fn)

    def generate_draft_sampled(
        self,
        prompt: list[int],
        max_tokens: int,
        *,
        draft,
        temperature: float,
        topp: float,
        seed: int,
        eos_id: int | set[int] | None = None,
        draft_len: int = 7,
        on_token: Callable[[int], None] | None = None,
        vocab_size: int | None = None,
        history: list[int] | None = None,
    ) -> GenerationResult:
        """Collecting wrapper over generate_draft_sampled_stream."""
        stats = RunStats()
        out: list[int] = []
        for t in self.generate_draft_sampled_stream(
                prompt, max_tokens, draft=draft, temperature=temperature,
                topp=topp, seed=seed, eos_id=eos_id, draft_len=draft_len,
                vocab_size=vocab_size, history=history, stats=stats):
            out.append(t)
            if on_token:
                on_token(t)
        return GenerationResult(out, stats)

    # -- continuous-batching slot steps (runtime/scheduler.py) ------------

    def slot_prefill_chunk(self, tokens: np.ndarray, pos: np.ndarray,
                           logit_index: np.ndarray) -> jax.Array:
        """One chunked-prefill forward over the batched cache: row r writes
        its (B, C) chunk's K/V at absolute offsets pos[r]..pos[r]+C-1 via
        the per-row scatter path, without disturbing any other row. Rows
        not prefilling this call are GATED OFF by passing pos[r] ==
        seq_len: their write indices land out of bounds and the drop-mode
        scatter discards them (models/transformer._scatter_cache_write),
        so a gated row's cache — mid-decode or idle — is untouched.
        Returns (B, vocab) logits read at per-row `logit_index` within the
        chunk (only rows finishing their prompt this chunk are consumed;
        the scheduler skips the D2H fetch entirely for mid-prompt chunks).

        The chunk width C is the ONLY compilation key
        (slot_prefill_chunk_C): the scheduler pads every tail chunk to a
        fixed C, so admission order/prompt lengths never mint new
        executables (the fixed-compilation-key discipline dlgrind DLG204
        pins). Does NOT touch self.pos — per-slot positions are owned by
        the scheduler."""
        from .faults import FAULTS

        FAULTS.fire("prefill_raise")  # injection point: host-side, before
        # any dispatch — arming it never alters the jitted program
        b, c = tokens.shape
        assert b == self.batch, (b, self.batch)
        key = ("slot_prefill", c)
        if key not in self._steps:
            common = self._forward_kwargs()

            def run(params, tokens, pos0, logit_index, cache):
                return forward(params, self.spec, tokens, pos0, cache,
                               logit_index=logit_index, **common)

            run.__name__ = f"slot_prefill_chunk_{c}"
            self._mint(key, jax.jit(run, donate_argnums=(4,)))
        tok = jnp.asarray(tokens, jnp.int32)
        posv = jnp.asarray(pos, jnp.int32)
        if self._token_sharding is not None:
            tok = jax.device_put(tok, self._token_sharding)
            posv = jax.device_put(posv,
                                  NamedSharding(self.mesh, P(DP_AXIS)))
        logits, self.cache = self._steps[key](
            self.params, tok, posv, jnp.asarray(logit_index, jnp.int32),
            self.cache)
        return logits

    def slot_decode_step(self, tokens: np.ndarray, pos: np.ndarray) -> jax.Array:
        """One decode step for the slot scheduler: row r feeds tokens[r]
        at its own absolute position pos[r] (per-row scatter write,
        donated cache). Rows without a decode token this step pass pos[r]
        == seq_len — their write drops out of bounds and their logits row
        is ignored. One compilation key total ("slot_decode"); self.pos is
        untouched (per-slot positions are the scheduler's)."""
        b, t = tokens.shape
        assert b == self.batch and t == 1, (tokens.shape, self.batch)
        key = "slot_decode"
        if key not in self._steps:
            common = self._forward_kwargs()

            def run(params, tokens, pos0, cache):
                return forward(params, self.spec, tokens, pos0, cache,
                               **common)

            run.__name__ = "slot_decode_step"
            self._mint(key, jax.jit(run, donate_argnums=(3,)))
        tok = jnp.asarray(tokens, jnp.int32)
        posv = jnp.asarray(pos, jnp.int32)
        if self._token_sharding is not None:
            tok = jax.device_put(tok, self._token_sharding)
            posv = jax.device_put(posv,
                                  NamedSharding(self.mesh, P(DP_AXIS)))
        logits, self.cache = self._steps[key](self.params, tok, posv,
                                              self.cache)
        return logits

    def slot_verify_step(self, tokens: np.ndarray, pos: np.ndarray,
                         n_vocab: int) -> tuple[np.ndarray, np.ndarray]:
        """One FIXED-WIDTH speculative verify step for the slot
        scheduler: row r feeds its (1 + K) segment [last token, draft...]
        at absolute positions pos[r]..pos[r]+K (the generate_batch_lookup
        padding trick as a slot executable — rows without a draft pad
        with their own token, gated rows pass pos[r] == seq_len and every
        write drops). Returns (greedy (B, 1+K) int32 — the target's
        argmax AFTER each segment position, computed ON DEVICE over the
        tokenizer vocab, and the position-0 logits (B, vocab) as a DEVICE
        array — what a plain slot_decode_step would have returned, so
        non-speculating rows ride the same forward and sample normally
        through Engine.sample_view).

        The width 1 + K and n_vocab are the ONLY compile keys
        ("slot_verify"): the scheduler always pads to its configured
        draft_len, so speculative serving mints exactly one verify
        executable, warmed by Scheduler.warmup() — the bounded-key
        discipline --freeze-compiles enforces. Unconfirmed draft writes
        beyond each row's accepted prefix are overwritten before any
        later query attends them (the engine-wide overrun invariant).
        self.pos untouched (per-slot positions are the scheduler's)."""
        from .draft import batched_verify

        b, t = tokens.shape
        assert b == self.batch, (b, self.batch)
        key = ("slot_verify", t, int(n_vocab))
        if key not in self._steps:
            common = self._forward_kwargs()
            spec = self.spec

            def run(params, tok, pos, cache, nv=int(n_vocab)):
                return batched_verify(params, spec, tok, pos, cache,
                                      n_vocab=nv, fwd_kwargs=common)

            run.__name__ = f"slot_verify_{t}"
            self._mint(key, jax.jit(run, donate_argnums=(3,)))
        tok = jnp.asarray(tokens, jnp.int32)
        posv = jnp.asarray(pos, jnp.int32)
        if self._token_sharding is not None:
            tok = jax.device_put(tok, self._token_sharding)
            posv = jax.device_put(posv,
                                  NamedSharding(self.mesh, P(DP_AXIS)))
        greedy, logits0, self.cache = self._steps[key](
            self.params, tok, posv, self.cache)
        # logits0 stays ON DEVICE: the scheduler wraps it in a sample
        # view (Engine.sample_view), so vocab-sharded engines never
        # fetch the (B, vocab) array — non-speculating rows sample from
        # the sharded candidates like any decode step
        return np.asarray(greedy), logits0

    # -- prefix-cache arena steps (runtime/prefix_cache.py) ---------------

    def new_prefix_arena(self, num_blocks: int, block_len: int):
        """Allocate the radix prefix cache's block arena: K and V arrays
        of (num_blocks, layers, kv_heads, block_len, head_size) in the
        cache dtype. Computed on device (jitted zeros — donation-safe by
        the _seed_jit discipline, though the arena itself is NEVER
        donated into a forward: blocks are immutable once published and
        shared across requests). The arena dies with the engine — a
        supervisor rebuild mints a fresh engine, a fresh arena, and an
        empty tree (runtime/resilience.EngineSupervisor._make_sched)."""
        assert self._pp == 1, "prefix cache does not support --pp"
        assert num_blocks >= 1 and 1 <= block_len <= self.seq_len
        shape = (num_blocks, self.spec.n_layers, self.spec.n_kv_heads,
                 block_len, self.spec.head_size)
        dt = self.cache_dtype
        key = ("prefix_arena", shape)
        if key not in self._steps:
            self._mint(key, jax.jit(
                lambda: (jnp.zeros(shape, dt), jnp.zeros(shape, dt))))
        return self._steps[key]()

    def slot_seed_prefix(self, arena_k, arena_v, row: int,
                         block_ids: np.ndarray) -> None:
        """Seed slot row `row`'s leading cache positions from arena
        blocks (on-device block-gather -> cache row write; the cache is
        donated and updated in place). `block_ids` is the fixed-width
        (seq_len // block_len,) vector — the scheduler pads it with
        block 0, so this is ONE compilation key total ("slot_seed"),
        fingerprinted in analysis/baseline.json like the other two
        serving executables. See seed_rows_from_blocks for the padding
        invariant and the f8 seeding guard; _seed_jit for the
        donation-safety/out_shardings discipline. Does not touch
        self.pos (per-slot positions are the scheduler's)."""
        mb, bl = block_ids.shape[0], arena_k.shape[3]
        key = ("slot_seed", mb, bl)
        if key not in self._steps:
            run = seed_rows_from_blocks
            self._mint(key, self._seed_jit(run, out_tree=self.cache,
                                           donate=(0,)))
        self.cache = self._steps[key](
            self.cache, arena_k, arena_v, jnp.int32(row),
            jnp.asarray(block_ids, jnp.int32))

    def slot_publish_block(self, arena_k, arena_v, row: int, offset: int,
                           dst: int):
        """Copy slot row `row`'s filled cache positions
        [offset, offset + block_len) into arena block `dst` and return
        the updated (arena_k, arena_v). The arenas are donated (in-place
        block write); the cache is only read. One compilation key total
        (row/offset/dst are traced scalars), so publishing never mints
        executables however requests finish. The copied bytes came from
        this engine's own saturating cache writes — the NaN-code guard
        runs on the SEED side, where the producer cannot be trusted."""
        bl = arena_k.shape[3]
        kvh, hs = self.spec.n_kv_heads, self.spec.head_size
        n_l = self.spec.n_layers
        key = ("slot_publish", bl)
        if key not in self._steps:
            def run(arena_k, arena_v, cache, row, off, dst):
                z = jnp.int32(0)
                outs = []
                for arena, leaves in ((arena_k, cache.k), (arena_v, cache.v)):
                    blk = jnp.stack([
                        lax.dynamic_slice(leaves[l], (row, z, off, z),
                                          (1, kvh, bl, hs))[0]
                        for l in range(n_l)])       # (L, KVH, bl, hs)
                    outs.append(lax.dynamic_update_slice(
                        arena, blk[None], (dst, z, z, z, z)))
                return tuple(outs)

            run.__name__ = "slot_publish_block"
            self._mint(key, jax.jit(run, donate_argnums=(0, 1)))
        return self._steps[key](arena_k, arena_v, self.cache,
                                jnp.int32(row), jnp.int32(offset),
                                jnp.int32(dst))

    # -- cross-replica KV block transfer (runtime/kv_transfer.py) ---------

    def block_export(self, arena_k, arena_v, src: int):
        """Gather arena block ``src`` as a device (L, KVH, bl, hs) K/V
        pair for host export. One compilation key per block length
        ("block_export" — src is a traced scalar), minted through the
        compile ledger like every serving executable and warmed by
        ``PrefixCache.warmup`` when transfer is enabled, so donor
        serving mints ZERO post-warmup keys."""
        key = ("block_export", arena_k.shape[3])
        if key not in self._steps:
            self._mint(key, jax.jit(export_arena_block))
        return self._steps[key](arena_k, arena_v, jnp.int32(src))

    def slot_import_block(self, arena_k, arena_v, k_blk, v_blk, dst: int):
        """Write one fetched host block pair into arena slot ``dst`` and
        return the updated (arena_k, arena_v) — the importer half of the
        transfer plane. Arenas donated; one compilation key per block
        length ("block_import"). See import_arena_block for why the
        bytes land raw (the seed-side f8 guard owns trust)."""
        key = ("block_import", arena_k.shape[3])
        if key not in self._steps:
            self._mint(key, jax.jit(import_arena_block,
                                    donate_argnums=(0, 1)))
        return self._steps[key](arena_k, arena_v,
                                jnp.asarray(k_blk, self.cache_dtype),
                                jnp.asarray(v_blk, self.cache_dtype),
                                jnp.int32(dst))

    # -- batched speculative (prompt-lookup) greedy generation ------------

    def generate_batch_lookup(
        self,
        prompts: list[list[int]],
        max_tokens: int,
        eos_id: int | set[int] | None = None,
        *,
        draft_len: int = 7,
        max_ngram: int = 3,
        vocab_size: int | None = None,
        histories: list[list[int]] | None = None,
        stop_flags: np.ndarray | None = None,
    ) -> list[list[int]]:
        """Batched prompt-lookup speculative decoding (VERDICT r4 #7):
        every row mines its own draft from its own history each step, the
        drafts RIGHT-PAD to the widest live draft (padding feeds the row's
        current token again — its writes land beyond the accepted prefix
        and are overwritten like any unconfirmed draft), and ONE verify
        forward of (B, 1 + k_max) confirms each row's accepted prefix + 1.
        Emitted streams are EXACTLY the per-row greedy streams (argmax
        verify — same contract as generate_lookup_stream), so decode stays
        weight-read-bound: b rows x multi-token accepts amortize one
        weight read per forward.

        Greedy only, single host loop. Returns one token list per row
        (stop token included — generate() parity). `last_accept_stats`
        holds (verify_forwards, total_tokens) summed over live rows.
        `histories[i]` (defaults to prompts[i]) seeds row i's draft-mining
        context, like the single-row stream's `history`. `stop_flags` rows
        set True BEFORE the call never emit (the API server pads sub-batch
        requests up to the engine's fixed batch with such rows); unlike
        generate_batch_stream's live flags, they are read once at start —
        text-level stops apply post-hoc on the collected rows."""
        from .speculative import count_accepted, find_draft

        b = len(prompts)
        assert b == self.batch, (b, self.batch)
        assert all(prompts), "empty prompt"
        stop_ids = ({eos_id} if isinstance(eos_id, int) else eos_id) or set()
        spec_v = min(vocab_size or self.spec.vocab_size,
                     self.spec.vocab_size)
        lens = np.asarray([len(p) for p in prompts], np.int32)
        t = int(lens.max())
        assert t < self.seq_len, "context overflow"

        # greedy argmax ON DEVICE: the verify loop only consumes argmaxes,
        # and fetching the full (B, T, V) logits per forward is ~8 MB of
        # D2H — on the tunneled platform that transfer alone capped the
        # batch-lookup bench at 59 tok/s aggregate; (B, T) int32 is ~256 B
        amax_key = ("bl_amax", spec_v)
        if amax_key not in self._steps:
            self._mint(amax_key, jax.jit(
                lambda l: jnp.argmax(
                    l[..., :spec_v].astype(jnp.float32), axis=-1
                ).astype(jnp.int32)))
        amax = self._steps[amax_key]

        # whole-batch right-padded prefill (same path as generate_batch)
        pre_fn = self._compiled_step(("bpre", t), with_logit_index=True)
        padded = np.zeros((b, t), np.int32)
        for i, p in enumerate(prompts):
            padded[i, : len(p)] = p
        tok = jnp.asarray(padded)
        if self._token_sharding is not None:
            tok = jax.device_put(tok, self._token_sharding)
        logits, self.cache = pre_fn(
            self.params, tok, jnp.asarray(lens - 1), self.cache)
        if max_tokens <= 0:  # hard-cap contract, same as generate()
            self.pos = int(lens.max())
            self.last_accept_stats = (1, 0)
            return [[] for _ in range(b)]
        first_np = np.asarray(amax(logits))  # (B,)

        out: list[list[int]] = [[] for _ in range(b)]
        hists: list[np.ndarray] = []
        cur = np.zeros(b, np.int32)
        done = (np.asarray(stop_flags, bool).copy() if stop_flags is not None
                else np.zeros(b, bool))
        pos = lens.copy()
        for i in range(b):
            cur[i] = int(first_np[i])
            hists.append(np.asarray(
                (histories[i] if histories is not None else prompts[i])
                + [int(first_np[i])], np.int32))
            if done[i]:
                continue  # pre-retired padding row: never emits
            tok_i = int(first_np[i])
            out[i].append(tok_i)
            if tok_i in stop_ids:
                done[i] = True
        self.pos = int(pos.max())
        n_forwards = 1
        # stats are valid even if the loop below never runs (budget 1, or
        # every row's first token is a stop token)
        self.last_accept_stats = (n_forwards, sum(len(o) for o in out))

        def alive(i: int) -> bool:
            return (not done[i] and len(out[i]) < max_tokens
                    and pos[i] < self.seq_len)

        while any(alive(i) for i in range(b)):
            drafts: list[list[int]] = []
            for i in range(b):
                if alive(i):
                    k = min(draft_len, self.seq_len - pos[i] - 1,
                            max_tokens - len(out[i]) - 1)
                    drafts.append(find_draft(hists[i], k,
                                             max_ngram=max_ngram)
                                  if k > 0 else [])
                else:
                    drafts.append([])
            k_max = max(len(d) for d in drafts)

            # rows feed [cur] + draft, padded to 1 + k_max with cur (the
            # padding's K/V writes sit beyond the accepted prefix and are
            # overwritten before any later query attends them; rows at the
            # context edge rely on the scatter's drop-mode OOB writes)
            seg = np.empty((b, 1 + k_max), np.int32)
            for i, d in enumerate(drafts):
                seg[i, 0] = cur[i]
                seg[i, 1: 1 + len(d)] = d
                seg[i, 1 + len(d):] = cur[i]

            fn = self._compiled_step(("blookup", 1 + k_max),
                                     logits_for_all=True)
            tok_dev = jnp.asarray(seg)
            posv = jnp.asarray(np.minimum(pos, self.seq_len - 1))
            if self._token_sharding is not None:
                tok_dev = jax.device_put(tok_dev, self._token_sharding)
                posv = jax.device_put(
                    posv, NamedSharding(self.mesh, P(DP_AXIS)))
            logits, self.cache = fn(self.params, tok_dev, posv, self.cache)
            greedy_np = np.asarray(amax(logits))  # (B, 1+k_max)
            n_forwards += 1

            for i in range(b):
                if not alive(i):
                    continue
                greedy = greedy_np[i]
                m = count_accepted(drafts[i], greedy)
                emitted = [int(g) for g in greedy[: m + 1]]
                for j, tk in enumerate(emitted):
                    if tk in stop_ids:
                        emitted = emitted[: j + 1]
                        done[i] = True
                        break
                emitted = emitted[: max_tokens - len(out[i])]
                pos[i] += len(emitted)  # 1 + accepted
                out[i].extend(emitted)
                cur[i] = emitted[-1]
                hists[i] = np.concatenate(
                    [hists[i], np.asarray(emitted, np.int32)])
            self.pos = int(np.minimum(pos, self.seq_len).max())
            self.last_accept_stats = (n_forwards, sum(len(o) for o in out))
        return out

    # -- batched generation (dp path) -------------------------------------

    def generate_batch(
        self,
        prompts: list[list[int]],
        max_tokens: int,
        sampler: Sampler,
        eos_id: int | set[int] | None = None,
    ) -> list[list[int]]:
        """Generate for `batch` independent sequences at once (right-padded
        prompts, per-sequence positions/eos). Net-new vs the reference's
        batch=1 engine (SURVEY.md §2.5 DP row); with a dp mesh the batch
        shards over dp. Greedy results match `batch` independent runs.

        Returns one token list per sequence; a row that hits its stop
        token includes it as the final entry (generate() parity — the
        stream below documents the same contract)."""
        out: list[list[int]] = [[] for _ in prompts]
        for step_toks in self.generate_batch_stream(prompts, max_tokens,
                                                    sampler, eos_id):
            for i, t in enumerate(step_toks):
                if t is not None:
                    out[i].append(t)
        return out

    def generate_batch_stream(
        self,
        prompts: list[list[int]],
        max_tokens: int,
        sampler: Sampler,
        eos_id: int | set[int] | None = None,
        stop_flags: np.ndarray | None = None,
    ) -> Iterator[list[int | None]]:
        """Step-level iterator form of generate_batch — the shape the API
        server's batch endpoint streams from. Each yield is one decode
        step's tokens: b entries, the row's newly sampled token (a stop
        token is included, then the row stops — generate() parity) or None
        for rows that are done/past budget. max_tokens is a hard cap like
        generate()'s: max_tokens <= 0 prefills but samples/emits nothing
        (no coins leave the shared sampler stream).

        `stop_flags` is an optional (b,) bool array OWNED BY THE CALLER:
        setting stop_flags[i] = True between steps retires row i — the API
        server's stop-sequence/marker scan happens on decoded TEXT, which
        the engine cannot see. A retired row yields None and stops stepping
        (its sampler-coin slot also frees, like an eos row's). Rows flagged
        BEFORE the first step never sample at all — the server pads
        sub-batch requests up to the engine's fixed batch with such rows,
        and they draw no coins from the shared sampler stream."""
        b = len(prompts)
        assert b == self.batch, (b, self.batch)
        assert all(prompts), "empty prompt"
        stop_ids = ({eos_id} if isinstance(eos_id, int) else eos_id) or set()
        lens = np.asarray([len(p) for p in prompts], np.int32)
        t = int(lens.max())
        assert t < self.seq_len, "context overflow"

        # whole-batch right-padded prefill; logits read at each row's last
        # real token. Padded slots write garbage K/V at positions >= len(p),
        # but those cache slots are overwritten by decode before any later
        # query position can attend to them (attention masks k_pos <= q_pos).
        pre_fn = self._compiled_step(("bpre", t), with_logit_index=True)
        vec_fn = self._compiled_step(("bvec", 1))

        padded = np.zeros((b, t), np.int32)
        for i, p in enumerate(prompts):
            padded[i, : len(p)] = p
        tok = jnp.asarray(padded)
        if self._token_sharding is not None:
            tok = jax.device_put(tok, self._token_sharding)
        logits, self.cache = pre_fn(
            self.params, tok, jnp.asarray(lens - 1), self.cache)
        if max_tokens <= 0:  # hard-cap contract, same as generate(); no
            self.pos = int(lens.max())  # D2H fetch for discarded logits
            return

        n_out = np.zeros(b, np.int64)
        done = np.zeros(b, bool)
        # one host-sampler call per step, in row order for live rows —
        # the shared xorshift stream's coins are drawn token-for-token
        # identical to per-row sample() calls. On vocab-sharded engines
        # the view serves greedy rows from the device argmax
        # (bit-identical) and sampled rows from the candidate scheme
        # (distribution-exact) instead of fetching (B, vocab) logits.
        # (Batched-numpy sampling was built and measured SLOWER than the
        # row loop in every branch — the negative result and the actual
        # large-dp answer, --device-sampling, are recorded in
        # sample_batch's docstring; VERDICT r3 weak #7.)
        temps = np.full((b,), sampler.temperature if sampler.temperature
                        else 1.0, np.float32)
        n_vocab = int(sampler.vocab_size)

        def sample_rows(lg, mask: np.ndarray) -> np.ndarray:
            view = self.sample_view(lg, temps, n_vocab)
            out = np.full(b, -1, np.int64)
            for i in np.nonzero(mask)[0]:
                out[i] = view.sample(sampler, int(i))
            return out

        live0 = (np.ones(b, bool) if stop_flags is None
                 else ~np.asarray(stop_flags, bool))
        cur = sample_rows(logits, live0).astype(np.int32)
        # sample_batch marks unselected rows -1; a pre-retired (padding)
        # row's token is still FED to the embedding gather every step, so
        # clamp it to a real id rather than lean on XLA's out-of-bounds
        # gather clamping (an implicit dependency otherwise)
        cur = np.where(live0, cur, 0).astype(np.int32)
        for i in range(b):
            if live0[i]:
                n_out[i] = 1
                if int(cur[i]) in stop_ids:
                    done[i] = True
        pos = lens.copy()  # next write position per row
        self.pos = int(pos.max())
        yield [int(c) if live0[i] else None for i, c in enumerate(cur)]

        def alive(i: int) -> bool:
            # a row generates while unstopped (model eos OR caller
            # stop_flags), under budget, and with a free cache slot
            # (pos < seq_len — generate()'s overflow guard, per row)
            if stop_flags is not None and stop_flags[i]:
                return False
            return (not done[i] and n_out[i] < max_tokens
                    and pos[i] < self.seq_len)

        while any(alive(i) for i in range(b)):
            tokv = jnp.asarray(cur[:, None])
            # exhausted rows clamp their (ignored) write to the last slot so
            # the scatter stays in bounds; their outputs stopped already
            posv = jnp.asarray(np.minimum(pos, self.seq_len - 1))
            if self._token_sharding is not None:
                tokv = jax.device_put(tokv, self._token_sharding)
                posv = jax.device_put(
                    posv, NamedSharding(self.mesh, P(DP_AXIS)))
            logits, self.cache = vec_fn(
                self.params, tokv, posv, self.cache)
            alive_mask = np.asarray([alive(i) for i in range(b)])
            nxt = sample_rows(logits, alive_mask)
            step: list[int | None] = [None] * b
            for i in np.nonzero(alive_mask)[0]:
                step[i] = int(nxt[i])
                n_out[i] += 1
                cur[i] = nxt[i]
                if int(nxt[i]) in stop_ids:
                    done[i] = True  # like generate(): stop token included,
                    # then the row stops
            pos = pos + 1
            self.pos = int(np.minimum(pos, self.seq_len).max())
            yield step

    # -- on-device SAMPLED decode loop ------------------------------------

    def generate_device(
        self,
        prompt: list[int],
        max_tokens: int,
        *,
        temperature: float,
        topp: float,
        seed: int,
        eos_id: int | set[int] | None = None,
        vocab_size: int | None = None,
    ) -> list[int]:
        """Sampled generation with the whole decode loop on device: one
        lax.while_loop whose body samples (temperature/top-p, reference
        xorshift* stream — ops/device_sampler.py) and steps the model, with
        no host round-trip per token. Net-new vs the reference, whose
        sampler is CPU-bound per token (ref: src/tokenizer.cpp:231-364).

        Matches generate()+Sampler semantics step for step (device CDFs
        accumulate in f32 vs the host's float64 — a neighboring-token pick
        is possible only within f32 epsilon of a CDF boundary). The loop
        exits ON DEVICE at the first stop token — an eos at step 3 of a
        512-token budget pays 3 forwards, not 512 — and, like generate(),
        never runs the forward for the last emitted token (no overrun cache
        writes, no rewind). batch == 1.

        vocab_size: sample only over the first vocab_size logits (the host
        Sampler likewise truncates to the TOKENIZER's vocab, which can be
        smaller than the model head — sampler.py:69)."""
        assert self.batch == 1, "generate_device is single-sequence"
        from ..ops.device_sampler import sample_token, state_from_seed

        stop_ids = ({eos_id} if isinstance(eos_id, int) else eos_id) or set()
        n_vocab = min(vocab_size or self.spec.vocab_size,
                      self.spec.vocab_size)
        logits = self.prefill(prompt)
        if max_tokens <= 0:  # hard-cap contract, same as generate()
            self.last_device_steps = 0
            return []
        # every stepped token is followed by its forward's cache write at
        # pos, so writes stay < seq_len; the final token is never stepped
        # (see below), so the loop can emit at the exact context edge
        max_tokens = min(max_tokens, self.seq_len - self.pos + 1)

        spec = self.spec
        key = ("dsample", max_tokens, float(temperature), float(topp),
               n_vocab, tuple(sorted(stop_ids)))
        if key not in self._steps:
            common = self._forward_kwargs()
            stop_arr = jnp.asarray(sorted(stop_ids), jnp.int32)

            @partial(jax.jit, donate_argnums=(3,))
            def run(params, logits0, pos0, cache, rng):
                buf0 = jnp.full((max_tokens,), -1, jnp.int32)

                def cond(carry):
                    _, _, _, _, _, i, stop = carry
                    return jnp.logical_and(~stop, i < max_tokens)

                def body(carry):
                    lgt, pos, cache, rng, buf, i, _ = carry
                    tok, rng = sample_token(lgt[0, :n_vocab], rng,
                                            temperature, topp)
                    buf = buf.at[i].set(tok)
                    stop = (jnp.any(tok == stop_arr) if stop_ids
                            else jnp.bool_(False))
                    # generate() parity: the last emitted token — stop or
                    # budget edge — is never stepped, so skip its forward
                    # (this is the early exit: eos at step k costs k
                    # forwards, not max_tokens)
                    skip = jnp.logical_or(stop, i == max_tokens - 1)
                    lgt, cache = lax.cond(
                        skip,
                        lambda cache: (lgt, cache),
                        lambda cache: forward(params, spec, tok[None, None],
                                              pos, cache, **common),
                        cache)
                    return (lgt, pos + 1, cache, rng, buf, i + 1, stop)

                (_, _, cache, _, buf, n, _) = lax.while_loop(
                    cond, body,
                    (logits0, pos0, cache, rng, buf0, jnp.int32(0),
                     jnp.bool_(False)))
                return buf, n, cache

            self._mint(key, run)

        toks, n, self.cache = self._steps[key](
            self.params, logits, jnp.int32(self.pos), self.cache,
            state_from_seed(seed))
        n = int(n)  # D2H is also the sync point
        # observability: device while-loop iterations this call (== sampled
        # tokens; forwards executed = n - 1) — proves the early exit ran
        self.last_device_steps = n
        out = [int(t) for t in np.asarray(toks[:n]).tolist()]
        # host-parity position: generate() never steps (so never writes) the
        # last emitted token — pos advances by the n - 1 forwards that ran
        self.pos += max(n - 1, 0)
        return out

    def generate_batch_device(
        self,
        prompts: list[list[int]],
        max_tokens: int,
        *,
        temperature: float,
        topp: float,
        seed: int,
        eos_id: int | set[int] | None = None,
        vocab_size: int | None = None,
    ) -> list[list[int]]:
        """Batched sampled generation with the whole decode loop on device:
        `batch` independent sequences, each with its OWN xorshift* stream —
        row i is seeded `seed + i`, so its tokens match a single-sequence
        generate_device run of that prompt with seed + i (greedy AND
        sampled; distinct per-row streams mean dp rows serving the SAME
        prompt still sample distinct continuations at temperature > 0,
        while the host generate_batch instead interleaves one shared
        sampler stream across rows). Composes with dp meshes: the batch and
        every per-row carry shard over dp. Removes generate_batch's
        per-row host sampling loop (the reference has no batching at all —
        SURVEY.md §2.5 DP row).

        Per-row early exit: a row stops at its stop token (recorded, like
        generate()) or when its cache fills; the device loop exits when
        every row is done. (One edge divergence from generate_device: at the
        exact context boundary the single-sequence path can emit one final
        unstepped token, this path — like the host generate_batch — ends
        the row.)"""
        from ..ops.device_sampler import sample_token, state_from_seed

        b = len(prompts)
        assert b == self.batch, (b, self.batch)
        assert all(prompts), "empty prompt"
        stop_ids = ({eos_id} if isinstance(eos_id, int) else eos_id) or set()
        n_vocab = min(vocab_size or self.spec.vocab_size,
                      self.spec.vocab_size)
        lens = np.asarray([len(p) for p in prompts], np.int32)
        t = int(lens.max())
        assert t < self.seq_len, "context overflow"

        # whole-batch right-padded prefill (same path as generate_batch)
        pre_fn = self._compiled_step(("bpre", t), with_logit_index=True)
        padded = np.zeros((b, t), np.int32)
        for i, p in enumerate(prompts):
            padded[i, : len(p)] = p
        tok = jnp.asarray(padded)
        if self._token_sharding is not None:
            tok = jax.device_put(tok, self._token_sharding)
        logits, self.cache = pre_fn(
            self.params, tok, jnp.asarray(lens - 1), self.cache)
        if max_tokens <= 0:  # hard-cap contract, same as generate()
            self.pos = int(lens.max())
            self.last_device_steps = 0
            return [[] for _ in range(b)]

        spec = self.spec
        seq_len = self.seq_len
        key = ("bdsample", max_tokens, float(temperature), float(topp),
               n_vocab, tuple(sorted(stop_ids)))
        if key not in self._steps:
            common = self._forward_kwargs()
            stop_arr = jnp.asarray(sorted(stop_ids), jnp.int32)
            sample_rows = jax.vmap(
                lambda lgt, st: sample_token(lgt, st, temperature, topp))

            @partial(jax.jit, donate_argnums=(3,))
            def run(params, logits0, pos0, cache, rng0):
                buf0 = jnp.full((b, max_tokens), -1, jnp.int32)
                feed0 = jnp.zeros((b,), jnp.int32)

                def cond(carry):
                    _, _, _, _, _, _, i, done = carry
                    return jnp.logical_and(i < max_tokens,
                                           jnp.any(~done))

                def body(carry):
                    lgt, pos, cache, rng, buf, feed, i, done = carry
                    # a full cache ends the row like the host loop's
                    # pos < seq_len guard (generate_batch)
                    done = jnp.logical_or(done, pos >= seq_len)
                    toks, rng_new = sample_rows(lgt[:, :n_vocab], rng)
                    record = ~done
                    buf = buf.at[:, i].set(jnp.where(record, toks, -1))
                    rng = jnp.where(record[:, None], rng_new, rng)
                    if stop_ids:
                        stopped = jnp.any(
                            toks[:, None] == stop_arr[None, :], axis=-1)
                        done = jnp.logical_or(done, record & stopped)
                    # done rows keep feeding their last token; their cache
                    # writes land at fresh (or dropped-OOB) slots no output
                    # depends on
                    feed = jnp.where(record, toks, feed)
                    lgt, cache = forward(params, spec, feed[:, None], pos,
                                         cache, **common)
                    return (lgt, pos + 1, cache, rng, buf, feed, i + 1, done)

                (_, _, cache, _, buf, _, n, _) = lax.while_loop(
                    cond, body,
                    (logits0, pos0, cache, rng0, buf0, feed0,
                     jnp.int32(0), jnp.zeros((b,), bool)))
                return buf, n, cache

            self._mint(key, run)

        posv = jnp.asarray(lens)
        rng0 = jnp.stack([state_from_seed(seed + i) for i in range(b)])
        if self._token_sharding is not None:
            posv = jax.device_put(posv,
                                  NamedSharding(self.mesh, P(DP_AXIS)))
        buf, n, self.cache = self._steps[key](
            self.params, logits, posv, self.cache, rng0)
        buf_np = np.asarray(buf)  # D2H is also the sync point
        # fetch the step-count scalar ONCE; the second int(n) this replaced
        # was a redundant device round-trip per call (dlgrind DLG107)
        n_steps = int(n)
        self.last_device_steps = n_steps
        out: list[list[int]] = []
        for i in range(b):
            row = buf_np[i]
            out.append([int(x) for x in row[row >= 0]])
        self.pos = int(min(lens.max() + n_steps, self.seq_len))
        return out

    # -- on-device greedy decode loop (benchmark path) --------------------

    def decode_greedy_device(self, first_token: int, n_tokens: int) -> tuple[np.ndarray, float]:
        """Fully on-device greedy decode of n_tokens via lax.scan — no host
        round-trip per token (net-new vs the reference's host loop; this is
        the latency-optimal TPU decode path). Returns (tokens, seconds)."""

        spec = self.spec
        key = ("greedy", n_tokens)
        if key not in self._steps:
            common = self._forward_kwargs()

            @partial(jax.jit, donate_argnums=(3,))
            def run(params, tok0, pos0, cache):
                def body(carry, _):
                    tok, pos, cache = carry
                    logits, cache = forward(
                        params, spec, tok, pos, cache, **common)
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    return (nxt[:, None], pos + 1, cache), nxt

                (_, _, cache), toks = jax.lax.scan(
                    body, (tok0, pos0, cache), None, length=n_tokens)
                return toks, cache

            self._mint(key, run)
            warm = True
        else:
            warm = False
        run = self._steps[key]

        tok0 = jnp.full((self.batch, 1), first_token, jnp.int32)
        if self._token_sharding is not None:
            tok0 = jax.device_put(tok0, self._token_sharding)

        pos0 = jnp.int32(self.pos)

        if warm:
            # compile + warm (excluded from timing); caches are donated, so
            # each call gets a fresh one. Repeat calls (bench best-of-N) hit
            # the cached executable and skip this.
            toks, _ = run(self.params, tok0, pos0, self._new_cache())
            _ = np.asarray(toks)  # sync via D2H # dlgrind: ignore[DLG107]

        t0 = time.perf_counter()
        toks, cache = run(self.params, tok0, pos0, self._new_cache())
        # the host transfer is the sync point: toks depends on every decode
        # step, and block_until_ready returns early (measured: impossible
        # sub-HBM-bandwidth timings) on the tunneled axon TPU platform
        toks_np = np.asarray(toks)  # dlgrind: ignore[DLG107]
        dt = time.perf_counter() - t0
        self.cache = cache
        self.pos += n_tokens
        return toks_np, dt
