"""Real-draft speculative decoding: a pluggable draft forward with its
own small KV cache, verified by the target engine's existing
rejection-resampling machinery.

Prompt-lookup speculation (runtime/speculative.py) only pays on
repetitive text — its drafts come from the context's own n-grams, and the
committed max-accept bench rows are best-case by construction (VERDICT
#6). This module generalizes the win to ARBITRARY text by drafting from a
real model:

  * **Self-draft (zero extra weights)** — the primary mode: the target
    model's own truncated-depth prefix (the first ``d`` layers plus the
    shared final norm + logits head) runs as the draft. It reuses the
    already-loaded weight buffers (a python-level slice of
    ``params["layers"]`` — no copy, no extra HBM) and keeps its own
    small ``d``-layer KV cache. Late layers of trained transformers
    refine rather than overturn the residual stream, so the prefix's
    argmax agrees with the full model's often enough to pay — and when
    it doesn't, verification makes wrong drafts cost only their (cheap)
    draft forwards, never a wrong token.
  * **Model draft** — a separate TinyLlama-class ``.m``
    (``--draft model:PATH``) rides the SAME machinery: a
    :class:`DraftModel` over its own spec/params with depth = its full
    layer count. The tokenizer (and so the vocab) must match the
    target's.

Cost model: one draft proposal is ONE dispatched program (a
``lax.scan`` of k greedy steps through d layers — k·d/L of a full
forward, and exactly one host round trip however large k is), and one
verify forward confirms accepted-prefix + 1 like the lookup path. Decode
is weight-read-bound on TPU and dispatch-bound on tunneled platforms;
both regimes amortize: the draft reads d/L of the weights, the verify
reads them once for up to k+1 tokens.

Correctness never depends on the draft: greedy emission is always the
TARGET's argmax over the verify logits (bit-identical to the plain
greedy stream — drafts only batch the confirmation), and sampled
emission goes through :func:`speculative.accept_or_resample_q`, which is
marginal-exact for any proposal distribution. A stale or unseeded draft
cache can only lower the accept rate.

Every draft executable is minted through the TARGET engine's compile
ledger (``Engine._mint``), so the recompile sentinel and
``--freeze-compiles`` cover the draft path, and the key set is bounded
by construction: one prefill width, one scan shape, one single-token
step. ``Scheduler.warmup()`` compiles all of them before the sentinel
arms. Docs: docs/serving.md "Speculative decoding".
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.transformer import KVCache, forward


def parse_draft_spec(s: str) -> tuple[str, str]:
    """``--draft`` argument -> ("self", depth-string) | ("model", path).
    Raises ValueError with a CLI-ready message on anything else (the
    dead-flag discipline: a bad draft spec is a parse-time error, never
    a silently ignored flag or a mid-serve crash)."""
    kind, _, arg = str(s).partition(":")
    if kind == "self":
        if not arg.isdigit() or int(arg) < 1:
            raise ValueError(
                f"--draft self:<depth> needs a positive layer count, got "
                f"{s!r}")
        return "self", arg
    if kind == "model":
        if not arg:
            raise ValueError("--draft model:<path> needs a .m path")
        return "model", arg
    raise ValueError(
        f"--draft {s!r} is not 'self:<depth>' or 'model:<path>'")


# -- traced bodies -----------------------------------------------------------
# Module-level so analysis/entrypoints.py fingerprints the SAME programs
# the engine jits (the slot_seed_prefix discipline): a drifting dtype or
# arity here would retrace per call and show up in dlgrind's DLG204 gate.


def draft_scan_tokens(params, spec, tok0, pos, cache, *, k, n_vocab,
                      fwd_kwargs):
    """k greedy autoregressive draft steps in ONE program: feed tok0 at
    per-row positions ``pos``, argmax (over the tokenizer vocab — the
    host Sampler's truncation, sampler.py:69), feed that, k times.
    Returns ((B, k) int32 draft tokens, updated draft cache). Gated rows
    pass pos == seq_len: every write drops out of bounds (the engine's
    standard OOB gating) and their tokens are garbage the caller
    ignores. Rows near the context edge rely on the same drop-mode
    scatter; their late tokens are never accepted (the verify caps at
    the row's headroom)."""

    def body(carry, _):
        tok, p, cache = carry
        logits, cache = forward(params, spec, tok, p, cache, **fwd_kwargs)
        nxt = jnp.argmax(logits[:, :n_vocab].astype(jnp.float32),
                         axis=-1).astype(jnp.int32)
        return (nxt[:, None], p + 1, cache), nxt

    (_, _, cache), toks = lax.scan(body, (tok0, pos, cache), None, length=k)
    return toks.T, cache  # (B, k)


def draft_prefill_cache(params, spec, tok, pos, cache, *, fwd_kwargs):
    """One (B, C) draft prefill chunk at per-row offsets; returns ONLY
    the updated cache — the logits head is dead code XLA eliminates, so
    a draft catch-up chunk never pays the wcls matmul. Gating and tail
    padding follow slot_prefill_chunk's invariants exactly (pad writes
    land beyond the real frontier and are overwritten before the draft
    attends them)."""
    _, cache = forward(params, spec, tok, pos, cache,
                       logit_index=jnp.zeros((tok.shape[0],), jnp.int32),
                       **fwd_kwargs)
    return cache


def batched_verify(params, spec, tok, pos, cache, *, n_vocab, fwd_kwargs):
    """The fixed-width slot verify forward: (B, 1+K) tokens at per-row
    positions with per-position logits, argmaxed ON DEVICE over the
    tokenizer vocab (fetching (B, T, V) floats per step is the D2H cost
    generate_batch_lookup already measured prohibitive; (B, T) int32 is
    bytes). Returns (greedy (B, 1+K) int32, position-0 logits (B, V) f32
    — the plain-decode logits non-speculating rows sample from, one
    fetch for both row classes, updated cache)."""
    logits, cache = forward(params, spec, tok, pos, cache,
                            logits_for_all=True, **fwd_kwargs)
    greedy = jnp.argmax(logits[..., :n_vocab].astype(jnp.float32),
                        axis=-1).astype(jnp.int32)
    return greedy, logits[:, 0], cache


# -- the draft model ---------------------------------------------------------


class DraftModel:
    """One draft forward (spec + params + its own KV cache shape) bound
    to a target :class:`runtime.engine.Engine`.

    The target engine supplies the batch/seq-len/cache-dtype shapes, the
    forward configuration, and — crucially — the compile ledger: every
    draft executable is minted via ``engine._mint`` under ``("sdraft_*",
    depth-label, ...)`` keys, so the recompile sentinel, the compile
    /stats block, and ``--freeze-compiles`` cover the draft path with no
    extra wiring. The draft's KV cache is the CALLER's state (the
    scheduler keeps one batched cache; a single-stream generation keeps
    its own): this object is immutable after construction and safely
    shared."""

    def __init__(self, engine, spec, params, *, label: str):
        if spec.vocab_size != engine.spec.vocab_size:
            raise ValueError(
                f"draft vocab {spec.vocab_size} != target vocab "
                f"{engine.spec.vocab_size} — draft and target must share "
                "the tokenizer (draft proposals are target token ids)")
        assert engine._pp == 1, "drafting does not support --pp"
        self.engine = engine
        self.spec = spec
        self.params = params
        self.label = label

    # -- constructors ------------------------------------------------------

    @classmethod
    def self_draft(cls, engine, depth: int) -> "DraftModel":
        """The zero-extra-weights mode: the target's first ``depth``
        layers + the shared embedding/final-norm/logits-head buffers.
        ``params["layers"]`` is a python slice of the target's list —
        the SAME device buffers, no copy."""
        depth = int(depth)
        if not 1 <= depth < engine.spec.n_layers:
            raise ValueError(
                f"--draft self:{depth}: depth must be in "
                f"1..{engine.spec.n_layers - 1} (the target has "
                f"{engine.spec.n_layers} layers; a full-depth 'draft' "
                "would just run the model twice)")
        spec = dataclasses.replace(engine.spec, n_layers=depth)
        params = dict(engine.params)
        params["layers"] = list(engine.params["layers"][:depth])
        return cls(engine, spec, params, label=f"self{depth}")

    @classmethod
    def from_file(cls, engine, path: str) -> "DraftModel":
        """A separate draft ``.m`` (TinyLlama-class): its own spec and
        weights, depth = its full layer count, same verify machinery.
        Loaded unsharded — model drafts require a mesh-less target (the
        self-draft inherits the target's sharding; a foreign checkpoint
        does not)."""
        if engine.mesh is not None:
            raise ValueError(
                "--draft model:PATH needs a mesh-less target engine "
                "(use --draft self:<depth>, which shares the target's "
                "sharded buffers)")
        from ..io.model_file import read_spec
        from ..models.loader import load_params_streamed
        from ..quants.types import FloatType

        spec = read_spec(path)
        mode = "q40" if spec.weights_float_type == FloatType.Q40 else "dense"
        params, _ = load_params_streamed(spec, path, None, mode=mode,
                                         dtype=engine.compute_dtype)
        return cls(engine, spec, params, label="model")

    # -- compiled draft programs ------------------------------------------

    def _kwargs(self) -> dict:
        # self-draft: the target's exact forward config (its params ARE
        # target buffers, sharding included). Model drafts loaded
        # unsharded keep the dtype/kernel knobs but no mesh.
        kw = self.engine._forward_kwargs()
        if self.label == "model":
            # vocab_mesh too: a file-loaded draft's tok_emb/wcls are
            # replicated single-device arrays — inheriting the target's
            # vocab sharding would reshard the whole draft embedding
            # through the sharded-gather shard_map on every dispatch
            kw.update(tp_mesh=None, sp_cache_mesh=None, pp_mesh=None,
                      vocab_mesh=None)
        return kw

    def new_cache(self) -> KVCache:
        """A fresh draft KV cache: depth layers x the TARGET's
        (batch, seq_len) shape in the target's cache dtype — d/L of the
        main cache's bytes. Built through a minted jitted maker (sharded
        placement on mesh engines, like Engine._new_cache)."""
        eng = self.engine
        key = ("sdraft_cache", self.label)
        if key not in eng._steps:
            spec, b, s, dt = self.spec, eng.batch, eng.seq_len, eng.cache_dtype
            mk = jax.jit(lambda: KVCache.create(spec, b, s, dt))
            if eng._cache_sharding is not None and self.label != "model":
                sh = KVCache((eng._cache_sharding,) * spec.n_layers,
                             (eng._cache_sharding,) * spec.n_layers)
                mk = jax.jit(lambda: KVCache.create(spec, b, s, dt),
                             out_shardings=sh)
            eng._mint(key, mk)
        return eng._steps[key]()

    def prefill_chunk(self, cache: KVCache, tok: np.ndarray,
                      pos: np.ndarray) -> KVCache:
        """One (B, C) draft prefill / catch-up chunk (gated rows pass
        pos == seq_len). C is part of the compile key; the scheduler
        always uses ONE fixed width (its widest rung), so this stays a
        single executable per draft."""
        eng = self.engine
        b, c = tok.shape
        key = ("sdraft_prefill", self.label, c)
        if key not in eng._steps:
            kw = self._kwargs()
            spec = self.spec

            def run(params, tok, pos, cache):
                return draft_prefill_cache(params, spec, tok, pos, cache,
                                           fwd_kwargs=kw)

            run.__name__ = f"draft_prefill_{self.label}_{c}"
            eng._mint(key, jax.jit(run, donate_argnums=(3,)))
        tokd, posd = self._put(tok, pos)
        return eng._steps[key](self.params, tokd, posd, cache)

    def propose(self, cache: KVCache, tok: np.ndarray, pos: np.ndarray,
                k: int, *, n_vocab: int) -> tuple[np.ndarray, KVCache]:
        """Greedy draft proposal: ONE dispatched scan of k draft steps.
        tok (B,) int32 is each row's last emitted token, fed at pos (B,)
        (== the target's next write position — the draft and target walk
        the same absolute positions). Returns ((B, k) np tokens, updated
        cache)."""
        eng = self.engine
        key = ("sdraft_scan", self.label, int(k), int(n_vocab))
        if key not in eng._steps:
            kw = self._kwargs()
            spec = self.spec

            def run(params, tok0, pos, cache, k=int(k), nv=int(n_vocab)):
                return draft_scan_tokens(params, spec, tok0, pos, cache,
                                         k=k, n_vocab=nv, fwd_kwargs=kw)

            run.__name__ = f"draft_scan_{self.label}_{k}"
            eng._mint(key, jax.jit(run, donate_argnums=(3,)))
        tokd, posd = self._put(np.asarray(tok, np.int32)[:, None], pos)
        toks, cache = eng._steps[key](self.params, tokd, posd, cache)
        return np.asarray(toks), cache

    def step_logits(self, cache: KVCache, tok: np.ndarray,
                    pos: np.ndarray) -> tuple[np.ndarray, KVCache]:
        """One single-token draft forward returning the full (B, V)
        logits — the SAMPLED draft loop's building block (the host draws
        each proposal from the draft's own distribution, so the next
        input is data-dependent and the loop cannot fuse into a scan).
        One compile key."""
        eng = self.engine
        key = ("sdraft_step", self.label)
        if key not in eng._steps:
            kw = self._kwargs()
            spec = self.spec

            def run(params, tok, pos, cache):
                return forward(params, spec, tok, pos, cache, **kw)

            run.__name__ = f"draft_step_{self.label}"
            eng._mint(key, jax.jit(run, donate_argnums=(3,)))
        tokd, posd = self._put(tok, pos)
        logits, cache = eng._steps[key](self.params, tokd, posd, cache)
        return np.asarray(logits), cache

    def _put(self, tok: np.ndarray, pos: np.ndarray):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import DP_AXIS

        eng = self.engine
        tokd = jnp.asarray(tok, jnp.int32)
        posd = jnp.asarray(pos, jnp.int32)
        if eng._token_sharding is not None and self.label != "model":
            tokd = jax.device_put(tokd, eng._token_sharding)
            posd = jax.device_put(
                posd, NamedSharding(eng.mesh, P(DP_AXIS)))
        return tokd, posd


def build_draft(engine, spec_str: str) -> DraftModel:
    """``--draft`` string -> DraftModel over ``engine`` (the factory the
    supervisor calls per generation: a rebuilt engine gets a fresh
    DraftModel over ITS buffers)."""
    kind, arg = parse_draft_spec(spec_str)
    if kind == "self":
        return DraftModel.self_draft(engine, int(arg))
    return DraftModel.from_file(engine, arg)
