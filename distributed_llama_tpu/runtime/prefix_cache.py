"""Radix prefix cache: cross-request KV reuse for the serving scheduler.

The engine's only KV state was per-sequence — every admission recomputed
its whole prompt even though production chat/RAG traffic is dominated by
shared prefixes (system prompts, few-shot templates, multi-turn history).
This module adds the missing subsystem in the style of SGLang's
RadixAttention (Zheng et al., 2024) over vLLM-shaped block granularity
(Kwon et al., SOSP '23), folded into this engine's fixed-compilation-key
discipline (PAPERS.md annotates both):

  * a RADIX INDEX over token prefixes at fixed block granularity — each
    edge is exactly one ``block_len``-token block (the tree IS
    block-granular, so edges never need splitting and lookup is a dict
    walk), key = the token-id block, value = an on-device block handle;
  * a REFERENCE-COUNTED BLOCK POOL carved from a dedicated
    ``(num_blocks, layers, kv_heads, block_len, head_size)`` K/V arena
    (``Engine.new_prefix_arena``) with LRU eviction of UNREFERENCED
    LEAVES — eviction can never free a block a pinned (in-flight) path
    references, and evicting leaves only keeps the tree prefix-closed;
  * scheduler integration (runtime/scheduler.py): on admission the
    longest cached prefix seeds the slot's cache rows via the jitted,
    donation-safe ``Engine.slot_seed_prefix`` and only the uncached
    suffix prefills; when a slot's prompt finishes prefilling, its
    PROMPT K/V is PUBLISHED back into the tree in blocks
    (``Engine.slot_publish_block``). Prefill-written blocks only —
    decode-step K/V is not guaranteed bitwise-equal to a cold
    prefill's, so publishing a decode extension would void the
    exact-parity guarantee (Scheduler._release_slot_cache).

Correctness invariants (the reason this file is small but subtle):

  * EXACT-TOKEN-MATCH ONLY — an edge matches iff its whole token block
    is identical; K/V stores post-RoPE keys at absolute positions, so a
    block is only valid as the same tokens at the same positions, which
    a prefix walk guarantees by construction.
  * BLOCKS ARE IMMUTABLE ONCE PUBLISHED — publish copies cache -> arena,
    seed copies arena -> cache; nothing ever writes a published block in
    place (a second publish of the same prefix walks the existing node
    and copies nothing).
  * A LOOKUP NEVER COVERS THE WHOLE PROMPT — at least one suffix token
    must prefill so the finishing chunk has real logits to sample from
    (the same ``len - 1`` cap the API server's legacy prefix reuse
    applies).
  * THE ARENA DIES WITH THE ENGINE — ``invalidate()`` drops the whole
    tree; the scheduler calls it on abort, and a supervisor rebuild
    mints a fresh engine + arena + empty tree
    (runtime/resilience.EngineSupervisor._make_sched), so recovered
    generations can never seed from a dead engine's blocks.

Thread model: every method is called from the scheduler's step loop
under its step mutex (admission, publish, retire all happen in-step);
the counters in ``stats`` are plain ints a /stats reader may snapshot
lock-free under the GIL.
"""

from __future__ import annotations

import heapq

import numpy as np

from .stats import PrefixCacheStats


class _Node:
    """One radix edge/node: ``key`` is the block's token tuple, ``block``
    the arena slot holding its K/V. ``refs`` counts in-flight slots
    pinned through this node; ``last_use`` is the LRU clock stamp."""

    __slots__ = ("key", "block", "parent", "children", "refs", "last_use",
                 "epoch")

    def __init__(self, key, block, parent, epoch=0):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: dict = {}
        self.refs = 0
        self.last_use = 0
        # invalidate() generation this node belongs to: a detached
        # depth>=2 node still hangs off its (equally detached) parent,
        # so the parent.children attachment check alone cannot tell it
        # from a live node — the epoch can, in O(1) per invalidate
        self.epoch = epoch


class PrefixCache:
    def __init__(self, engine, *, num_blocks: int, block_len: int,
                 stats: PrefixCacheStats | None = None,
                 transfer: bool = False):
        assert num_blocks >= 1, num_blocks
        assert 1 <= block_len <= engine.seq_len, block_len
        self.engine = engine
        # cross-replica KV block transfer (runtime/kv_transfer.py): when
        # armed, warmup() also compiles the block export/import
        # executables so donor serving and cache fills mint ZERO
        # post-warmup keys (--freeze-compiles stays green)
        self.transfer = bool(transfer)
        self.block_len = int(block_len)
        self.num_blocks = int(num_blocks)
        # fixed seed width: ONE compilation key for slot_seed_prefix —
        # every lookup result pads its block_ids up to this
        self.max_seed_blocks = max(engine.seq_len // self.block_len, 1)
        self.arena_k, self.arena_v = engine.new_prefix_arena(
            num_blocks, self.block_len)
        self._root = _Node(None, -1, None)  # sentinel: never evicted
        self._free = list(range(num_blocks))
        # LRU eviction candidates: a LAZY min-heap of
        # (last_use_at_push, seq, node). Entries go stale when the node
        # is re-touched, pinned, extended, or detached — _evict_lru_leaf
        # validates on pop and discards stale ones, so every candidate
        # transition is an O(log n) push instead of an O(nodes) tree
        # scan per allocated block inside the scheduler's step mutex
        self._heap: list = []
        self._seq = 0
        self._tick = 0
        self._epoch = 0
        self.stats = stats or PrefixCacheStats()
        self.stats.num_blocks = self.num_blocks
        self.stats.block_len = self.block_len

    # -- lookup / seed ----------------------------------------------------

    def _walk(self, tokens: list[int], max_blocks: int) -> list[_Node]:
        """Longest cached prefix of `tokens`, whole blocks only (a
        non-block-aligned remainder never matches — partial blocks are
        not indexed), capped at `max_blocks`."""
        bl = self.block_len
        path: list[_Node] = []
        node = self._root
        for i in range(min(len(tokens) // bl, max_blocks)):
            child = node.children.get(tuple(tokens[i * bl: (i + 1) * bl]))
            if child is None:
                break
            path.append(child)
            node = child
        return path

    def match_len(self, tokens: list[int]) -> int:
        """Read-only PEEK at the longest cached prefix (same whole-blocks,
        len-1-capped rule as ``lookup_pin``), for the router's cache-aware
        placement (runtime/router.py): no pin, no LRU touch, no stats —
        a routing probe must not skew hit_rate or protect blocks. Called
        from OUTSIDE the step mutex: the walk only READS children dicts
        (GIL-atomic per access), so a concurrent publish/evict can at
        worst make the answer transiently stale — which costs one
        suboptimal placement, never correctness (the admission's own
        lookup_pin re-walks under the mutex)."""
        usable = max(len(tokens) - 1, 0) // self.block_len
        return len(self._walk(tokens, usable)) * self.block_len

    def lookup_pin(self, tokens: list[int]):
        """Longest cached prefix usable for `tokens`: returns
        (n_tokens, block_ids, pins). The matched path is PINNED
        (refcounted) until the caller unpins — an in-flight slot's
        blocks can never be evicted out from under it. The match is
        capped at len(tokens) - 1 so at least one suffix token prefills
        (the finishing chunk must have real logits to sample)."""
        self._tick += 1
        self.stats.lookups += 1
        usable = max(len(tokens) - 1, 0) // self.block_len
        path = self._walk(tokens, usable)
        if not path:
            return 0, [], ()
        for node in path:
            node.refs += 1
            node.last_use = self._tick
        self.stats.hits += 1
        n = len(path) * self.block_len
        self.stats.tokens_saved += n
        return n, [node.block for node in path], tuple(path)

    def seed_slot(self, row: int, block_ids: list[int]) -> None:
        """Seed slot `row` from `block_ids` via the jitted entry point,
        padding to the fixed width (pad block 0: its writes land beyond
        the real prefix and are overwritten before any query attends
        them — seed_rows_from_blocks documents the invariant)."""
        ids = np.zeros((self.max_seed_blocks,), np.int32)
        ids[: len(block_ids)] = block_ids
        self.engine.slot_seed_prefix(self.arena_k, self.arena_v, row, ids)

    def unpin(self, pins) -> None:
        """Release a lookup_pin path (slot retired/aborted). Tolerates
        nodes an invalidate() already detached — their counters are
        orphaned bookkeeping, never a double-free (the free list is
        rebuilt wholesale on invalidate)."""
        for node in pins:
            node.refs = max(node.refs - 1, 0)
            self._push_candidate(node)  # may just have become evictable

    # -- publish ----------------------------------------------------------

    def publish(self, row: int, tokens: list[int]) -> None:
        """Index slot `row`'s filled K/V under `tokens` (whole blocks
        only). Walks existing nodes for free (dedup — republishing a
        shared prefix copies nothing) and copies only NEW blocks out of
        the cache row into freshly allocated arena slots. Stops at the
        first block the pool cannot serve (publish_drops) — dropping the
        TAIL keeps the tree prefix-closed.

        The walk path is PINNED while publishing: an allocation's
        eviction must never take the node the walk stands on (it would
        attach the next block under a detached parent — an unreachable
        subtree leaking pool slots)."""
        self._tick += 1
        bl = self.block_len
        node = self._root
        path: list[_Node] = []
        try:
            for i in range(min(len(tokens) // bl, self.max_seed_blocks)):
                key = tuple(tokens[i * bl: (i + 1) * bl])
                child = node.children.get(key)
                if child is None:
                    block = self._alloc()
                    if block is None:
                        self.stats.publish_drops += 1
                        return
                    self.arena_k, self.arena_v = (
                        self.engine.slot_publish_block(
                            self.arena_k, self.arena_v, row, i * bl, block))
                    child = _Node(key, block, node, epoch=self._epoch)
                    node.children[key] = child
                    self.stats.blocks_published += 1
                    self.stats.blocks_in_use += 1
                child.refs += 1
                path.append(child)
                child.last_use = self._tick
                node = child
        finally:
            for n in path:
                n.refs = max(n.refs - 1, 0)
            if path:
                self._push_candidate(path[-1])  # the walk's deepest leaf

    # -- cross-replica block transfer (runtime/kv_transfer.py) ------------

    def export_pin(self, tokens: list[int]):
        """Donor side of a cache FILL: the full whole-block matched path
        of ``tokens``, PINNED until the caller unpins — eviction must
        never free a block mid-transfer. Unlike ``lookup_pin`` there is
        NO len-1 cap: the cap exists so a SEEDING slot's finishing chunk
        samples real logits, but an exported block only ever reaches a
        sibling's radix tree, whose own admission lookup re-applies the
        cap. No hit/tokens_saved stats skew either — a transfer is not
        an admission. Returns (n_tokens, block_ids, pins)."""
        self._tick += 1
        path = self._walk(tokens, min(len(tokens) // self.block_len,
                                      self.max_seed_blocks))
        for node in path:
            node.refs += 1
            node.last_use = self._tick
        return (len(path) * self.block_len,
                [node.block for node in path], tuple(path))

    def export_block_host(self, block_id: int):
        """Fetch one arena block pair to host numpy — the bytes a
        BLOCK_DATA frame ships. Must run under the scheduler's step
        mutex like every arena access: a concurrent publish DONATES the
        arena arrays (slot_publish_block), so a reference snapshotted
        outside the mutex could be a deleted buffer by read time."""
        k, v = self.engine.block_export(self.arena_k, self.arena_v,
                                        block_id)
        return np.asarray(k), np.asarray(v)

    def import_path(self, tokens: list[int], start_block: int,
                    blocks: list) -> int:
        """Importer side of a cache FILL: attach fetched block pairs
        ``blocks`` (host (L, KVH, bl, hs) K/V arrays for whole blocks
        ``start_block..``) under the token path of ``tokens``, writing
        each NEW block into a freshly allocated arena slot
        (``Engine.slot_import_block``). Walks existing nodes for free
        (dedup — a racing local publish wins and the shipped bytes for
        that index are discarded); stops at the first block the pool
        cannot serve (dropping the TAIL keeps the tree prefix-closed);
        returns tokens actually imported. If the parent chain below
        ``start_block`` broke since the caller measured its own match
        (local eviction), nothing is attachable prefix-closed and the
        import aborts to 0 — the admission simply re-prefills.

        The walk path is pinned while importing, same as publish: an
        allocation's eviction must never take a node the walk stands
        on."""
        bl = self.block_len
        self._tick += 1
        node = self._root
        imported = 0
        end = min(start_block + len(blocks), self.max_seed_blocks,
                  len(tokens) // bl)
        path: list[_Node] = []
        try:
            for i in range(end):
                key = tuple(tokens[i * bl: (i + 1) * bl])
                child = node.children.get(key)
                if child is None:
                    if i < start_block:
                        return 0  # broken parent chain: unattachable
                    block = self._alloc()
                    if block is None:
                        break  # pool full of pinned/live blocks: drop tail
                    k_np, v_np = blocks[i - start_block]
                    self.arena_k, self.arena_v = (
                        self.engine.slot_import_block(
                            self.arena_k, self.arena_v, k_np, v_np,
                            block))
                    child = _Node(key, block, node, epoch=self._epoch)
                    node.children[key] = child
                    self.stats.blocks_in_use += 1
                    imported += 1
                child.refs += 1
                path.append(child)
                child.last_use = self._tick
                node = child
        finally:
            for n in path:
                n.refs = max(n.refs - 1, 0)
            if path:
                self._push_candidate(path[-1])
        return imported * bl

    def _alloc(self) -> int | None:
        if self._free:
            return self._free.pop()
        return self._evict_lru_leaf()

    def _entry_valid(self, last_use: int, node: _Node) -> bool:
        """Does a heap entry still describe reality? Stale when the node
        was re-touched (last_use moved), pinned, extended into an
        interior node, detached, or belongs to a pre-invalidate()
        epoch — a detached deep node still hangs off its detached
        parent, so the attachment check alone cannot catch it, and
        returning its block would double-allocate a slot the rebuilt
        free list already owns."""
        return (node.epoch == self._epoch
                and node.refs == 0 and not node.children
                and node.parent is not None
                and node.parent.children.get(node.key) is node
                and node.last_use == last_use)

    def _push_candidate(self, node: _Node) -> None:
        """Record `node` as a possible eviction victim. Only attached,
        unreferenced leaves qualify NOW; whether the entry is still
        valid at pop time is re-checked there (lazy invalidation)."""
        if (node.refs == 0 and not node.children
                and self._entry_valid(node.last_use, node)):
            self._seq += 1
            heapq.heappush(self._heap, (node.last_use, self._seq, node))
            if len(self._heap) > max(4 * self.num_blocks, 64):
                # compaction: stale entries are normally discarded only
                # by eviction pops, which never run while the free list
                # keeps serving — on a long-lived server with an ample
                # pool the heap would otherwise grow one entry per
                # request forever. Valid candidates are bounded by
                # num_blocks (leaves), so filtering back down is cheap
                # and amortized over the pushes that grew it.
                seen: set = set()
                kept = []
                for entry in self._heap:
                    lu, _, n = entry
                    if self._entry_valid(lu, n) and id(n) not in seen:
                        seen.add(id(n))
                        kept.append(entry)
                self._heap = kept
                heapq.heapify(self._heap)

    def _evict_lru_leaf(self) -> int | None:
        """Free the least-recently-used UNREFERENCED LEAF's block.
        Leaves-only keeps the tree prefix-closed (an interior block can
        never vanish from under its descendants); lookup_pin pins EVERY
        node on a matched path and publish pins its walk, so no
        in-flight source — and no node the current publish stands on —
        is ever a candidate. Pops the lazy heap until an entry still
        describes reality: re-touched/pinned/extended/detached nodes
        fail the check and are discarded (each was one O(log n) push)."""
        while self._heap:
            last_use, _, node = heapq.heappop(self._heap)
            if not self._entry_valid(last_use, node):
                continue  # stale entry — see _entry_valid
            del node.parent.children[node.key]
            self.stats.evictions += 1
            self.stats.blocks_in_use -= 1
            # the eviction may have exposed its parent as a new leaf
            self._push_candidate(node.parent)
            return node.block
        return None  # everything is pinned or interior: caller drops

    # -- lifecycle --------------------------------------------------------

    def invalidate(self) -> None:
        """Drop the whole tree and reclaim every block. Called when the
        engine generation the arena belongs to is being discarded
        (scheduler abort, supervisor rebuild, close) — restored/recovered
        engines must never seed from blocks a dead engine wrote. The
        arena arrays themselves are reused only through the rebuilt free
        list; in-flight pins reference detached nodes, which unpin()
        tolerates."""
        self._root.children.clear()
        self._free = list(range(self.num_blocks))
        self._heap.clear()
        # bump the epoch so detached survivors (a pinned deep node whose
        # late unpin() re-enqueues it, with its block also on the rebuilt
        # free list) can never pass the eviction validity check again
        self._epoch += 1
        self.stats.blocks_in_use = 0
        self.stats.invalidations += 1

    def warmup(self) -> None:
        """Compile the two arena executables (slot_seed + slot_publish)
        off the serving clock, state-neutrally: the seed writes arena
        bytes into row 0 of a FREE slot (overwritten by its next lease
        before any query attends — the standard invariant; the caller,
        Scheduler.warmup, asserts idleness) and the publish targets a
        block STILL ON THE FREE LIST, so the garbage it writes is
        overwritten by that block's first real allocation before any
        node can reference it. With the free list empty (a re-warm on a
        long-lived full pool — every block then backs a live node whose
        K/V must not be clobbered) the publish is skipped: a full pool
        means publishes already ran, so the executable is compiled."""
        self.seed_slot(0, [])
        if self._free:
            self.arena_k, self.arena_v = self.engine.slot_publish_block(
                self.arena_k, self.arena_v, 0, 0, self._free[-1])
        if self.transfer and self._free:
            # the transfer plane's two executables compile here too (a
            # fill or a donor query must never mint post-warmup keys):
            # export reads a FREE block's garbage, import writes it
            # straight back — state-neutral by the same free-list rule
            # as the publish warmup above
            k, v = self.engine.block_export(self.arena_k, self.arena_v,
                                            self._free[-1])
            self.arena_k, self.arena_v = self.engine.slot_import_block(
                self.arena_k, self.arena_v, np.asarray(k), np.asarray(v),
                self._free[-1])
