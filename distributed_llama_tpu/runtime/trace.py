"""Flight recorder: request tracing, step timeline, and the /metrics plane.

The serving stack is five layers deep (router → worker process →
supervisor → scheduler → engine) but until this module its only window
was aggregate ``/stats`` snapshots: when the chaos bench SIGKILLs a
worker mid-stream nothing could reconstruct WHICH request died WHERE,
and the batch-knee search (ROADMAP item 1) had no per-iteration data to
mine. Orca frames scheduling as an iteration-level tradeoff — chunked-
prefill width vs decode occupancy — which is only tunable if every
iteration is observable; vLLM's production deployments made block-pool
and batch-composition metrics the standard operational surface for
exactly this stack shape (PAPERS.md). This module is that surface:

  * **Per-request spans** — ``Tracer`` records each request's lifecycle
    (``enqueue → admit → seed → prefill → first_token → decode/N →
    finish|error``) plus the failure-machinery events that explain a
    timeline (``failover``, ``circuit``, ``fault``, ``worker_exit``,
    ``respawn``, ``engine_failure``, ``recovery``) into a fixed-capacity
    ring buffer. Appends are lock-cheap (``deque(maxlen=N).append`` is
    GIL-atomic; the only lock guards the step histograms and the sink),
    and the DISABLED path is an allocation-free no-op: hot call sites
    guard on ``TRACER.enabled`` before building any kwargs, so a server
    launched without ``--trace`` pays one attribute read per site.
  * **Step timeline** — every scheduler iteration records its batch
    composition (decode rows, prefill rows × chunk width, queue depth)
    and wall ms, histogrammed per composition
    (:class:`stats.StepTimelineStats`): the raw measurement the batch-
    knee search needs, and the ``dllama_step_ms`` family of /metrics.
  * **Export plane** — :func:`render_prometheus` turns the existing
    /stats summary dicts (supervisor- or router-shaped) plus the
    tracer's histograms into Prometheus text exposition format
    (``GET /metrics`` in apps/api_server.py, every serving tier);
    ``GET /admin/trace`` serves the ring as JSONL; ``--trace-dir``
    attaches a rotating JSONL sink with a per-request sample rate.

Trace ids are minted ONCE per client request (at the router or, single-
supervisor, at the scheduler door) and ride every event — including
across the process boundary: the submit frame carries the id to replica
workers (runtime/replica_worker.py, protocol v2) and workers ship their
span back in ``RMSG_TRACE`` frames, so a SIGKILL'd worker's partial
stream and its bit-identical sibling retry appear on ONE timeline.

Clock domain: every timestamp is ``time.perf_counter()`` — the same
monotonic clock the scheduler's deadlines, TTFT/ITL stats, and the
supervisor's watchdog already use (never ``time.time()``, which steps
under NTP and can yield negative intervals). One (wall, mono) anchor
pair per tracer converts to wall clock at EXPORT time only, which is
also how worker-process events rebase onto the parent's timeline.

Everything here is host code: no jitted entry point is touched, events
fire strictly pre/post device dispatch, and the dlgrind fingerprint set
is invariant by construction. Docs: docs/observability.md.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from .stats import StepTimelineStats

# event kinds a span may contain, in rough lifecycle order (the JSONL
# schema table in docs/observability.md mirrors this)
EVENT_KINDS = (
    "enqueue",        # scheduler door: request queued (n_prompt, rid)
    "admit",          # slot leased (slot, queue_ms)
    "seed",           # prefix-cache seed (hit = tokens seeded)
    "prefill",        # one prefill chunk dispatched for this row (off, n)
    "first_token",    # TTFT edge
    "decode",         # every Nth decode token (n_out)
    "finish",         # terminal: natural finish (reason, n_out)
    "error",          # terminal: structured error frame (code, retryable)
    "route",          # router placement (replica, reason, attempt)
    "kv_fill",        # cross-replica KV block fill (runtime/
    #                   kv_transfer.py): donor, transport=wire|local,
    #                   answered/filled tokens, ms, ok — linked under
    #                   the request's trace id
    "failover",       # retryable pre-stream failure -> re-place (replica,
    #                   code)
    "circuit",        # breaker transition (scope=router|engine|spawn,
    #                   state, replica)
    "fault",          # an armed fault site actually fired (site, key)
    "engine_failure",  # supervisor caught a crash/stall (kind, key)
    "recovery",       # supervisor rebuilt to ready (ms, key)
    "cluster_lost",   # ClusterPeerLost escalation / casualty span (node,
    #                   reason, phase — linked under the active trace id)
    "worker_exit",    # replica worker process died (replica, cls, rc)
    "respawn",        # worker respawned to routable (replica, ms)
    "spec",           # terminal speculative-decoding accept record for
    #                   one request (forwards, drafted, accepted) —
    #                   dlprof attributes verify-forward cost from it
    "step",           # scheduler iteration (timeline record)
    "handshake",      # cluster control star formed (role, peers)
    "cluster_tick",   # one cluster protocol frame handled (phase, rank)
    #                   — the multihost worker's span unit
    "bcast",          # startup data-plane broadcast timed (what, ms,
    #                   bytes — bcast_spec / bcast_model_tensors)
    "sync",           # sampled device sync/compute attribution: one
    #                   sampled step's collective vs total device ms
    #                   (runtime/profiler.py over netstats.per_step_op_ms)
    "compile",        # an executable was minted (key, ms, warm) —
    #                   runtime/profiler.CompileLedger
    "compile_after_warmup",  # the recompile sentinel fired (key, frozen)
    "profile",        # an /admin/profile capture completed (dir, ms)
    "scale_up",       # fleet controller added a replica (replica, tier,
    #                   pressure, ms, warm_fills — runtime/fleet.py)
    "scale_down",     # fleet controller drained + reaped a replica
    #                   (replica, tier, ms)
    "shed",           # overload door refused/degraded a request (reason,
    #                   tenant, rung, retry_after)
    "degrade",        # shed ladder moved a rung (rung, name, direction,
    #                   pressure)
)


def _sampled(tid: int, rate: float) -> bool:
    """Deterministic per-request sink sampling: the same trace id is
    always in or out of the sample, so a span is never half-persisted.
    Knuth multiplicative hash over the id — ids are sequential, and
    ``tid % k`` would correlate with placement order."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return ((tid * 2654435761) & 0xFFFFFFFF) / 4294967296.0 < rate


class TraceSink:
    """Rotating JSONL sink for trace events. One file at a time
    (``trace-00000001.jsonl`` …), rotated past ``max_bytes``, oldest
    files unlinked past ``max_files`` — a long-lived server's disk
    footprint is bounded by ``max_bytes * max_files``. Writes are
    line-buffered under one lock; the caller (Tracer) already decided
    sampling, so everything handed here is persisted."""

    def __init__(self, directory: str, *, max_bytes: int = 16 << 20,
                 max_files: int = 8):
        self.directory = directory
        self.max_bytes = int(max_bytes)
        self.max_files = int(max_files)
        self._lock = threading.Lock()
        self._fh = None  # dlrace: guarded-by(self._lock)
        self._n = 0  # dlrace: guarded-by(self._lock)
        self._seq = 0  # dlrace: guarded-by(self._lock)
        os.makedirs(directory, exist_ok=True)

    def _open_next(self) -> None:  # dlrace: holds(self._lock)
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
        self._seq += 1
        path = os.path.join(self.directory,
                            f"trace-{self._seq:08d}.jsonl")
        self._fh = open(path, "a", buffering=1)  # line-buffered
        self._n = self._fh.tell()
        old = sorted(f for f in os.listdir(self.directory)
                     if f.startswith("trace-") and f.endswith(".jsonl"))
        for f in old[:-self.max_files] if len(old) > self.max_files else ():
            try:
                os.unlink(os.path.join(self.directory, f))
            except OSError:
                pass

    def write(self, line: str) -> None:
        with self._lock:
            if self._fh is None or self._n >= self.max_bytes:
                self._open_next()
            self._fh.write(line + "\n")
            self._n += len(line) + 1

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


class Tracer:
    """Host-side flight recorder (module singleton: ``TRACER``).

    Disabled by default: hot call sites MUST guard with
    ``if TRACER.enabled:`` before building event kwargs, which keeps the
    off path allocation-free (the guard is one attribute read; no dict,
    no tuple, no call). When enabled, ``event()`` appends one small dict
    to a bounded ring (``deque.append`` — atomic under the GIL, no lock
    on the hot path) and optionally persists sampled spans to the JSONL
    sink. ``step()`` additionally feeds the per-composition step-ms
    histograms behind /metrics and the bench ``step_timeline`` blocks.
    """

    def __init__(self):
        self.enabled = False
        self.decode_every = 8     # decode progress event cadence (tokens)
        self.sample = 1.0         # sink sampling rate (ring records all)
        self._capacity = 8192
        self._ring: deque = deque(maxlen=self._capacity)  # dlrace: guarded-by(self._lock)
        self._lock = threading.Lock()
        self._next_id = 0  # dlrace: guarded-by(self._lock)
        self._sink: TraceSink | None = None
        self.steps = StepTimelineStats()
        self.dropped = 0          # ring evictions are implicit; this
        # counts only sink write failures (disk full etc.)
        # per-tid span index: by_id/export_span must not scan the whole
        # ring per completed request (the worker ships a span before
        # EVERY terminal frame — O(capacity) there scales the pump
        # thread's latency with --trace-buffer). Span events are
        # per-lifecycle (a handful per request), so a small lock here
        # never touches the per-step hot path (tid 0 skips it).
        self._spans: "dict[int, list]" = {}  # dlrace: guarded-by(self._span_lock)
        self._span_order: deque = deque()   # dlrace: guarded-by(self._span_lock)
        self._span_lock = threading.Lock()
        self._anchor()

    @property
    def _span_cap(self) -> int:
        return max(self._capacity // 8, 64)  # distinct live spans

    def _anchor(self) -> None:
        # one (wall, mono) pair: every stored ts is perf_counter (the
        # serving stack's single clock domain); wall conversion happens
        # at export only, so NTP steps can never corrupt an interval
        self.anchor_mono = time.perf_counter()
        self.anchor_wall = time.time()

    # -- configuration ------------------------------------------------------

    def configure(self, *, capacity: int | None = None,
                  sample: float | None = None,
                  decode_every: int | None = None,
                  sink_dir: str | None = None,
                  sink_max_bytes: int = 16 << 20,
                  sink_max_files: int = 8,
                  enabled: bool = True) -> None:
        """(Re)configure and enable. Reconfiguring replaces the ring (a
        capacity change cannot preserve eviction order) and the sink."""
        with self._lock:
            if capacity is not None:
                self._capacity = max(int(capacity), 16)
                self._ring = deque(maxlen=self._capacity)
                with self._span_lock:
                    self._spans = {}
                    self._span_order = deque()
            if sample is not None:
                assert 0.0 <= sample <= 1.0, sample
                self.sample = float(sample)
            if decode_every is not None:
                self.decode_every = max(int(decode_every), 1)
            if self._sink is not None:
                self._sink.close()
                self._sink = None
            if sink_dir is not None:
                self._sink = TraceSink(sink_dir, max_bytes=sink_max_bytes,
                                       max_files=sink_max_files)
            self._anchor()
            self.enabled = bool(enabled)

    def reset(self) -> None:
        """Disable and drop all state (test teardown; bench row
        isolation). The singleton survives — call sites keep their
        reference."""
        with self._lock:
            self.enabled = False
            self._ring = deque(maxlen=self._capacity)
            with self._span_lock:
                self._spans = {}
                self._span_order = deque()
            self.steps = StepTimelineStats()
            self._next_id = 0
            self.dropped = 0
            if self._sink is not None:
                self._sink.close()
                self._sink = None
            self._anchor()

    def new_id(self) -> int:
        """Mint one trace id (sequential, process-local; > 0 so 0 can
        mean "untraced" on the wire and in event records)."""
        with self._lock:
            self._next_id += 1
            return self._next_id

    def reserve(self, tid: int) -> None:
        """Adopt a REMOTELY-minted trace id: advance the local counter
        past it so this process's own future mints can never collide.
        Both sides of a star mint from 1, so a worker that records
        under the root's run tids AND mints its own (its scheduler
        door) would otherwise cross-link unrelated spans in the index
        and ship foreign events back on export_span."""
        with self._lock:
            if tid > self._next_id:
                self._next_id = int(tid)

    # -- recording ----------------------------------------------------------

    def event(self, kind: str, tid: int = 0, **fields) -> None:
        """Append one event. Callers on hot paths guard on ``enabled``
        BEFORE calling (the kwargs dict is the allocation the disabled
        path must not pay); this re-check only covers races with a
        concurrent reset()."""
        if not self.enabled:
            return
        rec = {"ts": time.perf_counter(), "kind": kind, "tid": tid}
        if fields:
            rec.update(fields)
        self._ring.append(rec)  # deque.append: atomic, lock-free
        if tid:
            self._index(tid, rec)
        sink = self._sink
        if sink is not None and (tid == 0 or _sampled(tid, self.sample)):
            try:
                sink.write(json.dumps(
                    {**rec, "ts_wall": self.to_wall(rec["ts"])}))
            except (OSError, ValueError):
                self.dropped += 1

    def step(self, *, decode_rows: int, prefill_rows: int, chunk: int,
             queue_depth: int, wall_ms: float,
             key: str | None = None) -> None:
        """One scheduler iteration: ring record + the per-composition
        histogram /metrics and the bench knee-search read."""
        if not self.enabled:
            return
        rec = {"ts": time.perf_counter(), "kind": "step", "tid": 0,
               "dec": decode_rows, "pre": prefill_rows, "chunk": chunk,
               "queue": queue_depth, "ms": round(wall_ms, 4)}
        if key is not None:
            rec["key"] = key
        self._ring.append(rec)
        self.steps.record(decode_rows, prefill_rows, chunk, wall_ms)
        sink = self._sink
        if sink is not None:
            try:
                sink.write(json.dumps(
                    {**rec, "ts_wall": self.to_wall(rec["ts"])}))
            except (OSError, ValueError):
                self.dropped += 1

    def ingest(self, events: list[dict], origin: str,
               anchor_wall: float | None = None) -> None:
        """Merge a WORKER PROCESS's span events (RMSG_TRACE payload) onto
        this tracer's timeline. Worker timestamps arrive as wall-clock
        (``ts_wall`` — monotonic clocks do not transfer between
        processes); they are rebased onto this process's perf_counter via
        the local anchor, so a merged timeline sorts correctly to within
        host wall-clock resolution (same box: microseconds)."""
        if not self.enabled:
            return
        for e in events:
            rec = dict(e)
            wall = rec.pop("ts_wall", None)
            if wall is None and anchor_wall is not None and "ts" in rec:
                wall = anchor_wall + rec["ts"]
            rec["ts"] = (self.anchor_mono + (wall - self.anchor_wall)
                         if wall is not None else time.perf_counter())
            rec["origin"] = origin
            self._ring.append(rec)
            if rec.get("tid"):
                self._index(rec["tid"], rec)

    # -- export -------------------------------------------------------------

    def to_wall(self, ts_mono: float) -> float:
        return self.anchor_wall + (ts_mono - self.anchor_mono)

    def recent(self, n: int = 200) -> list[dict]:
        """Last n events, oldest first (a snapshot — the ring keeps
        moving underneath)."""
        evs = list(self._ring)
        return evs[-n:] if n else evs

    def _index(self, tid: int, rec: dict) -> None:
        """Append one span event to the per-tid index (eviction = oldest
        SPAN past the cap — a span is dropped whole, never truncated)."""
        with self._span_lock:
            lst = self._spans.get(tid)
            if lst is None:
                while len(self._spans) >= self._span_cap:
                    old = self._span_order.popleft()
                    self._spans.pop(old, None)
                lst = self._spans[tid] = []
                self._span_order.append(tid)
            if len(lst) < 1024:
                # per-span bound: at the default decode cadence (8) this
                # covers a ~8k-token stream; past it the span keeps its
                # HEAD (the lifecycle story) and drops further decode
                # progress — total index memory stays bounded by
                # span_cap x 1024 regardless of stream lengths
                lst.append(rec)

    def by_id(self, tid: int) -> list[dict]:
        """One request's span, in order — the /admin/trace?id=N view and
        the worker's pre-terminal span ship. Served from the per-tid
        index, O(span length) not O(ring) (review-found: the O(ring)
        scan put a per-completed-request cost on the worker's pump
        thread that scaled with --trace-buffer); a span can therefore
        outlive its ring entries. Copied under the span lock — a
        concurrent append must never surface mid-iteration."""
        with self._span_lock:
            return list(self._spans.get(tid, ()))

    def export_span(self, tid: int) -> list[dict]:
        """The span as a cross-process payload: each event gains
        ``ts_wall`` so the receiving tracer can rebase it (see
        ``ingest``). Used by the replica worker's RMSG_TRACE frames."""
        return [{**e, "ts_wall": self.to_wall(e["ts"])}
                for e in self.by_id(tid)]

    def step_timeline(self) -> dict:
        """Per-composition step-ms summary (p50/p99/mean/n) — the bench
        ``step_timeline`` block and the /metrics ``dllama_step_ms``
        family."""
        return self.steps.summary()

    def summary(self) -> dict:
        """The tracer's own observability block (rides /stats when
        enabled)."""
        return {"enabled": self.enabled,
                "events": len(self._ring),
                "capacity": self._capacity,
                "next_id": self._next_id,
                "sample": self.sample,
                "sink_dropped": self.dropped,
                "sink": (self._sink.directory
                         if self._sink is not None else None)}


TRACER = Tracer()


# -- Prometheus text exposition ---------------------------------------------

# /stats summary counters -> Prometheus counters (same payload every tier
# already emits, so the three serving tiers export identically by
# construction)
_COUNTERS = (
    ("requests_submitted", "dllama_requests_submitted_total",
     "Requests accepted at the serving door"),
    ("requests_finished", "dllama_requests_finished_total",
     "Requests that received a terminal event"),
    ("requests_failed", "dllama_requests_failed_total",
     "Requests failed with a structured error frame"),
    ("requests_expired", "dllama_requests_expired_total",
     "Requests killed by deadline or queue-time budget"),
    ("requests_rejected", "dllama_requests_rejected_total",
     "Requests refused at submit (queue bound)"),
    ("tokens_out", "dllama_tokens_out_total", "Tokens emitted"),
    ("steps", "dllama_scheduler_steps_total", "Scheduler iterations"),
)

_GAUGES = (
    ("ttft_p50_ms", "dllama_ttft_ms", {"quantile": "0.5"},
     "Time to first token, sliding window"),
    ("ttft_p99_ms", "dllama_ttft_ms", {"quantile": "0.99"}, None),
    ("itl_p50_ms", "dllama_itl_ms", {"quantile": "0.5"},
     "Inter-token latency, sliding window"),
    ("itl_p99_ms", "dllama_itl_ms", {"quantile": "0.99"}, None),
    ("mean_slot_occupancy", "dllama_slot_occupancy_mean", {},
     "Mean live slots per scheduler iteration (window)"),
    ("max_queue_depth", "dllama_queue_depth_max", {},
     "Max admission-queue depth (window)"),
)

_RESILIENCE = (
    ("crashes", "dllama_supervisor_crashes_total"),
    ("watchdog_trips", "dllama_supervisor_watchdog_trips_total"),
    ("recoveries", "dllama_supervisor_recoveries_total"),
    ("rejected_unready", "dllama_supervisor_rejected_unready_total"),
    ("cluster_losses", "dllama_supervisor_cluster_losses_total"),
)

_ROUTER = (
    ("routed", "dllama_router_routed_total"),
    ("routed_cache_hit", "dllama_router_routed_cache_hit_total"),
    ("routed_affinity", "dllama_router_routed_affinity_total"),
    ("routed_fallback", "dllama_router_routed_fallback_total"),
    ("retries", "dllama_router_retries_total"),
    ("failovers_ok", "dllama_router_failovers_ok_total"),
    ("midstream_failures", "dllama_router_midstream_failures_total"),
    ("breaker_trips", "dllama_router_breaker_trips_total"),
    ("breaker_probes", "dllama_router_breaker_probes_total"),
    ("no_replica_rejections", "dllama_router_no_replica_rejections_total"),
)

_PREFIX = (
    ("lookups", "dllama_prefix_cache_lookups_total"),
    ("hits", "dllama_prefix_cache_hits_total"),
    ("tokens_saved", "dllama_prefix_cache_tokens_saved_total"),
    ("tokens_prefilled", "dllama_prefix_cache_tokens_prefilled_total"),
    ("blocks_published", "dllama_prefix_cache_blocks_published_total"),
    ("evictions", "dllama_prefix_cache_evictions_total"),
    ("publish_drops", "dllama_prefix_cache_publish_drops_total"),
)
# blocks_in_use is a LEVEL (drops when blocks free/evict) — emitted as a
# gauge, never through the counter table: rate() over a shrinking
# "counter" reads every drop as a counter reset
_PREFIX_GAUGES = (
    ("blocks_in_use", "dllama_prefix_cache_blocks_in_use"),
)


def _esc(v) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


class _Prom:
    """Tiny exposition-format builder: groups samples per metric name so
    each name gets exactly one # HELP/# TYPE header (the format
    requirement scrapers enforce)."""

    def __init__(self):
        self._meta: dict[str, tuple[str, str]] = {}
        self._samples: dict[str, list[str]] = {}

    def add(self, name: str, value, labels: dict | None = None,
            help_: str | None = None, type_: str = "gauge") -> None:
        if value is None:
            return
        if name not in self._meta:
            self._meta[name] = (help_ or name, type_)
            self._samples[name] = []
        lab = ""
        if labels:
            lab = "{" + ",".join(f'{k}="{_esc(v)}"'
                                 for k, v in labels.items()) + "}"
        self._samples[name].append(f"{name}{lab} {value}")

    def render(self) -> str:
        out = []
        for name, (help_, type_) in self._meta.items():
            out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {type_}")
            out.extend(self._samples[name])
        return "\n".join(out) + "\n"


def _add_block(p: _Prom, block: dict | None, table, *, type_: str,
               labels: dict | None = None) -> None:
    if not block:
        return
    for row in table:
        key, name = row[0], row[1]
        p.add(name, block.get(key), labels=labels, type_=type_)


def _add_device_blocks(p: _Prom, summary: dict,
                       labels: dict | None = None) -> None:
    """The device-tier families (runtime/profiler.py): compile ledger,
    HBM ledger, sampled device-time attribution — rendered from the
    same /stats blocks every tier already carries, top-level AND
    per-replica (labelled)."""
    pre = "dllama_replica_" if labels else "dllama_"
    comp = summary.get("compiles")
    if comp:
        p.add(pre + "compiles_after_warmup_total",
              comp.get("after_warmup"), labels, type_="counter",
              help_="Compiles minted after the serving set was warm "
                    "(the recompile sentinel)")
        for key, rec in (comp.get("by_key") or {}).items():
            lab = {**(labels or {}), "key": _esc(key)}
            p.add(pre + "compiles_total", rec.get("count"), lab,
                  type_="counter", help_="Executable mints by compile key")
            p.add(pre + "compile_ms", rec.get("ms"), lab,
                  type_="counter",
                  help_="Cumulative trace+compile wall ms by compile key")
    hbm = summary.get("hbm")
    if hbm:
        for cat, field in (("weights", "weights_bytes"),
                           ("vocab", "vocab_bytes"),
                           ("kv_slots", "kv_slot_bytes"),
                           ("prefix_arena", "prefix_arena_bytes"),
                           ("logits_workspace", "logits_workspace_bytes")):
            p.add(pre + "hbm_bytes", hbm.get(field),
                  {**(labels or {}), "category": cat},
                  help_="Live HBM bytes by category (known array shapes)")
        p.add(pre + "hbm_device_bytes", hbm.get("device_bytes_in_use"),
              {**(labels or {}), "kind": "in_use"},
              help_="Backend allocator stats, where provided")
        p.add(pre + "hbm_device_bytes", hbm.get("device_bytes_limit"),
              {**(labels or {}), "kind": "limit"})
        p.add(pre + "hbm_slots_addable", hbm.get("slots_addable"), labels,
              help_="KV slots that still fit free HBM (headroom)")
        p.add(pre + "hbm_prefix_blocks_addable",
              hbm.get("prefix_blocks_addable"), labels,
              help_="Prefix-arena blocks that still fit free HBM")
    dev = summary.get("device_time")
    if dev:
        p.add(pre + "profile_sampled_steps_total",
              dev.get("sampled_steps"), labels, type_="counter",
              help_="Scheduler steps captured for device-time attribution")
        for entry, rec in (dev.get("by_entry") or {}).items():
            lab = {**(labels or {}), "entry": _esc(entry)}
            p.add(pre + "device_ms", rec.get("p50_ms"),
                  {**lab, "quantile": "0.5"},
                  help_="Sampled per-step device ms by entry point")
            p.add(pre + "device_samples_total", rec.get("n"), lab,
                  type_="counter")
        sync = dev.get("sync")
        if sync and sync.get("n"):
            # the reference's I/T/S split reborn: per sampled step,
            # device collective (sync) ms vs total device ms
            p.add(pre + "step_sync_ms", sync.get("sync_p50_ms"),
                  {**(labels or {}), "quantile": "0.5"},
                  help_="Sampled per-step device collective ms (the "
                        "sync half of the sync/compute split)")
            p.add(pre + "step_sync_ms", sync.get("sync_p99_ms"),
                  {**(labels or {}), "quantile": "0.99"})
            p.add(pre + "step_sync_share", sync.get("sync_share"),
                  labels,
                  help_="Collective share of sampled device step time "
                        "(window mean)")


_CLUSTER_COUNTERS = (
    ("pings_sent", "dllama_cluster_pings_sent_total"),
    ("pongs_received", "dllama_cluster_pongs_received_total"),
    ("pongs_sent", "dllama_cluster_pongs_sent_total"),
    ("frames_sent", "dllama_cluster_frames_sent_total"),
    ("frames_received", "dllama_cluster_frames_received_total"),
    ("connect_retries", "dllama_cluster_connect_retries_total"),
)


def _add_cluster(p: _Prom, cluster: dict | None) -> None:
    """The cluster-plane families (parallel/multihost ClusterStats +
    its dlwire ledger): every counter the /stats block carries, the
    phase label, the startup broadcast timings, and the measured wire
    ledger — tier-invariant like every other family (the api server
    attaches the cluster block in every tier, so a launch flag can
    never drop these from a scrape)."""
    if not cluster:
        return
    p.add("dllama_cluster_peers_lost_total",
          len(cluster.get("peers_lost") or ()), type_="counter",
          help_="Structured ClusterPeerLost detections")
    for key, name in _CLUSTER_COUNTERS:
        p.add(name, cluster.get(key), type_="counter")
    p.add("dllama_cluster_nnodes", cluster.get("nnodes"),
          help_="Configured cluster size")
    ph = cluster.get("phase")
    if ph:
        p.add("dllama_cluster_phase", 1, {"phase": _esc(ph)},
              help_="Current cluster phase (info-style: constant 1, "
                    "phase in the label)")
    p.add("dllama_cluster_bcast_ms", cluster.get("bcast_spec_ms"),
          {"what": "spec"},
          help_="Startup data-plane broadcast wall ms by phase")
    p.add("dllama_cluster_bcast_ms", cluster.get("bcast_tensors_ms"),
          {"what": "tensors"})
    if cluster.get("bcast_tensors_bytes"):
        p.add("dllama_cluster_bcast_bytes_total",
              cluster.get("bcast_tensors_bytes"), {"what": "tensors"},
              type_="counter",
              help_="Tensor bytes streamed through the startup broadcast")
    wire = cluster.get("wire") or {}
    for peer, rec in (wire.get("peers") or {}).items():
        for dirn in ("tx", "rx"):
            for kind, kb in (rec.get(dirn) or {}).items():
                lab = {"peer": str(peer), "kind": _esc(kind), "dir": dirn}
                p.add("dllama_wire_bytes_total", kb.get("bytes"), lab,
                      type_="counter",
                      help_="Measured control-plane bytes by peer, MSG "
                            "kind, and direction (the dlwire ledger)")
                p.add("dllama_wire_frames_total", kb.get("frames"), lab,
                      type_="counter",
                      help_="Measured control-plane frames")
        rtt = rec.get("rtt_ms") or {}
        p.add("dllama_heartbeat_rtt_ms", rtt.get("p50_ms"),
              {"peer": str(peer), "quantile": "0.5"},
              help_="PING→PONG round trip per peer (window)")
        p.add("dllama_heartbeat_rtt_ms", rtt.get("p99_ms"),
              {"peer": str(peer), "quantile": "0.99"})
        p.add("dllama_cluster_clock_offset_ms",
              rec.get("clock_offset_ms"), {"peer": str(peer)},
              help_="PING/PONG-midpoint clock-offset estimate (peer wall "
                    "minus local wall, at the best-RTT sample)")


_SPEC_COUNTERS = (
    ("verify_forwards", "spec_verify_forwards_total",
     "Fixed-width speculative verify forwards dispatched"),
    ("draft_forwards", "spec_draft_forwards_total",
     "Draft dispatches (one k-token scan or prefill chunk == one)"),
    ("drafted", "spec_drafted_tokens_total",
     "Draft tokens proposed to the verifier"),
    ("accepted", "spec_accepted_tokens_total",
     "Draft tokens the verify forward confirmed"),
    ("emitted_spec", "spec_emitted_tokens_total",
     "Tokens emitted by speculating rows"),
    ("degraded_steps", "spec_degraded_steps_total",
     "Iterations the SLO admission policy ran with drafting disabled"),
)


def _add_spec(p: _Prom, spec: dict | None, *, labels: dict | None = None,
              prefix: str = "dllama_") -> None:
    """The speculative-decoding family (runtime/draft.py accept record,
    stats.SpecStats summary): honest accept-rate observability in every
    tier — the block is attached even with drafting off (mode "off",
    zeros), so the family can never vanish off a launch flag. One
    renderer for the top-level summary and each replica's block
    (`dllama_replica_spec_*`, replica-labelled)."""
    if not spec:
        return
    per = " (per replica)" if prefix != "dllama_" else ""
    p.add(f"{prefix}spec_mode", 1,
          {**(labels or {}), "mode": _esc(spec.get("mode", "off")),
           "draft_len": str(spec.get("draft_len", 0))},
          help_=f"Draft mode in effect (info-style: constant 1){per}")
    for key, name, help_ in _SPEC_COUNTERS:
        p.add(f"{prefix}{name}", spec.get(key), labels, type_="counter",
              help_=help_ + per)
    p.add(f"{prefix}spec_accept_rate", spec.get("accept_rate"), labels,
          help_="Accepted / drafted over the scheduler generation — the "
                "number that says whether speculation pays on this "
                f"traffic (docs/operations.md){per}")
    p.add(f"{prefix}spec_tokens_per_verify", spec.get("tokens_per_verify"),
          labels,
          help_=f"Mean tokens emitted per verify forward{per}")


_KVX_COUNTERS = (
    ("fills_requested", "kv_transfer_fills_requested_total",
     "Cache-fill attempts (a sibling's cache led the placed replica's)"),
    ("fills_ok", "kv_transfer_fills_total",
     "Fills that imported >= 1 block (the re-prefill actually avoided)"),
    ("fill_fallbacks", "kv_transfer_fallbacks_total",
     "Fills degraded to a plain local re-prefill (donor death, torn "
     "frame, deadline — never a request failure)"),
    ("fill_misses", "kv_transfer_fill_misses_total",
     "Donor answered shorter than the shadow promised (eviction)"),
    ("tokens_filled", "kv_transfer_tokens_filled_total",
     "Prompt tokens imported instead of re-prefilled"),
    ("blocks_filled", "kv_transfer_blocks_filled_total",
     "Arena blocks imported"),
    ("blocks_exported", "kv_transfer_blocks_exported_total",
     "Arena blocks served to siblings (donor side)"),
    ("queries_served", "kv_transfer_queries_total",
     "RMSG_BLOCK_QUERY connections served (donor side)"),
    ("prefill_passes", "kv_transfer_prefill_passes_total",
     "Disaggregated prefill-tier passes completed"),
    ("prefill_pass_fallbacks", "kv_transfer_prefill_fallbacks_total",
     "Requests that fell back to the unified mixed path"),
    ("shadow_truncates", "kv_transfer_shadow_truncates_total",
     "Stale shadow-index paths cleared by a QUERY miss answer"),
)


def _add_kv_transfer(p: _Prom, kvx: dict | None, *,
                     labels: dict | None = None,
                     prefix: str = "dllama_") -> None:
    """The KV block transfer family (runtime/kv_transfer.py,
    stats.KVTransferStats summary): fills, fallbacks, bytes, and
    transfer-time tails in every tier incl. idle — the block is attached
    even with transfer off (enabled=False, zeros), so the family can
    never vanish off a launch flag. One renderer for the top-level
    aggregate and each replica's block (`dllama_replica_kv_transfer_*`,
    replica-labelled)."""
    if not kvx:
        return
    per = " (per replica)" if prefix != "dllama_" else ""
    p.add(f"{prefix}kv_transfer_info", 1,
          {**(labels or {}), "enabled": str(bool(kvx.get("enabled"))),
           "tier": _esc(kvx.get("tier", "mixed"))},
          help_=f"Transfer plane identity (constant 1){per}")
    for key, name, help_ in _KVX_COUNTERS:
        p.add(f"{prefix}{name}", kvx.get(key), labels, type_="counter",
              help_=help_ + per)
    for key, dirn in (("bytes_rx", "rx"), ("bytes_tx", "tx")):
        p.add(f"{prefix}kv_transfer_bytes_total", kvx.get(key),
              {**(labels or {}), "dir": dirn}, type_="counter",
              help_=f"Block K/V payload bytes moved{per} (frame-exact "
                    "wire bytes live in the per-replica wire ledger)")
    p.add(f"{prefix}kv_transfer_ms", kvx.get("transfer_p50_ms"),
          {**(labels or {}), "quantile": "0.5"},
          help_=f"Whole-fill wall ms (connect to last import){per}")
    p.add(f"{prefix}kv_transfer_ms", kvx.get("transfer_p99_ms"),
          {**(labels or {}), "quantile": "0.99"})


def _add_admission(p: _Prom, adm: dict | None, *,
                   labels: dict | None = None,
                   prefix: str = "dllama_") -> None:
    """The SLO-aware admission family (runtime/scheduler.AdmissionPolicy
    summary): live chunk width + the EWMAs the policy steers on. One
    renderer for both homes — the top-level supervisor summary and each
    replica's block (`dllama_replica_admission_*`, replica-labelled)."""
    if not adm:
        return
    per = " (per replica)" if prefix != "dllama_" else ""
    p.add(f"{prefix}admission_chunk_width", adm.get("chunk_width"),
          labels,
          help_=f"Current adaptive chunked-prefill width (tokens){per}")
    p.add(f"{prefix}admission_chunk_changes_total", adm.get("shrinks"),
          {**(labels or {}), "direction": "shrink"}, type_="counter",
          help_=f"Adaptive chunk-width rung transitions{per}")
    p.add(f"{prefix}admission_chunk_changes_total", adm.get("widens"),
          {**(labels or {}), "direction": "widen"}, type_="counter")
    p.add(f"{prefix}admission_itl_ewma_ms", adm.get("itl_ewma_ms"),
          labels,
          help_=f"Live inter-token-latency EWMA the policy steers on{per}")
    p.add(f"{prefix}admission_ttft_ewma_ms", adm.get("ttft_ewma_ms"),
          labels, help_=f"Live time-to-first-token EWMA{per}")


_FLEET_COUNTERS = (
    ("scale_ups", "fleet_scale_ups_total",
     "Replicas added by the autoscaler"),
    ("scale_downs", "fleet_scale_downs_total",
     "Replicas drained and reaped by the autoscaler"),
    ("scale_blocked_hbm", "fleet_scale_blocked_hbm_total",
     "Scale-ups refused by the HBM ledger's slots_addable ceiling"),
    ("spawn_failures", "fleet_spawn_failures_total",
     "Scale-up spawns that failed (controller backs off)"),
    ("warm_fills", "fleet_warm_fills_total",
     "KV warm-fills replayed into fresh replicas from siblings"),
    ("sheds", "fleet_sheds_total",
     "Requests refused by the overload ladder"),
    ("clamped", "fleet_clamped_total",
     "Requests admitted with max_tokens clamped by the ladder"),
)


def _add_fleet(p: _Prom, fleet: dict | None, *,
               labels: dict | None = None,
               prefix: str = "dllama_") -> None:
    """The fleet-brain family (runtime/fleet.py, stats.FleetStats +
    FleetController summary): autoscale decisions, ladder rung, and
    per-tenant fairness in every tier incl. idle — like kv_transfer,
    the block is attached even with the controller off (enabled=False,
    zeros), so the family can never vanish off a launch flag."""
    if not fleet:
        return
    p.add(f"{prefix}fleet_info", 1,
          {**(labels or {}), "enabled": str(bool(fleet.get("enabled"))),
           "autoscaling": str(bool(fleet.get("autoscaling")))},
          help_="Fleet controller identity (constant 1)")
    p.add(f"{prefix}fleet_ticks_total", fleet.get("ticks"), labels,
          type_="counter", help_="Controller decision ticks")
    p.add(f"{prefix}fleet_pressure", fleet.get("pressure"), labels,
          help_="Smoothed occupancy pressure the scaler steers on (0-1)")
    p.add(f"{prefix}fleet_replicas", fleet.get("actual_replicas"),
          {**(labels or {}), "kind": "actual"},
          help_="Replica counts as the controller sees them")
    p.add(f"{prefix}fleet_replicas", fleet.get("target_replicas"),
          {**(labels or {}), "kind": "target"})
    p.add(f"{prefix}fleet_replicas", fleet.get("min_replicas"),
          {**(labels or {}), "kind": "min"})
    p.add(f"{prefix}fleet_replicas", fleet.get("max_replicas"),
          {**(labels or {}), "kind": "max"})
    for key, name, help_ in _FLEET_COUNTERS:
        p.add(f"{prefix}{name}", fleet.get(key), labels, type_="counter",
              help_=help_)
    for reason, n in (fleet.get("sheds_by_reason") or {}).items():
        p.add(f"{prefix}fleet_sheds_by_reason_total", n,
              {**(labels or {}), "reason": _esc(reason)}, type_="counter",
              help_="Ladder refusals by rung reason")
    ladder = fleet.get("ladder")
    if ladder:
        p.add(f"{prefix}fleet_ladder_rung", ladder.get("rung"),
              {**(labels or {}), "name": _esc(ladder.get("name"))},
              help_="Current shed-ladder rung (0 = healthy)")
        p.add(f"{prefix}fleet_ladder_moves_total",
              ladder.get("escalations"),
              {**(labels or {}), "direction": "escalate"},
              type_="counter", help_="Ladder rung transitions")
        p.add(f"{prefix}fleet_ladder_moves_total", ladder.get("recoveries"),
              {**(labels or {}), "direction": "recover"}, type_="counter")
        p.add(f"{prefix}fleet_retry_after_seconds",
              ladder.get("retry_after_s"), labels,
              help_="Live drain-rate-derived Retry-After hint")
    for name, row in (fleet.get("tenants") or {}).items():
        lab = {**(labels or {}), "tenant": _esc(name)}
        p.add(f"{prefix}fleet_tenant_weight", row.get("weight"), lab,
              help_="Configured weighted-fair share")
        p.add(f"{prefix}fleet_tenant_admitted_total", row.get("admitted"),
              lab, type_="counter", help_="Requests admitted per tenant")
        p.add(f"{prefix}fleet_tenant_shed_total", row.get("shed"), lab,
              type_="counter", help_="Requests shed per tenant")
        p.add(f"{prefix}fleet_tenant_tokens_charged_total",
              row.get("tokens_charged"), lab, type_="counter",
              help_="Token cost charged against the tenant budget")
        if row.get("budget_remaining") is not None:
            p.add(f"{prefix}fleet_tenant_budget_remaining",
                  row.get("budget_remaining"), lab,
                  help_="Token-bucket balance (absent = unlimited)")


def render_prometheus(summary: dict | None, *, tracer: Tracer | None = None,
                      model: str = "dllama", mode: str = "scheduler",
                      state: str | None = None,
                      build: dict | None = None) -> str:
    """The GET /metrics body: the /stats summary dict (supervisor- or
    router-shaped; None while the front door is unbuilt or in legacy
    mode) + the tracer's step-timeline histograms, as Prometheus text
    exposition format. Every serving tier hands its EXISTING summary
    here, so the metric names are tier-invariant and a replica's
    counters appear both aggregated and per-replica (labelled)."""
    p = _Prom()
    p.add("dllama_up", 1, {"model": model, "mode": mode},
          help_="The serving process is up", type_="gauge")
    if build:
        # the build-info idiom: constant 1, identity in the labels —
        # join on it to annotate every other series with version/backend
        p.add("dllama_build_info", 1,
              {k: _esc(v) for k, v in build.items()},
              help_="Build identity (constant 1; info in the labels)")
    states = ("ready", "recovering", "broken", "draining", "closed",
              "degraded", "off", "idle")
    st = state or (summary or {}).get("state")
    if st is not None:
        for s in states:
            p.add("dllama_state", int(st == s), {"state": _esc(s)},
                  help_="Serving front-door state (one-hot)")
        if st not in states:
            p.add("dllama_state", 1, {"state": _esc(st)})
    if summary:
        for key, name, help_ in _COUNTERS:
            p.add(name, summary.get(key), help_=help_, type_="counter")
        for key, name, labels, help_ in _GAUGES:
            p.add(name, summary.get(key), labels=labels, help_=help_)
        _add_block(p, summary.get("prefix_cache"), _PREFIX, type_="counter")
        _add_block(p, summary.get("prefix_cache"), _PREFIX_GAUGES,
                   type_="gauge")
        _add_block(p, summary.get("resilience"), _RESILIENCE,
                   type_="counter")
        res = summary.get("resilience") or {}
        p.add("dllama_supervisor_recovery_ms", res.get("recovery_p50_ms"),
              {"quantile": "0.5"},
              help_="Failure-detected to ready-again latency")
        p.add("dllama_supervisor_recovery_ms", res.get("recovery_p99_ms"),
              {"quantile": "0.99"})
        _add_block(p, summary.get("router"), _ROUTER, type_="counter")
        auto = summary.get("autosize")
        if auto:
            # the startup auto-sizing decision (runtime/profiler.
            # resolve_auto_shape): what was chosen and why, as gauges an
            # operator can alert on (a knee drifting under live load
            # shows up as dllama_step_ms disagreeing with these)
            p.add("dllama_autosize_serve_batch", auto.get("serve_batch"),
                  {"basis": _esc(auto.get("serve_batch_basis"))},
                  help_="Auto-resolved --serve-batch (KV slots)")
            if auto.get("prefix_blocks_basis") != "static":
                p.add("dllama_autosize_prefix_blocks",
                      auto.get("prefix_blocks"),
                      {"basis": _esc(auto.get("prefix_blocks_basis"))},
                      help_="Auto-resolved --prefix-blocks (arena blocks)")
            p.add("dllama_autosize_knee_rows",
                  (auto.get("inputs") or {}).get("knee_rows"),
                  {"basis": _esc((auto.get("inputs") or {})
                                 .get("knee_basis"))},
                  help_="Batch knee that capped the auto-sizing")
        _add_admission(p, summary.get("admission"))
        _add_spec(p, summary.get("spec"))
        _add_kv_transfer(p, summary.get("kv_transfer"))
        _add_fleet(p, summary.get("fleet"))
        _add_device_blocks(p, summary)
        for rep in summary.get("replicas") or ():
            lab = {"replica": str(rep.get("replica"))}
            p.add("dllama_replica_up",
                  int(rep.get("state") == "ready"
                      and not rep.get("draining")
                      and not rep.get("breaker_open")), lab,
                  help_="Replica is routable")
            for key, name, help_ in _COUNTERS:
                p.add(name.replace("dllama_", "dllama_replica_"),
                      rep.get(key), lab, type_="counter",
                      help_=help_ and f"{help_} (per replica)")
            _add_block(p, rep.get("prefix_cache"), tuple(
                (k, n.replace("dllama_", "dllama_replica_"))
                for k, n in _PREFIX), type_="counter", labels=lab)
            _add_block(p, rep.get("prefix_cache"), tuple(
                (k, n.replace("dllama_", "dllama_replica_"))
                for k, n in _PREFIX_GAUGES), type_="gauge", labels=lab)
            # per-replica admission policy state (the router's aggregate
            # summary carries none — each replica's scheduler owns its
            # own policy, so the family must ride the replica label or a
            # multi-replica tier would lose it entirely, the PR-8 rule)
            _add_admission(p, rep.get("admission"), labels=lab,
                           prefix="dllama_replica_")
            # per-replica accept record (each replica's scheduler owns
            # its own SpecStats — on router tiers the family rides the
            # replica label, same rule as admission)
            _add_spec(p, rep.get("spec"), labels=lab,
                      prefix="dllama_replica_")
            # per-replica transfer record (a worker's donor serving +
            # its own fills — the aggregate block sums these)
            _add_kv_transfer(p, rep.get("kv_transfer"), labels=lab,
                             prefix="dllama_replica_")
            _add_device_blocks(p, rep, labels=lab)
            proc = rep.get("proc")
            if proc:
                p.add("dllama_replica_proc_exits_total", proc.get("exits"),
                      lab, type_="counter",
                      help_="Deaths of ready worker processes")
                p.add("dllama_replica_proc_respawns_total",
                      proc.get("respawns"), lab, type_="counter")
                p.add("dllama_replica_proc_spawn_failures_total",
                      proc.get("spawn_failures"), lab, type_="counter")
                for cls, n in (proc.get("exit_classes") or {}).items():
                    p.add("dllama_replica_proc_exit_class_total", n,
                          {**lab, "class": _esc(cls)}, type_="counter",
                          help_="Classified worker exits")
                p.add("dllama_replica_proc_respawn_ms",
                      proc.get("respawn_p50_ms"),
                      {**lab, "quantile": "0.5"},
                      help_="Death-detected to routable-again latency")
        _add_cluster(p, summary.get("cluster"))
    if tracer is not None and tracer.enabled:
        t = tracer.summary()
        p.add("dllama_trace_events", t["events"],
              help_="Events in the flight-recorder ring")
        p.add("dllama_trace_next_id", t["next_id"], type_="counter",
              help_="Trace ids minted")
        p.add("dllama_trace_sink_dropped_total", t["sink_dropped"],
              type_="counter")
        for comp, row in tracer.step_timeline().items():
            lab = {"decode_rows": str(comp[0]),
                   "prefill_rows": str(comp[1]), "chunk": str(comp[2])}
            p.add("dllama_step_ms", row["p50_ms"],
                  {**lab, "quantile": "0.5"},
                  help_="Scheduler step wall ms by batch composition")
            p.add("dllama_step_ms", row["p99_ms"],
                  {**lab, "quantile": "0.99"})
            p.add("dllama_steps_by_composition_total", row["n"], lab,
                  type_="counter",
                  help_="Scheduler iterations by batch composition")
    return p.render()
