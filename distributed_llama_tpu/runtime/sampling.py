"""Host side of vocab-sharded sampling (ops/sharded_vocab.py).

The device half ships tiny per-shard summaries — the global argmax and
k candidates per shard with an exactness guard; this module turns them
into tokens with the host Sampler's exact semantics:

  * :func:`sample_candidates` — the oracle's top-p nucleus walk run on
    the merged candidates, EXACT whenever the truncation point provably
    sits above the guard (the candidate set contains every token at or
    above it); returns None when exactness cannot be proven and the
    caller must fall back.
  * :class:`FullLogitsView` / :class:`ShardedLogitsView` — the one
    sampling surface the scheduler and the batch generator consume:
    ``argmax(row, n_vocab)`` and ``sample(sampler, row)``. The full view
    is the replicated parity oracle (host Sampler on fetched logits,
    exactly the pre-sharding path); the sharded view serves greedy rows
    BIT-IDENTICALLY from the device argmax, sampled rows from the
    candidate scheme, and falls back to ONE replicated (vocab,) row
    fetch — never the (B, vocab) array — for anything unprovable.

Docs: docs/parallelism.md ("Vocab sharding") carries the exactness
argument in full.
"""

from __future__ import annotations

import numpy as np


def draw_coin(sampler) -> float:
    """Advance the sampler's xorshift stream one step and return the
    uniform — the same coin `Sampler.sample` would have flipped on the
    full logits (works for both the python and native backends via the
    rng_state property)."""
    from ..utils.rng import xorshift_f32

    s, v = xorshift_f32(sampler.rng_state)
    sampler.rng_state = s
    return v


def sample_candidates(sampler, cand_p: np.ndarray, cand_id: np.ndarray,
                      guard: np.ndarray, argmax_tok: int) -> int | None:
    """Sample one token from the per-shard top-k candidate summary,
    EXACTLY distributed as ``sampler.sample`` on the full logits — or
    return None when exactness cannot be proven from the candidates
    alone (the caller then falls back to the replicated row fetch).

    Exactness argument (docs/parallelism.md "Vocab sharding" carries the
    long form): every token NOT in the candidate set has prob <=
    v_guard = max over shards of that shard's k-th-largest prob. The
    oracle (sampler._sample_topp / topp_nucleus) walks tokens with
    prob >= cutoff in (prob desc, id asc) order and truncates at the
    first index whose cumulative mass crosses topp (inclusive). If the
    crossing element's prob is STRICTLY above v_guard, every token at
    or above it — ties included — is a candidate and ordered exactly as
    the oracle orders it, so the truncated set, its cumulative masses,
    and the draw within it are the oracle's. If the walk never crosses
    (the nucleus is the whole cutoff-filtered set), exactness instead
    needs v_guard < cutoff (no non-candidate passes the filter). The
    probabilities themselves are the device softmax's f32 values — the
    same real quantity the oracle computes, to rounding.

    Only the nucleus mode (0 < topp < 1) is candidate-exact; pure
    multinomial (topp <= 0 or >= 1) needs the full CDF and always
    falls back. Temperature 0 never lands here (the caller returns the
    sharded argmax, bit-identical to np.argmax)."""
    topp = float(sampler.topp)
    if topp <= 0.0 or topp >= 1.0:
        return None
    n = int(sampler.vocab_size)
    v_guard = float(np.max(guard))
    cutoff = (1.0 - topp) / (n - 1)
    keep = cand_p >= cutoff
    p = cand_p[keep]
    ids = cand_id[keep]
    if p.size == 0:
        # the oracle's empty-nucleus branch keeps the (first) argmax —
        # which the sharded argmax already pinned; exact only when no
        # hidden token passes the cutoff either
        if v_guard >= cutoff:
            return None
        draw_coin(sampler)  # the oracle consumes its coin here too
        return int(argmax_tok)
    # the oracle's stable descending sort == (prob desc, id asc)
    order = np.lexsort((ids, -p))
    p = p[order]
    ids = ids[order]
    cum = np.cumsum(p.astype(np.float64))
    over = np.nonzero(cum > topp)[0]
    exact_all = v_guard < cutoff
    if over.size:
        last = int(over[0])
        if not exact_all and not (p[last] > v_guard):
            return None  # truncation point at/below the guard: a hidden
            # token could belong above it
    else:
        if not exact_all:
            return None  # nucleus = the whole filtered set, but the
            # tail past the candidates is unknown
        last = len(ids) - 1
    coin = draw_coin(sampler)
    r = coin * cum[last]
    idx = int(np.searchsorted(cum[: last + 1], r, side="right"))
    idx = min(idx, last)
    return int(ids[idx])


class FullLogitsView:
    """The replicated parity oracle: full (B, vocab) logits on host,
    every row sampled by the host Sampler exactly as before vocab
    sharding existed."""

    sharded = False

    def __init__(self, logits_np: np.ndarray):
        self.lg = logits_np

    def argmax(self, row: int, n_vocab: int) -> int:
        return int(np.argmax(self.lg[row, :n_vocab]))

    def sample(self, sampler, row: int) -> int:
        return int(sampler.sample(self.lg[row]))

    def row(self, row: int) -> np.ndarray:
        return self.lg[row]


class ShardedLogitsView:
    """Sampling access to one step's logits WITHOUT the (B, vocab)
    fetch: greedy rows read the device argmax, sampled rows run the
    candidate scheme, and anything the candidates cannot prove exact —
    guard failures, pure-multinomial requests, foreign sampler vocabs —
    fetches ONE replicated (vocab,) row through `fetch_row` (the warmed
    parity-oracle executable) and samples the oracle way. `stats` is a
    plain dict the engine owns: {"sharded", "fallback"} counters."""

    sharded = True

    def __init__(self, amax: np.ndarray, cand_p: np.ndarray,
                 cand_id: np.ndarray, guard: np.ndarray, n_vocab: int,
                 fetch_row, stats: dict | None = None):
        self.amax = amax
        self.cand_p = cand_p
        self.cand_id = cand_id
        self.guard = guard
        self.n_vocab = int(n_vocab)
        self._fetch_row = fetch_row
        self.stats = stats if stats is not None else {}

    def _count(self, key: str) -> None:
        self.stats[key] = self.stats.get(key, 0) + 1

    def argmax(self, row: int, n_vocab: int) -> int:
        if n_vocab == self.n_vocab:
            self._count("sharded")
            return int(self.amax[row])
        self._count("fallback")
        return int(np.argmax(self._fetch_row(row)[:n_vocab]))

    def row(self, row: int) -> np.ndarray:
        return self._fetch_row(row)

    def sample(self, sampler, row: int) -> int:
        if getattr(sampler, "vocab_size", None) == self.n_vocab:
            if sampler.temperature == 0.0:
                # np.argmax parity: the device argmax is masked at the
                # same vocab and tie-breaks to the lowest index (ONE
                # greedy implementation — argmax() above)
                return self.argmax(row, self.n_vocab)
            tok = sample_candidates(sampler, self.cand_p[row],
                                    self.cand_id[row], self.guard[row],
                                    int(self.amax[row]))
            if tok is not None:
                self._count("sharded")
                return tok
        self._count("fallback")
        return int(sampler.sample(self._fetch_row(row)))
