"""Process-isolated serving replicas: worker entry point, framed-codec
transport, and the parent-side process supervisor.

PR 6's replica tier made replica failure invisible to clients, but every
replica was a THREAD in one interpreter: a single XLA segfault (the
rc=-11 class that tier itself root-caused) or a wedged native call is
still a whole-service fault domain. This module moves the fault boundary
to the OS process:

  * ``python -m distributed_llama_tpu.runtime.replica_worker`` runs ONE
    supervised Scheduler+Engine (runtime/resilience.EngineSupervisor —
    the exact PR-3 object, watchdog and all) per OS process and serves
    submit/stream/admin over the PR-5 length-prefixed frame codec
    (parallel/multihost._send_frame/_recv_frame) with per-socket
    deadlines on every send/recv and keepalive frames while a step runs
    long. Because the transport IS the PR-5 codec, the socket-layer
    fault sites (``recv_stall``/``frame_truncate``/``peer_close``) fire
    inside it unchanged.
  * ``WorkerClient`` is the parent-side speaker: one short-lived
    connection per request (a dead worker is an EOF on exactly the
    requests it was serving, nothing else), plus a persistent control
    connection for health/stats/admin. Connection loss mid-stream
    surfaces as a STRUCTURED retryable ``RequestError`` — which feeds
    the router's EXISTING bounded-failover machinery, so greedy retries
    of not-yet-streamed requests stay bit-identical (the sampler spec
    rides the submit frame; the worker reconstructs it).
  * ``WorkerProc`` spawns and monitors a local worker process: port
    handshake via an atomically-written port file, logs to a per-replica
    file, exit-code CLASSIFICATION (``classify_exit`` — a SIGKILL reads
    as ``signal:SIGKILL``, a config typo as ``config_error``), and the
    respawn/backoff/breaker policy lives in the router-side handle
    (runtime/router.RemoteReplicaHandle).

The worker deals exclusively in TOKEN IDS — no tokenizer, no HTTP: the
API layer, routing, retry budget, and text scanning all stay in the
parent. Everything here is host-side socket/process plumbing: no jitted
entry point is added or changed (each worker compiles the same pinned
``slot_prefill_chunk``/``slot_decode_step`` programs), so the dlgrind
fingerprint set is invariant by construction.

Chaos surface: a worker armed with ``DLLAMA_FAULTS=worker_exit:...`` in
its environment ``os._exit``s hard immediately before a token frame —
the in-process, count-deterministic stand-in for SIGKILL/OOM; the chaos
tests (tests/test_replica_procs.py) also deliver REAL ``SIGKILL -9`` to
a live worker mid-stream and pin zero unstreamed request failures.

Ops runbook: docs/operations.md "Process-isolated replicas".
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import queue as _pyqueue

import numpy as np

from ..parallel.multihost import (ClusterProtocolError, _recv_frame,
                                  _send_frame)
from .faults import FAULTS
from .kv_transfer import RMSG_BLOCK_QUERY
from .resilience import EngineUnready
from .scheduler import (PromptTooLong, QueueFull, RequestError,
                        SchedulerClosed)
from .stats import RequestStats, ServeStats
from .trace import TRACER

# v2: the submit header grew a trace id (flight-recorder span linkage
# across the process boundary, runtime/trace.py) and workers ship their
# span events back in RMSG_TRACE frames — the version handshake turns a
# mixed-version parent/worker pair into a clean HELLO failure instead of
# a misparsed frame. v3: RMSG_PROFILE (on-demand jax.profiler capture,
# runtime/profiler.py) joined the control verbs. v4: the KV block
# transfer plane (runtime/kv_transfer.py) — RMSG_BLOCK_* verbs, and the
# submit header grew fill_port/fill_expected (the router's fetch-from-
# donor instruction) with the ACCEPT echoing the donor's answer.
# v5: multi-tenant fairness (runtime/fleet.py) — the submit header grew
# priority (band index into fleet.PRIORITIES) + tenant_len, with the
# tenant name riding the payload after the fill host, so the worker-side
# WFQ orders the queue where the waiting actually happens.
REPLICA_PROTOCOL_VERSION = 5

# message kinds — a namespace distinct from the cluster control plane's
# MSG_* so a replica socket accidentally pointed at a cluster control
# port (or vice versa) fails the handshake instead of misparsing frames
RMSG_HELLO = 100        # client -> worker: [protocol_version]
RMSG_HELLO_ACK = 101    # worker -> client: [version, ok, batch, seq_len, pid]
RMSG_SUBMIT = 102       # client -> worker: the request header + prompt ints
RMSG_ACCEPT = 103       # worker -> client: [request_id]
RMSG_REFUSE = 104       # worker -> client: JSON {code, message, ...}
RMSG_TOKEN = 105        # worker -> client: [token]
RMSG_DONE = 106         # worker -> client: JSON {finish_reason}
RMSG_ERROR = 107        # worker -> client: JSON structured error frame
RMSG_CANCEL = 108       # client -> worker, on the submit socket
RMSG_KEEPALIVE = 109    # worker -> client while a step runs long
RMSG_PING = 110         # client -> worker (control): health probe
RMSG_PONG = 111         # worker -> client: JSON health payload
RMSG_STATS = 112        # client -> worker (control)
RMSG_STATS_ACK = 113    # worker -> client: JSON supervisor summary
RMSG_RESET = 114        # client -> worker: reset the ENGINE breaker
RMSG_REBUILD = 115      # client -> worker: rebuild the supervisor in place
RMSG_SHUTDOWN = 116     # client -> worker: graceful exit 0
RMSG_OK = 117           # worker -> client: JSON ack for admin verbs
RMSG_TRACE = 118        # worker -> client: JSON span events for this
#                         request's trace id, sent just before the
#                         terminal frame (the parent tracer merges them
#                         onto its own timeline — runtime/trace.py)
RMSG_PROFILE = 119      # client -> worker (control): [ms] — write one
#                         jax.profiler trace of the next ms milliseconds
#                         into THIS worker's capture dir; RMSG_OK carries
#                         {dir} back (the /admin/profile relay,
#                         runtime/profiler.py)
# 120..124: the KV block transfer verbs (RMSG_BLOCK_QUERY/ACK/FETCH/
#           DATA/END) — runtime/kv_transfer.py owns them; the server
#           below dispatches a QUERY-opening connection to BlockDonor

# [max_tokens, temp_bits, topp_bits, rng_lo, rng_hi, vocab, deadline_ms,
#  n_eos, trace_id, fill_port, fill_expected, fill_donor, priority,
#  tenant_len] then n_eos stop ids then the prompt; the payload carries
# the fill donor's host (utf-8, empty when fill_port == 0 — no fill
# requested) followed by tenant_len bytes of utf-8 tenant name
# (tenant_len == 0 — untagged). fill_donor is the donor's replica id:
# the importer's wire ledger and kv_fill trace events attribute per
# donor, not to a constant peer. priority indexes fleet.PRIORITIES
# (negative — untagged, the worker's default band).
_SUBMIT_HEADER = 14

EXIT_WORKER_FAULT = 86   # the worker_exit fault site's os._exit code

_COUNTER_KEYS = ("requests_submitted", "requests_finished",
                 "requests_failed", "requests_expired",
                 "requests_rejected", "tokens_out", "steps")


def _f32_bits(x: float) -> int:
    return int(np.float32(x).view(np.int32))


def _bits_f32(b: int) -> float:
    return float(np.int32(b).view(np.float32))


# -- worker-side server ----------------------------------------------------


def _sup_counters(sup) -> dict:
    """Cross-generation counter totals of one EngineSupervisor WITHOUT the
    percentile sorts of summary() — cheap enough to ride every PONG (the
    parent caches them, so a SIGKILL loses at most one poll interval of
    counts and never double-counts)."""
    with sup._state_lock:
        sched = sup._sched
        carry = dict(sup._carry)
        dead = list(sup._dead_stats)
    return {k: (getattr(sched.stats, k, 0) + carry[k]
                + sum(getattr(d, k, 0) for d in dead))
            for k in _COUNTER_KEYS}


class ReplicaServer:
    """The worker process's serving loop: accept framed connections, run
    one supervised engine, stream tokens. One thread per connection; a
    submit connection carries exactly one request (ACCEPT → TOKEN* →
    DONE/ERROR), a control connection loops PING/STATS/admin verbs.

    ``sup_factory`` builds the EngineSupervisor — kept so RMSG_REBUILD
    can replace the whole supervisor in place (the rolling-restart verb:
    fresh engine + cache + empty prefix tree, params shared via the
    factory's closure) while counters carry across the swap."""

    def __init__(self, sup_factory, *, host: str = "127.0.0.1",
                 port: int = 0, io_timeout: float = 30.0,
                 keepalive: float = 2.0, idle_timeout: float = 600.0,
                 fault_key: str | None = None,
                 profile_dir: str | None = None,
                 kv_transfer: bool = False, tier: str = "mixed"):
        from .kv_transfer import BlockDonor
        from .stats import KVTransferStats

        self._factory = sup_factory
        self._io = float(io_timeout)
        self._keepalive = float(keepalive)
        self._idle = float(idle_timeout)
        self._fault_key = fault_key
        self._profile_dir = profile_dir  # RMSG_PROFILE capture home
        self._sup_lock = threading.RLock()
        self.sup = sup_factory()  # dlrace: guarded-by(self._sup_lock)
        # cross-replica KV block transfer (runtime/kv_transfer.py): this
        # worker serves sibling QUERY/FETCH connections as a donor and
        # runs its own fills when a submit carries donor coordinates.
        # The stats block rides every /stats reply even when disabled
        # (enabled=False — a tier must not lose the family to a flag);
        # `tier` is this worker's disaggregation role, advertised on
        # every PONG so the router places by role.
        self.tier = tier if tier in ("prefill", "decode", "mixed") \
            else "mixed"
        self.kvx_stats = KVTransferStats(enabled=bool(kv_transfer),
                                         tier=self.tier)
        self._kv_transfer = bool(kv_transfer)
        # this worker's replica index (fault_key "rK" -> K): the
        # requester id its fills stamp on BLOCK_QUERY frames so donors
        # account wire bytes per peer
        try:
            self.replica_index = int((fault_key or "r0").lstrip("r"))
        except ValueError:
            self.replica_index = 0
        pc = self.sup.prefix_cache
        if pc is not None:
            from .kv_transfer import block_payload_bytes

            eng = self.sup.engine
            self.kvx_stats.block_len = pc.block_len
            self.kvx_stats.block_bytes = block_payload_bytes(
                eng.spec.n_layers, eng.spec.n_kv_heads, pc.block_len,
                eng.spec.head_size, eng.cache_dtype)
        self._donor = BlockDonor(lambda: self.sup, self.kvx_stats,
                                 fault_key=fault_key,
                                 io_timeout=self._io)
        # rebuild carry: RMSG_REBUILD swaps the supervisor wholesale, so
        # the dying one's cross-generation totals fold in here and every
        # STATS/PONG reply adds them back — counters never reset or
        # double-count across a rolling restart (tests/test_router.py
        # pins the same contract for thread replicas)
        self._carry = {k: 0 for k in _COUNTER_KEYS}
        self._bind = (host, int(port))
        self._srv: socket.socket | None = None
        self._done = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> int:
        self._srv = socket.create_server(self._bind, backlog=16,
                                         reuse_port=False)
        self._srv.settimeout(0.2)
        t = threading.Thread(target=self._accept_loop,
                             name="dllama-replica-accept", daemon=True)
        t.start()
        return self._srv.getsockname()[1]

    def wait(self) -> None:
        self._done.wait()

    def shutdown(self) -> None:
        """Graceful exit: stop accepting, fail in-flight work with
        structured shutdown frames (EngineSupervisor.close's contract),
        release main()."""
        if self._done.is_set():
            return
        self._done.set()
        try:
            with self._sup_lock:
                self.sup.close(timeout=10.0)
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._done.is_set():
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._conn_main, args=(conn,),
                             daemon=True).start()

    # -- per-connection protocol -------------------------------------------

    def _conn_main(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            frame = _recv_frame(conn, timeout=self._io)
            if frame is None or frame[0] != RMSG_HELLO or not frame[1]:
                return
            ok = int(frame[1][0] == REPLICA_PROTOCOL_VERSION)
            with self._sup_lock:
                eng = self.sup.engine
            _send_frame(conn, RMSG_HELLO_ACK,
                        [REPLICA_PROTOCOL_VERSION, ok, eng.batch,
                         eng.seq_len, os.getpid()], timeout=self._io)
            if not ok:
                return
            frame = _recv_frame(conn, timeout=self._idle)
            if frame is None:
                return
            if frame[0] == RMSG_SUBMIT:
                self._handle_submit(conn, frame[1], frame[2])
            elif frame[0] == RMSG_BLOCK_QUERY:  # donor serving
                # (runtime/kv_transfer.BlockDonor). A worker with the
                # transfer plane OFF answers a clean miss instead of
                # serving: its prefix cache never warmed the export
                # executable, so serving would mint a post-warmup
                # compile key (and refuse under --freeze-compiles) —
                # reachable in mixed --replica-hosts fleets where each
                # worker's own config decides kv_transfer
                if self._kv_transfer:
                    self._donor.serve(conn, frame)
                else:
                    from .kv_transfer import RMSG_BLOCK_ACK

                    _send_frame(conn, RMSG_BLOCK_ACK,
                                [0, 0, 0, 0, 0, 0, 0],
                                timeout=self._io)
            else:
                self._control_loop(conn, frame)
        except (OSError, ClusterProtocolError):
            pass  # a dead/garbled client costs this connection only
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle_submit(self, conn: socket.socket, ints: list[int],
                       payload: bytes = b"") -> None:
        from ..sampler import Sampler

        if len(ints) < _SUBMIT_HEADER:
            raise ClusterProtocolError(f"short submit header: {len(ints)}")
        (max_tokens, temp_b, topp_b, rng_lo, rng_hi, vocab,
         deadline_ms, n_eos, trace_id, fill_port,
         fill_expected, fill_donor, prio_idx,
         tenant_len) = ints[:_SUBMIT_HEADER]
        # fairness tags (v5): the fill host is the payload's head, the
        # tenant name its tail — split by the header's declared length
        fill_payload, tenant = payload, None
        if tenant_len > 0:
            fill_payload = payload[:-tenant_len]
            tenant = payload[-tenant_len:].decode("utf-8",
                                                  errors="replace")
        from .fleet import PRIORITIES

        priority = (PRIORITIES[prio_idx]
                    if 0 <= prio_idx < len(PRIORITIES) else "normal")
        eos = [int(t) for t in ints[_SUBMIT_HEADER:_SUBMIT_HEADER + n_eos]]
        prompt = [int(t) for t in ints[_SUBMIT_HEADER + n_eos:]]
        sampler = Sampler(int(vocab), temperature=_bits_f32(temp_b),
                          topp=_bits_f32(topp_b),
                          seed=(rng_lo & 0xFFFFFFFF) | (rng_hi << 32))
        # the wire carries the REMAINING budget (absolute perf_counter
        # clocks do not transfer between processes); rebased here so the
        # scheduler's in-step reaper enforces the same end-to-end bound
        deadline = (None if deadline_ms < 0
                    else time.perf_counter() + deadline_ms / 1e3)
        with self._sup_lock:
            sup = self.sup
        # cache FILL on miss (runtime/kv_transfer.py): the router knows a
        # sibling holds a longer prefix than this replica — fetch its
        # blocks into the local radix tree BEFORE admission, so the
        # ordinary _admit seeds them and only the uncached suffix
        # prefills. Degrades to a plain re-prefill on ANY failure; the
        # donor's answer rides the ACCEPT frame back so the router can
        # clear stale shadow entries (a QUERY miss == donor eviction).
        fill_answer = -1
        # the transfer's budget is bounded by the REQUEST's remaining
        # budget, not just the io timeout: a wedged donor must degrade
        # to a re-prefill with time left to actually serve — a fill
        # that eats the whole deadline would convert a transfer failure
        # into the client-visible request failure the degrade contract
        # forbids (half the budget for the fill, floor 0.25 s to skip
        # hopeless attempts)
        fill_budget = min(self._io, 15.0)
        if deadline_ms >= 0:
            fill_budget = min(fill_budget, deadline_ms / 1e3 * 0.5)
        if fill_port > 0 and self._kv_transfer and fill_budget >= 0.25:
            from .kv_transfer import fill_from_wire

            host = (fill_payload.decode("utf-8", errors="replace")
                    if fill_payload else "127.0.0.1")
            try:
                sched = sup._sched
            except AttributeError:
                sched = None
            if sched is not None:
                fill_answer = fill_from_wire(
                    sched, prompt, host, int(fill_port),
                    int(fill_expected), stats=self.kvx_stats,
                    protocol_version=REPLICA_PROTOCOL_VERSION,
                    trace_id=int(trace_id),
                    requester=self.replica_index,
                    donor_peer=int(fill_donor),
                    io_timeout=min(self._io, 10.0),
                    deadline_s=fill_budget)
        try:
            # the PARENT minted the trace id: worker-side scheduler events
            # carry it so the shipped span merges onto the parent's
            # timeline (trace_id=0 -> None lets an untraced parent leave
            # the worker's own minting behavior unchanged)
            req = sup.submit(prompt, int(max_tokens), sampler,
                             eos_id=set(eos) or None, deadline=deadline,
                             trace_id=int(trace_id) or None,
                             tenant=tenant, priority=priority)
        except QueueFull as e:
            self._refuse(conn, {"code": "queue_full", "message": str(e),
                                "retry_after": e.retry_after})
            return
        except EngineUnready as e:
            self._refuse(conn, {"code": "unready", "message": str(e),
                                "state": e.state,
                                "retry_after": e.retry_after})
            return
        except PromptTooLong as e:
            self._refuse(conn, {"code": "prompt_too_long",
                                "message": str(e)})
            return
        except SchedulerClosed as e:
            self._refuse(conn, {"code": "closed", "message": str(e)})
            return
        # two Python socket objects over one fd (the multihost._Peer
        # discipline): the cancel watcher re-arms read deadlines on
        # `conn` while this thread sends tokens on the dup — shared
        # settimeout() state would race the two directions' budgets
        wsock = conn.dup()
        done = threading.Event()
        try:
            # the ACCEPT echoes the fill verdict (donor's answered match
            # in tokens; -1 = no verdict) + what the router expected —
            # the shadow-staleness feedback channel, no extra RPC
            _send_frame(wsock, RMSG_ACCEPT,
                        [req.id, fill_answer, int(fill_expected)],
                        timeout=self._io)
            threading.Thread(target=self._cancel_watcher,
                             args=(conn, req, done), daemon=True).start()
            self._pump(wsock, req)
        except (OSError, ClusterProtocolError):
            req.cancel()  # client gone: free the slot now
        finally:
            done.set()
            try:
                wsock.close()
            except OSError:
                pass

    def _cancel_watcher(self, conn: socket.socket, req, done) -> None:
        """Read the submit socket for RMSG_CANCEL / EOF while the stream
        runs — a disconnected client's request must stop burning forwards
        (the scheduler reaps the cancel at its next iteration)."""
        while not done.is_set():
            try:
                frame = _recv_frame(conn, timeout=0.25)
            except socket.timeout:
                continue
            except (OSError, ClusterProtocolError):
                req.cancel()
                return
            if frame is None:          # client closed its end
                req.cancel()
                return
            if frame[0] == RMSG_CANCEL:
                req.cancel()           # keep reading to the EOF

    def _pump(self, wsock: socket.socket, req) -> None:
        """Drain one ServeRequest's event queue onto the socket. Reads
        the queue directly (not tokens()) so idle gaps turn into
        keepalive frames instead of a client-side deadline: the client's
        per-frame recv deadline then only ever fires on a genuinely
        frozen worker process, while slow steps and the worker's OWN
        stall/crash recovery stay inside the protocol."""
        while True:
            try:
                kind, val = req.events.get(timeout=self._keepalive)
            except _pyqueue.Empty:
                _send_frame(wsock, RMSG_KEEPALIVE, [], timeout=self._io)
                continue
            if kind == "token":
                if FAULTS.triggered("worker_exit", key=self._fault_key):
                    # the SIGKILL/OOM shape, count-deterministic: no
                    # flush, no teardown, no DONE frame — the client
                    # sees a mid-frame EOF exactly like a real -9
                    os._exit(EXIT_WORKER_FAULT)
                _send_frame(wsock, RMSG_TOKEN, [val], timeout=self._io)
            elif kind == "done":
                self._ship_trace(wsock, req)
                _send_frame(wsock, RMSG_DONE, [], json.dumps(
                    {"finish_reason": req.finish_reason or val}).encode(),
                    timeout=self._io)
                return
            else:  # structured error frame (dict) or legacy string
                frame = (dict(val) if isinstance(val, dict)
                         else {"code": "error", "message": str(val),
                               "retryable": True})
                self._ship_trace(wsock, req)
                _send_frame(wsock, RMSG_ERROR, [],
                            json.dumps(frame).encode(), timeout=self._io)
                return

    def _ship_trace(self, wsock: socket.socket, req) -> None:
        """Ship this request's worker-side span ahead of the terminal
        frame (RMSG_TRACE): events carry wall-clock timestamps so the
        parent tracer rebases them onto ITS monotonic timeline — a
        surviving request's cross-process story merges; a SIGKILLed
        worker simply never ships (the parent's own casualty events and
        the monitor's classified worker_exit tell that half)."""
        tid = getattr(req, "trace_id", 0)
        if not tid or not TRACER.enabled:
            return
        events = TRACER.export_span(tid)
        if events:
            _send_frame(wsock, RMSG_TRACE, [tid],
                        json.dumps({"events": events}).encode(),
                        timeout=self._io)

    def _refuse(self, conn: socket.socket, payload: dict) -> None:
        _send_frame(conn, RMSG_REFUSE, [], json.dumps(payload).encode(),
                    timeout=self._io)

    # -- control connection ------------------------------------------------

    def _control_loop(self, conn: socket.socket, frame) -> None:
        while frame is not None and not self._done.is_set():
            kind = frame[0]
            if kind == RMSG_PING:
                _send_frame(conn, RMSG_PONG, frame[1],
                            json.dumps(self._health()).encode(),
                            timeout=self._io)
            elif kind == RMSG_STATS:
                _send_frame(conn, RMSG_STATS_ACK, [],
                            json.dumps(self._summary()).encode(),
                            timeout=self._io)
            elif kind == RMSG_RESET:
                with self._sup_lock:
                    self.sup.reset_breaker()
                self._ok(conn)
            elif kind == RMSG_REBUILD:
                self._rebuild()
                self._ok(conn)
            elif kind == RMSG_PROFILE:
                # on-demand capture relay (POST /admin/profile on the
                # parent): synchronous — the OK frame means the trace is
                # on disk in THIS worker's capture dir. The client sizes
                # its recv deadline to ms + slack.
                ms = float(frame[1][0]) if frame[1] else 100.0
                _send_frame(conn, RMSG_OK, [],
                            json.dumps(self._profile(ms)).encode(),
                            timeout=self._io)
            elif kind == RMSG_SHUTDOWN:
                self._ok(conn)
                self.shutdown()
                return
            else:
                return  # unknown verb: drop the connection
            frame = _recv_frame(conn, timeout=self._idle)

    def _ok(self, conn: socket.socket) -> None:
        _send_frame(conn, RMSG_OK, [], json.dumps({"ok": True}).encode(),
                    timeout=self._io)

    def _profile(self, ms: float) -> dict:
        """One jax.profiler capture into this worker's own directory
        (two processes must never share one trace dir, same rule as the
        trace sink's per-worker subdirs)."""
        import tempfile

        from .profiler import PROFILER

        base = self._profile_dir or tempfile.mkdtemp(
            prefix=f"dlprof-worker-{os.getpid()}-")
        d = os.path.join(base, f"profile-{int(time.time() * 1e3):x}")
        try:
            return {"ok": True, **PROFILER.capture(d, ms)}
        except RuntimeError as e:  # capture busy
            return {"ok": False, "error": str(e)}

    def _health(self) -> dict:
        """The PONG payload: routability signals + counter snapshot. The
        parent's monitor caches it, so placement (load), drain (busy) and
        the shadow-index invalidation (recoveries — a supervisor rebuild
        emptied the radix tree) never RPC on the submit hot path."""
        with self._sup_lock:
            sup = self.sup
            carry = dict(self._carry)
        sched = sup._sched
        load = (len(sched._queue)
                + sum(1 for s in sched.slots if s.req is not None))
        counters = _sup_counters(sup)
        for k in _COUNTER_KEYS:
            counters[k] += carry[k]
        return {"state": sup.state, "ready": sup.ready, "load": load,
                "busy": load > 0,
                "recoveries": sup.sup_stats.recoveries,
                # the disaggregation role — connect-mode routers learn it
                # from here (spawn mode ships it in the worker config)
                "tier": self.tier,
                "counters": counters}

    def _summary(self) -> dict:
        with self._sup_lock:
            sup = self.sup
            carry = dict(self._carry)
        out = sup.summary()
        for k in _COUNTER_KEYS:
            out[k] = out.get(k, 0) + carry[k]
        out["pid"] = os.getpid()
        # this worker's transfer-plane record (donor serving + its own
        # fills) — present even when transfer is off (enabled=False)
        out["kv_transfer"] = self.kvx_stats.summary()
        out["tier"] = self.tier
        if TRACER.enabled:
            # the step timeline is WORKER-local (the parent never sees
            # our iterations) — ride it on the stats reply so the bench
            # procs row and a curious operator get it across the
            # boundary without a new verb
            out["step_timeline"] = TRACER.steps.summary_json()
            out["trace"] = TRACER.summary()
        return out

    def _rebuild(self) -> None:
        """The rolling-restart verb: tear down the current supervisor
        (in-flight work gets structured shutdown frames — the router
        drains the replica first, so normally there is none), fold its
        lifetime counters into the carry, build a fresh one (params
        shared through the factory closure; warmup runs inside the
        supervisor constructor so the replica answers ready=True only
        once it can actually serve)."""
        with self._sup_lock:
            old = self.sup
            old.close(timeout=30.0)
            for k, v in _sup_counters(old).items():
                self._carry[k] += v
            self.sup = self._factory()


# -- worker construction from a config dict --------------------------------


def build_supervisor_factory(cfg: dict):
    """(engine config dict) -> zero-arg EngineSupervisor factory.

    Two engine sources:
      * ``test_spec`` — a ModelSpec field dict + RNG ``seed``/``scale``:
        deterministic synthetic weights (models/params.random_tensors),
        so a parent process building the SAME spec/seed holds
        bit-identical params — the greedy-parity oracle for the
        process-kill chaos tests and the bench row.
      * ``model`` — a reference-format ``.m`` path, streamed exactly like
        the CLI loads it (each worker process owns its weights: process
        isolation trades the thread tier's shared buffers for a real
        fault boundary).

    Params load ONCE here; the factory closes over them, so supervisor
    crash-recovery rebuilds (and RMSG_REBUILD swaps) mint fresh engines +
    caches without re-reading weights."""
    import jax.numpy as jnp

    from ..models.spec import ArchType, HiddenAct, ModelSpec
    from .engine import Engine
    from .resilience import EngineSupervisor

    dtypes = {"f32": jnp.float32, "bf16": jnp.bfloat16,
              "f8": jnp.float8_e4m3fn}
    compute = dtypes[cfg.get("compute_dtype", "f32")]
    cache = dtypes[cfg.get("cache_dtype", cfg.get("compute_dtype", "f32"))]

    if "test_spec" in cfg:
        from ..models.params import load_params, random_tensors

        ts = dict(cfg["test_spec"])
        ts["arch"] = ArchType[ts.get("arch", "LLAMA")]
        ts["hidden_act"] = HiddenAct[ts.get("hidden_act", "SILU")]
        spec = ModelSpec(**ts)
        host = random_tensors(spec, seed=int(cfg.get("seed", 0)),
                              scale=float(cfg.get("scale", 0.02)))
        params = load_params(spec, host, mode=cfg.get("mode", "dense"),
                             dtype=compute)
        model_fp = 0
    else:
        from ..io.model_file import content_fingerprint, read_spec
        from ..models.loader import load_params_streamed
        from ..quants.types import FloatType

        wft = cfg.get("weights_float_type")
        spec = read_spec(cfg["model"],
                         weights_float_type=(FloatType[wft.upper()]
                                             if wft else None))
        model_fp = content_fingerprint(cfg["model"])
        mode = "q40" if spec.weights_float_type == FloatType.Q40 else "dense"
        params, _ = load_params_streamed(spec, cfg["model"], None,
                                         mode=mode, dtype=compute)

    batch = int(cfg.get("batch", 1))
    max_seq = cfg.get("max_seq_len")
    serve = dict(cfg.get("serve", {}))

    def engine_factory():
        return Engine(spec, params, batch=batch, max_seq_len=max_seq,
                      compute_dtype=compute, cache_dtype=cache,
                      use_pallas=cfg.get("pallas"),
                      model_fingerprint=model_fp)

    n_blocks = 0
    if cfg.get("prefix_cache"):
        bl = int(cfg.get("prefix_block_len", 32))
        seq = max_seq or spec.seq_len
        n_blocks = int(cfg.get("prefix_blocks", 0)) or max(
            2 * batch * seq // bl, 1)
    sup_kwargs = dict(
        chunk=serve.get("chunk") or None,
        max_queue=int(serve.get("max_queue", 0)) or 4 * batch,
        request_deadline=serve.get("request_deadline") or None,
        stall_timeout=serve.get("stall_timeout") or 10.0,
        prefix_blocks=n_blocks,
        prefix_block_len=int(cfg.get("prefix_block_len", 32)),
        # KV block transfer (runtime/kv_transfer.py): arms the prefix
        # cache's export/import warmup so fills/donor serving mint zero
        # post-warmup compile keys
        kv_transfer=bool(cfg.get("kv_transfer")),
        fault_key=cfg.get("fault_key"),
        # SLO-aware admission runs INSIDE each worker (the policy reads
        # the worker's own step timeline; its block rides the stats
        # reply like every other per-replica block)
        slo_ttft_ms=serve.get("slo_ttft_ms"),
        slo_itl_ms=serve.get("slo_itl_ms"),
        # per-slot speculative decoding (runtime/draft.py): each worker
        # builds its own DraftModel over its own engine per generation —
        # the spec string ships, never weight buffers; model-draft
        # workers load the draft .m themselves like the target .m
        draft=cfg.get("draft"), draft_len=int(cfg.get("draft_len", 0)),
        draft_vocab=cfg.get("draft_vocab"))

    # multi-tenant weighted-fair admission (runtime/fleet.py): the budget
    # SPEC ships in the config; the ledger lives worker-side, held
    # outside the supervisor's generations so budgets survive rebuilds —
    # fairness must hold in this worker's queue, where waiting happens
    tb = serve.get("tenant_budgets")
    if tb:
        from .fleet import TenantLedger, WFQueue, parse_tenant_budgets

        ledger = TenantLedger(parse_tenant_budgets(tb))
        sup_kwargs["fair_queue_factory"] = lambda: WFQueue(ledger)

    return lambda: EngineSupervisor(engine_factory, **sup_kwargs)


def config_from_cli_args(args, serve_batch: int) -> dict:
    """The worker config the api server ships to locally-spawned replicas
    (``--replica-procs``): exactly the engine+serving knobs `dllama api`
    itself was launched with, minus everything that stays in the parent
    (tokenizer, routing, HTTP)."""
    return {
        "model": args.model,
        "weights_float_type": getattr(args, "weights_float_type", None),
        "batch": serve_batch,
        "max_seq_len": getattr(args, "max_seq_len", None),
        "compute_dtype": getattr(args, "compute_dtype", "bf16"),
        "cache_dtype": getattr(args, "cache_dtype", "bf16"),
        "pallas": getattr(args, "pallas", None),
        "prefix_cache": bool(getattr(args, "prefix_cache", False)),
        "prefix_blocks": int(getattr(args, "prefix_blocks", 0) or 0),
        "prefix_block_len": int(getattr(args, "prefix_block_len", None)
                                or 32),
        # KV block transfer (runtime/kv_transfer.py): the enable flag
        # ships; the per-replica `tier` role is stamped per index by
        # build_front_door, like fault_key
        "kv_transfer": bool(getattr(args, "kv_transfer", False)),
        # speculative decoding (runtime/draft.py): the draft SPEC ships
        # (the worker builds the DraftModel over its own engine);
        # draft_vocab is filled in by the api server once the tokenizer
        # is loaded (the verify argmax truncates at the tokenizer vocab).
        # The draft-len DEFAULT (7) applies here like on the local tiers
        # — argparse's sentinel is None, and shipping 0 with a draft
        # armed would trip the scheduler's draft_len >= 1 assertion in
        # every worker (review-found; regression-tested)
        "draft": getattr(args, "draft", None),
        "draft_len": int(getattr(args, "draft_len", None)
                         or (7 if getattr(args, "draft", None) else 0)),
        "serve": {
            "chunk": getattr(args, "serve_chunk", 0),
            "max_queue": getattr(args, "queue_depth", 0),
            "request_deadline": getattr(args, "request_deadline", 0.0),
            "stall_timeout": getattr(args, "stall_timeout", 0.0),
            "slo_ttft_ms": getattr(args, "slo_ttft_ms", None),
            "slo_itl_ms": getattr(args, "slo_itl_ms", None),
            # weighted-fair admission (runtime/fleet.py): the raw
            # --tenant-budgets spec ships so each worker's own WFQ
            # orders its queue by the same weights/budgets
            "tenant_budgets": getattr(args, "tenant_budgets", None),
        },
        # device-tier observability: the recompile sentinel freezes and
        # the attribution sampler sample INSIDE each worker; /admin/
        # profile captures land under per-worker subdirs of profile_dir
        "freeze_compiles": bool(getattr(args, "freeze_compiles", False)),
        "profile_sample": int(getattr(args, "profile_sample", 0) or 0),
        "profile_dir": getattr(args, "profile_dir", None),
        # flight recorder: workers trace whenever the parent does, so
        # span events exist on both sides of the process boundary
        **({"trace": {
            "capacity": getattr(args, "trace_buffer", None) or 8192,
            "sample": (1.0 if getattr(args, "trace_sample", None) is None
                       else args.trace_sample),
            "decode_every": getattr(args, "trace_decode_every", None) or 8,
            "dir": getattr(args, "trace_dir", None),
        }} if getattr(args, "trace", False) else {}),
    }


# -- worker CLI ------------------------------------------------------------


def _emit(event: str, **fields) -> None:
    print(json.dumps({"event": event, "t_wall": time.time(), **fields}),
          flush=True)


def main(argv: list[str] | None = None) -> int:
    p = argparse_parser()
    args = p.parse_args(argv)
    # config problems exit FAST (code 2), before the heavyweight jax
    # import: the parent's spawn breaker must see a crash-loop in
    # milliseconds per attempt, not a backend initialization each
    try:
        with open(args.config) as f:
            cfg = json.load(f)
        if "test_spec" not in cfg and "model" not in cfg:
            raise ValueError("config needs 'test_spec' or 'model'")
    except (OSError, ValueError) as e:
        _emit("config_error", error=f"{type(e).__name__}: {e}")
        return 2

    tr = cfg.get("trace")
    if tr:
        # per-worker flight recorder (runtime/trace.py): spans ship back
        # to the parent in RMSG_TRACE frames; a sink directory gets a
        # per-worker subdir so two processes never fight over one file
        # rotation sequence
        sink = tr.get("dir")
        if sink:
            sink = os.path.join(
                sink, f"worker-{cfg.get('fault_key') or os.getpid()}")
        TRACER.configure(capacity=int(tr.get("capacity", 8192)),
                         sample=float(tr.get("sample", 1.0)),
                         decode_every=int(tr.get("decode_every", 8)),
                         sink_dir=sink)

    # device-tier observability (runtime/profiler.py): the worker runs
    # its own compile ledger / recompile sentinel and sampled device-time
    # attribution — their blocks ride the stats reply like every other
    # per-replica block
    from .profiler import COMPILES, PROFILER

    if cfg.get("freeze_compiles"):
        COMPILES.freeze = True
    PROFILER.sample_every = int(cfg.get("profile_sample", 0) or 0)
    profile_dir = cfg.get("profile_dir")
    if profile_dir:
        profile_dir = os.path.join(
            profile_dir, f"worker-{cfg.get('fault_key') or os.getpid()}")

    sup_factory = build_supervisor_factory(cfg)
    server = ReplicaServer(sup_factory, host=args.host, port=args.port,
                           io_timeout=args.io_timeout,
                           keepalive=args.keepalive,
                           fault_key=cfg.get("fault_key"),
                           profile_dir=profile_dir,
                           kv_transfer=bool(cfg.get("kv_transfer")),
                           tier=cfg.get("tier") or "mixed")
    port = server.start()
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"port": port, "pid": os.getpid()}, f)
        os.replace(tmp, args.port_file)  # atomic: the parent never reads
        # a half-written handshake
    _emit("listening", port=port, pid=os.getpid(),
          fault_key=cfg.get("fault_key"))

    def _term(*_):
        server.shutdown()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    server.wait()
    _emit("exiting")
    return 0


def argparse_parser():
    import argparse

    p = argparse.ArgumentParser(
        prog="replica_worker",
        description="One supervised serving replica (Scheduler + Engine) "
                    "behind the framed replica protocol. Spawned by "
                    "`dllama api --replica-procs N`, or started by hand "
                    "on another host for --replica-hosts.")
    p.add_argument("--config", required=True,
                   help="JSON engine+serving config (see "
                        "build_supervisor_factory)")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (0.0.0.0 for --replica-hosts "
                        "workers; the protocol has no auth — firewall "
                        "accordingly)")
    p.add_argument("--port", type=int, default=0,
                   help="TCP port (0 = OS-assigned; see --port-file)")
    p.add_argument("--port-file", default=None,
                   help="write {port, pid} JSON here once listening — "
                        "the parent's spawn handshake")
    p.add_argument("--io-timeout", type=float, default=30.0,
                   help="per-socket deadline on every framed send/recv")
    p.add_argument("--keepalive", type=float, default=2.0,
                   help="keepalive frame cadence while a step runs long")
    return p


# -- parent-side client ----------------------------------------------------


class _RemoteStream:
    """One in-flight request on a worker process, as the parent sees it:
    duck-types the ``ServeRequest`` consumer surface (``tokens()``,
    ``cancel()``, ``finished``, ``finish_reason``, ``stats``) so
    ``RouterRequest`` wraps remote and in-process replicas identically.
    Connection loss before the terminal frame raises a RETRYABLE
    structured ``RequestError`` (code ``replica_lost``) — the router's
    existing failover machinery takes it from there."""

    def __init__(self, sock: socket.socket, io_timeout: float,
                 n_prompt: int, rid: int, trace_id: int = 0,
                 origin: str = "worker"):
        self.id = rid
        self.trace_id = trace_id
        self._origin = origin
        self._sock = sock
        self._wsock = sock.dup()   # cancel() sends here; reads stay on
        # _sock so the two directions' deadlines never share settimeout
        self._io = io_timeout
        self._iterating = False
        self.finished = threading.Event()
        self.finish_reason: str | None = None
        self.stats = RequestStats(n_prompt=n_prompt)
        self.stats.t_submit = time.perf_counter()
        # the ACCEPT frame's fill verdict (runtime/kv_transfer.py): the
        # donor's answered match in tokens (-1 = no fill / no verdict)
        # and what the router expected off its shadow index — the
        # router reads these right after submit to clear stale shadow
        # entries (a miss answer == donor-side eviction)
        self.fill_answer = -1
        self.fill_expected = 0

    def cancel(self) -> None:
        try:
            _send_frame(self._wsock, RMSG_CANCEL, [], timeout=2.0)
        except (OSError, ClusterProtocolError):
            pass  # worker gone: nothing left to cancel
        if not self._iterating:
            # no consumer will ever run tokens()'s finally: close now so
            # an abandoned pre-stream request cannot leak the socket
            self._close()

    def tokens(self, timeout: float = 600.0):
        self._iterating = True
        try:
            while True:
                try:
                    frame = _recv_frame(self._sock,
                                        timeout=min(self._io, timeout))
                except (OSError, ClusterProtocolError) as e:
                    self._trace_lost(f"{type(e).__name__}")
                    raise RequestError(
                        "replica_lost",
                        f"replica connection lost mid-request: "
                        f"{type(e).__name__}: {e}", retryable=True) from e
                if frame is None:
                    # mid-stream EOF: the worker process died (SIGKILL,
                    # OOM, segfault) — the kernel closed its sockets
                    self._trace_lost("eof")
                    raise RequestError(
                        "replica_lost",
                        "replica closed the connection before the "
                        "terminal frame (process died?)", retryable=True)
                kind = frame[0]
                if kind == RMSG_TOKEN:
                    now = time.perf_counter()
                    if self.stats.t_first is None:
                        self.stats.t_first = now
                        if TRACER.enabled and self.trace_id:
                            # the CLIENT-side TTFT edge: a SIGKILLed
                            # worker can never ship its span, so the
                            # casualty's "it was streaming" fact must be
                            # recorded on this side of the boundary.
                            # side="client" tells it apart from the
                            # worker's OWN first_token (which arrives
                            # later via RMSG_TRACE with the same origin
                            # but a smaller, worker-internal ttft_ms)
                            TRACER.event("first_token", self.trace_id,
                                         side="client",
                                         origin=self._origin,
                                         ttft_ms=round(
                                             (now - self.stats.t_submit)
                                             * 1e3, 3))
                    self.stats.n_out += 1
                    yield int(frame[1][0])
                elif kind == RMSG_KEEPALIVE:
                    continue
                elif kind == RMSG_TRACE:
                    # the worker's span events, wall-stamped; merge them
                    # onto the parent timeline (no-op when untraced)
                    if TRACER.enabled:
                        payload = json.loads(frame[2] or b"{}")
                        TRACER.ingest(payload.get("events", []),
                                      origin=self._origin)
                    continue
                elif kind == RMSG_DONE:
                    payload = json.loads(frame[2] or b"{}")
                    self.finish_reason = payload.get("finish_reason")
                    self.stats.t_done = time.perf_counter()
                    return
                elif kind == RMSG_ERROR:
                    fr = json.loads(frame[2] or b"{}")
                    self.finish_reason = "error"
                    raise RequestError(fr.get("code", "error"),
                                       fr.get("message", "replica error"),
                                       fr.get("retryable", True))
                else:
                    self._trace_lost(f"frame_kind_{kind}")
                    raise RequestError(
                        "replica_lost",
                        f"unexpected frame kind {kind} in a token stream",
                        retryable=True)
        finally:
            self.finished.set()
            self._close()

    def _trace_lost(self, how: str) -> None:
        """Parent-side casualty record: the worker died (or tore the
        connection) mid-request, so ITS tracer can never ship this span
        — the error event the timeline needs lives on this side."""
        if TRACER.enabled and self.trace_id:
            TRACER.event("error", self.trace_id, code="replica_lost",
                         retryable=True, n_out=self.stats.n_out,
                         how=how, side="client", origin=self._origin)

    def _close(self) -> None:
        for s in (self._sock, self._wsock):
            try:
                s.close()
            except OSError:
                pass


class WorkerClient:
    """Framed-codec speaker for one worker process. Submits open a fresh
    connection per request (failure isolation: a dying worker EOFs
    exactly the streams it owned); health/stats/admin verbs share one
    persistent control connection under a lock, reconnecting on error.
    Duck-types the slice of the EngineSupervisor surface the router's
    remote handle delegates here."""

    def __init__(self, host: str, port: int, *, io_timeout: float = 30.0,
                 connect_timeout: float = 5.0):
        self.addr = (host, int(port))
        self._io = float(io_timeout)
        self._connect_timeout = float(connect_timeout)
        self._ctrl: socket.socket | None = None
        self._ctrl_lock = threading.Lock()
        # shape template from the worker's HELLO ack (the slice of the
        # Engine surface the HTTP handlers read) — cached on the first
        # successful connect, kept across respawns (same config)
        self.batch: int | None = None
        self.seq_len: int | None = None
        # client-side latency window: the RequestStats the router's
        # summary() merges into tier percentiles (counters come from the
        # worker's own RSTATS — this window is timings only, so nothing
        # double-counts)
        self.stats = ServeStats()

    def set_addr(self, host: str, port: int) -> None:
        """Point at a respawned worker's new port (under the control
        lock so an in-flight admin verb never splits across processes)."""
        with self._ctrl_lock:
            self.addr = (host, int(port))
            self._drop_ctrl_locked()

    # -- submit path -------------------------------------------------------

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self.addr,
                                        timeout=self._connect_timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _send_frame(sock, RMSG_HELLO, [REPLICA_PROTOCOL_VERSION],
                        timeout=self._io)
            frame = _recv_frame(sock, timeout=self._io)
            if (frame is None or frame[0] != RMSG_HELLO_ACK
                    or len(frame[1]) < 2 or not frame[1][1]):
                raise ClusterProtocolError(
                    f"replica handshake rejected: {frame!r}")
            if len(frame[1]) >= 4:
                self.batch = int(frame[1][2])
                self.seq_len = int(frame[1][3])
            return sock
        except BaseException:
            sock.close()
            raise

    def submit(self, prompt, max_tokens, sampler, eos_id=None,
               deadline=None, trace_id: int = 0,
               fill: tuple | None = None, tenant: str | None = None,
               priority: str = "normal") -> _RemoteStream:
        """Place one request on the worker. Door refusals re-raise the
        SAME exception types the in-process supervisor uses (QueueFull /
        EngineUnready / PromptTooLong / SchedulerClosed), so the router's
        walk-past-refusals placement loop needs no remote special case; a
        worker that cannot even be reached is an EngineUnready door
        refusal too (the process is dead or respawning — its monitor
        will say so shortly).

        ``fill`` = (donor_host, donor_port, expected_tokens, donor_id)
        instructs the worker to fetch the donor's published KV blocks
        before admission (runtime/kv_transfer.py); the ACCEPT's fill
        verdict lands on the returned stream."""
        prompt = [int(t) for t in prompt]
        eos = ([eos_id] if isinstance(eos_id, int)
               else sorted(int(t) for t in (eos_id or ())))
        deadline_ms = (-1 if deadline is None else
                       max(int((deadline - time.perf_counter()) * 1e3), 0))
        fill_host, fill_port, fill_expected, fill_donor = (
            fill or ("", 0, 0, 0))
        # v5: priority rides as an index into fleet.PRIORITIES (-1 =
        # untagged), the tenant as payload-tail bytes sized by tenant_len
        from .fleet import PRIORITIES
        prio_idx = (PRIORITIES.index(priority)
                    if priority in PRIORITIES else -1)
        tenant_bytes = (tenant or "").encode("utf-8")
        rng = sampler.rng_state
        ints = [int(max_tokens), _f32_bits(sampler.temperature),
                _f32_bits(sampler.topp), rng & 0xFFFFFFFF,
                (rng >> 32) & 0xFFFFFFFF, sampler.vocab_size,
                deadline_ms, len(eos), int(trace_id), int(fill_port),
                int(fill_expected), int(fill_donor), prio_idx,
                len(tenant_bytes), *eos, *prompt]
        try:
            sock = self._connect()
        except (OSError, ClusterProtocolError) as e:
            raise EngineUnready(f"unreachable ({type(e).__name__})",
                                1.0) from e
        try:
            _send_frame(sock, RMSG_SUBMIT, ints,
                        payload=fill_host.encode("utf-8") + tenant_bytes,
                        timeout=self._io)
            frame = _recv_frame(sock, timeout=self._io)
        except (OSError, ClusterProtocolError) as e:
            sock.close()
            # the worker died between connect and accept: nothing can
            # have streamed, so this is a door refusal, not a failure
            raise EngineUnready(f"lost during submit "
                                f"({type(e).__name__})", 1.0) from e
        if frame is not None and frame[0] == RMSG_REFUSE:
            payload = json.loads(frame[2] or b"{}")
            sock.close()
            code = payload.get("code")
            msg = payload.get("message", code or "refused")
            if code == "queue_full":
                raise QueueFull(0, 0,
                                retry_after=payload.get("retry_after", 1.0))
            if code == "prompt_too_long":
                raise PromptTooLong(msg)
            if code == "closed":
                raise SchedulerClosed(msg)
            raise EngineUnready(payload.get("state", code or "unready"),
                                payload.get("retry_after", 1.0))
        if frame is None or frame[0] != RMSG_ACCEPT:
            sock.close()
            raise EngineUnready("bad accept frame", 1.0)
        rs = _RemoteStream(sock, self._io, len(prompt),
                           int(frame[1][0]) if frame[1] else 0,
                           trace_id=int(trace_id),
                           origin=f"worker@{self.addr[0]}:{self.addr[1]}")
        if len(frame[1]) >= 3:
            # the fill verdict the ACCEPT echoed (see _handle_submit)
            rs.fill_answer = int(frame[1][1])
            rs.fill_expected = int(frame[1][2])
        self.stats.requests.append(rs.stats)
        return rs

    # -- control path ------------------------------------------------------

    def _drop_ctrl_locked(self) -> None:
        if self._ctrl is not None:
            try:
                self._ctrl.close()
            except OSError:
                pass
            self._ctrl = None

    def _request(self, kind: int, ints=(), timeout: float | None = None):
        t = timeout or self._io
        with self._ctrl_lock:
            for attempt in (0, 1):
                try:
                    if self._ctrl is None:
                        self._ctrl = self._connect()
                    _send_frame(self._ctrl, kind, ints, timeout=t)
                    frame = _recv_frame(self._ctrl, timeout=t)
                    if frame is None:
                        raise ClusterProtocolError("control EOF")
                    return frame
                except (OSError, ClusterProtocolError):
                    self._drop_ctrl_locked()
                    if attempt:
                        raise
        raise AssertionError("unreachable")

    def ping(self, timeout: float = 3.0) -> dict | None:
        """Health probe; None when the worker is unreachable (the monitor
        turns that into ready=False, never an exception)."""
        try:
            frame = self._request(RMSG_PING, [0], timeout=timeout)
            if frame[0] != RMSG_PONG:
                return None
            return json.loads(frame[2] or b"{}")
        except (OSError, ClusterProtocolError):
            return None

    def stats_summary(self, timeout: float = 10.0) -> dict | None:
        try:
            frame = self._request(RMSG_STATS, timeout=timeout)
            if frame[0] != RMSG_STATS_ACK:
                return None
            return json.loads(frame[2] or b"{}")
        except (OSError, ClusterProtocolError):
            return None

    def reset_breaker(self, timeout: float = 10.0) -> bool:
        try:
            return self._request(RMSG_RESET, timeout=timeout)[0] == RMSG_OK
        except (OSError, ClusterProtocolError):
            return False

    def profile(self, ms: float, timeout: float | None = None
                ) -> dict | None:
        """RMSG_PROFILE: capture `ms` milliseconds of jax.profiler trace
        in the worker, into ITS capture dir. Synchronous — the deadline
        covers the capture window plus slack; None when the worker is
        unreachable or the verb failed."""
        try:
            frame = self._request(RMSG_PROFILE, [int(ms)],
                                  timeout=(timeout
                                           or float(ms) / 1e3 + 30.0))
            if frame[0] != RMSG_OK:
                return None
            out = json.loads(frame[2] or b"{}")
            return out if out.get("ok") else None
        except (OSError, ClusterProtocolError):
            return None

    def rebuild(self, timeout: float = 120.0) -> bool:
        """RMSG_REBUILD blocks until the worker's fresh supervisor is
        warmed — the rolling-restart step completes only once the replica
        can actually serve again."""
        try:
            return self._request(RMSG_REBUILD,
                                 timeout=timeout)[0] == RMSG_OK
        except (OSError, ClusterProtocolError):
            return False

    def shutdown(self, timeout: float = 10.0) -> bool:
        try:
            return self._request(RMSG_SHUTDOWN,
                                 timeout=timeout)[0] == RMSG_OK
        except (OSError, ClusterProtocolError):
            return False

    def close(self) -> None:
        with self._ctrl_lock:
            self._drop_ctrl_locked()


# -- parent-side process spawn/monitor -------------------------------------


def classify_exit(rc: int | None) -> str:
    """Human- and machine-readable exit classification for the supervisor
    log and the per-replica /stats proc block. Negative returncodes are
    deaths by signal (``signal:SIGKILL`` is the -9 the chaos tests
    deliver); 2 is a config error (a crash-loop the spawn breaker must
    catch); EXIT_WORKER_FAULT is the injected hard-exit site."""
    if rc is None:
        return "running"
    if rc == 0:
        return "clean"
    if rc < 0:
        try:
            return "signal:" + signal.Signals(-rc).name
        except ValueError:
            return f"signal:{-rc}"
    return {2: "config_error",
            EXIT_WORKER_FAULT: "fault_exit"}.get(rc, f"error:{rc}")


class WorkerProc:
    """Spawn record for one local replica worker process: config file,
    per-attempt port file (the ready handshake), a log file the worker's
    stdout/stderr append to, and bounded waits everywhere. Respawn
    policy (backoff, breaker, carry) lives in the router-side handle —
    this class only knows how to start, watch, and stop ONE attempt."""

    def __init__(self, rid: int, config: dict, *, workdir: str,
                 host: str = "127.0.0.1", io_timeout: float = 30.0,
                 keepalive: float = 2.0, faults: str | None = None,
                 env: dict | None = None):
        self.rid = rid
        self.host = host
        self._io = io_timeout
        self._keepalive = keepalive
        self._faults = faults
        self._env = dict(env or {})
        self._workdir = workdir
        self._attempt = 0
        os.makedirs(workdir, exist_ok=True)
        self.config_path = os.path.join(workdir, f"r{rid}.config.json")
        with open(self.config_path, "w") as f:
            json.dump(config, f)
        self.log_path = os.path.join(workdir, f"r{rid}.log")
        self.proc: subprocess.Popen | None = None
        self.port: int | None = None
        self.pid: int | None = None

    def spawn(self) -> None:
        self._attempt += 1
        self.port = None
        self._port_file = os.path.join(
            self._workdir, f"r{self.rid}.port.{self._attempt}")
        env = dict(os.environ)
        # never inherit the parent's armed faults: a chaos test arming
        # replica_raise for the PARENT's schedulers must not also crash
        # every worker (workers get their own arming via `faults`)
        env.pop("DLLAMA_FAULTS", None)
        if self._faults:
            env["DLLAMA_FAULTS"] = self._faults
        # the package must be importable regardless of the parent's cwd
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        env.update(self._env)
        log = open(self.log_path, "ab")
        try:
            self.proc = subprocess.Popen(
                [sys.executable, "-m",
                 "distributed_llama_tpu.runtime.replica_worker",
                 "--config", self.config_path,
                 "--port-file", self._port_file,
                 "--host", self.host, "--port", "0",
                 "--io-timeout", str(self._io),
                 "--keepalive", str(self._keepalive)],
                env=env, stdout=log, stderr=log)
        finally:
            log.close()  # the child holds its own copies of the fds

    def wait_ready(self, timeout: float = 120.0) -> int:
        """Block until the worker wrote its port file (it binds only
        after params load + supervisor warmup, so a readable port means
        a servable replica). Raises with the log tail when the process
        died first or the deadline passed."""
        end = time.perf_counter() + timeout
        while time.perf_counter() < end:
            rc = self.proc.poll()
            if rc is not None:
                raise RuntimeError(
                    f"replica worker r{self.rid} exited during startup "
                    f"({classify_exit(rc)})\n{self.log_tail()}")
            if os.path.exists(self._port_file):
                with open(self._port_file) as f:
                    info = json.load(f)
                self.port = int(info["port"])
                self.pid = int(info["pid"])
                return self.port
            time.sleep(0.05)
        raise RuntimeError(
            f"replica worker r{self.rid} did not come up within "
            f"{timeout:.0f}s\n{self.log_tail()}")

    def poll(self) -> int | None:
        return self.proc.poll() if self.proc is not None else None

    def kill(self, sig: int = signal.SIGKILL) -> None:
        if self.proc is not None and self.proc.poll() is None:
            os.kill(self.proc.pid, sig)

    def stop(self, timeout: float = 10.0) -> int | None:
        """SIGTERM (graceful worker drain) escalating to SIGKILL at the
        deadline; reaps and returns the exit code."""
        if self.proc is None:
            return None
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10.0)
        return self.proc.returncode

    def log_tail(self, nbytes: int = 2000) -> str:
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(f.tell() - nbytes, 0))
                return f.read().decode("utf-8", errors="replace")
        except OSError:
            return "<no log>"


if __name__ == "__main__":
    sys.exit(main())
