"""Engine supervision: watchdog, crash recovery, backoff, circuit breaker.

The reference engine has NO fault tolerance — a wedged or crashed node
takes the whole cluster down (SURVEY §"no fault tolerance") — and this
repo has been bitten by exactly that shape: the TPU plugin HANGS rather
than errors when its tunnel is down (tests/test_bench_outage.py), and the
scheduler's only in-loop handling was a blanket abort that failed every
request and hoped the engine was still usable. ``EngineSupervisor`` makes
the serving hot loop survive faults instead of merely reporting them:

  * it OWNS the step loop (the scheduler's ``start()`` thread is not used
    under supervision) and catches step exceptions;
  * a WATCHDOG thread reads the scheduler's in-step heartbeat
    (``Scheduler._step_t0``) and declares a stall when one step exceeds
    ``stall_timeout`` — the axon-hang signature, which no exception will
    ever surface (the thread is wedged inside a jax call and cannot be
    interrupted; it is abandoned, its generation discarded);
  * RECOVERY aborts in-flight/queued requests with structured error
    frames (``RequestError`` payloads), rebuilds the engine + scheduler
    through ``engine_factory`` under exponential backoff, and resumes
    admitting — a CIRCUIT BREAKER keeps the supervisor unready after
    ``breaker_threshold`` consecutive failures (``reset_breaker()`` is
    the operator's manual half-open);
  * ADMISSION CONTROL: while not ready, ``submit()`` raises
    ``EngineUnready`` with a ``retry_after`` hint; the queue bound and
    per-request deadlines live in the scheduler it supervises
    (``QueueFull`` / "deadline" frames) so overload returns fast
    structured rejections instead of unbounded latency.

Generations: every (engine, scheduler) pair is one generation. Failure
invalidates the generation FIRST (a wedged step thread that eventually
wakes finds ``gen != self._gen`` and exits without touching anything),
then aborts the old generation's requests, then rebuilds. The recovery
path reuses the same two jitted entry points as steady state
(``slot_prefill_chunk``/``slot_decode_step`` — fingerprints pinned in
analysis/baseline.json), so a rebuilt engine's first step compiles the
identical programs and dlgrind's gate covers it by construction.

Docs: docs/operations.md (tuning, drain procedure, fault injection).
"""

from __future__ import annotations

import contextlib
import threading
import time

from .scheduler import Scheduler
from .stats import SupervisorStats
from .trace import TRACER

READY = "ready"
RECOVERING = "recovering"
BROKEN = "broken"          # circuit open: stays unready until reset
DRAINING = "draining"
CLOSED = "closed"

_COUNTER_KEYS = ("requests_submitted", "requests_finished",
                 "requests_failed", "requests_expired",
                 "requests_rejected", "tokens_out", "steps")


class EngineUnready(RuntimeError):
    """Admission refused: the engine is recovering, broken, or draining.
    ``retry_after`` is the client hint (HTTP Retry-After at the API
    layer)."""

    def __init__(self, state: str, retry_after: float):
        super().__init__(f"engine not ready (state: {state})")
        self.state = state
        self.retry_after = retry_after


class EngineSupervisor:
    """Supervised continuous-batching front door. Duck-types the
    ``Scheduler`` surface the API server uses — ``submit``, ``engine``,
    ``stats``, ``exclusive()``, ``close()`` — plus the resilience surface:
    ``ready``/``state``, ``summary()``, ``drain()``, ``reset_breaker()``.
    """

    def __init__(self, engine_factory, *, chunk: int | None = None,
                 max_queue: int = 0, queue_timeout: float | None = None,
                 request_deadline: float | None = None,
                 stall_timeout: float = 10.0, watchdog_poll: float = 0.02,
                 backoff_base: float = 0.1, backoff_max: float = 5.0,
                 breaker_threshold: int = 3,
                 prefix_blocks: int = 0, prefix_block_len: int = 32,
                 kv_transfer: bool = False,
                 fault_key: str | None = None,
                 slo_ttft_ms: float | None = None,
                 slo_itl_ms: float | None = None,
                 draft: str | None = None, draft_len: int = 0,
                 draft_vocab: int | None = None,
                 fair_queue_factory=None):
        self._factory = engine_factory
        self._chunk = chunk
        # replica identity at the key-filtered fault sites (runtime/
        # faults.py replica_raise/replica_stall) — every generation's
        # scheduler carries it, so an armed kill follows THIS replica
        # across rebuilds
        self._fault_key = fault_key
        # prefix_blocks > 0 attaches a radix prefix cache
        # (runtime/prefix_cache.py) to every generation's scheduler. The
        # cache is minted FRESH in _make_sched: its block arena holds
        # K/V only the generation's own engine wrote, so a rebuild
        # invalidates the whole tree by construction (plus the explicit
        # Scheduler._abort_all invalidate on the dying generation).
        self._prefix_blocks = int(prefix_blocks)
        self._prefix_block_len = int(prefix_block_len)
        # cross-replica KV block transfer (runtime/kv_transfer.py): arms
        # the per-generation prefix cache's export/import warmup so
        # fills and donor serving mint ZERO post-warmup compile keys
        self._kv_transfer = bool(kv_transfer)
        # SLO targets for the adaptive admission policy — every rebuilt
        # generation's scheduler gets a FRESH policy (its EWMAs describe
        # the dead engine's steps; the new one re-learns in a few steps)
        self._slo_ttft_ms = slo_ttft_ms
        self._slo_itl_ms = slo_itl_ms
        # per-slot speculative decoding (runtime/draft.py): the spec
        # string ("self:2" / "model:PATH") is rebuilt into a DraftModel
        # PER GENERATION inside _make_sched — a self-draft's params are
        # views of the dying engine's buffers and must never outlive it
        self._draft = draft
        self._draft_len = int(draft_len)
        self._draft_vocab = draft_vocab
        # multi-tenant weighted-fair admission (runtime/fleet.py): a
        # zero-arg callable minting a fresh WFQueue per generation —
        # the TenantLedger behind it is held by the CALLER (the fleet
        # controller / API layer) so budgets survive rebuilds, the same
        # externally-held discipline as the counter carry below
        self._fair_queue_factory = fair_queue_factory
        self.max_queue = int(max_queue)
        self._queue_timeout = queue_timeout
        self._request_deadline = request_deadline
        self.stall_timeout = float(stall_timeout)
        self._poll = watchdog_poll
        self._backoff_base = backoff_base
        self._backoff_max = backoff_max
        self.breaker_threshold = int(breaker_threshold)

        self.sup_stats = SupervisorStats()
        self._state_lock = threading.RLock()
        # dead generations' ServeStats stay LIVE in _dead_stats (a
        # straggler — e.g. the failed-during-submit fallback — may still
        # increment one briefly after the swap; summing live objects
        # never loses those counts); only ancient generations past the
        # cap are compressed into the _carry snapshot, long after any
        # writer can exist
        self._dead_stats: list = []  # dlrace: guarded-by(self._state_lock)
        self._carry = {k: 0 for k in _COUNTER_KEYS}  # dlrace: guarded-by(self._state_lock)
        self._stop = False
        self._gen = 0  # dlrace: guarded-by(self._state_lock)
        self._state = READY  # dlrace: guarded-by(self._state_lock)
        self._sched = self._make_sched(engine_factory())  # dlrace: guarded-by(self._state_lock)
        # compile the serving executables BEFORE the watchdog exists: a
        # first-step compile must never read as a stall (see
        # Scheduler.warmup) and /readyz must mean "will serve promptly"
        self._sched.warmup()
        self._loop_threads: dict[int, threading.Thread] = {}
        self._rebuild_thread: threading.Thread | None = None  # dlrace: guarded-by(self._state_lock)
        self._start_loop(self._sched, self._gen)
        self._watchdog_thread = threading.Thread(
            target=self._watchdog, name="dllama-watchdog", daemon=True)
        self._watchdog_thread.start()

    # -- scheduler surface (what the API server/tests already use) --------

    @property
    def engine(self):
        return self._sched.engine

    @property
    def stats(self):
        """The CURRENT generation's ServeStats (windows/percentiles);
        cross-generation totals live in summary()."""
        return self._sched.stats

    @property
    def prefix_cache(self):
        """The CURRENT generation's radix prefix cache (None when off) —
        like `stats`, this swaps wholesale on recovery."""
        return self._sched.prefix_cache

    @property
    def state(self) -> str:
        with self._state_lock:
            return self._state

    @property
    def ready(self) -> bool:
        """Readiness = engine healthy AND queue under bound — the
        /readyz contract."""
        with self._state_lock:
            if self._state != READY:
                return False
            sched = self._sched
        return not self.max_queue or len(sched._queue) < self.max_queue

    def submit(self, prompt, max_tokens, sampler, eos_id=None,
               deadline=None, trace_id=None, tenant=None,
               priority="normal"):
        with self._state_lock:
            if self._state != READY:
                self.sup_stats.rejected_unready += 1
                raise EngineUnready(self._state, self._retry_after())
            sched = self._sched
        req = sched.submit(prompt, max_tokens, sampler, eos_id=eos_id,
                           deadline=deadline, trace_id=trace_id,
                           tenant=tenant, priority=priority)
        if sched._stop and not req.finished.is_set():
            # the generation died between the state check and the enqueue:
            # its abort may already have drained the queue, so deliver this
            # request's terminal frame ourselves rather than strand it
            sched._fail_req(req, {"code": "engine_error",
                                  "message": "engine failed during submit",
                                  "retryable": True})
        return req

    @contextlib.contextmanager
    def exclusive(self):
        """Borrow the current generation's engine (Scheduler.exclusive).
        Refused while not ready — a borrower must never receive an engine
        that is about to be discarded. A crash inside the borrow (the
        drain loop or the borrower's own engine use — everything fallible
        at the API layer is parsed BEFORE entering) is an engine failure
        like any step crash: it triggers the same recovery (abort frames,
        rebuild, backoff) and re-raises to the borrower."""
        with self._state_lock:
            if self._state != READY:
                raise EngineUnready(self._state, self._retry_after())
            sched, gen = self._sched, self._gen
        try:
            with sched.exclusive() as eng:
                yield eng
        except Exception as e:  # noqa: BLE001 — GeneratorExit (client
            # disconnect teardown) is BaseException and passes through
            self._on_failure(gen, f"{type(e).__name__}: {e} "
                                  "(exclusive borrow)", kind="crash")
            raise

    def close(self, timeout: float = 30.0) -> None:
        end = time.perf_counter() + timeout
        with self._state_lock:
            self._stop = True
            self._state = CLOSED
            self._gen += 1  # invalidate every loop thread
            sched = self._sched
            rebuild = self._rebuild_thread
        sched.close(timeout=timeout)
        if rebuild is not None and rebuild.is_alive():
            # a close that lands mid-rebuild must WAIT for the rebuild's
            # factory/warmup to notice _stop: a daemon thread still inside
            # an XLA compile when the interpreter finalizes is a segfault,
            # not a clean exit (seen as intermittent rc=-11 in the bench
            # subprocess after a kill-then-close chaos pass)
            rebuild.join(timeout=max(end - time.perf_counter(), 1.0))
        if self._watchdog_thread.is_alive():
            self._watchdog_thread.join(timeout=max(self._poll * 10, 1.0))

    # -- resilience surface ------------------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful drain: stop admitting (state DRAINING — /readyz goes
        unready, submits are refused), keep stepping until in-flight and
        queued work completes or `timeout` elapses. Returns True when the
        scheduler went idle in time; stragglers past the deadline are the
        caller's to abort (close())."""
        with self._state_lock:
            if self._state == READY:
                self._state = DRAINING
            elif self._state in (RECOVERING, BROKEN):
                return True  # nothing in flight: failures already aborted
        end = time.perf_counter() + timeout
        while time.perf_counter() < end:
            sched = self._sched
            # lock-free busy check (has_work() takes the step mutex, which
            # a wedged forward may hold forever)
            if not sched._queue and all(s.req is None for s in sched.slots):
                return True
            time.sleep(0.02)
        return False

    def trip_cluster(self, exc) -> None:
        """Map a :class:`parallel.multihost.ClusterPeerLost` onto the
        BROKEN path: the engine's mesh spans a process that is gone, so a
        local rebuild cannot help — every in-flight/queued request gets a
        structured ``cluster_peer_lost`` error frame immediately (instead
        of hanging to its deadline in a collective that will never
        complete) and the circuit opens without burning rebuild attempts.
        ``reset_breaker()`` remains the operator's half-open once the
        worker is back. Idempotent; callable from the link's detection
        thread while the step thread is wedged (the abort path takes no
        step mutex — Scheduler._abort_all)."""
        with self._state_lock:
            if self._state in (CLOSED, BROKEN):
                return
            self._gen += 1          # wedged/stale threads exit on wake
            old = self._sched
            old._stop = True
            self._state = BROKEN
            self.sup_stats.cluster_losses += 1
            self.sup_stats.consecutive_failures = self.breaker_threshold
        if TRACER.enabled:
            TRACER.event("cluster_lost", 0, msg=str(exc)[:200],
                         key=self._fault_key)
        # retryable=False: the SAME replica cannot serve a retry until an
        # operator (or orchestrator) restores the lost worker and resets
        # the breaker — clients should fail over, not hammer
        old._abort_all(str(exc), code="cluster_peer_lost", retryable=False)

    def reset_breaker(self) -> None:
        """Operator half-open: clear the failure streak and try one
        rebuild. No-op unless the breaker is open."""
        with self._state_lock:
            if self._state != BROKEN:
                return
            self.sup_stats.consecutive_failures = 0
            self._state = RECOVERING
            self._rebuild_thread = threading.Thread(
                target=self._rebuild, args=(time.perf_counter(),),
                daemon=True)
        if TRACER.enabled:
            TRACER.event("circuit", 0, scope="engine", state="half_open",
                         key=self._fault_key)
        self._rebuild_thread.start()

    def summary(self) -> dict:
        """ServeStats summary with cross-generation counter totals folded
        in, plus the supervisor block — the /stats payload."""
        with self._state_lock:
            sched = self._sched
            carry = dict(self._carry)
            dead = list(self._dead_stats)
            state = self._state
        out = sched.stats.summary()
        for k in _COUNTER_KEYS:
            out[k] = (out.get(k, 0) + carry[k]
                      + sum(getattr(d, k, 0) for d in dead))
        out["state"] = state
        out["resilience"] = self.sup_stats.summary()
        # device-tier blocks (runtime/profiler.py): live-bytes by
        # category for the CURRENT generation's engine + arena, the
        # process compile ledger, and — when --profile-sample is on —
        # the sampled per-entry-point device-time attribution. Cheap per
        # scrape: weights bytes are cached on the engine, the rest are
        # a handful of nbytes reads and dict copies.
        from .profiler import COMPILES, PROFILER, hbm_ledger

        try:
            out["hbm"] = hbm_ledger(sched.engine, sched.prefix_cache)
        except Exception:  # noqa: BLE001 — a mid-rebuild engine swap
            pass           # must never fail a stats scrape
        out["compiles"] = COMPILES.summary()
        if PROFILER.sample_every:
            out["device_time"] = PROFILER.summary()
        return out

    def _retry_after(self) -> float:
        # RECOVERING: one backoff step is the honest estimate; BROKEN:
        # nothing will change until an operator intervenes — back way off
        n = max(self.sup_stats.consecutive_failures, 1)
        if self._state == BROKEN:
            return 30.0
        return min(self._backoff_base * (2 ** (n - 1)), self._backoff_max)

    # -- internals ---------------------------------------------------------

    def _make_sched(self, engine) -> Scheduler:
        pc = None
        if self._prefix_blocks > 0:
            from .prefix_cache import PrefixCache

            pc = PrefixCache(engine, num_blocks=self._prefix_blocks,
                             block_len=self._prefix_block_len,
                             transfer=self._kv_transfer)
        draft_factory = None
        if self._draft:
            from .draft import build_draft

            spec_str = self._draft
            draft_factory = lambda eng: build_draft(eng, spec_str)  # noqa: E731
        return Scheduler(engine, chunk=self._chunk,
                         max_queue=self.max_queue,
                         queue_timeout=self._queue_timeout,
                         request_deadline=self._request_deadline,
                         prefix_cache=pc, fault_key=self._fault_key,
                         slo_ttft_ms=self._slo_ttft_ms,
                         slo_itl_ms=self._slo_itl_ms,
                         draft_factory=draft_factory,
                         draft_len=self._draft_len,
                         draft_vocab=self._draft_vocab,
                         fair_queue=(self._fair_queue_factory()
                                     if self._fair_queue_factory else None))

    def _start_loop(self, sched: Scheduler, gen: int) -> None:
        for g in [g for g, t in self._loop_threads.items()
                  if not t.is_alive()]:
            del self._loop_threads[g]  # dead generations; wedged ones stay
        t = threading.Thread(target=self._loop, args=(sched, gen),
                             name=f"dllama-supervised-step-gen{gen}",
                             daemon=True)
        self._loop_threads[gen] = t
        t.start()

    def _loop(self, sched: Scheduler, gen: int) -> None:
        """Supervised step loop — Scheduler._run's body, with failures
        escalated to recovery instead of swallowed."""
        while not self._stop and gen == self._gen and not sched._stop:
            sched._wake.clear()
            try:
                with sched._mutex:
                    did = sched._step_locked()
            except Exception as e:  # noqa: BLE001 — any step failure
                self._on_failure(gen, f"{type(e).__name__}: {e}",
                                 kind="crash")
                return
            if did and self.sup_stats.consecutive_failures:
                with self._state_lock:
                    if gen == self._gen:
                        # a real step succeeded post-recovery: streak over
                        self.sup_stats.consecutive_failures = 0
            if not did and not self._stop and gen == self._gen:
                sched._wake.wait(timeout=0.05)

    def _watchdog(self) -> None:
        """Detect the stall no exception will ever report: a step body
        running longer than stall_timeout. The wedged thread cannot be
        interrupted — its generation is discarded and it exits on wake."""
        while not self._stop:
            time.sleep(self._poll)
            with self._state_lock:
                if self._state != READY:
                    continue
                sched, gen = self._sched, self._gen
            t0 = sched._step_t0
            if t0 is not None and time.perf_counter() - t0 > self.stall_timeout:
                self.sup_stats.watchdog_trips += 1
                self._on_failure(
                    gen, f"step stalled > {self.stall_timeout:.1f}s "
                         "(watchdog)", kind="stall")

    def _on_failure(self, gen: int, msg: str, kind: str) -> None:
        """Failure entry point (loop crash or watchdog stall): invalidate
        the generation, fail its requests with structured frames, then
        rebuild in the background. Idempotent per generation."""
        with self._state_lock:
            if gen != self._gen or self._state in (CLOSED,):
                return
            t_detect = time.perf_counter()
            self._gen += 1          # wedged/stale threads exit on wake
            old = self._sched
            old._stop = True
            self._state = RECOVERING
            if kind == "crash":
                self.sup_stats.crashes += 1
            self.sup_stats.consecutive_failures += 1
        if TRACER.enabled:
            TRACER.event("engine_failure", 0, failure=kind, msg=msg[:200],
                         gen=gen, key=self._fault_key)
        # abort OUTSIDE the state lock (waiter wakeups run arbitrary
        # consumer code) and WITHOUT the step mutex (a wedged step holds
        # it forever) — see Scheduler._abort_all
        old._abort_all(f"engine failure: {msg}")
        t = threading.Thread(target=self._rebuild, args=(t_detect,),
                             daemon=True)
        with self._state_lock:
            self._rebuild_thread = t
        t.start()

    def _rebuild(self, t_detect: float) -> None:
        """Backoff → factory → install → resume. Runs on its own thread
        (the failing thread is wedged or must exit; the watchdog must keep
        watching). Factory failures count toward the breaker."""
        while not self._stop:
            with self._state_lock:
                n = self.sup_stats.consecutive_failures
                if n >= self.breaker_threshold:
                    self._state = BROKEN  # circuit open: stay unready
                    if TRACER.enabled:
                        TRACER.event("circuit", 0, scope="engine",
                                     state="open", fails=n,
                                     key=self._fault_key)
                    return
            time.sleep(min(self._backoff_base * (2 ** max(n - 1, 0)),
                           self._backoff_max))
            if self._stop:
                return  # closed during backoff: skip the doomed compile
            try:
                sched = self._make_sched(self._factory())
                # compile while still unready — the watchdog only watches
                # READY generations, so rebuild compile time can never
                # trip it (a stall_timeout below compile time would
                # otherwise recovery-loop forever)
                sched.warmup()
            except Exception:  # noqa: BLE001 — a failing factory is just
                with self._state_lock:  # another consecutive failure
                    self.sup_stats.consecutive_failures += 1
                continue
            with self._state_lock:
                if self._stop or self._state == CLOSED:
                    sched.close(timeout=1.0)
                    return
                self._gen += 1
                gen = self._gen
                self._dead_stats.append(self._sched.stats)
                if len(self._dead_stats) > 32:
                    old = self._dead_stats.pop(0)  # ancient: no writers
                    for k in _COUNTER_KEYS:
                        self._carry[k] += getattr(old, k, 0)
                self._sched = sched
                self._state = READY
                self.sup_stats.recoveries += 1
                recovery_ms = (time.perf_counter() - t_detect) * 1e3
                self.sup_stats.recovery_ms.append(recovery_ms)
            if TRACER.enabled:
                TRACER.event("recovery", 0, ms=round(recovery_ms, 3),
                             gen=gen, key=self._fault_key)
            self._start_loop(sched, gen)
            return
