from .engine import Engine, GenerationResult
from .faults import FAULTS, FaultError
from .resilience import EngineSupervisor, EngineUnready
from .scheduler import (PromptTooLong, QueueFull, RequestError, Scheduler,
                        SchedulerClosed, ServeRequest)
from .stats import ServeStats, StepStats, StepTimelineStats, SupervisorStats
from .trace import TRACER, Tracer

__all__ = ["Engine", "GenerationResult", "PromptTooLong", "Scheduler",
           "ServeRequest", "ServeStats", "StepStats", "FAULTS",
           "FaultError", "EngineSupervisor", "EngineUnready", "QueueFull",
           "RequestError", "SchedulerClosed", "SupervisorStats",
           "StepTimelineStats", "TRACER", "Tracer"]
