from .engine import Engine, GenerationResult
from .stats import StepStats

__all__ = ["Engine", "GenerationResult", "StepStats"]
