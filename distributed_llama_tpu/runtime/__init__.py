from .engine import Engine, GenerationResult
from .scheduler import PromptTooLong, Scheduler, ServeRequest
from .stats import ServeStats, StepStats

__all__ = ["Engine", "GenerationResult", "PromptTooLong", "Scheduler",
           "ServeRequest", "ServeStats", "StepStats"]
