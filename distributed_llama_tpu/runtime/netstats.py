"""Wire/collective observability.

The reference surfaces per-token transfer time and sent/received kB from its
socket byte counters (ref: src/socket.cpp:266-271, printed as the T/S/R
columns in benchmark mode — src/apps/dllama/dllama.cpp:74-91). Under XLA the
collectives live inside one compiled program, so the equivalent here is:

  * `estimate_decode_wire` — exact modeled bytes per decoded token per
    device, derived from the mesh and the sharding design (which collectives
    GSPMD/shard_map emit is determined by the partition specs, so the byte
    count is computable, not guessed);
  * `measure_allreduce_ms` — a timed collective microbench on the real mesh,
    giving the per-token transfer-time estimate the reference measures
    directly.

Ring-algorithm cost model: an all-reduce moves 2*(n-1)/n * payload per
device, an all-gather / all-to-all (n-1)/n * payload (SURVEY.md §3.4 maps
the reference's per-layer broadcast/gather pairs onto these).

The MEASURED side (dlwire) lives next to the model: the multihost
control plane's socket ledger (parallel/multihost.py → stats.WireStats)
counts real bytes per (peer, kind, direction), :func:`per_step_op_ms`
attributes real device collective ms per executed step from a profiler
capture, and :func:`reconcile_wire` closes the loop — measured against
modeled, drift flagged at ≥25% like the autotune knee check.
"""

from __future__ import annotations

import time
from typing import NamedTuple

import numpy as np

from ..models.spec import ModelSpec


class WireEstimate(NamedTuple):
    sent_kb_per_token: float          # per device, per decoded token
    breakdown: dict                   # component -> kB


def _ar(n: int, payload: float) -> float:
    """Ring all-reduce bytes sent per device."""
    return 2 * (n - 1) / n * payload


def _ag(n: int, payload: float) -> float:
    """Ring all-gather (or all-to-all) bytes sent per device; `payload` is
    the full gathered size."""
    return (n - 1) / n * payload


def estimate_decode_wire(
    spec: ModelSpec,
    mesh,
    *,
    q80: bool = False,
    act_bytes: int = 4,
    batch: int = 1,
    shard_vocab: bool = False,
    vocab_topk: int = 32,
) -> WireEstimate:
    """Modeled bytes each device sends per decoded token.

    tp: 2 partial-sum all-reduces per dense layer (wo, w2 — the reference's
    2 broadcast + 2 gather pairs collapse to these, SURVEY.md §3.4), one per
    active expert + one for wo on MoE layers, plus the vocab-sharded logits
    all-gather. q80 mode swaps the f32 all-reduce for the two-shot quantized
    exchange (int8 + f16 block scales = 1.0625 B/value).
    sp: the decode-attention stat merge (acc + m + l per layer).
    dp: no inter-device traffic at inference.
    shard_vocab (ops/sharded_vocab.py): the embedding gather costs one
    extra dim-sized all-reduce per forward, and the full-logits gather is
    REPLACED by the candidate-summary gather (S·k probs+ids + guards per
    row — hundreds of bytes where the logits were vocab·4).
    """
    if mesh is None:
        return WireEstimate(0.0, {})
    tp = mesh.shape.get("tp", 1)
    sp = mesh.shape.get("sp", 1)
    dp = mesh.shape.get("dp", 1)
    ep = mesh.shape.get("ep", 1)
    b_local = max(1, batch // dp)
    bd: dict[str, float] = {}

    val_bytes = 1.0625 if q80 else act_bytes  # int8 + f16/32-block scale
    if tp > 1:
        # with ep the MoE expert-sum reduce moves out of the tp column (see
        # ep_moe_reduce below); only the attention wo reduce stays per-layer
        if spec.is_moe:
            reduces_per_layer = 1 if ep > 1 else 1 + spec.n_active_experts
        else:
            reduces_per_layer = 2
        per_reduce = spec.dim * b_local * val_bytes
        layer_fn = _ar  # both the f32 all-reduce and the 2-shot q80
        # exchange move 2*(n-1)/n * payload per device
        bd["tp_partial_sums"] = (spec.n_layers * reduces_per_layer
                                 * layer_fn(tp, per_reduce))
        if shard_vocab:
            bd["vocab_embed_psum"] = _ar(tp, spec.dim * b_local
                                         * act_bytes)
            k = min(vocab_topk, max(spec.vocab_size // tp, 1))
            bd["vocab_sample_gather"] = _ag(
                tp, b_local * (tp * k * 8 + tp * 4 + 4))
        else:
            bd["tp_logits_gather"] = _ag(tp,
                                         spec.vocab_size * b_local * 4)
    if ep > 1:
        # one MoE output reduce per layer (parallel/ep_moe.py): exact mode is
        # a single all-reduce over the ep*tp group; q80 mode is a quantized
        # 2-shot over tp followed by an exact f32 psum over ep
        per = spec.dim * b_local
        if q80 and tp > 1:
            moe = _ar(tp, per * val_bytes) + _ar(ep, per * act_bytes)
        else:
            moe = _ar(ep * tp, per * act_bytes)
        bd["ep_moe_reduce"] = spec.n_layers * moe
    if sp > 1:
        stat = spec.n_heads * spec.head_size + 2 * spec.n_heads  # acc + m + l
        bd["sp_attn_merge"] = spec.n_layers * _ar(sp, stat * b_local * 4)
    pp = mesh.shape.get("pp", 1)
    if pp > 1:
        # one masked-psum live-stage broadcast of the activations per stage
        # (parallel/pp.py)
        bd["pp_stage_handoff"] = pp * _ar(pp, spec.dim * b_local * act_bytes)

    total = sum(bd.values())
    return WireEstimate(total / 1024.0,
                        {k: v / 1024.0 for k, v in bd.items()})


def estimate_serve_wire(
    spec: ModelSpec,
    mesh,
    *,
    batch: int = 1,
    occupancy: float | None = None,
    q80: bool = False,
    act_bytes: int = 4,
) -> WireEstimate:
    """Per-EMITTED-token wire under the continuous-batching scheduler
    (runtime/scheduler.py): a slot-scheduler decode step moves the full
    batch-B collective payload no matter how many slots are live (gated
    rows ride through every collective with the rest of the batch), so
    the per-emitted-token cost is the batch-B step estimate divided by
    the mean slot occupancy. occupancy == batch reproduces the static
    batched estimate; occupancy -> 1 degrades to B× the per-token wire —
    the quantitative reason queue pressure, not slot count, sets serving
    efficiency."""
    step = estimate_decode_wire(spec, mesh, q80=q80, act_bytes=act_bytes,
                                batch=batch)
    # `is not None`, not truthiness: a measured occupancy of 0.0 (idle
    # window) must clamp to the degenerate worst case below, not silently
    # take the full-batch best case
    occ = float(occupancy) if occupancy is not None else float(batch)
    occ = max(min(occ, float(batch)), 1e-6)
    return WireEstimate(step.sent_kb_per_token / occ,
                        {k: v / occ for k, v in step.breakdown.items()})


def estimate_prefix_reuse(
    spec: ModelSpec,
    mesh,
    *,
    tokens_saved: int,
    tokens_copied: int | None = None,
    cache_bytes: float = 2.0,
    q80: bool = False,
    act_bytes: int = 4,
    batch: int = 1,
) -> dict:
    """Modeled cost/benefit of serving `tokens_saved` prompt tokens from
    the radix prefix cache (runtime/prefix_cache.py) instead of
    prefilling them.

    A seeded token SKIPS its prefill forward entirely, so it saves the
    full per-token collective payload of a forward — the same per-layer
    reduces estimate_decode_wire models (prefill segments move the same
    per-token bytes as decode; only the segment width batches them).
    What it pays instead is a pure-HBM block copy that rides NO
    collective: 2 (K and V) * layers * kv_heads * head_size *
    cache_bytes per token COPIED — and `tokens_copied` is NOT
    `tokens_saved`: Engine.slot_seed_prefix always gathers the FULL
    fixed seed width (seq_len // block_len blocks, the price of keeping
    ONE compilation key), so every hit copies ~seq_len tokens' worth of
    K/V however short the match. Callers must pass the real figure
    (hits * (seq_len // block_len) * block_len); it defaults to
    tokens_saved only as the lower bound. This is why a deep context
    with tiny matches can pay more HBM than it saves — and why the
    bench row reports both numbers side by side.

    The wire side is why prefix reuse is still a near-strict win on
    meshes: the copy rides no collective, HBM bandwidth is orders of
    magnitude above ICI for the same bytes, and on a single chip the
    copy replaces whole forwards' weight reads + FLOPs.

    Returns {"wire_saved_kb", "hbm_copy_kb", "kb_saved_per_token"} —
    the bench's BENCH_PREFIX row reports these next to the measured
    TTFT delta."""
    per_tok_kb = estimate_decode_wire(spec, mesh, q80=q80,
                                      act_bytes=act_bytes,
                                      batch=batch).sent_kb_per_token
    copy_b = (2 * spec.n_layers * spec.n_kv_heads * spec.head_size
              * cache_bytes)
    copied = tokens_saved if tokens_copied is None else tokens_copied
    return {
        "wire_saved_kb": round(per_tok_kb * tokens_saved, 3),
        "hbm_copy_kb": round(copy_b * copied / 1024.0, 3),
        "kb_saved_per_token": round(per_tok_kb, 4),
    }


def estimate_block_transfer(
    spec: ModelSpec,
    *,
    tokens: int,
    block_len: int,
    cache_bytes: float = 2.0,
    link_gbps: float | None = None,
    prefill_tok_per_s: float | None = None,
    mesh=None,
    q80: bool = False,
    batch: int = 1,
) -> dict:
    """Model one cross-replica KV block transfer (runtime/kv_transfer.py)
    against the re-prefill it replaces — the "when does a fill pay"
    arithmetic (docs/serving.md "KV block transfer").

    The WIRE side is exact: ``tokens`` rounds down to whole blocks, each
    block ships one RMSG_BLOCK_DATA frame of 2 (K and V) * layers *
    kv_heads * block_len * head_size * cache_bytes payload plus the
    framed-codec overhead (parallel/multihost.frame_bytes — the same
    arithmetic the dlwire reconcile tests pin the measured ledger
    against), bracketed by the HELLO/QUERY/ACK/FETCH/END frames. The
    REPLACED side is the prefill forward those tokens would have run:
    per-token collective bytes (estimate_decode_wire — prefill moves the
    same per-token reduces as decode, batched by segment width) and, when
    a measured ``prefill_tok_per_s`` is given, the wall time. With a
    ``link_gbps`` both sides resolve to milliseconds and ``pays`` says
    whether the transfer wins; without them the byte model stands alone
    (``pays`` = None — never fabricated).

    ``modeled_data_bytes`` is the exact figure ``reconcile_wire`` closes
    against the measured BLOCK_DATA ledger entry at the 25% bar."""
    from ..parallel.multihost import frame_bytes

    bl = int(block_len)
    n_blocks = max(int(tokens), 0) // bl
    per_block = int(2 * spec.n_layers * spec.n_kv_heads * bl
                    * spec.head_size * cache_bytes)
    data_bytes = n_blocks * frame_bytes(1, per_block)
    # HELLO [v] + QUERY [requester, n_have, *tokens] + FETCH [s, e] tx;
    # HELLO_ACK [5] + ACK [7] + END [1] rx — tiny next to the payload,
    # counted so the model reconciles frame-exactly
    overhead = (frame_bytes(1, 0) + frame_bytes(2 + int(tokens), 0)
                + frame_bytes(2, 0) + frame_bytes(5, 0)
                + frame_bytes(7, 0) + frame_bytes(1, 0))
    out = {
        "tokens": n_blocks * bl,
        "n_blocks": n_blocks,
        "block_payload_bytes": per_block,
        "modeled_data_bytes": data_bytes,
        "overhead_bytes": overhead,
        "transfer_bytes": data_bytes + overhead,
        "reprefill_wire_kb": round(
            estimate_decode_wire(spec, mesh, q80=q80,
                                 batch=batch).sent_kb_per_token
            * n_blocks * bl, 3),
        "transfer_ms": None, "reprefill_ms": None, "pays": None,
    }
    if link_gbps:
        out["transfer_ms"] = round(
            (data_bytes + overhead) * 8 / (link_gbps * 1e9) * 1e3, 3)
    if prefill_tok_per_s:
        out["reprefill_ms"] = round(
            n_blocks * bl / prefill_tok_per_s * 1e3, 3)
    if out["transfer_ms"] is not None and out["reprefill_ms"] is not None:
        out["pays"] = out["transfer_ms"] < out["reprefill_ms"]
    return out


# measured-vs-modeled movement worth flagging, the same 25% bar the
# autotune knee-drift check uses (tools/dlprof.py mirrors both — it must
# run with no repo on the path; tests pin the mirrors against each other)
WIRE_DRIFT_FRAC = 0.25


def reconcile_wire(measured: float, modeled: float, *,
                   threshold: float = WIRE_DRIFT_FRAC,
                   unit: str = "bytes") -> dict:
    """Measured wire traffic (the dlwire ledger) vs the model — the
    closed loop the reference's printed T/S columns never had. Units are
    the caller's (control-plane bytes against frame-size arithmetic;
    per-token kB against :func:`estimate_decode_wire`) — only the RATIO
    matters here. ``drift`` trips at ``threshold`` relative movement:
    past it either the model is wrong (a collective the estimate does
    not know about) or the measurement is (bytes leaking outside the
    ledger) — both are findings. Modeled == 0 cannot reconcile: the
    result says so instead of dividing."""
    measured = float(measured)
    modeled = float(modeled)
    out = {"measured": round(measured, 4), "modeled": round(modeled, 4),
           "unit": unit, "threshold": threshold,
           "drift_frac": None, "drift": False, "note": None}
    if modeled > 0:
        frac = abs(measured - modeled) / modeled
        out["drift_frac"] = round(frac, 4)
        out["drift"] = frac >= threshold
        if out["drift"]:
            out["note"] = (f"measured {unit} moved {frac:.0%} from the "
                           f"model (>= {threshold:.0%}): the byte model "
                           "or the ledger is wrong — investigate before "
                           "trusting either")
    elif measured > 0:
        out["note"] = ("no model to reconcile against (modeled == 0) — "
                       "measured traffic stands alone")
    return out


COLLECTIVE_MARKERS = ("all-reduce", "all-gather", "reduce-scatter",
                      "all-to-all", "collective-permute")


def per_step_op_ms(trace_dir: str, markers: tuple = COLLECTIVE_MARKERS,
                   module_hint: str | None = None) -> list:
    """Parse a jax.profiler trace into PER-STEP summed device time (ms) of
    ops whose name contains any marker — the measured analogue of the
    reference's genuinely per-token T column (ref:
    src/apps/dllama/dllama.cpp:74-79), where `measure_allreduce_ms` is only
    a repeated microbench constant.

    A "step" is one executed XLA module (the engine's jitted forward): the
    device plane's "XLA Modules" line has one event per execution, and each
    op event on the "XLA Ops"/"Async XLA Ops" lines is bucketed into the
    module span containing it. Returns one float per module execution in
    timeline order; [] when the trace has no device plane (CPU runs) — the
    caller falls back to the microbench."""
    import bisect
    import glob

    try:
        from jax.profiler import ProfileData
    except ImportError:  # older jax without the xplane parser
        return []
    files = sorted(glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True))
    if not files:
        return []
    pd = ProfileData.from_file(files[-1])
    for plane in pd.planes:
        if not plane.name.startswith("/device:"):
            continue
        lines = {ln.name: ln for ln in plane.lines}
        mods = lines.get("XLA Modules")
        if mods is None:
            continue
        spans = sorted(
            (e.start_ns, e.end_ns) for e in mods.events
            if module_hint is None or module_hint in e.name)
        if not spans:
            continue
        starts = [s for s, _ in spans]
        out = [0.0] * len(spans)
        for ln_name in ("XLA Ops", "Async XLA Ops"):
            ops = lines.get(ln_name)
            if ops is None:
                continue
            for e in ops.events:
                if not any(m in e.name for m in markers):
                    continue
                i = bisect.bisect_right(starts, e.start_ns) - 1
                if i >= 0 and e.start_ns < spans[i][1]:
                    out[i] += e.duration_ns / 1e6
        return out
    return []


def per_trace_attribution(trace_dir: str) -> tuple[dict, float]:
    """ONE ProfileData walk returning both halves the sampled-step
    ingest needs: ({module name: total device ms}, total collective
    device ms). The separate :func:`per_module_ms` /
    :func:`per_step_op_ms` entry points each re-parse the whole xplane
    protobuf (tens of ms to seconds on a big trace) — the per-sample
    ingest thread must not pay that twice for one capture. Returns
    ({}, 0.0) when the trace has no device plane (CPU runs)."""
    import glob

    try:
        from jax.profiler import ProfileData
    except ImportError:
        return {}, 0.0
    files = sorted(glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True))
    if not files:
        return {}, 0.0
    pd = ProfileData.from_file(files[-1])
    mods: dict[str, float] = {}
    sync_ms = 0.0
    for plane in pd.planes:
        if not plane.name.startswith("/device:"):
            continue
        for ln in plane.lines:
            if ln.name == "XLA Modules":
                for e in ln.events:
                    name = e.name.split("(")[0]
                    if name.startswith("jit_"):
                        name = name[4:]
                    mods[name] = mods.get(name, 0.0) + e.duration_ns / 1e6
            elif ln.name in ("XLA Ops", "Async XLA Ops"):
                for e in ln.events:
                    if any(m in e.name for m in COLLECTIVE_MARKERS):
                        sync_ms += e.duration_ns / 1e6
    return ({k: round(v, 4) for k, v in mods.items()},
            round(sync_ms, 4))


def per_module_ms(trace_dir: str) -> dict:
    """Parse a jax.profiler trace into PER-ENTRY-POINT summed device time:
    {module name: total ms across its executions in the trace}. The same
    ProfileData walk as :func:`per_step_op_ms`, but keyed by module NAME
    instead of bucketing op events into execution spans — this is the
    attribution the sampled step profiler (runtime/profiler.py) records:
    the engine names every jitted wrapper by role (``slot_decode_step``,
    ``slot_prefill_chunk_16``, ``prefill_seg`` ... — Engine._compiled_step),
    so the XLA Modules line's event names map straight onto serving
    entry points. Returns {} when the trace has no device plane (CPU
    backends emit host planes only) — the caller treats attribution as
    best-effort."""
    import glob

    try:
        from jax.profiler import ProfileData
    except ImportError:  # older jax without the xplane parser
        return {}
    files = sorted(glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True))
    if not files:
        return {}
    pd = ProfileData.from_file(files[-1])
    out: dict[str, float] = {}
    for plane in pd.planes:
        if not plane.name.startswith("/device:"):
            continue
        for ln in plane.lines:
            if ln.name != "XLA Modules":
                continue
            for e in ln.events:
                # module names arrive as e.g. "jit_slot_decode_step(...)"
                # or with an id suffix — strip to the stable stem
                name = e.name.split("(")[0]
                if name.startswith("jit_"):
                    name = name[4:]
                out[name] = out.get(name, 0.0) + e.duration_ns / 1e6
    return {k: round(v, 4) for k, v in out.items()}


def measure_allreduce_ms(mesh, payload_elems: int, iters: int = 16,
                         axes: tuple = ("tp",)) -> float:
    """Time one f32 all-reduce of `payload_elems` over the given mesh axes
    (jointly — e.g. ("ep", "tp") for the MoE group reduce) — the measured
    analogue of the reference's per-token T column. Returns ms per
    all-reduce (amortized over iters; sync via device->host transfer, the
    only true sync on tunneled TPU platforms)."""
    import jax
    from ..parallel.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    axes = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    if n <= 1:
        return 0.0

    @jax.jit
    def run(x):
        def body(v):
            for _ in range(iters):
                v = jax.lax.psum(v, axes) * (1.0 / n)
            return v
        return shard_map(body, mesh=mesh, in_specs=P(axes),
                         out_specs=P(axes), check_vma=False)(x)

    # explicit placement + local-shard fetch: on a multi-process mesh the
    # sharded output spans non-addressable devices, so sync on a LOCAL shard
    # (its completion implies the collective chain ran); np.asarray of the
    # full array would raise, and block_until_ready lies on tunneled TPUs
    x = jax.device_put(np.ones((n, payload_elems), np.float32),
                       NamedSharding(mesh, P(axes)))

    def sync(out):
        np.asarray(out.addressable_shards[0].data)

    sync(run(x))  # compile + warm
    t0 = time.perf_counter()
    sync(run(x))
    dt = time.perf_counter() - t0
    return dt / iters * 1e3


def measure_ppermute_ms(mesh, payload_elems: int, iters: int = 16,
                        axis: str = "pp") -> float:
    """Time one f32 next-neighbor ppermute of `payload_elems` over `axis` —
    the GPipe microbatch activation hop (parallel/pp.py pp_layers_gpipe's
    shift()). Same sync discipline as measure_allreduce_ms. Returns ms per
    hop, 0.0 when the axis is absent/size 1."""
    import jax
    from ..parallel.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape.get(axis, 1)
    if n <= 1:
        return 0.0
    perm = [(i, i + 1) for i in range(n - 1)]

    @jax.jit
    def run(x):
        def body(v):
            for _ in range(iters):
                v = jax.lax.ppermute(v, axis, perm)
            return v
        return shard_map(body, mesh=mesh, in_specs=P(axis),
                         out_specs=P(axis), check_vma=False)(x)

    x = jax.device_put(np.ones((n, payload_elems), np.float32),
                       NamedSharding(mesh, P(axis)))

    def sync(out):
        np.asarray(out.addressable_shards[0].data)

    sync(run(x))  # compile + warm
    t0 = time.perf_counter()
    sync(run(x))
    dt = time.perf_counter() - t0
    return dt / iters * 1e3
